"""Build the optional C++ fast-path extension (cometbft_tpu._native).

    python setup.py build_ext --inplace

The engine also self-builds it on first use via
cometbft_tpu/crypto/_native_loader.py; this setup.py is the standard
packaging entry point.
"""
from setuptools import Extension, find_packages, setup

setup(
    name="cometbft-tpu",
    version="1.0.0",
    packages=find_packages(include=["cometbft_tpu*"]),
    ext_modules=[Extension(
        "cometbft_tpu._native",
        sources=["native/_native.cpp"],
        include_dirs=["native"],
        extra_compile_args=["-O3", "-std=c++17"],
        language="c++",
    )],
)
