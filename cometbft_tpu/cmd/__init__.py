"""CLI entry point: python -m cometbft_tpu.cmd <command>."""
