"""The node operator CLI.

Reference: cmd/cometbft/ — init, start, show-node-id, show-validator,
gen-node-key, gen-validator, unsafe-reset-all, testnet, version,
rollback (cmd/cometbft/commands/).
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import shutil
import sys


def _load_config(home: str):
    from ..confix import effective_config
    return effective_config(home)


def cmd_init(args) -> int:
    from ..node import init_files
    cfg = _load_config(args.home)
    doc = init_files(cfg, chain_id=args.chain_id)
    print(f"Initialized node in {args.home} "
          f"(chain_id={doc.chain_id})")
    return 0


def cmd_start(args) -> int:
    from ..node import Node
    # live-stack debugging for a wedged/starved node: SIGUSR1 dumps
    # every thread's Python stack to stderr without killing the
    # process (faulthandler is async-signal-safe, so this works even
    # when the event loop is livelocked and RPC cannot answer)
    import faulthandler
    import signal
    try:
        faulthandler.register(signal.SIGUSR1)
    except (AttributeError, ValueError, OSError):
        pass   # platform without SIGUSR1 / non-main thread
    cfg = _load_config(args.home)
    if args.proxy_app:
        cfg.base.proxy_app = args.proxy_app
    if args.p2p_laddr:
        cfg.p2p.laddr = args.p2p_laddr
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    if args.persistent_peers:
        cfg.p2p.persistent_peers = args.persistent_peers
    if args.log_level:
        cfg.base.log_level = args.log_level

    async def main():
        node = Node(cfg)
        await node.start()
        stop = asyncio.Event()
        try:
            import signal
            loop = asyncio.get_running_loop()
            for sig in (signal.SIGINT, signal.SIGTERM):
                loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, ImportError):
            pass
        await stop.wait()
        await node.stop()

    asyncio.run(main())
    return 0


def cmd_show_node_id(args) -> int:
    from ..p2p.key import NodeKey
    cfg = _load_config(args.home)
    nk = NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
    print(nk.id)
    return 0


def cmd_show_validator(args) -> int:
    from ..privval import FilePV
    cfg = _load_config(args.home)
    pv = FilePV.load_or_generate(
        cfg.base.path(cfg.base.priv_validator_key_file),
        cfg.base.path(cfg.base.priv_validator_state_file))
    pub = pv.get_pub_key()
    from ..types.genesis import pub_key_to_json
    print(json.dumps(pub_key_to_json(pub)))
    return 0


def cmd_gen_node_key(args) -> int:
    from ..p2p.key import NodeKey
    cfg = _load_config(args.home)
    path = cfg.base.path(cfg.base.node_key_file)
    if os.path.exists(path):
        print(f"node key already exists at {path}", file=sys.stderr)
        return 1
    nk = NodeKey.generate()
    nk.save_as(path)
    print(nk.id)
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """Reference: commands/reset.go — wipe data, keep keys, reset
    priv validator state."""
    from ..privval import FilePV
    cfg = _load_config(args.home)
    data_dir = cfg.base.path(cfg.base.db_dir)
    if os.path.isdir(data_dir):
        shutil.rmtree(data_dir)
    os.makedirs(data_dir, exist_ok=True)
    key_file = cfg.base.path(cfg.base.priv_validator_key_file)
    if os.path.exists(key_file):
        pv = FilePV.load(key_file,
                         cfg.base.path(
                             cfg.base.priv_validator_state_file))
        pv.reset()
    print(f"Reset {data_dir}")
    return 0


def cmd_config_validate(args) -> int:
    """Reference: `cometbft config` (internal/confix) — validate the
    persisted config file."""
    from ..config import ConfigError, validate_basic
    cfg = _load_config(args.home)
    try:
        validate_basic(cfg)
    except ConfigError as e:
        print(f"config invalid: {e}")
        return 1
    print("config is valid")
    return 0


def cmd_config_view(args) -> int:
    """Print the effective config (defaults + overrides) as JSON
    (reference: confix view)."""
    from .. import confix
    print(json.dumps(
        confix.config_to_dict(confix.effective_config(args.home)),
        indent=2, sort_keys=True))
    return 0


def cmd_config_get(args) -> int:
    from .. import confix
    try:
        print(json.dumps(confix.get_value(args.home, args.key)))
    except KeyError:
        print(f"unknown key {args.key!r}")
        return 1
    return 0


def cmd_config_set(args) -> int:
    from .. import confix
    try:
        v = confix.set_value(args.home, args.key, args.value)
    except (KeyError, ValueError) as e:
        print(f"cannot set {args.key!r}: {e}")
        return 1
    print(f"{args.key} = {json.dumps(v)}")
    return 0


def cmd_config_diff(args) -> int:
    """Show overrides differing from defaults plus unknown entries
    (reference: confix diff)."""
    from .. import confix
    print(json.dumps(confix.diff_from_defaults(args.home), indent=2,
                     sort_keys=True))
    return 0


def cmd_config_migrate(args) -> int:
    """Normalize the persisted config against the current schema
    (reference: confix migrate)."""
    from .. import confix
    log = confix.migrate(args.home, dry_run=args.dry_run)
    for line in log:
        print(("would have " if args.dry_run else "") + line)
    if not log:
        print("config already up to date")
    return 0


def cmd_priv_val_server(args) -> int:
    """Standalone remote signer daemon: dial the node's privval
    listener and serve signing requests from a FilePV (reference:
    cmd/priv_val_server + privval/signer_server.go)."""
    import asyncio

    from ..privval import FilePV
    from ..privval.signer import SignerServer

    pv = FilePV.load_or_generate(args.priv_key_file, args.state_file)
    print(f"remote signer: validator "
          f"{pv.get_pub_key().address().hex().upper()[:12]} "
          f"-> {args.addr} (chain {args.chain_id})")

    async def main():
        srv = SignerServer(args.addr, args.chain_id, pv,
                           retries=10 ** 9)
        await srv.start()
        try:
            await asyncio.Event().wait()
        finally:
            await srv.stop()
    try:
        asyncio.run(main())
    except KeyboardInterrupt:
        pass
    return 0


def cmd_generate_manifests(args) -> int:
    """Reference: test/e2e/generator — write N random manifests."""
    from ..tools.manifest import generate

    os.makedirs(args.o, exist_ok=True)
    for i in range(args.n):
        m = generate(seed=args.seed + i)
        path = os.path.join(args.o, f"gen-{args.seed + i:03d}.json")
        m.save(path)
        print(path)
    return 0


def cmd_load(args) -> int:
    """Timestamped-tx load generation (reference: test/loadtime
    cmd/load)."""
    import asyncio

    from ..tools import loadtime

    async def run():
        res = await loadtime.generate(
            args.endpoints.split(","), rate=args.rate,
            connections=args.connections,
            duration_s=args.duration, size=args.size,
            method=args.broadcast_tx_method)
        print(json.dumps({
            "experiment_id": res.experiment_id, "sent": res.sent,
            "accepted": res.accepted, "errors": res.errors,
            "duration_s": round(res.duration_s, 3)}))
        if args.report:
            rep = await loadtime.report(
                args.endpoints.split(",")[0],
                experiment_id=res.experiment_id)
            print(json.dumps(rep.to_dict()))
    asyncio.run(run())
    return 0


def cmd_load_report(args) -> int:
    """Latency + block-interval report over committed blocks
    (reference: test/loadtime cmd/report + e2e runner/benchmark.go)."""
    import asyncio

    from ..tools import loadtime

    async def run():
        rep = await loadtime.report(
            args.endpoint, experiment_id=args.experiment_id or None,
            from_height=args.from_height, to_height=args.to_height)
        print(json.dumps(rep.to_dict(), indent=2))
    asyncio.run(run())
    return 0


def cmd_inspect(args) -> int:
    """Serve read-only RPC over the data stores of a stopped/crashed
    node — no consensus, no p2p (reference: commands/inspect.go +
    inspect/inspect.go)."""
    import asyncio

    cfg = _load_config(args.home)

    class _InspectNode:
        """The minimal node surface rpc/core needs for read paths."""

        def __init__(self):
            from ..db import new_db
            from ..state.store import Store
            from ..store import BlockStore
            from ..types.events import EventBus
            from ..types.genesis import GenesisDoc
            db_dir = cfg.base.path(cfg.base.db_dir)
            backend = cfg.base.db_backend
            self.block_store = BlockStore(
                new_db("blockstore", backend, db_dir))
            self.state_store = Store(new_db("state", backend, db_dir))
            self.genesis_doc = GenesisDoc.from_file(
                cfg.base.path(cfg.base.genesis_file))
            self.event_bus = EventBus()
            self.mempool = None
            self.consensus_state = None
            self.config = cfg
            from ..indexer import BlockIndexer, TxIndexer
            idx_db = new_db("tx_index", backend, db_dir)
            self.tx_indexer = TxIndexer(idx_db)
            self.block_indexer = BlockIndexer(idx_db)
            self.metrics_registry = None

        def status(self):
            h = self.block_store.height
            meta = self.block_store.load_block_meta(h)
            return {"node_info": {"moniker": "inspect"},
                    "sync_info": {
                        "latest_block_height": str(h),
                        "latest_block_hash":
                            meta.block_id.hash.hex().upper()
                            if meta else "",
                        "earliest_block_height":
                            str(self.block_store.base),
                        "catching_up": False}}

    async def run():
        from ..rpc import core as rpc_core
        from ..rpc.server import RPCServer
        node = _InspectNode()
        cfg.rpc.laddr = args.rpc_laddr or cfg.rpc.laddr or \
            "tcp://127.0.0.1:26657"
        # restricted read-only route set (reference: inspect/rpc.go
        # Routes) — store/index reads only, no mempool/consensus/p2p
        env = rpc_core.Environment(node)
        all_routes = rpc_core.routes(env)
        routes = {name: all_routes[name] for name in (
            "health", "status", "genesis", "block", "block_by_hash",
            "block_results", "commit", "blockchain", "validators",
            "consensus_params", "tx", "tx_search", "block_search",
        ) if name in all_routes}
        srv = RPCServer(node, cfg.rpc, routes=routes)
        await srv.start()
        print(f"inspect server on {srv.listen_addr} "
              f"(height {node.block_store.height})")
        await asyncio.Event().wait()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


def cmd_reindex_event(args) -> int:
    """Rebuild the tx/block indexes from the block store + stored
    FinalizeBlockResponses (reference: commands/reindex_event.go)."""
    from ..abci import types as abci
    from ..db import new_db
    from ..indexer import BlockIndexer, TxIndexer
    from ..state.store import Store
    from ..store import BlockStore

    cfg = _load_config(args.home)
    db_dir = cfg.base.path(cfg.base.db_dir)
    backend = cfg.base.db_backend
    block_store = BlockStore(new_db("blockstore", backend, db_dir))
    state_store = Store(new_db("state", backend, db_dir))
    idx_db = new_db("tx_index", backend, db_dir)
    txi, bi = TxIndexer(idx_db), BlockIndexer(idx_db)

    start = args.start_height or block_store.base
    end = args.end_height or block_store.height
    n_txs = n_blocks = 0
    for h in range(start, end + 1):
        block = block_store.load_block(h)
        resp = state_store.load_finalize_block_response(h)
        if block is None or resp is None:
            continue
        bi.index(h, resp.events)
        n_blocks += 1
        for i, tx in enumerate(block.data.txs):
            if i < len(resp.tx_results):
                txi.index(abci.TxResult(height=h, index=i, tx=tx,
                                        result=resp.tx_results[i]))
                n_txs += 1
    print(f"reindexed {n_blocks} blocks / {n_txs} txs "
          f"(heights {start}..{end})")
    return 0


def cmd_debug_dump(args) -> int:
    """Capture a diagnostic bundle from a RUNNING node over RPC
    (reference: cmd/cometbft/commands/debug — status, net_info,
    consensus state, config, metrics)."""
    import asyncio
    import json as _json
    import os as _os

    async def run():
        from ..rpc.client import HTTPClient
        cli = HTTPClient(args.rpc_laddr)
        out_dir = args.output_directory
        _os.makedirs(out_dir, exist_ok=True)
        for method in ("status", "net_info", "consensus_state",
                       "num_unconfirmed_txs"):
            try:
                res = await cli.call(method)
            except Exception as e:  # noqa: BLE001 — best-effort bundle
                res = {"error": str(e)}
            with open(_os.path.join(out_dir, f"{method}.json"),
                      "w") as f:
                _json.dump(res, f, indent=2)
        # metrics exposition
        import urllib.request
        try:
            url = args.rpc_laddr.replace("tcp://", "http://")
            with urllib.request.urlopen(f"{url}/metrics",
                                        timeout=5) as r:
                text = r.read().decode()
        except Exception as e:  # noqa: BLE001
            text = f"# error: {e}\n"
        with open(_os.path.join(out_dir, "metrics.txt"), "w") as f:
            f.write(text)
        print(f"debug bundle written to {out_dir}")

    asyncio.run(run())
    return 0


def cmd_light(args) -> int:
    """Reference: cmd/cometbft/commands/light.go — stand-alone verifying
    proxy daemon."""
    import asyncio

    from ..light.proxy import LightProxy

    async def run():
        proxy = LightProxy(
            args.chain_id, args.primary, list(args.witness),
            args.trusted_height, bytes.fromhex(args.trusted_hash),
            args.laddr)
        await proxy.start()
        await asyncio.Event().wait()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


def cmd_testnet(args) -> int:
    """Generate configs/genesis for an N-validator local testnet
    (reference: commands/testnet.go)."""
    from ..config import Config
    from ..node import init_files
    from ..privval import FilePV
    from ..types.genesis import GenesisDoc, GenesisValidator
    from ..types.timestamp import Timestamp
    from ..p2p.key import NodeKey

    n = args.v
    out = args.o
    pvs, node_ids = [], []
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        cfg = Config()
        cfg.base.home = home
        os.makedirs(os.path.join(home, "config"), exist_ok=True)
        os.makedirs(os.path.join(home, "data"), exist_ok=True)
        pv = FilePV.load_or_generate(
            cfg.base.path(cfg.base.priv_validator_key_file),
            cfg.base.path(cfg.base.priv_validator_state_file),
            key_type=getattr(args, "key_type", "ed25519"))
        nk = NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
        pvs.append(pv)
        node_ids.append(nk.id)
    doc = GenesisDoc(
        chain_id=args.chain_id or "local-testnet",
        genesis_time=Timestamp.now(),
        validators=[GenesisValidator(address=b"",
                                     pub_key=pv.get_pub_key(),
                                     power=1)
                    for pv in pvs])
    doc.validate_and_complete()
    base_p2p, base_rpc = args.starting_p2p_port, args.starting_rpc_port
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        doc.save_as(os.path.join(home, "config", "genesis.json"))
        peers = ",".join(
            f"{node_ids[j]}@127.0.0.1:{base_p2p + j}"
            for j in range(n) if j != i)
        with open(os.path.join(home, "config", "config.json"),
                  "w") as f:
            json.dump({
                "p2p": {"laddr": f"tcp://127.0.0.1:{base_p2p + i}",
                        "persistent_peers": peers},
                "rpc": {"laddr": f"tcp://127.0.0.1:{base_rpc + i}"},
            }, f, indent=2)
    print(f"Successfully initialized {n} node directories in {out}")
    return 0


def cmd_version(args) -> int:
    from .. import version
    print(version.CMT_SEM_VER)
    return 0


def cmd_rollback(args) -> int:
    """Reference: commands/rollback.go + state/rollback.go."""
    from ..db import new_db
    from ..state.rollback import rollback_state
    from ..state.store import Store
    from ..store import BlockStore
    cfg = _load_config(args.home)
    db_dir = cfg.base.path(cfg.base.db_dir)
    bs = BlockStore(new_db("blockstore", cfg.base.db_backend, db_dir))
    ss = Store(new_db("state", cfg.base.db_backend, db_dir))
    height, app_hash = rollback_state(ss, bs,
                                      remove_block=args.hard)
    print(f"Rolled back state to height {height} and hash "
          f"{app_hash.hex().upper()}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="cometbft-tpu",
        description="TPU-native BFT consensus node")
    p.add_argument("--home", default=os.path.expanduser("~/.cometbft"),
                   help="node home directory")
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("init", help="initialize files for a node")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("start", help="run the node")
    sp.add_argument("--proxy_app", default="")
    sp.add_argument("--p2p.laddr", dest="p2p_laddr", default="")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr", default="")
    sp.add_argument("--p2p.persistent_peers",
                    dest="persistent_peers", default="")
    sp.add_argument("--log_level", default="")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("show-node-id", help="show the node ID")
    sp.set_defaults(fn=cmd_show_node_id)

    sp = sub.add_parser("show-validator",
                        help="show the validator pubkey")
    sp.set_defaults(fn=cmd_show_validator)

    sp = sub.add_parser("gen-node-key", help="generate a node key")
    sp.set_defaults(fn=cmd_gen_node_key)

    sp = sub.add_parser("unsafe-reset-all",
                        help="wipe data, keep keys")
    sp.set_defaults(fn=cmd_unsafe_reset_all)

    sp = sub.add_parser("testnet",
                        help="generate a local testnet")
    sp.add_argument("--v", type=int, default=4,
                    help="number of validators")
    sp.add_argument("--o", default="./mytestnet",
                    help="output directory")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--starting-p2p-port", type=int, default=26656)
    sp.add_argument("--starting-rpc-port", type=int, default=26657)
    sp.add_argument("--key-type", dest="key_type", default="ed25519",
                    help="validator key type: ed25519|secp256k1|bls12_381 "
                         "(reference: testnet.go --key-type)")
    sp.set_defaults(fn=cmd_testnet)

    sp = sub.add_parser(
        "light", help="run a light-client verifying RPC proxy")
    sp.add_argument("chain_id")
    sp.add_argument("--primary", required=True,
                    help="primary full node RPC address")
    sp.add_argument("--witness", action="append", default=[],
                    help="witness RPC address (repeatable)")
    sp.add_argument("--trusted-height", type=int, required=True)
    sp.add_argument("--trusted-hash", required=True,
                    help="hex header hash at the trusted height")
    sp.add_argument("--laddr", default="tcp://127.0.0.1:8888")
    sp.set_defaults(fn=cmd_light)

    sp = sub.add_parser("config", help="config tooling")
    cfgsub = sp.add_subparsers(dest="config_cmd", required=True)
    cv = cfgsub.add_parser("validate", help="validate the config file")
    cv.set_defaults(fn=cmd_config_validate)
    cv = cfgsub.add_parser("view", help="print the effective config")
    cv.set_defaults(fn=cmd_config_view)
    cv = cfgsub.add_parser("get", help="print one config value")
    cv.add_argument("key", help="section.key")
    cv.set_defaults(fn=cmd_config_get)
    cv = cfgsub.add_parser("set", help="persist one config value")
    cv.add_argument("key", help="section.key")
    cv.add_argument("value")
    cv.set_defaults(fn=cmd_config_set)
    cv = cfgsub.add_parser("diff",
                           help="show changes vs the defaults")
    cv.set_defaults(fn=cmd_config_diff)
    cv = cfgsub.add_parser(
        "migrate", help="normalize the config file to this schema")
    cv.add_argument("--dry-run", action="store_true")
    cv.set_defaults(fn=cmd_config_migrate)

    sp = sub.add_parser(
        "priv-val-server",
        help="standalone remote signer daemon (dials the node)")
    sp.add_argument("--addr", required=True,
                    help="node's priv_validator_laddr to dial")
    sp.add_argument("--chain-id", required=True)
    sp.add_argument("--priv-key-file", required=True)
    sp.add_argument("--state-file", required=True)
    sp.set_defaults(fn=cmd_priv_val_server)

    sp = sub.add_parser(
        "generate-manifests",
        help="randomly sample testnet manifests (e2e generator)")
    sp.add_argument("-o", default=".", help="output directory")
    sp.add_argument("-n", type=int, default=4,
                    help="number of manifests")
    sp.add_argument("--seed", type=int, default=0)
    sp.set_defaults(fn=cmd_generate_manifests)

    sp = sub.add_parser("load", help="generate timestamped tx load")
    sp.add_argument("--endpoints", required=True,
                    help="comma-separated RPC base URLs")
    sp.add_argument("--rate", type=int, default=100)
    sp.add_argument("--connections", type=int, default=1)
    sp.add_argument("--duration", type=float, default=10.0)
    sp.add_argument("--size", type=int, default=256)
    sp.add_argument("--broadcast-tx-method", default="sync",
                    choices=["sync", "async"])
    sp.add_argument("--report", action="store_true",
                    help="print the latency report afterwards")
    sp.set_defaults(fn=cmd_load)

    sp = sub.add_parser(
        "load-report", help="latency report over committed blocks")
    sp.add_argument("--endpoint", required=True)
    sp.add_argument("--experiment-id", default="")
    sp.add_argument("--from-height", type=int, default=0)
    sp.add_argument("--to-height", type=int, default=0)
    sp.set_defaults(fn=cmd_load_report)

    sp = sub.add_parser(
        "inspect", help="read-only RPC over a stopped node's data")
    sp.add_argument("--rpc.laddr", dest="rpc_laddr", default="")
    sp.set_defaults(fn=cmd_inspect)

    sp = sub.add_parser("reindex-event",
                        help="rebuild tx/block indexes from stores")
    sp.add_argument("--start-height", type=int, default=0)
    sp.add_argument("--end-height", type=int, default=0)
    sp.set_defaults(fn=cmd_reindex_event)

    sp = sub.add_parser("debug", help="debug a running node")
    dbg = sp.add_subparsers(dest="debug_cmd", required=True)
    dd = dbg.add_parser("dump", help="capture a diagnostic bundle")
    dd.add_argument("output_directory")
    dd.add_argument("--rpc-laddr", default="tcp://127.0.0.1:26657")
    dd.set_defaults(fn=cmd_debug_dump)

    sp = sub.add_parser("rollback", help="roll back one height")
    sp.add_argument("--hard", action="store_true",
                    help="also remove the block")
    sp.set_defaults(fn=cmd_rollback)

    sp = sub.add_parser("version", help="show version")
    sp.set_defaults(fn=cmd_version)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
