"""KV tx/block indexers.

Reference: state/txindex/kv/kv.go (TxIndexer over events with the
pubsub query language) and state/indexer/block/kv (block events).
Records: tx hash → TxResult proto; composite event key
(type.attr/value/height/index) → tx hash or block height.
"""
from __future__ import annotations

import struct
from typing import Optional

from ..abci import types as abci
from ..db import DB
from ..libs.pubsub import Query
from ..wire import abci_pb, encode, decode

_TX_RESULT = b"tx/"
_TX_EVENT = b"te/"
_BLOCK_EVENT = b"be/"
_BLOCK_HEIGHT_REG = b"bh/"      # height -> hex key list (for pruning)
_TX_HEIGHT_REG = b"th/"         # height+hash -> hex key list


def _hex(k: bytes) -> bytes:
    return k.hex().encode()


_BLOCK_HEIGHT_KEY = "block.height"
_TX_HEIGHT_KEY = "tx.height"
_TX_HASH_KEY = "tx.hash"


# per-height registries share one wire format: hex-encoded keys
# joined by NUL (the raw keys themselves contain NUL separators)

def _reg_encode(keys: list[bytes]) -> bytes:
    return b"\x00".join(_hex(k) for k in keys)


def _reg_delete(batch, reg: bytes) -> int:
    n = 0
    for hexkey in reg.split(b"\x00"):
        if hexkey:
            batch.delete(bytes.fromhex(hexkey.decode()))
            n += 1
    return n


def _event_key(prefix: bytes, composite: str, value: str,
               height: int, tie: bytes) -> bytes:
    return (prefix + composite.encode() + b"\x00" + value.encode() +
            b"\x00" + struct.pack(">q", height) + b"\x00" + tie)


class TxIndexer:
    """Reference: state/txindex/indexer.go:24 TxIndexer interface."""

    def __init__(self, db: DB):
        self._db = db

    def index(self, tx_result: abci.TxResult) -> None:
        from ..types.tx import tx_hash
        h = tx_hash(tx_result.tx)
        raw = encode(abci_pb.TX_RESULT, {
            **({"height": tx_result.height}
               if tx_result.height else {}),
            **({"index": tx_result.index} if tx_result.index else {}),
            **({"tx": tx_result.tx} if tx_result.tx else {}),
            "result": _exec_result_proto(tx_result.result),
        })
        batch = self._db.new_batch()
        batch.set(_TX_RESULT + h, raw)
        # implicit tx.height/tx.hash attributes + app events
        keys = []
        for composite, value in _iter_event_attrs(
                tx_result.result.events):
            keys.append(_event_key(_TX_EVENT, composite, value,
                                   tx_result.height, h))
        for k in keys:
            batch.set(k, h)
        batch.set(_event_key(_TX_EVENT, _TX_HEIGHT_KEY,
                             str(tx_result.height), tx_result.height,
                             h), h)
        # per-(height,hash) registry of app-event keys so pruning can
        # delete them even when the same tx hash is re-committed at a
        # later height (the stored record then carries the later
        # height, and these keys could not be recomputed from it);
        # event-less txs need no entry — prune's recompute path
        # correctly deletes nothing for them
        if keys:
            batch.set(_TX_HEIGHT_REG + struct.pack(
                ">q", tx_result.height) + h, _reg_encode(keys))
        batch.write()

    def prune(self, from_height: int, to_height: int) -> int:
        """Delete indexed txs with height in [from, to) (reference:
        state/txindex/kv Prune, driven by the pruning service).  The
        txs at each height are found via the implicit tx.height index
        entries, then their event keys are recomputed from the stored
        TxResult — deletion is proportional to the data pruned, not
        the index size."""
        if to_height <= from_height:
            return 0
        pruned = 0
        batch = self._db.new_batch()
        for h in range(from_height, to_height):
            hk = _event_key(_TX_EVENT, _TX_HEIGHT_KEY, str(h), h, b"")
            for k, tx_hash_ in list(self._db.iterator(
                    hk, hk + b"\xff" * 40)):
                # this height's app-event keys come from the registry
                # — the stored record may carry a LATER height (same
                # tx hash re-committed), so they can't be recomputed
                reg_key = (_TX_HEIGHT_REG + struct.pack(">q", h) +
                           tx_hash_)
                reg = self._db.get(reg_key)
                if reg is not None:
                    _reg_delete(batch, reg)
                    batch.delete(reg_key)
                raw = self._db.get(_TX_RESULT + tx_hash_)
                # only delete the stored record if it belongs to THIS
                # height — the same tx hash re-committed later
                # overwrites the record, and the retained copy must
                # survive (its event keys embed the later height)
                if raw is not None:
                    d = decode(abci_pb.TX_RESULT, raw)
                    if d.get("height", 0) == h:
                        if reg is None:
                            # pre-registry record: recompute from the
                            # stored result (correct for this case —
                            # record height matches)
                            res = _exec_result_from_proto(
                                d.get("result") or {})
                            for composite, value in _iter_event_attrs(
                                    res.events):
                                batch.delete(_event_key(
                                    _TX_EVENT, composite, value, h,
                                    tx_hash_))
                        batch.delete(_TX_RESULT + tx_hash_)
                        pruned += 1
                batch.delete(k)
        batch.write()
        return pruned

    def get(self, tx_hash_: bytes) -> Optional[abci.TxResult]:
        raw = self._db.get(_TX_RESULT + tx_hash_)
        if raw is None:
            return None
        d = decode(abci_pb.TX_RESULT, raw)
        return abci.TxResult(
            height=d.get("height", 0), index=d.get("index", 0),
            tx=d.get("tx", b""),
            result=_exec_result_from_proto(d.get("result") or {}))

    def search(self, query: Query, limit: int = 100) -> list[bytes]:
        """Tx hashes whose indexed events satisfy the query (AND of
        conditions, like the reference's kv search).  Equality
        conditions narrow the scan to the exact value's key range."""
        result: Optional[set[bytes]] = None
        for cond in query.conditions:
            matches = set()
            prefix = _TX_EVENT + cond.key.encode() + b"\x00"
            lo, hi = _cond_range(prefix, cond)
            for k, v in self._db.iterator(lo, hi):
                rest = k[len(prefix):]
                value = rest.split(b"\x00", 1)[0].decode(
                    errors="replace")
                if cond.matches_value(value):
                    matches.add(v)
            result = matches if result is None else result & matches
            if not result:
                return []
        return list(result or [])[:limit]


class BlockIndexer:
    """Reference: state/indexer/block/kv."""

    def __init__(self, db: DB):
        self._db = db

    def index(self, height: int, events: list) -> None:
        batch = self._db.new_batch()
        tie = struct.pack(">q", height)
        keys = [_event_key(_BLOCK_EVENT, _BLOCK_HEIGHT_KEY,
                           str(height), height, tie)]
        for composite, value in _iter_event_attrs(events):
            keys.append(_event_key(_BLOCK_EVENT, composite, value,
                                   height, tie))
        for k in keys:
            batch.set(k, tie)
        # per-height registry of emitted keys so pruning touches only
        # the pruned heights (keys can't be recomputed from height
        # alone — the events aren't stored here)
        batch.set(_BLOCK_HEIGHT_REG + tie, _reg_encode(keys))
        batch.write()

    def prune(self, from_height: int, to_height: int) -> int:
        """Delete block-event index entries with height in [from, to)
        (reference: state/indexer/block/kv Prune).  Uses the
        per-height key registry written by index(), so the pass only
        touches the pruned heights."""
        if to_height <= from_height:
            return 0
        pruned = 0
        need_scan = False
        batch = self._db.new_batch()
        for h in range(from_height, to_height):
            reg_key = _BLOCK_HEIGHT_REG + struct.pack(">q", h)
            reg = self._db.get(reg_key)
            if reg is None:
                # height indexed before the registry existed — fall
                # back to one legacy scan below rather than silently
                # leaking its entries past the watermark
                need_scan = True
                continue
            pruned += _reg_delete(batch, reg)
            batch.delete(reg_key)
        if need_scan:
            for k, _ in list(self._db.iterator(
                    _BLOCK_EVENT, _BLOCK_EVENT + b"\xff" * 64)):
                # key tail is fixed-width: ...<height:8>\0<tie:8>
                if len(k) < 17 or k[-9] != 0:
                    continue
                h = struct.unpack(">q", k[-17:-9])[0]
                if from_height <= h < to_height:
                    batch.delete(k)
                    pruned += 1
        batch.write()
        return pruned

    def search(self, query: Query, limit: int = 100) -> list[int]:
        result: Optional[set[int]] = None
        for cond in query.conditions:
            matches = set()
            prefix = _BLOCK_EVENT + cond.key.encode() + b"\x00"
            lo, hi = _cond_range(prefix, cond)
            for k, v in self._db.iterator(lo, hi):
                rest = k[len(prefix):]
                value = rest.split(b"\x00", 1)[0].decode(
                    errors="replace")
                if cond.matches_value(value):
                    matches.add(struct.unpack(">q", v)[0])
            result = matches if result is None else result & matches
            if not result:
                return []
        return sorted(result or [])[:limit]


def _cond_range(prefix: bytes, cond) -> tuple[bytes, bytes]:
    """Key range for one condition scan.  String equality narrows to
    the exact value's range (O(matches) instead of O(all values for
    the key)); everything else scans the composite-key prefix.
    Numeric equality can't narrow: '7' matches event value '7.0'."""
    from ..libs.pubsub import _as_number
    if cond.op == "=" and isinstance(cond.value, str) and \
            _as_number(cond.value) is None:
        exact = prefix + cond.value.encode() + b"\x00"
        return exact, exact + b"\xff" * 64
    return prefix, prefix + b"\xff" * 64


def _iter_event_attrs(events):
    for ev in events or []:
        for attr in ev.attributes:
            if attr.index and ev.type and attr.key:
                yield f"{ev.type}.{attr.key}", attr.value


def _exec_result_proto(r: abci.ExecTxResult) -> dict:
    d: dict = {}
    if r.code:
        d["code"] = r.code
    if r.data:
        d["data"] = r.data
    if r.log:
        d["log"] = r.log
    if r.gas_wanted:
        d["gas_wanted"] = r.gas_wanted
    if r.gas_used:
        d["gas_used"] = r.gas_used
    if r.events:
        d["events"] = [{
            **({"type": e.type} if e.type else {}),
            "attributes": [
                {**({"key": a.key} if a.key else {}),
                 **({"value": a.value} if a.value else {}),
                 **({"index": True} if a.index else {})}
                for a in e.attributes]} for e in r.events]
    if r.codespace:
        d["codespace"] = r.codespace
    return d


def _exec_result_from_proto(d: dict) -> abci.ExecTxResult:
    return abci.ExecTxResult(
        code=d.get("code", 0), data=d.get("data", b""),
        log=d.get("log", ""),
        gas_wanted=d.get("gas_wanted", 0),
        gas_used=d.get("gas_used", 0),
        events=[abci.Event(
            type=e.get("type", ""),
            attributes=[abci.EventAttribute(
                key=a.get("key", ""), value=a.get("value", ""),
                index=a.get("index", False))
                for a in e.get("attributes", [])])
            for e in d.get("events", [])],
        codespace=d.get("codespace", ""))
