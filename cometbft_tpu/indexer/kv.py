"""KV tx/block indexers.

Reference: state/txindex/kv/kv.go (TxIndexer over events with the
pubsub query language) and state/indexer/block/kv (block events).
Records: tx hash → TxResult proto; composite event key
(type.attr/value/height/index) → tx hash or block height.
"""
from __future__ import annotations

import struct
from typing import Optional

from ..abci import types as abci
from ..db import DB
from ..libs.pubsub import Query
from ..wire import abci_pb, encode, decode

_TX_RESULT = b"tx/"
_TX_EVENT = b"te/"
_BLOCK_EVENT = b"be/"
_BLOCK_HEIGHT_KEY = "block.height"
_TX_HEIGHT_KEY = "tx.height"
_TX_HASH_KEY = "tx.hash"


def _event_key(prefix: bytes, composite: str, value: str,
               height: int, tie: bytes) -> bytes:
    return (prefix + composite.encode() + b"\x00" + value.encode() +
            b"\x00" + struct.pack(">q", height) + b"\x00" + tie)


class TxIndexer:
    """Reference: state/txindex/indexer.go:24 TxIndexer interface."""

    def __init__(self, db: DB):
        self._db = db

    def index(self, tx_result: abci.TxResult) -> None:
        from ..types.tx import tx_hash
        h = tx_hash(tx_result.tx)
        raw = encode(abci_pb.TX_RESULT, {
            **({"height": tx_result.height}
               if tx_result.height else {}),
            **({"index": tx_result.index} if tx_result.index else {}),
            **({"tx": tx_result.tx} if tx_result.tx else {}),
            "result": _exec_result_proto(tx_result.result),
        })
        batch = self._db.new_batch()
        batch.set(_TX_RESULT + h, raw)
        # implicit tx.height/tx.hash attributes + app events
        for composite, value in _iter_event_attrs(
                tx_result.result.events):
            batch.set(_event_key(_TX_EVENT, composite, value,
                                 tx_result.height, h), h)
        batch.set(_event_key(_TX_EVENT, _TX_HEIGHT_KEY,
                             str(tx_result.height), tx_result.height,
                             h), h)
        batch.write()

    def get(self, tx_hash_: bytes) -> Optional[abci.TxResult]:
        raw = self._db.get(_TX_RESULT + tx_hash_)
        if raw is None:
            return None
        d = decode(abci_pb.TX_RESULT, raw)
        return abci.TxResult(
            height=d.get("height", 0), index=d.get("index", 0),
            tx=d.get("tx", b""),
            result=_exec_result_from_proto(d.get("result") or {}))

    def search(self, query: Query, limit: int = 100) -> list[bytes]:
        """Tx hashes whose indexed events satisfy the query (AND of
        conditions, like the reference's kv search)."""
        result: Optional[set[bytes]] = None
        for cond in query.conditions:
            matches = set()
            prefix = _TX_EVENT + cond.key.encode() + b"\x00"
            for k, v in self._db.iterator(prefix,
                                          prefix + b"\xff" * 64):
                rest = k[len(prefix):]
                value = rest.split(b"\x00", 1)[0].decode(
                    errors="replace")
                if cond.matches_value(value):
                    matches.add(v)
            result = matches if result is None else result & matches
            if not result:
                return []
        return list(result or [])[:limit]


class BlockIndexer:
    """Reference: state/indexer/block/kv."""

    def __init__(self, db: DB):
        self._db = db

    def index(self, height: int, events: list) -> None:
        batch = self._db.new_batch()
        tie = struct.pack(">q", height)
        batch.set(_event_key(_BLOCK_EVENT, _BLOCK_HEIGHT_KEY,
                             str(height), height, tie), tie)
        for composite, value in _iter_event_attrs(events):
            batch.set(_event_key(_BLOCK_EVENT, composite, value,
                                 height, tie), tie)
        batch.write()

    def search(self, query: Query, limit: int = 100) -> list[int]:
        result: Optional[set[int]] = None
        for cond in query.conditions:
            matches = set()
            prefix = _BLOCK_EVENT + cond.key.encode() + b"\x00"
            for k, v in self._db.iterator(prefix,
                                          prefix + b"\xff" * 64):
                rest = k[len(prefix):]
                value = rest.split(b"\x00", 1)[0].decode(
                    errors="replace")
                if cond.matches_value(value):
                    matches.add(struct.unpack(">q", v)[0])
            result = matches if result is None else result & matches
            if not result:
                return []
        return sorted(result or [])[:limit]


def _iter_event_attrs(events):
    for ev in events or []:
        for attr in ev.attributes:
            if attr.index and ev.type and attr.key:
                yield f"{ev.type}.{attr.key}", attr.value


def _exec_result_proto(r: abci.ExecTxResult) -> dict:
    d: dict = {}
    if r.code:
        d["code"] = r.code
    if r.data:
        d["data"] = r.data
    if r.log:
        d["log"] = r.log
    if r.gas_wanted:
        d["gas_wanted"] = r.gas_wanted
    if r.gas_used:
        d["gas_used"] = r.gas_used
    if r.events:
        d["events"] = [{
            **({"type": e.type} if e.type else {}),
            "attributes": [
                {**({"key": a.key} if a.key else {}),
                 **({"value": a.value} if a.value else {}),
                 **({"index": True} if a.index else {})}
                for a in e.attributes]} for e in r.events]
    if r.codespace:
        d["codespace"] = r.codespace
    return d


def _exec_result_from_proto(d: dict) -> abci.ExecTxResult:
    return abci.ExecTxResult(
        code=d.get("code", 0), data=d.get("data", b""),
        log=d.get("log", ""),
        gas_wanted=d.get("gas_wanted", 0),
        gas_used=d.get("gas_used", 0),
        events=[abci.Event(
            type=e.get("type", ""),
            attributes=[abci.EventAttribute(
                key=a.get("key", ""), value=a.get("value", ""),
                index=a.get("index", False))
                for a in e.get("attributes", [])])
            for e in d.get("events", [])],
        codespace=d.get("codespace", ""))
