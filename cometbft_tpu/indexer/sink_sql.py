"""SQL event sink.

Reference: state/indexer/sink/psql (psql.go + schema.sql) — an
operator-queryable relational mirror of block/tx events.  The sink
speaks BOTH targets with the same relational schema (blocks,
tx_results, events, attributes + the joined views):

- `tx_index.psql_conn = <path|:memory:>` — the embedded SQLite
  engine (no external database needed);
- `tx_index.psql_conn = postgres://user:pw@host/db` — a real
  PostgreSQL server via psycopg2 (gated: a clear error is raised
  when the driver isn't installed; this image ships without it).

Operator SQL written for the reference's views runs unchanged.  Like
the reference sink, it is write-only from the node's perspective:
tx_search/block_search RPCs are NOT served from this sink (psql.go
returns "not supported" for reads) — operators query the database
directly.
"""
from __future__ import annotations

import sqlite3
from datetime import datetime, timezone
from typing import Optional

from ..abci import types as abci
from ..wire import abci_pb, encode

_SCHEMA = """
CREATE TABLE IF NOT EXISTS blocks (
  rowid      INTEGER PRIMARY KEY AUTOINCREMENT,
  height     BIGINT NOT NULL,
  chain_id   VARCHAR NOT NULL,
  created_at TIMESTAMPTZ NOT NULL,
  UNIQUE (height, chain_id)
);
CREATE INDEX IF NOT EXISTS idx_blocks_height_chain
  ON blocks(height, chain_id);
CREATE TABLE IF NOT EXISTS tx_results (
  rowid      INTEGER PRIMARY KEY AUTOINCREMENT,
  block_id   BIGINT NOT NULL REFERENCES blocks(rowid),
  "index"    INTEGER NOT NULL,
  created_at TIMESTAMPTZ NOT NULL,
  tx_hash    VARCHAR NOT NULL,
  tx_result  BLOB NOT NULL,
  UNIQUE (block_id, "index")
);
CREATE TABLE IF NOT EXISTS events (
  rowid    INTEGER PRIMARY KEY AUTOINCREMENT,
  block_id BIGINT NOT NULL REFERENCES blocks(rowid),
  tx_id    BIGINT NULL REFERENCES tx_results(rowid),
  type     VARCHAR NOT NULL
);
CREATE TABLE IF NOT EXISTS attributes (
  event_id      BIGINT NOT NULL REFERENCES events(rowid),
  key           VARCHAR NOT NULL,
  composite_key VARCHAR NOT NULL,
  value         VARCHAR NULL,
  UNIQUE (event_id, key)
);
CREATE VIEW IF NOT EXISTS event_attributes AS
  SELECT block_id, tx_id, type, key, composite_key, value
  FROM events LEFT JOIN attributes
    ON (events.rowid = attributes.event_id);
CREATE VIEW IF NOT EXISTS block_events AS
  SELECT blocks.rowid as block_id, height, chain_id, type, key,
         composite_key, value
  FROM blocks JOIN event_attributes
    ON (blocks.rowid = event_attributes.block_id)
  WHERE event_attributes.tx_id IS NULL;
CREATE VIEW IF NOT EXISTS tx_events AS
  SELECT height, "index", chain_id, type, key, composite_key, value,
         tx_results.created_at
  FROM blocks JOIN tx_results ON (blocks.rowid = tx_results.block_id)
  JOIN event_attributes ON
    (tx_results.rowid = event_attributes.tx_id)
  WHERE event_attributes.tx_id IS NOT NULL;
"""


def _psql_schema() -> str:
    """The same schema in PostgreSQL dialect (reference: schema.sql —
    BIGSERIAL keys, BYTEA blobs; rowid is an explicit column in both
    dialects, so every query below runs unchanged)."""
    s = _SCHEMA.replace("INTEGER PRIMARY KEY AUTOINCREMENT",
                        "BIGSERIAL PRIMARY KEY")
    s = s.replace("BLOB", "BYTEA")
    return s.replace("CREATE VIEW IF NOT EXISTS",
                     "CREATE OR REPLACE VIEW")


class _Cursor:
    """Driver-adapting cursor: rewrites the module's ?-placeholder
    SQL to the target's paramstyle at the single choke point."""

    def __init__(self, cur, ph: str):
        self._cur = cur
        self._ph = ph

    def execute(self, sql, params=()):
        if self._ph != "?":
            sql = sql.replace("?", self._ph)
        return self._cur.execute(sql, params)

    def insert_returning(self, sql, params=()):
        """INSERT and return the new rowid.  psycopg2's lastrowid is
        the table OID (0 for ordinary tables), so the %s dialect uses
        INSERT ... RETURNING rowid instead."""
        if self._ph == "?":
            self._cur.execute(sql, params)
            return self._cur.lastrowid
        self._cur.execute(
            sql.replace("?", self._ph) + " RETURNING rowid", params)
        return self._cur.fetchone()[0]

    def __getattr__(self, name):
        return getattr(self._cur, name)


class SQLEventSink:
    """Write-side event sink with the reference's psql schema."""

    def __init__(self, conn_str: str, chain_id: str):
        # conn_str: a PostgreSQL DSN (postgres://...) or a filesystem
        # path / :memory: for the embedded engine
        if conn_str.startswith(("postgres://", "postgresql://")):
            try:
                import psycopg2
            except ImportError:
                raise RuntimeError(
                    "tx_index.psql_conn is a PostgreSQL DSN but "
                    "psycopg2 is not installed — install it or "
                    "point psql_conn at an embedded database path")
            self._conn = psycopg2.connect(conn_str)
            self._ph = "%s"
            cur = self._conn.cursor()
            for stmt in _psql_schema().split(";"):
                if stmt.strip():
                    cur.execute(stmt)
        else:
            self._conn = sqlite3.connect(conn_str,
                                         check_same_thread=False)
            self._ph = "?"
            self._conn.executescript(_SCHEMA)
        self._conn.commit()
        self.chain_id = chain_id

    def _cursor(self) -> _Cursor:
        return _Cursor(self._conn.cursor(), self._ph)

    def close(self) -> None:
        self._conn.close()

    # -- write side --------------------------------------------------------
    def _rollback(self) -> None:
        try:
            self._conn.rollback()
        except Exception:
            pass

    def index_block_events(self, height: int, events: list) -> None:
        """Reference: psql.go IndexBlockEvents — insert the block row
        plus its begin/end-block-style events."""
        try:
            self._index_block_events(height, events)
        except Exception:
            self._rollback()
            raise

    def _index_block_events(self, height: int, events: list) -> None:
        now = datetime.now(timezone.utc).isoformat()
        cur = self._cursor()
        cur.execute(
            "INSERT INTO blocks (height, chain_id, created_at) "
            "VALUES (?, ?, ?) "
            "ON CONFLICT (height, chain_id) DO UPDATE SET "
            "created_at = excluded.created_at",
            (height, self.chain_id, now))
        cur.execute(
            "SELECT rowid FROM blocks WHERE height = ? AND "
            "chain_id = ?", (height, self.chain_id))
        block_rowid = cur.fetchone()[0]
        # re-indexing the same height must replace, not duplicate
        self._delete_events(cur, block_rowid, tx_events=False)
        # the reference also records the implicit block.height event
        self._insert_events(cur, block_rowid, None, [
            abci.Event(type="block", attributes=[
                abci.EventAttribute(key="height", value=str(height),
                                    index=True)])] + list(events or []))
        self._conn.commit()

    def index_tx_events(self, tx_results: list) -> None:
        try:
            self._index_tx_events(tx_results)
        except Exception:
            self._rollback()
            raise

    def _index_tx_events(self, tx_results: list) -> None:
        """Reference: psql.go IndexTxEvents — insert tx_results rows
        and their events (the TxResult proto bytes are stored for
        round-tripping)."""
        from ..types.tx import tx_hash
        now = datetime.now(timezone.utc).isoformat()
        cur = self._cursor()
        for txr in tx_results:
            cur.execute(
                "SELECT rowid FROM blocks WHERE height = ? AND "
                "chain_id = ?", (txr.height, self.chain_id))
            row = cur.fetchone()
            if row is None:
                block_rowid = cur.insert_returning(
                    "INSERT INTO blocks (height, chain_id, created_at)"
                    " VALUES (?, ?, ?)",
                    (txr.height, self.chain_id, now))
            else:
                block_rowid = row[0]
            raw = encode(abci_pb.TX_RESULT, {
                **({"height": txr.height} if txr.height else {}),
                **({"index": txr.index} if txr.index else {}),
                **({"tx": txr.tx} if txr.tx else {}),
                "result": _exec_result_proto(txr.result),
            })
            cur.execute(
                "INSERT INTO tx_results "
                "(block_id, \"index\", created_at, tx_hash, tx_result)"
                " VALUES (?, ?, ?, ?, ?) "
                "ON CONFLICT (block_id, \"index\") DO UPDATE SET "
                "tx_result = excluded.tx_result",
                (block_rowid, txr.index, now,
                 tx_hash(txr.tx).hex().upper(), raw))
            cur.execute(
                "SELECT rowid FROM tx_results WHERE block_id = ? AND "
                "\"index\" = ?", (block_rowid, txr.index))
            tx_rowid = cur.fetchone()[0]
            # replace any events from an earlier delivery of this tx
            cur.execute(
                "DELETE FROM attributes WHERE event_id IN "
                "(SELECT rowid FROM events WHERE tx_id = ?)",
                (tx_rowid,))
            cur.execute("DELETE FROM events WHERE tx_id = ?",
                        (tx_rowid,))
            implicit = [
                abci.Event(type="tx", attributes=[
                    abci.EventAttribute(
                        key="hash",
                        value=tx_hash(txr.tx).hex().upper(),
                        index=True)]),
                abci.Event(type="tx", attributes=[
                    abci.EventAttribute(key="height",
                                        value=str(txr.height),
                                        index=True)]),
            ]
            self._insert_events(cur, block_rowid, tx_rowid,
                                implicit + list(txr.result.events or []))
        self._conn.commit()

    def _delete_events(self, cur, block_id: int,
                       tx_events: bool) -> None:
        cond = "IS NOT NULL" if tx_events else "IS NULL"
        cur.execute(
            "DELETE FROM attributes WHERE event_id IN "
            f"(SELECT rowid FROM events WHERE block_id = ? "
            f" AND tx_id {cond})", (block_id,))
        cur.execute(
            f"DELETE FROM events WHERE block_id = ? AND "
            f"tx_id {cond}", (block_id,))

    def _insert_events(self, cur, block_id: int, tx_id: Optional[int],
                       events: list) -> None:
        for ev in events:
            if not ev.type:
                continue
            event_id = cur.insert_returning(
                "INSERT INTO events (block_id, tx_id, type) "
                "VALUES (?, ?, ?)", (block_id, tx_id, ev.type))
            for attr in ev.attributes or []:
                if not attr.key:
                    continue
                cur.execute(
                    "INSERT INTO attributes "
                    "(event_id, key, composite_key, value) "
                    "VALUES (?, ?, ?, ?) "
                    "ON CONFLICT (event_id, key) DO UPDATE SET "
                    "value = excluded.value",
                    (event_id, attr.key, f"{ev.type}.{attr.key}",
                     attr.value))

    # -- adapters so IndexerService can drive the sink ---------------------
    @property
    def tx_indexer(self) -> "_SinkTxAdapter":
        return _SinkTxAdapter(self)

    @property
    def block_indexer(self) -> "_SinkBlockAdapter":
        return _SinkBlockAdapter(self)


class _SinkTxAdapter:
    def __init__(self, sink: SQLEventSink):
        self._sink = sink

    def index(self, tx_result) -> None:
        self._sink.index_tx_events([tx_result])

    def get(self, tx_hash_: bytes):
        return None         # reads unsupported (reference psql.go)

    def search(self, query, limit: int = 100) -> list:
        raise NotImplementedError(
            "the SQL sink does not serve searches; query the "
            "database directly (reference: psql sink)")

    def prune(self, from_height: int, to_height: int) -> int:
        cur = self._sink._cursor()
        cur.execute(
            "DELETE FROM attributes WHERE event_id IN "
            "(SELECT events.rowid FROM events JOIN blocks "
            " ON events.block_id = blocks.rowid "
            " WHERE blocks.height >= ? AND blocks.height < ? "
            " AND events.tx_id IS NOT NULL)",
            (from_height, to_height))
        cur.execute(
            "DELETE FROM events WHERE tx_id IS NOT NULL AND "
            "block_id IN (SELECT rowid FROM blocks WHERE "
            "height >= ? AND height < ?)",
            (from_height, to_height))
        cur.execute(
            "DELETE FROM tx_results WHERE block_id IN "
            "(SELECT rowid FROM blocks WHERE height >= ? AND "
            "height < ?)", (from_height, to_height))
        n = cur.rowcount
        self._sink._conn.commit()
        return max(n, 0)


class _SinkBlockAdapter:
    def __init__(self, sink: SQLEventSink):
        self._sink = sink

    def index(self, height: int, events: list) -> None:
        self._sink.index_block_events(height, events)

    def search(self, query, limit: int = 100) -> list:
        raise NotImplementedError(
            "the SQL sink does not serve searches; query the "
            "database directly (reference: psql sink)")

    def prune(self, from_height: int, to_height: int) -> int:
        cur = self._sink._cursor()
        cur.execute(
            "DELETE FROM attributes WHERE event_id IN "
            "(SELECT events.rowid FROM events JOIN blocks "
            " ON events.block_id = blocks.rowid "
            " WHERE blocks.height >= ? AND blocks.height < ? "
            " AND events.tx_id IS NULL)",
            (from_height, to_height))
        cur.execute(
            "DELETE FROM events WHERE tx_id IS NULL AND block_id IN "
            "(SELECT rowid FROM blocks WHERE height >= ? AND "
            "height < ?)", (from_height, to_height))
        n = cur.rowcount
        self._sink._conn.commit()
        return max(n, 0)


def _exec_result_proto(r) -> dict:
    from .kv import _exec_result_proto as impl
    return impl(r)
