"""IndexerService: subscribes to the event bus and feeds the indexers.

Reference: state/txindex/indexer_service.go.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from ..abci import types as abci
from ..libs.log import Logger, new_logger
from ..types import events as ev_types
from .kv import BlockIndexer, TxIndexer

_SUBSCRIBER = "indexer-service"


class IndexerService:
    def __init__(self, tx_indexer: TxIndexer,
                 block_indexer: BlockIndexer, event_bus,
                 logger: Optional[Logger] = None):
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.event_bus = event_bus
        self.logger = logger if logger is not None else \
            new_logger("txindex")
        self._tasks: list[asyncio.Task] = []

    async def start(self) -> None:
        tx_sub = self.event_bus.subscribe(
            _SUBSCRIBER, ev_types.EVENT_QUERY_TX, out_capacity=1000)
        block_sub = self.event_bus.subscribe(
            _SUBSCRIBER, ev_types.EVENT_QUERY_NEW_BLOCK_EVENTS,
            out_capacity=100)
        loop = asyncio.get_running_loop()
        self._tasks = [loop.create_task(self._tx_routine(tx_sub)),
                       loop.create_task(self._block_routine(block_sub))]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        try:
            self.event_bus.unsubscribe_all(_SUBSCRIBER)
        except Exception:
            pass

    async def _tx_routine(self, sub) -> None:
        try:
            while True:
                msg = await sub.next()
                p = msg.data.payload
                self.tx_indexer.index(abci.TxResult(
                    height=p["height"], index=p["index"],
                    tx=p["tx"], result=p["result"]))
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self.logger.error("tx indexing stopped", err=str(e))

    async def _block_routine(self, sub) -> None:
        try:
            while True:
                msg = await sub.next()
                p = msg.data.payload
                self.block_indexer.index(p["height"],
                                         p.get("events", []))
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self.logger.error("block indexing stopped", err=str(e))
