"""Tx and block event indexing for RPC search queries."""
from .kv import BlockIndexer, TxIndexer
from .service import IndexerService
from .sink_sql import SQLEventSink

__all__ = ["BlockIndexer", "TxIndexer", "IndexerService",
           "SQLEventSink"]
