"""MConnection: multiplexed prioritized streams over one connection.

Reference: p2p/transport/tcp/conn/connection.go:68 — per-channel send
queues, priority-weighted least-ratio scheduling, 1024-byte packet
payloads, ping/pong keepalive, flow control.  Packets here ride the
SecretConnection's message frames; the scheduler picks the channel with
the lowest sent-bytes/priority ratio, exactly the reference's
least-ratio rule.
"""
from __future__ import annotations

import asyncio
import struct
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional

from ..libs import tracing
from ..libs.flowrate import RateLimiter
from ..libs.log import Logger, new_logger

MAX_PACKET_PAYLOAD_SIZE = 1024
_PING_INTERVAL_S = 60.0
_PONG_TIMEOUT_S = 45.0

# packet types
_PKT_PING = 0x01
_PKT_PONG = 0x02
_PKT_MSG = 0x03


class MConnectionError(Exception):
    pass


@dataclass
class ChannelDescriptor:
    """Reference: conn.ChannelDescriptor."""
    id: int
    priority: int = 1
    send_queue_capacity: int = 100
    recv_message_capacity: int = 22 * 1024 * 1024


class _Channel:
    def __init__(self, desc: ChannelDescriptor):
        self.desc = desc
        self.send_queue: asyncio.Queue[bytes] = asyncio.Queue(
            desc.send_queue_capacity)
        self.sending: bytes = b""
        self.sent_pos = 0
        self.recv_buffer = bytearray()
        self.recently_sent = 0   # for least-ratio scheduling
        self.last_msg_len = 0    # size of the last fully-sent message

    def is_send_pending(self) -> bool:
        return bool(self.sending) or not self.send_queue.empty()

    def next_packet(self) -> tuple[bytes, bool]:
        """(payload, eof) for the next packet of the current message."""
        if not self.sending:
            self.sending = self.send_queue.get_nowait()
            self.sent_pos = 0
        chunk = self.sending[self.sent_pos:
                             self.sent_pos + MAX_PACKET_PAYLOAD_SIZE]
        self.sent_pos += len(chunk)
        eof = self.sent_pos >= len(self.sending)
        if eof:
            self.last_msg_len = self.sent_pos
            self.sending = b""
            self.sent_pos = 0
        self.recently_sent += len(chunk)
        return chunk, eof

    def recv_packet(self, payload: bytes, eof: bool,
                    max_size: int) -> Optional[bytes]:
        self.recv_buffer += payload
        if len(self.recv_buffer) > max_size:
            raise MConnectionError(
                f"recv message exceeds {max_size} bytes on channel "
                f"{self.desc.id}")
        if eof:
            msg = bytes(self.recv_buffer)
            self.recv_buffer.clear()
            return msg
        return None


class MConnection:
    """on_receive(channel_id, msg_bytes) is awaited for every complete
    message; on_error(exc) fires once when the connection dies."""

    def __init__(self, sconn, channels: list[ChannelDescriptor],
                 on_receive: Callable[[int, bytes], Awaitable[None]],
                 on_error: Callable[[Exception], None],
                 logger: Optional[Logger] = None,
                 send_rate: float = 5_120_000,
                 recv_rate: float = 5_120_000,
                 metrics=None, peer_id: str = ""):
        if metrics is None:
            from .metrics import Metrics
            metrics = Metrics()
        self.metrics = metrics
        self.peer_id = peer_id or "unknown"
        self._pending_bytes = 0
        self._sconn = sconn
        self._channels = {d.id: _Channel(d) for d in channels}
        for d in channels:
            self.metrics.touch_channel(f"{d.id:#x}")
        self._on_receive = on_receive
        self._on_error = on_error
        # token-bucket flow control, 5 MB/s defaults (reference:
        # internal/flowrate via connection.go sendSomePacketMsgs /
        # recvRoutine; config p2p.send_rate/recv_rate)
        self.send_limiter = RateLimiter(send_rate)
        self.recv_limiter = RateLimiter(recv_rate)
        self.logger = logger if logger is not None else \
            new_logger("mconn")
        self._send_event = asyncio.Event()
        self._tasks: list[asyncio.Task] = []
        self._closed = False
        self._last_recv = 0.0

    def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._last_recv = loop.time()
        self._tasks = [
            loop.create_task(self._send_routine()),
            loop.create_task(self._recv_routine()),
            loop.create_task(self._ping_routine()),
        ]

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for t in self._tasks:
            t.cancel()
        self._sconn.close()

    # ------------------------------------------------------------------
    def send(self, channel_id: int, msg: bytes) -> bool:
        """Queue a message; False when the channel queue is full
        (reference: Peer.TrySend semantics)."""
        ch = self._channels.get(channel_id)
        if ch is None or self._closed:
            return False
        try:
            ch.send_queue.put_nowait(msg)
        except asyncio.QueueFull:
            # the canonical gossip stall: TrySend dropped on a full
            # per-channel queue — flight-recorded so /trace shows
            # which peer/channel backpressured a height
            tracing.instant(tracing.P2P, "send_queue_full",
                            chan=channel_id, peer=self.peer_id[:12])
            self.metrics.send_queue_drops.with_labels(
                f"{channel_id:#x}").add()
            return False
        self._pending_bytes += len(msg)
        self.metrics.peer_pending_send_bytes.with_labels(
            self.peer_id).set(self._pending_bytes)
        self._send_event.set()
        return True

    async def send_blocking(self, channel_id: int, msg: bytes) -> bool:
        ch = self._channels.get(channel_id)
        if ch is None or self._closed:
            return False
        if ch.send_queue.full():
            # the queue-stall distribution: how long a blocking send
            # waited for queue space on this channel
            _t0 = asyncio.get_running_loop().time()
            await ch.send_queue.put(msg)
            self.metrics.queue_stall_seconds.with_labels(
                f"{channel_id:#x}").observe(
                asyncio.get_running_loop().time() - _t0)
        else:
            await ch.send_queue.put(msg)
        self._pending_bytes += len(msg)
        self.metrics.peer_pending_send_bytes.with_labels(
            self.peer_id).set(self._pending_bytes)
        self._send_event.set()
        return True

    # ------------------------------------------------------------------
    def _pick_channel(self) -> Optional[_Channel]:
        """Least sent-bytes/priority ratio wins (reference:
        sendPacketMsg)."""
        best, best_ratio = None, None
        for ch in self._channels.values():
            if not ch.is_send_pending():
                continue
            ratio = ch.recently_sent / max(1, ch.desc.priority)
            if best_ratio is None or ratio < best_ratio:
                best, best_ratio = ch, ratio
        return best

    async def _send_routine(self) -> None:
        try:
            while not self._closed:
                ch = self._pick_channel()
                if ch is None:
                    self._send_event.clear()
                    await self._send_event.wait()
                    continue
                payload, eof = ch.next_packet()
                pkt = bytes([_PKT_MSG, ch.desc.id,
                             1 if eof else 0]) + payload
                _t0 = asyncio.get_running_loop().time()
                await self.send_limiter.take(len(pkt))
                _dt = asyncio.get_running_loop().time() - _t0
                if _dt > 0:
                    self.metrics.send_rate_limiter_delay.with_labels(
                        self.peer_id).add(_dt)
                    self.metrics.queue_stall_seconds.with_labels(
                        f"{ch.desc.id:#x}").observe(_dt)
                    tracing.instant(tracing.P2P, "send_rate_stall",
                                    chan=ch.desc.id,
                                    peer=self.peer_id[:12],
                                    stall_ms=round(_dt * 1e3, 3))
                await self._sconn.write_msg(pkt)
                if eof:
                    # one event per complete message, not per packet
                    tracing.instant(tracing.P2P, "send",
                                    chan=ch.desc.id,
                                    peer=self.peer_id[:12],
                                    bytes=ch.last_msg_len)
                    self.metrics.message_send_size_bytes.with_labels(
                        f"{ch.desc.id:#x}").observe(ch.last_msg_len)
                self.metrics.message_send_bytes_total.with_labels(
                    f"{ch.desc.id:#x}").add(len(pkt))
                self._pending_bytes = max(
                    0, self._pending_bytes - len(payload))
                self.metrics.peer_pending_send_bytes.with_labels(
                    self.peer_id).set(self._pending_bytes)
                # decay the ratio counters periodically
                if ch.recently_sent > 1 << 20:
                    for c in self._channels.values():
                        c.recently_sent //= 2
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._fail(e)

    async def _recv_routine(self) -> None:
        try:
            while not self._closed:
                msg = await self._sconn.read_msg()
                _t0 = asyncio.get_running_loop().time()
                await self.recv_limiter.take(len(msg))
                _dt = asyncio.get_running_loop().time() - _t0
                if _dt > 0:
                    self.metrics.recv_rate_limiter_delay.with_labels(
                        self.peer_id).add(_dt)
                self._last_recv = asyncio.get_running_loop().time()
                if len(msg) >= 2 and msg[0] == _PKT_MSG:
                    self.metrics.message_receive_bytes_total \
                        .with_labels(f"{msg[1]:#x}").add(len(msg))
                if not msg:
                    raise MConnectionError("empty packet")
                ptype = msg[0]
                if ptype == _PKT_PING:
                    # reply immediately — write_msg buffers whole
                    # frames synchronously, so it interleaves safely
                    # with the send routine at frame granularity
                    await self._sconn.write_msg(bytes([_PKT_PONG]))
                elif ptype == _PKT_PONG:
                    pass
                elif ptype == _PKT_MSG:
                    if len(msg) < 3:
                        raise MConnectionError("short msg packet")
                    chan_id, eof = msg[1], bool(msg[2])
                    ch = self._channels.get(chan_id)
                    if ch is None:
                        raise MConnectionError(
                            f"unknown channel {chan_id:#x}")
                    complete = ch.recv_packet(
                        msg[3:], eof, ch.desc.recv_message_capacity)
                    if complete is not None:
                        tracing.instant(tracing.P2P, "recv",
                                        chan=chan_id,
                                        peer=self.peer_id[:12],
                                        bytes=len(complete))
                        self.metrics.message_recv_size_bytes \
                            .with_labels(f"{chan_id:#x}").observe(
                                len(complete))
                        await self._on_receive(chan_id, complete)
                else:
                    raise MConnectionError(
                        f"unknown packet type {ptype:#x}")
        except asyncio.CancelledError:
            raise
        except (asyncio.IncompleteReadError, ConnectionError, OSError,
                Exception) as e:
            self._fail(e)

    async def _ping_routine(self) -> None:
        """Keepalive + dead-link detection: if nothing at all has been
        received for a ping interval plus the pong timeout, the link is
        declared dead (reference: pongTimeout teardown)."""
        try:
            while not self._closed:
                await asyncio.sleep(_PING_INTERVAL_S)
                await self._sconn.write_msg(bytes([_PKT_PING]))
                now = asyncio.get_running_loop().time()
                if now - self._last_recv > \
                        _PING_INTERVAL_S + _PONG_TIMEOUT_S:
                    raise MConnectionError(
                        "pong timeout: connection is dead")
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._fail(e)

    def _fail(self, e: Exception) -> None:
        if self._closed:
            return
        self.close()
        try:
            self._on_error(e)
        except Exception:
            self.logger.error("on_error callback raised while "
                              "handling connection failure",
                              peer=self.peer_id, exc_info=True)
