"""Switch: peer lifecycle + reactor registry + broadcast.

Reference: p2p/switch.go (:867) — reactors claim channels, dial/accept
loops produce authenticated peers, Receive routes inbound messages to
the owning reactor, StopPeerForError tears down; p2p/peer.go — the
per-peer service wrapping an MConnection.
"""
from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from typing import Optional

from .. import version as _version
from ..libs import tracing
from ..libs.log import Logger, new_logger
from ..libs.supervisor import Supervisor
from .conn import ChannelDescriptor, MConnection
from .key import NodeKey, node_id_from_pub_key
from .secret_connection import SecretConnection


class SwitchError(Exception):
    pass


@dataclass
class NodeInfo:
    """Identity + capability advertisement exchanged at handshake.

    Reference: p2p/internal/nodeinfo/nodeinfo.go."""
    node_id: str = ""
    listen_addr: str = ""
    network: str = ""          # chain id
    version: str = _version.CMT_SEM_VER
    channels: bytes = b""
    moniker: str = "anonymous"
    block_version: int = _version.BLOCK_PROTOCOL
    p2p_version: int = _version.P2P_PROTOCOL
    # optional protocol capabilities (e.g. "txrecon/1",
    # "compactblocks/1", "votebatch/1"): purely additive negotiation —
    # a capability is USED on a link only when both sides advertise
    # it, and a peer that sends none (an older build) gets the
    # pre-capability wire behavior (flood gossip, full block parts,
    # single-vote messages).  Never part of compatible_with.
    features: tuple = ()

    def to_json(self) -> bytes:
        return json.dumps({
            "node_id": self.node_id, "listen_addr": self.listen_addr,
            "network": self.network, "version": self.version,
            "channels": self.channels.hex(), "moniker": self.moniker,
            "block_version": self.block_version,
            "p2p_version": self.p2p_version,
            "features": list(self.features),
        }).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "NodeInfo":
        d = json.loads(raw)
        return cls(node_id=d.get("node_id", ""),
                   listen_addr=d.get("listen_addr", ""),
                   network=d.get("network", ""),
                   version=d.get("version", ""),
                   channels=bytes.fromhex(d.get("channels", "")),
                   moniker=d.get("moniker", ""),
                   block_version=d.get("block_version", 0),
                   p2p_version=d.get("p2p_version", 0),
                   features=tuple(d.get("features", ())))

    def compatible_with(self, other: "NodeInfo") -> Optional[str]:
        """None when compatible, else the reason (reference:
        nodeinfo CompatibleWith)."""
        if self.block_version != other.block_version:
            return (f"peer block version {other.block_version} != "
                    f"{self.block_version}")
        if self.network != other.network:
            return f"peer network {other.network!r} != {self.network!r}"
        if not set(self.channels) & set(other.channels):
            return "no common channels"
        return None


class Peer:
    """Reference: p2p/peer.go — wraps the MConnection for one peer."""

    def __init__(self, node_info: NodeInfo, mconn: MConnection,
                 outbound: bool, remote_addr: str):
        self.node_info = node_info
        self.mconn = mconn
        self.outbound = outbound
        self.remote_addr = remote_addr
        self.data: dict = {}   # reactor-attached state (e.g. PeerState)

    @property
    def id(self) -> str:
        return self.node_info.node_id

    def has_feature(self, name: str) -> bool:
        """Did the peer advertise this capability at handshake?"""
        return name in self.node_info.features

    def send(self, channel_id: int, msg: bytes) -> bool:
        return self.mconn.send(channel_id, msg)

    async def send_blocking(self, channel_id: int, msg: bytes) -> bool:
        return await self.mconn.send_blocking(channel_id, msg)

    def close(self) -> None:
        self.mconn.close()

    def __repr__(self) -> str:
        return f"Peer{{{self.id[:12]} {self.remote_addr}}}"


class Reactor:
    """Reference: p2p/base_reactor.go:15."""

    def __init__(self, name: str):
        self.name = name
        self.switch: Optional["Switch"] = None
        self.logger = new_logger(name.lower())
        self._own_supervisor: Optional[Supervisor] = None

    @property
    def supervisor(self) -> Supervisor:
        """Every reactor background loop is supervisor-owned: a crash
        restarts the loop (with metrics) instead of silently killing
        it.  Reactors attached to a switch share its supervisor;
        standalone reactors (tests) lazily get a private one."""
        if self.switch is not None:
            return self.switch.supervisor
        if self._own_supervisor is None:
            self._own_supervisor = Supervisor(self.name.lower(),
                                              logger=self.logger)
        return self._own_supervisor

    def get_channels(self) -> list[ChannelDescriptor]:
        return []

    def get_features(self) -> list[str]:
        """Capability strings this reactor wants advertised in the
        handshake NodeInfo (config-gated; see NodeInfo.features)."""
        return []

    async def add_peer(self, peer: Peer) -> None:
        pass

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        pass

    async def receive(self, chan_id: int, peer: Peer,
                      msg_bytes: bytes) -> None:
        pass


class Switch:
    def __init__(self, node_key: NodeKey, network: str,
                 listen_addr: str = "",
                 moniker: str = "anonymous",
                 logger: Optional[Logger] = None,
                 send_rate: float = 5_120_000,
                 recv_rate: float = 5_120_000,
                 metrics=None,
                 supervisor_metrics=None):
        self.node_key = node_key
        self.network = network
        self.listen_addr = listen_addr
        self.moniker = moniker
        self.send_rate = send_rate
        self.recv_rate = recv_rate
        self.logger = logger if logger is not None else \
            new_logger("p2p")
        if metrics is None:
            from .metrics import Metrics
            metrics = Metrics()
        self.metrics = metrics
        self.reactors: dict[str, Reactor] = {}
        self._chan_to_reactor: dict[int, Reactor] = {}
        self._channel_descs: list[ChannelDescriptor] = []
        self.peers: dict[str, Peer] = {}
        self._server: Optional[asyncio.base_events.Server] = None
        self._persistent_addrs: list[str] = []
        self._dial_tasks: list = []   # SupervisedTask handles
        # peer ids whose addresses must never be gossiped via PEX
        # (reference: sw.AddPrivatePeerIDs / p2p.private_peer_ids)
        self.private_ids: set[str] = set()
        # one-for-one supervision of every switch/reactor background
        # loop; reactors reach it via Reactor.supervisor
        self.supervisor = Supervisor("p2p", logger=self.logger,
                                     metrics=supervisor_metrics)
        # test seam (nemesis/fuzz link faults): wraps the authenticated
        # secret connection before the MConnection is built —
        # conn_wrapper(sconn, peer_node_id, outbound) -> conn
        self.conn_wrapper = None

    # ------------------------------------------------------------------
    def add_reactor(self, reactor: Reactor) -> None:
        for desc in reactor.get_channels():
            if desc.id in self._chan_to_reactor:
                raise SwitchError(
                    f"channel {desc.id:#x} already claimed")
            self._chan_to_reactor[desc.id] = reactor
            self._channel_descs.append(desc)
            # per-channel size/stall distributions exist from reactor
            # registration on, not from the first peer — a zero-peer
            # node still scrapes the full bucket ladders
            self.metrics.touch_channel(f"{desc.id:#x}")
        self.reactors[reactor.name] = reactor
        reactor.switch = self

    def node_info(self) -> NodeInfo:
        feats: set[str] = set()
        for reactor in self.reactors.values():
            feats.update(reactor.get_features())
        return NodeInfo(
            node_id=self.node_key.id,
            listen_addr=self.listen_addr,
            network=self.network,
            channels=bytes(sorted(self._chan_to_reactor)),
            moniker=self.moniker,
            features=tuple(sorted(feats)),
        )

    # ------------------------------------------------------------------
    async def start(self) -> None:
        if self.listen_addr:
            host, port = _split_addr(self.listen_addr)
            self._server = await asyncio.start_server(
                self._accept, host, port)
            addr = self._server.sockets[0].getsockname()
            self.listen_addr = f"{addr[0]}:{addr[1]}"
            self.logger.info("P2P listening", addr=self.listen_addr)

    async def stop(self) -> None:
        await self.supervisor.stop()
        self._dial_tasks = []
        if self._server is not None:
            self._server.close()
        for peer in list(self.peers.values()):
            await self.stop_peer(peer, "switch stopping")

    @property
    def local_port(self) -> int:
        return int(self.listen_addr.rsplit(":", 1)[1])

    # ------------------------------------------------------------------
    async def dial_peer(self, addr: str) -> Peer:
        """Dial, upgrade to a secret connection, handshake, add."""
        host, port = _split_addr(addr)
        reader, writer = await asyncio.open_connection(host, port)
        try:
            return await self._upgrade(reader, writer, outbound=True,
                                       remote_addr=addr)
        except Exception:
            writer.close()
            raise

    async def _accept(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        peername = writer.get_extra_info("peername")
        addr = f"{peername[0]}:{peername[1]}" if peername else "?"
        try:
            await self._upgrade(reader, writer, outbound=False,
                                remote_addr=addr)
        except Exception as e:
            self.logger.info("inbound handshake failed", addr=addr,
                             err=str(e))
            writer.close()

    async def _upgrade(self, reader, writer, outbound: bool,
                       remote_addr: str) -> Peer:
        sconn = await SecretConnection.make(reader, writer,
                                            self.node_key.priv_key)
        # node info exchange
        await sconn.write_msg(self.node_info().to_json())
        their_info = NodeInfo.from_json(await sconn.read_msg())
        expected_id = node_id_from_pub_key(sconn.remote_pub_key)
        if their_info.node_id != expected_id:
            raise SwitchError(
                f"peer claimed id {their_info.node_id[:12]} but "
                f"authenticated as {expected_id[:12]}")
        reason = self.node_info().compatible_with(their_info)
        if reason is not None:
            raise SwitchError(f"incompatible peer: {reason}")
        if their_info.node_id == self.node_key.id:
            raise SwitchError("connected to self")
        if their_info.node_id in self.peers:
            raise SwitchError("duplicate peer")

        peer_holder: list[Peer] = []

        conn = sconn
        if self.conn_wrapper is not None:
            # nemesis/fuzz seam: slot link-fault wrappers between the
            # authenticated transport and the MConnection
            conn = self.conn_wrapper(sconn, their_info.node_id,
                                     outbound)

        async def on_receive(chan_id: int, msg: bytes) -> None:
            reactor = self._chan_to_reactor.get(chan_id)
            if reactor is not None and peer_holder:
                await reactor.receive(chan_id, peer_holder[0], msg)

        def on_error(e: Exception) -> None:
            if peer_holder:
                # supervised one-shot: a crash inside stop_peer is
                # metered and retried instead of vanishing with the
                # fire-and-forget task
                self.supervisor.spawn(
                    lambda: self.stop_peer(peer_holder[0], str(e)),
                    name=f"stop_peer:{their_info.node_id[:12]}",
                    kind="stop_peer")

        mconn = MConnection(conn, self._channel_descs, on_receive,
                            on_error, send_rate=self.send_rate,
                            recv_rate=self.recv_rate,
                            metrics=self.metrics,
                            peer_id=their_info.node_id)
        peer = Peer(their_info, mconn, outbound, remote_addr)
        peer_holder.append(peer)
        self.peers[peer.id] = peer
        self.metrics.peers.set(len(self.peers))
        tracing.instant(tracing.P2P, "peer_add", peer=peer.id[:12],
                        outbound=outbound)
        mconn.start()
        for reactor in self.reactors.values():
            await reactor.add_peer(peer)
        self.logger.info("Added peer", peer=peer.id[:12],
                         outbound=outbound)
        return peer

    async def stop_peer(self, peer: Peer, reason: str) -> None:
        """Reference: Switch.StopPeerForError."""
        if self.peers.pop(peer.id, None) is None:
            return
        self.metrics.peers.set(len(self.peers))
        tracing.instant(tracing.P2P, "peer_remove",
                        peer=peer.id[:12], reason=reason[:64])
        peer.close()
        for reactor in self.reactors.values():
            await reactor.remove_peer(peer, reason)
        self.logger.info("Removed peer", peer=peer.id[:12],
                         reason=reason)

    # ------------------------------------------------------------------
    def broadcast(self, channel_id: int, msg: bytes) -> None:
        """Queue to every peer (reference: Switch.Broadcast)."""
        for peer in self.peers.values():
            peer.send(channel_id, msg)

    def num_peers(self) -> int:
        return len(self.peers)

    # ------------------------------------------------------------------
    def dial_peers_async(self, addrs: list[str],
                         persistent: bool = True) -> None:
        """Background dialing with exponential backoff for persistent
        peers (reference: dial loops + reconnect).  Each dial loop is
        supervisor-owned: an uncaught exception restarts it instead of
        silently ending redials for that address."""
        for addr in addrs:
            self._dial_tasks.append(self.supervisor.spawn(
                lambda a=addr, p=persistent: self._dial_loop(a, p),
                name=f"dial:{addr}", kind="dial"))

    async def _dial_loop(self, addr: str, persistent: bool) -> None:
        """Dial with backoff; persistent peers are re-dialed forever
        after any disconnect (reference: reconnectToPeer)."""
        backoff = 0.2
        while True:
            peer = None
            try:
                peer = await self.dial_peer(addr)
            except SwitchError as e:
                if "connected to self" in str(e):
                    return
                if "duplicate peer" in str(e):
                    peer = "duplicate"
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # any transport/handshake failure (ConnectionError,
                # IncompleteReadError — an EOFError, not an OSError —
                # timeouts, garbage from a mid-reset peer) must NOT
                # kill the persistent redial loop (reference:
                # reconnectToPeer retries on every error)
                self.logger.debug("dial failed", addr=addr,
                                  err=str(e))
            if peer is None:
                if not persistent:
                    return
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 10.0)
                continue
            if not persistent:
                return
            backoff = 0.2
            # watch for disconnect, then re-dial
            peer_id = peer.id if isinstance(peer, Peer) else None
            while True:
                await asyncio.sleep(1.0)
                if peer_id is not None:
                    if peer_id not in self.peers:
                        break
                else:
                    # duplicate: find the live peer for this addr
                    if not any(p.remote_addr == addr or
                               p.node_info.listen_addr == addr
                               for p in self.peers.values()):
                        break


def _split_addr(addr: str) -> tuple[str, int]:
    addr = addr.replace("tcp://", "")
    host, port = addr.rsplit(":", 1)
    return host or "127.0.0.1", int(port)
