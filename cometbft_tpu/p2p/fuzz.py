"""Fuzzed connection wrapper: byzantine-ish link-layer fault injection.

Reference: p2p/internal/fuzz/fuzz.go:131 — a conn wrapper that randomly
drops, delays, or corrupts frames, used to harden the p2p stack against
misbehaving links.  Wraps the SecretConnection frame interface
(read_msg/write_msg) so it slots under MConnection transparently.
"""
from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass


@dataclass
class FuzzConfig:
    """Probabilities are per-frame and independent."""
    prob_drop_write: float = 0.0      # silently discard an outgoing frame
    prob_delay: float = 0.0           # sleep before delivering
    max_delay_s: float = 0.05
    prob_corrupt_read: float = 0.0    # flip a byte in an incoming frame
    prob_reorder: float = 0.0         # deliver a frame after its successor
    prob_duplicate: float = 0.0       # deliver an outgoing frame twice
    seed: int = 0


class FuzzedConnection:
    """Wraps any object with async read_msg()/write_msg(b)/close()."""

    def __init__(self, conn, config: FuzzConfig):
        self._conn = conn
        self.config = config
        self._rng = random.Random(config.seed or None)
        self.dropped = 0
        self.delayed = 0
        self.corrupted = 0
        self.reordered = 0
        self.duplicated = 0
        self._held: bytes | None = None   # one-frame reorder window

    async def write_msg(self, data: bytes) -> None:
        # the reorder/duplicate draws are gated on their probability
        # being set, so existing seeded schedules (drop/delay/corrupt
        # only) consume the exact same RNG sequence as before
        cfg = self.config
        if self._rng.random() < cfg.prob_drop_write:
            self.dropped += 1
            return
        if self._rng.random() < cfg.prob_delay:
            self.delayed += 1
            await asyncio.sleep(self._rng.random() * cfg.max_delay_s)
        if cfg.prob_reorder and self._held is None and \
                self._rng.random() < cfg.prob_reorder:
            # hold this frame back; it ships right after the NEXT
            # frame (frame boundaries preserved, order swapped)
            self._held = data
            self.reordered += 1
            return
        await self._conn.write_msg(data)
        if cfg.prob_duplicate and \
                self._rng.random() < cfg.prob_duplicate:
            self.duplicated += 1
            await self._conn.write_msg(data)
        if self._held is not None:
            held, self._held = self._held, None
            await self._conn.write_msg(held)

    async def read_msg(self) -> bytes:
        data = await self._conn.read_msg()
        cfg = self.config
        if data and self._rng.random() < cfg.prob_corrupt_read:
            self.corrupted += 1
            i = self._rng.randrange(len(data))
            data = data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]
        if self._rng.random() < cfg.prob_delay:
            self.delayed += 1
            await asyncio.sleep(self._rng.random() * cfg.max_delay_s)
        return data

    def close(self) -> None:
        if self._held is not None:
            # a frame held for reorder with no successor is a drop,
            # not a reorder — keep the counters truthful
            self._held = None
            self.reordered -= 1
            self.dropped += 1
        self._conn.close()

    def __getattr__(self, name):
        return getattr(self._conn, name)
