"""Fuzzed connection wrapper: byzantine-ish link-layer fault injection.

Reference: p2p/internal/fuzz/fuzz.go:131 — a conn wrapper that randomly
drops, delays, or corrupts frames, used to harden the p2p stack against
misbehaving links.  Wraps the SecretConnection frame interface
(read_msg/write_msg) so it slots under MConnection transparently.
"""
from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass


@dataclass
class FuzzConfig:
    """Probabilities are per-frame and independent."""
    prob_drop_write: float = 0.0      # silently discard an outgoing frame
    prob_delay: float = 0.0           # sleep before delivering
    max_delay_s: float = 0.05
    prob_corrupt_read: float = 0.0    # flip a byte in an incoming frame
    seed: int = 0


class FuzzedConnection:
    """Wraps any object with async read_msg()/write_msg(b)/close()."""

    def __init__(self, conn, config: FuzzConfig):
        self._conn = conn
        self.config = config
        self._rng = random.Random(config.seed or None)
        self.dropped = 0
        self.delayed = 0
        self.corrupted = 0

    async def write_msg(self, data: bytes) -> None:
        cfg = self.config
        if self._rng.random() < cfg.prob_drop_write:
            self.dropped += 1
            return
        if self._rng.random() < cfg.prob_delay:
            self.delayed += 1
            await asyncio.sleep(self._rng.random() * cfg.max_delay_s)
        await self._conn.write_msg(data)

    async def read_msg(self) -> bytes:
        data = await self._conn.read_msg()
        cfg = self.config
        if data and self._rng.random() < cfg.prob_corrupt_read:
            self.corrupted += 1
            i = self._rng.randrange(len(data))
            data = data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]
        if self._rng.random() < cfg.prob_delay:
            self.delayed += 1
            await asyncio.sleep(self._rng.random() * cfg.max_delay_s)
        return data

    def close(self) -> None:
        self._conn.close()

    def __getattr__(self, name):
        return getattr(self._conn, name)
