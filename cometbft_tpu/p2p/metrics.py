"""P2P metrics (reference: p2p/metrics.go + metrics.gen.go — per-
channel byte counters, peer gauge, flow-control delay)."""
from __future__ import annotations

from typing import Optional

from ..libs import metrics as libmetrics


class Metrics:
    def __init__(self, registry: Optional[libmetrics.Registry] = None):
        m = registry if registry is not None else libmetrics.Registry()
        self.peers = m.gauge(
            "p2p", "peers", "Number of peers.")
        self.message_receive_bytes_total = m.counter(
            "p2p", "message_receive_bytes_total",
            "Number of bytes of each message type received.",
            labels=("chID",))
        self.message_send_bytes_total = m.counter(
            "p2p", "message_send_bytes_total",
            "Number of bytes of each message type sent.",
            labels=("chID",))
        self.peer_pending_send_bytes = m.gauge(
            "p2p", "peer_pending_send_bytes",
            "Pending bytes to be sent to a given peer.",
            labels=("peer_id",))
        self.recv_rate_limiter_delay = m.counter(
            "p2p", "recv_rate_limiter_delay",
            "Seconds spent sleeping in the receive rate limiter.",
            labels=("peer_id",))
        self.send_rate_limiter_delay = m.counter(
            "p2p", "send_rate_limiter_delay",
            "Seconds spent sleeping in the send rate limiter.",
            labels=("peer_id",))
