"""P2P metrics (reference: p2p/metrics.go + metrics.gen.go — per-
channel byte counters, peer gauge, flow-control delay)."""
from __future__ import annotations

from typing import Optional

from ..libs import metrics as libmetrics


class Metrics:
    def __init__(self, registry: Optional[libmetrics.Registry] = None):
        m = registry if registry is not None else libmetrics.Registry()
        self.peers = m.gauge(
            "p2p", "peers", "Number of peers.")
        self.message_receive_bytes_total = m.counter(
            "p2p", "message_receive_bytes_total",
            "Number of bytes of each message type received.",
            labels=("chID",))
        self.message_send_bytes_total = m.counter(
            "p2p", "message_send_bytes_total",
            "Number of bytes of each message type sent.",
            labels=("chID",))
        self.peer_pending_send_bytes = m.gauge(
            "p2p", "peer_pending_send_bytes",
            "Pending bytes to be sent to a given peer.",
            labels=("peer_id",))
        self.recv_rate_limiter_delay = m.counter(
            "p2p", "recv_rate_limiter_delay",
            "Seconds spent sleeping in the receive rate limiter.",
            labels=("peer_id",))
        self.send_rate_limiter_delay = m.counter(
            "p2p", "send_rate_limiter_delay",
            "Seconds spent sleeping in the send rate limiter.",
            labels=("peer_id",))
        # metrics v2: distributions per channel (channel ids are a
        # small fixed set claimed by reactors, so the label is
        # bounded; peers are NOT a histogram label on purpose —
        # buckets x peers would explode under churn)
        _size_buckets = (16, 64, 256, 1024, 4096, 16384, 65536,
                         262144, 1048576, 4194304)
        self.message_send_size_bytes = m.histogram(
            "p2p", "message_send_size_bytes",
            "Histogram of complete message sizes sent per channel.",
            labels=("chID",), buckets=_size_buckets)
        self.message_recv_size_bytes = m.histogram(
            "p2p", "message_recv_size_bytes",
            "Histogram of complete message sizes received per "
            "channel.", labels=("chID",), buckets=_size_buckets)
        self.queue_stall_seconds = m.histogram(
            "p2p", "queue_stall_seconds",
            "Histogram of time a send stalled per channel: blocking "
            "waits on a full send queue plus rate-limiter sleeps in "
            "the send routine.", labels=("chID",),
            buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1,
                     0.5, 1.0, 5.0))
        self.send_queue_drops = m.counter(
            "p2p", "send_queue_drops",
            "Number of messages dropped by TrySend on a full "
            "per-channel send queue.", labels=("chID",))
        # bytes-useful vs bytes-sent per channel (docs/gossip.md):
        # reactors credit payload bytes that carried NOVEL content
        # (a tx the pool admitted, a block part the part set lacked,
        # a vote the peer-state bitmap lacked); the ratio against
        # message_send/receive_bytes_total is the redundancy of each
        # gossip plane
        self.message_useful_bytes_total = m.counter(
            "p2p", "message_useful_bytes_total",
            "Received bytes whose payload was novel to this node, "
            "credited per channel by the owning reactor.",
            labels=("chID",))

    def touch_channel(self, ch_id: str) -> None:
        """Materialize the per-channel series at connection setup so
        /metrics always exposes the full bucket ladder for every
        claimed channel, observations or not (the exposition contract
        test relies on this)."""
        self.message_send_size_bytes.with_labels(ch_id)
        self.message_recv_size_bytes.with_labels(ch_id)
        self.queue_stall_seconds.with_labels(ch_id)
        self.message_useful_bytes_total.with_labels(ch_id)
