"""SecretConnection: authenticated encryption for peer links.

Reference: p2p/transport/tcp/conn/secret_connection.go:67,101 — STS-style
handshake: X25519 ECDH → KDF → ChaCha20-Poly1305 AEAD with counter
nonces, then an ed25519 proof of the node identity over a handshake
challenge.  The reference derives the challenge with a merlin/STROBE
transcript; here the transcript hash is HKDF-SHA256 over the same inputs
(ephemeral keys sorted lexicographically + DH secret) — equivalent
binding, not wire-compatible with Go peers by design.
"""
from __future__ import annotations

import asyncio
import struct

try:
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey, X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import (
        ChaCha20Poly1305,
    )
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
    from cryptography.hazmat.primitives.serialization import (
        Encoding, PublicFormat,
    )
    _HAVE_OPENSSL = True
except ImportError:
    # Dependency gate: without the OpenSSL bindings the whole p2p
    # stack used to die at import.  The self-contained fallback
    # (native C++ AEAD + python X25519/HKDF) is bit-compatible, so
    # mixed deployments interoperate.
    _HAVE_OPENSSL = False
    ChaCha20Poly1305 = None  # type: ignore[assignment]

from ..crypto import _aead_fallback
from ..crypto import ed25519
from ..crypto.keys import PrivKey, PubKey

DATA_LEN_SIZE = 4
DATA_MAX_SIZE = 1024
TOTAL_FRAME_SIZE = DATA_MAX_SIZE + DATA_LEN_SIZE
AEAD_TAG_SIZE = 16
SEALED_FRAME_SIZE = TOTAL_FRAME_SIZE + AEAD_TAG_SIZE
_NONCE_SIZE = 12

_HKDF_INFO = b"CMT_TPU_SECRET_CONNECTION_KEY_AND_CHALLENGE_GEN"


class SecretConnectionError(Exception):
    pass


class AuthFailureError(SecretConnectionError):
    pass


def _derive(dh_secret: bytes, lo: bytes, hi: bytes,
            loc_is_least: bool) -> tuple[bytes, bytes, bytes]:
    """(recv_secret, send_secret, challenge) — reference:
    deriveSecrets + transcript challenge extraction."""
    if _HAVE_OPENSSL:
        okm = HKDF(algorithm=hashes.SHA256(), length=96, salt=lo + hi,
                   info=_HKDF_INFO).derive(dh_secret)
    else:
        okm = _aead_fallback.hkdf_sha256(dh_secret, lo + hi,
                                         _HKDF_INFO, 96)
    s1, s2, challenge = okm[:32], okm[32:64], okm[64:]
    if loc_is_least:
        return s2, s1, challenge   # recv, send
    return s1, s2, challenge


def _new_aead(key: bytes):
    if _HAVE_OPENSSL:
        return ChaCha20Poly1305(key)
    return _aead_fallback.ChaCha20Poly1305(key)


def _gen_ephemeral() -> tuple[object, bytes]:
    """(private handle, raw public key bytes)."""
    if _HAVE_OPENSSL:
        priv = X25519PrivateKey.generate()
        return priv, priv.public_key().public_bytes(
            Encoding.Raw, PublicFormat.Raw)
    return _aead_fallback.x25519_keypair()


def _dh(priv, rem_pub: bytes) -> bytes:
    if _HAVE_OPENSSL:
        return priv.exchange(X25519PublicKey.from_public_bytes(
            rem_pub))
    out = _aead_fallback.x25519(priv, rem_pub)
    if out == bytes(32):
        # match OpenSSL's contributory-behavior check: a small-order
        # peer point yields the all-zero secret, which would let an
        # active attacker fix the session keys
        raise SecretConnectionError(
            "x25519: low-order peer public key")
    return out


class SecretConnection:
    """Frames every write into fixed-size sealed chunks so traffic
    analysis sees uniform ciphertext (reference: fixed 1044-byte sealed
    frames)."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter,
                 send_aead: ChaCha20Poly1305,
                 recv_aead: ChaCha20Poly1305,
                 remote_pub_key: PubKey):
        self._reader = reader
        self._writer = writer
        self._send_aead = send_aead
        self._recv_aead = recv_aead
        self._send_nonce = 0
        self._recv_nonce = 0
        self._recv_buffer = b""
        self.remote_pub_key = remote_pub_key

    # ------------------------------------------------------------------
    @classmethod
    async def make(cls, reader: asyncio.StreamReader,
                   writer: asyncio.StreamWriter,
                   loc_priv_key: PrivKey) -> "SecretConnection":
        """The 2-round handshake (reference: MakeSecretConnection)."""
        eph_priv, eph_pub = _gen_ephemeral()

        # 1) exchange ephemeral pubkeys in the clear
        writer.write(eph_pub)
        await writer.drain()
        rem_eph_pub = await reader.readexactly(32)

        lo, hi = sorted([eph_pub, rem_eph_pub])
        loc_is_least = eph_pub == lo
        dh_secret = _dh(eph_priv, rem_eph_pub)
        recv_secret, send_secret, challenge = _derive(
            dh_secret, lo, hi, loc_is_least)

        sc = cls(reader, writer, _new_aead(send_secret),
                 _new_aead(recv_secret), remote_pub_key=None)

        # 2) prove identity: send (pubkey || sig(challenge)) encrypted
        loc_pub = loc_priv_key.pub_key()
        sig = loc_priv_key.sign(challenge)
        await sc.write_msg(loc_pub.bytes() + sig)
        auth = await sc.read_msg()
        if len(auth) != 32 + 64:
            raise AuthFailureError("malformed auth message")
        rem_pub = ed25519.Ed25519PubKey(auth[:32])
        if not rem_pub.verify_signature(challenge, auth[32:]):
            raise AuthFailureError("challenge verification failed")
        sc.remote_pub_key = rem_pub
        return sc

    # ------------------------------------------------------------------
    def _next_nonce(self, recv: bool) -> bytes:
        if recv:
            n = self._recv_nonce
            self._recv_nonce += 1
        else:
            n = self._send_nonce
            self._send_nonce += 1
        if n >= 1 << 95:
            raise SecretConnectionError("nonce overflow")
        return n.to_bytes(_NONCE_SIZE, "little")

    def _seal_chunk(self, chunk: bytes) -> bytes:
        frame = struct.pack("<I", len(chunk)) + chunk
        frame = frame.ljust(TOTAL_FRAME_SIZE, b"\x00")
        return self._send_aead.encrypt(
            self._next_nonce(recv=False), frame, None)

    async def write_msg(self, data: bytes) -> None:
        """Write one message: full chunks then a terminating short
        (possibly empty) chunk, so read_msg always sees the boundary."""
        view = memoryview(data)
        while len(view) >= DATA_MAX_SIZE:
            self._writer.write(self._seal_chunk(bytes(
                view[:DATA_MAX_SIZE])))
            view = view[DATA_MAX_SIZE:]
        self._writer.write(self._seal_chunk(bytes(view)))
        await self._writer.drain()

    async def _read_frame(self) -> bytes:
        sealed = await self._reader.readexactly(SEALED_FRAME_SIZE)
        frame = self._recv_aead.decrypt(
            self._next_nonce(recv=True), sealed, None)
        ln = struct.unpack("<I", frame[:DATA_LEN_SIZE])[0]
        if ln > DATA_MAX_SIZE:
            raise SecretConnectionError(f"frame length {ln} too large")
        return frame[DATA_LEN_SIZE:DATA_LEN_SIZE + ln]

    async def read_chunk(self) -> bytes:
        """One decrypted chunk (up to 1024 bytes) — MConnection packets
        are framed inside these."""
        return await self._read_frame()

    async def read_msg(self) -> bytes:
        """Read one full-frame message written by write_msg: reads
        frames until a non-full chunk terminates the message."""
        out = bytearray()
        while True:
            chunk = await self._read_frame()
            out += chunk
            if len(chunk) < DATA_MAX_SIZE:
                return bytes(out)

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:
            pass
