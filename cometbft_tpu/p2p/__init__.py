"""P2P: the distributed communication backend.

Reference: p2p/ — Switch (peer lifecycle + reactor registry),
MConnection (multiplexed prioritized streams over one TCP conn),
SecretConnection (authenticated encryption), PEX/address book.

Validators are WAN peers: this host-side socket stack carries consensus;
TPU ICI/DCN is used only inside the crypto offload (SURVEY §5).
"""
from .key import NodeKey, node_id_from_pub_key
from .switch import Reactor, Switch, Peer

__all__ = ["NodeKey", "node_id_from_pub_key", "Reactor", "Switch",
           "Peer"]
