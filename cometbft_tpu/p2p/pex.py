"""PEX: peer exchange + address book.

Reference: p2p/pex/ — pex_reactor.go (:756, PexChannel 0x00, address
requests/responses, seed crawl mode) and addrbook.go (:921, bucketed
address book with persistence).  The book here keeps the same contract
(routable addresses, last-seen tracking, JSON persistence, random
selection) with a flat table in place of the old/new bucket machinery.
"""
from __future__ import annotations

import asyncio
import json
import os
import random
import time
from dataclasses import dataclass, field
from typing import Optional

from ..libs.log import Logger
from .conn import ChannelDescriptor
from .switch import Peer, Reactor
from ..wire import encode, decode
from ..wire.proto import F, Msg

PEX_CHANNEL = 0x00
_REQUEST_INTERVAL_S = 30.0
_MAX_ADDRS_PER_MSG = 100

PEX_ADDR = Msg("cometbft.p2p.v1.PexAddress",
               F(1, "id", "string"), F(2, "ip", "string"),
               F(3, "port", "uint32"))
PEX_REQUEST = Msg("cometbft.p2p.v1.PexRequest")
PEX_ADDRS = Msg("cometbft.p2p.v1.PexAddrs",
                F(1, "addrs", "msg", msg=PEX_ADDR, repeated=True))
PEX_MESSAGE = Msg("cometbft.p2p.v1.Message",
                  F(1, "pex_request", "msg", msg=PEX_REQUEST),
                  F(2, "pex_addrs", "msg", msg=PEX_ADDRS))


@dataclass
class KnownAddress:
    node_id: str
    ip: str
    port: int
    last_seen: float = field(default_factory=time.time)
    attempts: int = 0

    @property
    def dial_addr(self) -> str:
        return f"{self.ip}:{self.port}"


class AddrBook:
    """Reference: p2p/pex/addrbook.go — persistence + random pick."""

    def __init__(self, path: str = "", strict: bool = True):
        self.path = path
        self.strict = strict
        self._addrs: dict[str, KnownAddress] = {}
        if path and os.path.exists(path):
            self._load()

    def add_address(self, node_id: str, ip: str, port: int) -> bool:
        if not node_id or port <= 0:
            return False
        if self.strict and not _routable(ip):
            return False
        ka = self._addrs.get(node_id)
        if ka is None:
            self._addrs[node_id] = KnownAddress(node_id, ip, port)
            return True
        ka.ip, ka.port = ip, port
        ka.last_seen = time.time()
        return False

    def mark_good(self, node_id: str) -> None:
        ka = self._addrs.get(node_id)
        if ka is not None:
            ka.attempts = 0
            ka.last_seen = time.time()

    def mark_attempt(self, node_id: str) -> None:
        ka = self._addrs.get(node_id)
        if ka is not None:
            ka.attempts += 1

    def remove(self, node_id: str) -> None:
        self._addrs.pop(node_id, None)

    def pick_addresses(self, n: int,
                       exclude: Optional[set] = None
                       ) -> list[KnownAddress]:
        pool = [a for a in self._addrs.values()
                if not exclude or a.node_id not in exclude]
        random.shuffle(pool)
        return pool[:n]

    def size(self) -> int:
        return len(self._addrs)

    def save(self) -> None:
        if not self.path:
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "w") as f:
            json.dump([{"id": a.node_id, "ip": a.ip, "port": a.port,
                        "last_seen": a.last_seen}
                       for a in self._addrs.values()], f, indent=2)

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                for d in json.load(f):
                    self._addrs[d["id"]] = KnownAddress(
                        d["id"], d["ip"], int(d["port"]),
                        d.get("last_seen", 0.0))
        except (json.JSONDecodeError, KeyError, OSError):
            pass


def _routable(ip: str) -> bool:
    # local addresses are fine for testnets when strict=False; strict
    # mode refuses the obvious non-routables except RFC1918 (validators
    # commonly peer over private networks)
    return not ip.startswith(("0.", "255."))


class PexReactor(Reactor):
    def __init__(self, book: AddrBook, seed_mode: bool = False,
                 max_outbound: int = 10,
                 logger: Optional[Logger] = None):
        super().__init__("PEX")
        if logger is not None:
            self.logger = logger
        self.book = book
        self.seed_mode = seed_mode
        self.max_outbound = max_outbound
        self._task: Optional[asyncio.Task] = None

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=PEX_CHANNEL, priority=1,
                                  send_queue_capacity=10)]

    async def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(
            self._ensure_peers_routine())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        self.book.save()

    # ------------------------------------------------------------------
    async def add_peer(self, peer: Peer) -> None:
        # record the peer's self-reported listen address
        la = peer.node_info.listen_addr
        if la and ":" in la:
            ip, port = la.rsplit(":", 1)
            self.book.add_address(peer.id, ip, int(port))
            self.book.mark_good(peer.id)
        # ask it for more peers
        peer.send(PEX_CHANNEL,
                  encode(PEX_MESSAGE, {"pex_request": {}}))

    async def receive(self, chan_id: int, peer: Peer,
                      msg_bytes: bytes) -> None:
        d = decode(PEX_MESSAGE, msg_bytes)
        if "pex_request" in d:
            addrs = self.book.pick_addresses(
                _MAX_ADDRS_PER_MSG, exclude={peer.id})
            peer.send(PEX_CHANNEL, encode(PEX_MESSAGE, {"pex_addrs": {
                "addrs": [{"id": a.node_id, "ip": a.ip,
                           "port": a.port} for a in addrs]}}))
            # seed nodes hang up after serving addresses
            if self.seed_mode and self.switch is not None:
                await self.switch.stop_peer(peer, "seed served addrs")
        elif "pex_addrs" in d:
            for a in d["pex_addrs"].get("addrs", []):
                self.book.add_address(a.get("id", ""),
                                      a.get("ip", ""),
                                      a.get("port", 0))

    # ------------------------------------------------------------------
    async def _ensure_peers_routine(self) -> None:
        """Dial book addresses while below the outbound target
        (reference: ensurePeersRoutine)."""
        try:
            while True:
                await asyncio.sleep(1.0)
                sw = self.switch
                if sw is None:
                    continue
                out = sum(1 for p in sw.peers.values() if p.outbound)
                if out >= self.max_outbound:
                    continue
                connected = set(sw.peers)
                connected.add(sw.node_key.id)
                for ka in self.book.pick_addresses(
                        self.max_outbound - out, exclude=connected):
                    self.book.mark_attempt(ka.node_id)
                    try:
                        await sw.dial_peer(ka.dial_addr)
                        self.book.mark_good(ka.node_id)
                    except Exception:
                        if ka.attempts > 10:
                            self.book.remove(ka.node_id)
        except asyncio.CancelledError:
            raise
