"""PEX: peer exchange + address book.

Reference: p2p/pex/ — pex_reactor.go (:756, PexChannel 0x00, address
requests/responses, seed crawl mode) and addrbook.go (:921, bucketed
address book with persistence).  The book here keeps the same contract
(routable addresses, last-seen tracking, JSON persistence, random
selection) with a flat table in place of the old/new bucket machinery.
"""
from __future__ import annotations

import asyncio
import json
import os
import random
import time
from dataclasses import dataclass, field
from typing import Optional

from ..libs.log import Logger
from .conn import ChannelDescriptor
from .switch import Peer, Reactor
from ..wire import encode, decode
from ..wire.proto import F, Msg

PEX_CHANNEL = 0x00
_REQUEST_INTERVAL_S = 30.0
_MAX_ADDRS_PER_MSG = 100

PEX_ADDR = Msg("cometbft.p2p.v1.PexAddress",
               F(1, "id", "string"), F(2, "ip", "string"),
               F(3, "port", "uint32"))
PEX_REQUEST = Msg("cometbft.p2p.v1.PexRequest")
PEX_ADDRS = Msg("cometbft.p2p.v1.PexAddrs",
                F(1, "addrs", "msg", msg=PEX_ADDR, repeated=True))
PEX_MESSAGE = Msg("cometbft.p2p.v1.Message",
                  F(1, "pex_request", "msg", msg=PEX_REQUEST),
                  F(2, "pex_addrs", "msg", msg=PEX_ADDRS))


@dataclass
class KnownAddress:
    node_id: str
    ip: str
    port: int
    # monotonic: last_seen feeds interval arithmetic (freshness
    # ordering, eviction), which a wall-clock step would corrupt; the
    # JSON book converts to/from wall time at the save/load boundary
    last_seen: float = field(default_factory=time.monotonic)
    attempts: int = 0
    is_old: bool = False        # promoted after a successful connection
    bucket: int = 0

    @property
    def dial_addr(self) -> str:
        return f"{self.ip}:{self.port}"


# bucket geometry (reference: p2p/pex/params.go — 256 new buckets, 64
# old buckets, 64 addresses each)
_NEW_BUCKETS = 256
_OLD_BUCKETS = 64
_BUCKET_CAP = 64
_MAX_ATTEMPTS_NEW = 16      # failed-dial cap before a NEW address is dropped


class AddrBook:
    """Bucketed address book (reference: p2p/pex/addrbook.go:921).

    Addresses start in one of 256 NEW buckets (indexed by a keyed hash of
    the node id, so an attacker cannot target a victim's buckets without
    the local key); a successful connection promotes to one of 64 OLD
    buckets.  Full buckets evict: NEW buckets drop their worst entry
    (most failed attempts, then oldest), OLD buckets demote their oldest
    entry back to NEW.  Repeated dial failures remove NEW addresses."""

    def __init__(self, path: str = "", strict: bool = True,
                 key: str = ""):
        import secrets as _secrets
        self.path = path
        self.strict = strict
        self.key = key or _secrets.token_hex(12)
        self._addrs: dict[str, KnownAddress] = {}
        if path and os.path.exists(path):
            self._load()

    # -- bucket mechanics --------------------------------------------------
    def _bucket_index(self, node_id: str, old: bool) -> int:
        import hashlib as _hashlib
        h = _hashlib.sha256(
            (self.key + ("o" if old else "n") + node_id).encode()
        ).digest()
        n = _OLD_BUCKETS if old else _NEW_BUCKETS
        return int.from_bytes(h[:4], "big") % n

    def _bucket_members(self, old: bool, idx: int) -> list[KnownAddress]:
        return [a for a in self._addrs.values()
                if a.is_old == old and a.bucket == idx]

    def _worst_of(self, members: list[KnownAddress]) -> KnownAddress:
        return max(members, key=lambda a: (a.attempts, -a.last_seen))

    # -- public surface ----------------------------------------------------
    def add_address(self, node_id: str, ip: str, port: int) -> bool:
        if not node_id or port <= 0:
            return False
        if self.strict and not _routable(ip):
            return False
        ka = self._addrs.get(node_id)
        if ka is not None:
            ka.ip, ka.port = ip, port
            ka.last_seen = time.monotonic()
            return False
        idx = self._bucket_index(node_id, old=False)
        members = self._bucket_members(False, idx)
        if len(members) >= _BUCKET_CAP:
            # evict the worst NEW entry of this bucket (reference:
            # addrbook.go addToNewBucket -> expireNew)
            self._addrs.pop(self._worst_of(members).node_id, None)
        self._addrs[node_id] = KnownAddress(node_id, ip, port,
                                            bucket=idx)
        return True

    def mark_good(self, node_id: str) -> None:
        """Successful connection: promote NEW -> OLD (reference:
        MarkGood -> moveToOld)."""
        ka = self._addrs.get(node_id)
        if ka is None:
            return
        ka.attempts = 0
        ka.last_seen = time.monotonic()
        if ka.is_old:
            return
        idx = self._bucket_index(node_id, old=True)
        members = self._bucket_members(True, idx)
        if len(members) >= _BUCKET_CAP:
            # demote the oldest OLD entry back to a NEW bucket
            demoted = min(members, key=lambda a: a.last_seen)
            demoted.is_old = False
            demoted.bucket = self._bucket_index(demoted.node_id,
                                                old=False)
        ka.is_old = True
        ka.bucket = idx

    def mark_attempt(self, node_id: str) -> None:
        ka = self._addrs.get(node_id)
        if ka is None:
            return
        ka.attempts += 1
        if not ka.is_old and ka.attempts > _MAX_ATTEMPTS_NEW:
            # unreachable NEW addresses age out (reference: removeBad)
            self._addrs.pop(node_id, None)

    def remove(self, node_id: str) -> None:
        self._addrs.pop(node_id, None)

    def pick_addresses(self, n: int,
                       exclude: Optional[set] = None,
                       old_bias_pct: int = 30) -> list[KnownAddress]:
        """Random selection biased between OLD (proven) and NEW
        addresses (reference: addrbook.go GetSelectionWithBias)."""
        pool_old = [a for a in self._addrs.values()
                    if a.is_old and (not exclude or
                                     a.node_id not in exclude)]
        pool_new = [a for a in self._addrs.values()
                    if not a.is_old and (not exclude or
                                         a.node_id not in exclude)]
        random.shuffle(pool_old)
        random.shuffle(pool_new)
        n_old = min(len(pool_old), max(0, n * old_bias_pct // 100))
        out = pool_old[:n_old] + pool_new[:n - n_old]
        if len(out) < n:        # top up from whichever side has more
            leftovers = pool_old[n_old:] + pool_new[n - n_old:]
            out.extend(leftovers[:n - len(out)])
        random.shuffle(out)
        return out[:n]

    def size(self) -> int:
        return len(self._addrs)

    def save(self) -> None:
        if not self.path:
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # persist wall time (meaningful across reboots); in-memory
        # last_seen is monotonic, so convert via the current offset
        now_m, now_w = time.monotonic(), time.time()
        with open(self.path, "w") as f:
            json.dump({"key": self.key, "addrs": [
                {"id": a.node_id, "ip": a.ip, "port": a.port,
                 "last_seen": now_w - max(0.0, now_m - a.last_seen),
                 "attempts": a.attempts,
                 "is_old": a.is_old, "bucket": a.bucket}
                for a in self._addrs.values()]}, f, indent=2)

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                raw = json.load(f)
            if isinstance(raw, dict):
                self.key = raw.get("key", self.key)
                entries = raw.get("addrs", [])
            else:                      # legacy flat format
                entries = raw
            now_m, now_w = time.monotonic(), time.time()
            for d in entries:
                # wall -> monotonic: age the entry by its wall-clock
                # staleness (clamped — a future wall stamp is "now")
                age = max(0.0, now_w - d.get("last_seen", 0.0))
                self._addrs[d["id"]] = KnownAddress(
                    d["id"], d["ip"], int(d["port"]),
                    now_m - age,
                    attempts=d.get("attempts", 0),
                    is_old=d.get("is_old", False),
                    bucket=d.get("bucket", 0))
        except (json.JSONDecodeError, KeyError, OSError):
            pass


def _routable(ip: str) -> bool:
    # local addresses are fine for testnets when strict=False; strict
    # mode refuses the obvious non-routables except RFC1918 (validators
    # commonly peer over private networks)
    return not ip.startswith(("0.", "255."))


class PexReactor(Reactor):
    def __init__(self, book: AddrBook, seed_mode: bool = False,
                 max_outbound: int = 10,
                 logger: Optional[Logger] = None):
        super().__init__("PEX")
        if logger is not None:
            self.logger = logger
        self.book = book
        self.seed_mode = seed_mode
        self.max_outbound = max_outbound
        self._task = None   # SupervisedTask

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=PEX_CHANNEL, priority=1,
                                  send_queue_capacity=10)]

    async def start(self) -> None:
        self._task = self.supervisor.spawn(
            lambda: self._ensure_peers_routine(),
            name="pex_ensure_peers", kind="pex_ensure_peers")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
        self.book.save()

    # ------------------------------------------------------------------
    async def add_peer(self, peer: Peer) -> None:
        # record the peer's self-reported listen address
        la = peer.node_info.listen_addr
        if la and ":" in la:
            ip, port = la.rsplit(":", 1)
            self.book.add_address(peer.id, ip, int(port))
            self.book.mark_good(peer.id)
        # ask it for more peers
        peer.send(PEX_CHANNEL,
                  encode(PEX_MESSAGE, {"pex_request": {}}))

    async def receive(self, chan_id: int, peer: Peer,
                      msg_bytes: bytes) -> None:
        d = decode(PEX_MESSAGE, msg_bytes)
        if "pex_request" in d:
            private = (self.switch.private_ids
                       if self.switch is not None else set())
            addrs = self.book.pick_addresses(
                _MAX_ADDRS_PER_MSG, exclude={peer.id} | private)
            peer.send(PEX_CHANNEL, encode(PEX_MESSAGE, {"pex_addrs": {
                "addrs": [{"id": a.node_id, "ip": a.ip,
                           "port": a.port} for a in addrs]}}))
            # seed nodes hang up after serving addresses
            if self.seed_mode and self.switch is not None:
                await self.switch.stop_peer(peer, "seed served addrs")
        elif "pex_addrs" in d:
            for a in d["pex_addrs"].get("addrs", []):
                self.book.add_address(a.get("id", ""),
                                      a.get("ip", ""),
                                      a.get("port", 0))

    # ------------------------------------------------------------------
    async def _ensure_peers_routine(self) -> None:
        """Dial book addresses while below the outbound target
        (reference: ensurePeersRoutine)."""
        try:
            while True:
                await asyncio.sleep(1.0)
                sw = self.switch
                if sw is None:
                    continue
                out = sum(1 for p in sw.peers.values() if p.outbound)
                if out >= self.max_outbound:
                    continue
                connected = set(sw.peers)
                connected.add(sw.node_key.id)
                for ka in self.book.pick_addresses(
                        self.max_outbound - out, exclude=connected):
                    self.book.mark_attempt(ka.node_id)
                    try:
                        await sw.dial_peer(ka.dial_addr)
                        self.book.mark_good(ka.node_id)
                    except Exception as e:
                        self.logger.debug(
                            "pex dial failed", addr=ka.dial_addr,
                            attempts=ka.attempts, err=str(e))
                        if ka.attempts > 10:
                            self.book.remove(ka.node_id)
        except asyncio.CancelledError:
            raise
