"""Node identity: ed25519 node key; ID = hex(address).

Reference: p2p/internal/nodekey/ (node_key.go) — ID is the hex-encoded
20-byte address of the node pubkey.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass

from ..crypto import ed25519
from ..crypto.keys import PrivKey, PubKey


def node_id_from_pub_key(pub_key: PubKey) -> str:
    return pub_key.address().hex()


@dataclass
class NodeKey:
    priv_key: PrivKey

    @property
    def id(self) -> str:
        return node_id_from_pub_key(self.priv_key.pub_key())

    def pub_key(self) -> PubKey:
        return self.priv_key.pub_key()

    @classmethod
    def generate(cls) -> "NodeKey":
        return cls(priv_key=ed25519.gen_priv_key())

    def save_as(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({
                "priv_key": {
                    "type": "tendermint/PrivKeyEd25519",
                    "value": __import__("base64").b64encode(
                        self.priv_key.bytes()).decode(),
                }
            }, f, indent=2)
        os.chmod(path, 0o600)   # private key: owner-only

    @classmethod
    def load(cls, path: str) -> "NodeKey":
        with open(path) as f:
            d = json.load(f)
        raw = __import__("base64").b64decode(d["priv_key"]["value"])
        return cls(priv_key=ed25519.Ed25519PrivKey(raw))

    @classmethod
    def load_or_gen(cls, path: str) -> "NodeKey":
        if os.path.exists(path):
            return cls.load(path)
        nk = cls.generate()
        nk.save_as(path)
        return nk
