"""Evidence reactor: gossip pending evidence.

Reference: internal/evidence/reactor.go (:255) — EvidenceChannel 0x38,
per-peer broadcast routine walking the pending list.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from ..libs.log import Logger
from ..p2p.conn import ChannelDescriptor
from ..p2p.switch import Peer, Reactor
from ..types.evidence import evidence_from_proto_wrapped
from ..wire import pb, encode, decode
from ..wire.proto import F, Msg
from .pool import EvidenceError, EvidencePool

EVIDENCE_CHANNEL = 0x38
_BROADCAST_INTERVAL_S = 0.5

EVIDENCE_LIST_MSG = Msg(
    "cometbft.evidence.v2.EvidenceList",
    F(1, "evidence", "msg", msg=pb.EVIDENCE, repeated=True))


class EvidenceReactor(Reactor):
    def __init__(self, pool: EvidencePool,
                 logger: Optional[Logger] = None):
        super().__init__("EVIDENCE")
        if logger is not None:
            self.logger = logger
        self.pool = pool
        self._tasks: dict[str, object] = {}   # SupervisedTask

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=EVIDENCE_CHANNEL, priority=6,
                                  send_queue_capacity=100)]

    async def add_peer(self, peer: Peer) -> None:
        self._tasks[peer.id] = self.supervisor.spawn(
            lambda: self._broadcast_routine(peer),
            name=f"evidence_broadcast:{peer.id[:12]}",
            kind="evidence_broadcast")

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        t = self._tasks.pop(peer.id, None)
        if t is not None:
            t.cancel()

    async def receive(self, chan_id: int, peer: Peer,
                      msg_bytes: bytes) -> None:
        try:
            d = decode(EVIDENCE_LIST_MSG, msg_bytes)
            for wrapped in d.get("evidence", []):
                ev = evidence_from_proto_wrapped(wrapped)
                try:
                    self.pool.add_evidence(ev)
                except EvidenceError as e:
                    self.logger.info("rejected evidence from peer",
                                     peer=peer.id[:12], err=str(e))
        except Exception as e:
            self.logger.error("bad evidence message", err=str(e))

    async def _broadcast_routine(self, peer: Peer) -> None:
        sent: set[bytes] = set()
        seen_version = -1
        try:
            while True:
                if self.pool.version != seen_version:
                    seen_version = self.pool.version
                    pending = self.pool.all_pending()
                    live = {ev.hash() for ev in pending}
                    sent &= live   # forget committed/pruned evidence
                    for ev in pending:
                        h = ev.hash()
                        if h in sent:
                            continue
                        if peer.send(EVIDENCE_CHANNEL, encode(
                                EVIDENCE_LIST_MSG,
                                {"evidence": [ev.to_proto_wrapped()]})):
                            sent.add(h)
                await asyncio.sleep(_BROADCAST_INTERVAL_S)
        except asyncio.CancelledError:
            raise
        # crashes propagate to the supervisor, which restarts this
        # loop instead of letting evidence gossip die silently
