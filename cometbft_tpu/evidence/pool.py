"""Evidence pool: db-backed pending misbehavior evidence.

Reference: internal/evidence/pool.go — pending evidence keyed by
(height, hash), committed markers, expiry by age (blocks AND duration),
ReportConflictingVotes from consensus, verification (verify.go).
"""
from __future__ import annotations

import struct
from typing import Optional

from ..db import DB
from ..libs.log import Logger, new_logger
from ..state.state import State as SMState
from ..types.evidence import (
    DuplicateVoteEvidence, Evidence, LightClientAttackEvidence,
    evidence_from_proto_wrapped,
)
from ..types.timestamp import Timestamp
from ..types.vote import Vote
from ..wire import pb, encode, decode

_PENDING = b"\x00"
_COMMITTED = b"\x01"


def _key(prefix: bytes, height: int, ev_hash: bytes) -> bytes:
    return prefix + struct.pack(">q", height) + ev_hash


class EvidenceError(Exception):
    pass


class EvidencePool:
    def __init__(self, db: DB, state_store, block_store,
                 logger: Optional[Logger] = None):
        self._db = db
        self.state_store = state_store
        self.block_store = block_store
        self.logger = logger if logger is not None else \
            new_logger("evidence")
        self.state: Optional[SMState] = state_store.load()
        # bumped whenever the pending set changes, so reactors can skip
        # rescans when nothing moved
        self.version = 0
        # evidence from our own conflicting-vote reports awaiting
        # block time assignment
        self._consensus_buffer: list[tuple[Vote, Vote]] = []

    # ------------------------------------------------------------------
    def report_conflicting_votes(self, vote_a: Vote,
                                 vote_b: Vote) -> None:
        """Called by consensus on detected equivocation (reference:
        pool.ReportConflictingVotes; processed on the next Update)."""
        self._consensus_buffer.append((vote_a, vote_b))

    def add_evidence(self, ev: Evidence) -> None:
        """Verify + persist gossiped/rpc evidence (reference:
        AddEvidence)."""
        if self._is_pending(ev) or self._is_committed(ev):
            return
        self.verify(ev)
        self._add_pending(ev)
        self.logger.info("Verified new evidence of byzantine behavior",
                         evidence=ev.hash().hex().upper()[:12])

    # ------------------------------------------------------------------
    def verify(self, ev: Evidence) -> None:
        """Reference: internal/evidence/verify.go."""
        state = self.state or self.state_store.load()
        if state is None:
            raise EvidenceError("no state to verify evidence against")
        height = state.last_block_height
        ev_params = state.consensus_params.evidence

        block_meta = self.block_store.load_block_meta(ev.height)
        if block_meta is None:
            raise EvidenceError(
                f"don't have header at height {ev.height}")
        ev_time = block_meta.header.time

        # expiry: BOTH age thresholds must pass for expiry
        age_blocks = height - ev.height
        age_ns = Timestamp.now().unix_ns() - ev_time.unix_ns()
        if age_blocks > ev_params.max_age_num_blocks and \
                age_ns > ev_params.max_age_duration_ns:
            raise EvidenceError(
                f"evidence from height {ev.height} is too old")

        if isinstance(ev, DuplicateVoteEvidence):
            self._verify_duplicate_vote(ev, state, ev_time)
        elif isinstance(ev, LightClientAttackEvidence):
            self._verify_light_client_attack(ev, state)
        else:
            raise EvidenceError(f"unknown evidence type {type(ev)}")

    def _signed_header(self, height: int):
        from ..types.block import SignedHeader
        meta = self.block_store.load_block_meta(height)
        commit = self.block_store.load_block_commit(height)
        if commit is None:
            commit = self.block_store.load_seen_commit(height)
        if meta is None or commit is None:
            raise EvidenceError(f"no header/commit at height {height}")
        return SignedHeader(header=meta.header, commit=commit)

    def _verify_light_client_attack(self, ev: LightClientAttackEvidence,
                                    state: SMState) -> None:
        """Reconstruct and verify the attack against OUR chain
        (reference: verify.go VerifyLightClientAttack :105 + the common/
        trusted header plumbing in verify :55-84)."""
        from ..types.validation import (
            Fraction, VerificationError, verify_commit_light,
            verify_commit_light_trusting,
        )
        common_header = self._signed_header(ev.height)
        common_vals = self.state_store.load_validators(ev.height)
        conflicting = ev.conflicting_block
        conf_height = conflicting.height
        trusted_header = common_header
        if ev.height != conf_height:
            try:
                trusted_header = self._signed_header(conf_height)
            except EvidenceError:
                # forward lunatic: we don't have a block there yet —
                # judge against our latest (reference: verify.go :71-83)
                trusted_header = self._signed_header(
                    self.block_store.height)
                if trusted_header.header.time.unix_ns() < \
                        conflicting.signed_header.header.time.unix_ns():
                    raise EvidenceError(
                        "latest block is before conflicting block — "
                        "cannot judge forward lunatic attack")

        chain_id = state.chain_id
        try:
            if common_header.header.height != conf_height:
                # lunatic: 1/3 of the COMMON set must have signed it
                verify_commit_light_trusting(
                    chain_id, common_vals,
                    conflicting.signed_header.commit,
                    Fraction(1, 3), count_all_signatures=True,
                    signer_vals=conflicting.validator_set)
            elif ev.conflicting_header_is_invalid(
                    trusted_header.header):
                raise EvidenceError(
                    "common height equals conflicting height so the "
                    "conflicting header must be correctly derived")
            # 2/3+ of the conflicting set signed the conflicting block
            verify_commit_light(
                chain_id, conflicting.validator_set,
                conflicting.signed_header.commit.block_id,
                conf_height, conflicting.signed_header.commit,
                count_all_signatures=True)
        except VerificationError as e:
            raise EvidenceError(
                f"invalid conflicting block commit: {e}") from None

        if ev.total_voting_power != common_vals.total_voting_power():
            raise EvidenceError(
                f"evidence voting power {ev.total_voting_power} != "
                f"common set power {common_vals.total_voting_power()}")

        conf_time = conflicting.signed_header.header.time
        if conf_height > trusted_header.header.height:
            if conf_time.unix_ns() > \
                    trusted_header.header.time.unix_ns():
                raise EvidenceError(
                    "conflicting block does not violate monotonic time")
        elif trusted_header.header.hash() == \
                conflicting.signed_header.header.hash():
            raise EvidenceError(
                "trusted header hash matches the conflicting header")

        # the ABCI-facing fields must match what WE derive
        # (reference: validateABCIEvidence :218)
        expect = ev.get_byzantine_validators(common_vals,
                                             trusted_header)
        if len(expect) != len(ev.byzantine_validators):
            raise EvidenceError(
                f"expected {len(expect)} byzantine validators, "
                f"got {len(ev.byzantine_validators)}")
        for want, got in zip(expect, ev.byzantine_validators):
            if want.address != got.address:
                raise EvidenceError(
                    "unexpected byzantine validator address")
        if ev.timestamp != common_header.header.time:
            raise EvidenceError(
                "evidence timestamp != common header time")

    def _verify_duplicate_vote(self, ev: DuplicateVoteEvidence,
                               state: SMState,
                               ev_time: Timestamp) -> None:
        """Reference: verify.go VerifyDuplicateVote."""
        val_set = self.state_store.load_validators(ev.height)
        _, val = val_set.get_by_address(
            ev.vote_a.validator_address)
        if val is None:
            raise EvidenceError(
                "address not a validator at evidence height")
        ev.validate_basic()
        ev.validate_abci()
        if ev.total_voting_power != val_set.total_voting_power():
            raise EvidenceError(
                f"total voting power mismatch: "
                f"{ev.total_voting_power} vs "
                f"{val_set.total_voting_power()}")
        if ev.validator_power != val.voting_power:
            raise EvidenceError("validator power mismatch")
        if ev.timestamp != ev_time:
            raise EvidenceError("evidence time mismatch")
        ev.vote_a.verify(state.chain_id, val.pub_key)
        ev.vote_b.verify(state.chain_id, val.pub_key)

    # ------------------------------------------------------------------
    def pending_evidence(self, max_bytes: int
                         ) -> tuple[list[Evidence], int]:
        """Reference: PendingEvidence — for block proposal."""
        out, size = [], 0
        for _, raw in self._db.iterator(_PENDING,
                                        _PENDING + b"\xff" * 9):
            ev = evidence_from_proto_wrapped(
                decode(pb.EVIDENCE, raw))
            n = len(raw)
            if max_bytes >= 0 and size + n > max_bytes:
                break
            out.append(ev)
            size += n
        return out, size

    def check_evidence(self, evidence: list) -> None:
        """Validate a proposed block's evidence list (reference:
        CheckEvidence)."""
        seen = set()
        for ev in evidence:
            h = ev.hash()
            if h in seen:
                raise EvidenceError("duplicate evidence in block")
            seen.add(h)
            if self._is_committed(ev):
                raise EvidenceError("evidence was already committed")
            if not self._is_pending(ev):
                self.verify(ev)

    def update(self, state: SMState, evidence: list) -> None:
        """Post-commit: mark committed, prune expired, flush consensus
        buffer (reference: pool.Update)."""
        self.state = state
        for ev in evidence:
            self._mark_committed(ev)
        self._process_consensus_buffer(state)
        self._prune_expired(state)

    def _process_consensus_buffer(self, state: SMState) -> None:
        buf, self._consensus_buffer = self._consensus_buffer, []
        for vote_a, vote_b in buf:
            try:
                block_meta = self.block_store.load_block_meta(
                    vote_a.height)
                if block_meta is None:
                    continue
                val_set = self.state_store.load_validators(
                    vote_a.height)
                ev = DuplicateVoteEvidence.new(
                    vote_a, vote_b, block_meta.header.time, val_set)
                if not self._is_pending(ev) and \
                        not self._is_committed(ev):
                    self._add_pending(ev)
                    self.logger.info(
                        "Generated duplicate-vote evidence",
                        height=vote_a.height)
            except Exception as e:
                self.logger.error(
                    "failed to generate evidence from conflicting "
                    "votes", err=str(e))

    # ------------------------------------------------------------------
    def _bump_version(self) -> None:
        self.version += 1

    def _add_pending(self, ev: Evidence) -> None:
        raw = encode(pb.EVIDENCE, ev.to_proto_wrapped())
        self._db.set(_key(_PENDING, ev.height, ev.hash()), raw)
        self._bump_version()

    def _is_pending(self, ev: Evidence) -> bool:
        return self._db.has(_key(_PENDING, ev.height, ev.hash()))

    def _is_committed(self, ev: Evidence) -> bool:
        return self._db.has(_key(_COMMITTED, ev.height, ev.hash()))

    def _mark_committed(self, ev: Evidence) -> None:
        self._db.set(_key(_COMMITTED, ev.height, ev.hash()), b"\x01")
        self._db.delete(_key(_PENDING, ev.height, ev.hash()))
        self._bump_version()

    def _prune_expired(self, state: SMState) -> None:
        """Expiry requires BOTH age thresholds (blocks AND duration) to
        pass, same as verify (reference: isExpired)."""
        params = state.consensus_params.evidence
        height = state.last_block_height
        now_ns = Timestamp.now().unix_ns()
        for k, raw in list(self._db.iterator(
                _PENDING, _PENDING + b"\xff" * 9)):
            ev_height = struct.unpack(">q", k[1:9])[0]
            if height - ev_height <= params.max_age_num_blocks:
                continue
            meta = self.block_store.load_block_meta(ev_height)
            ev_time_ns = meta.header.time.unix_ns() \
                if meta is not None else 0
            if now_ns - ev_time_ns > params.max_age_duration_ns:
                self._db.delete(k)
                self._bump_version()

    def all_pending(self) -> list[Evidence]:
        out, _ = self.pending_evidence(-1)
        return out
