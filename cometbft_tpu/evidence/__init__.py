"""Evidence: pool + verification + gossip of validator misbehavior."""
from .pool import EvidencePool, EvidenceError
from .reactor import EvidenceReactor

__all__ = ["EvidencePool", "EvidenceError", "EvidenceReactor"]
