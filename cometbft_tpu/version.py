"""Protocol versions.

Reference: version/version.go:21 — block protocol 11, p2p protocol 9,
ABCI semver.
"""

CMT_SEM_VER = "1.0.0-tpu"
ABCI_SEM_VER = "2.2.0"
ABCI_VERSION = ABCI_SEM_VER

# uint64 protocol versions
P2P_PROTOCOL = 9
BLOCK_PROTOCOL = 11
