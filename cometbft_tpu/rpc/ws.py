"""WebSocket JSON-RPC: RFC-6455 framing + event subscriptions.

Reference: rpc/jsonrpc/server/ws_handler.go (wsConnection: read/write
routines, JSON-RPC over text frames) and rpc/core/events.go
(subscribe/unsubscribe/unsubscribe_all against the EventBus; events are
delivered as JSON-RPC notifications whose id is the subscribe id).
"""
from __future__ import annotations

import asyncio
import base64
import hashlib
import json
import struct
from typing import Optional

from ..libs import pubsub
from ..libs.log import new_logger

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

# opcodes
OP_CONT, OP_TEXT, OP_BIN, OP_CLOSE, OP_PING, OP_PONG = \
    0x0, 0x1, 0x2, 0x8, 0x9, 0xA

MAX_FRAME = 16 * 1024 * 1024


class WSError(Exception):
    pass


def accept_key(client_key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((client_key + _GUID).encode()).digest()).decode()


def handshake_response(headers: dict) -> bytes:
    key = headers.get("sec-websocket-key", "")
    if not key:
        raise WSError("missing Sec-WebSocket-Key")
    return (
        b"HTTP/1.1 101 Switching Protocols\r\n"
        b"Upgrade: websocket\r\n"
        b"Connection: Upgrade\r\n"
        b"Sec-WebSocket-Accept: " + accept_key(key).encode() +
        b"\r\n\r\n")


async def read_message(reader: asyncio.StreamReader,
                       on_control=None) -> tuple[int, bytes]:
    """Read one complete (possibly fragmented) message -> (opcode, data).

    RFC 6455 permits control frames BETWEEN the fragments of a message;
    when `on_control(op, payload)` (async) is given, PING/PONG frames are
    delivered to it without discarding accumulated fragments.  OP_CLOSE
    always returns immediately — the connection is ending."""
    opcode = None
    data = b""
    while True:
        hdr = await reader.readexactly(2)
        fin = bool(hdr[0] & 0x80)
        op = hdr[0] & 0x0F
        masked = bool(hdr[1] & 0x80)
        ln = hdr[1] & 0x7F
        if ln == 126:
            ln = struct.unpack(">H", await reader.readexactly(2))[0]
        elif ln == 127:
            ln = struct.unpack(">Q", await reader.readexactly(8))[0]
        if ln > MAX_FRAME:
            raise WSError("frame too large")
        mask = await reader.readexactly(4) if masked else b""
        payload = await reader.readexactly(ln)
        if masked:
            payload = bytes(b ^ mask[i % 4]
                            for i, b in enumerate(payload))
        if op == OP_CLOSE:
            return op, payload
        if op in (OP_PING, OP_PONG):
            if on_control is not None:
                await on_control(op, payload)
                continue
            return op, payload
        if opcode is None:
            opcode = op
        data += payload
        if fin:
            return opcode, data


def frame(opcode: int, payload: bytes, mask: bool = False) -> bytes:
    """One frame; mask=True for client->server frames (RFC 6455 §5.3)."""
    import os
    hdr = bytes([0x80 | opcode])
    ln = len(payload)
    mask_bit = 0x80 if mask else 0
    if ln < 126:
        hdr += bytes([mask_bit | ln])
    elif ln < 65536:
        hdr += bytes([mask_bit | 126]) + struct.pack(">H", ln)
    else:
        hdr += bytes([mask_bit | 127]) + struct.pack(">Q", ln)
    if mask:
        key = os.urandom(4)
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
        return hdr + key + payload
    return hdr + payload


class WsSession:
    """One WebSocket JSON-RPC session: normal RPC methods plus
    subscribe/unsubscribe with EventBus-driven pushes."""

    def __init__(self, server, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, remote: str):
        self.server = server            # RPCServer
        self.reader = reader
        self.writer = writer
        self.remote = remote
        self.logger = new_logger("rpc-ws")
        self._send_lock = asyncio.Lock()
        self._pumps: dict[str, asyncio.Task] = {}

    @property
    def _event_bus(self):
        return self.server.node.event_bus

    async def run(self, headers: dict) -> None:
        self.writer.write(handshake_response(headers))
        await self.writer.drain()
        async def on_control(op, payload):
            if op == OP_PING:
                await self._send_raw(frame(OP_PONG, payload))

        try:
            while True:
                op, data = await read_message(self.reader, on_control)
                if op == OP_CLOSE:
                    await self._send_raw(frame(OP_CLOSE, data[:2]))
                    return
                if op not in (OP_TEXT, OP_BIN):
                    continue
                try:
                    req = json.loads(data)
                except json.JSONDecodeError:
                    await self._send_json({"jsonrpc": "2.0", "id": None,
                                           "error": {"code": -32700,
                                                     "message":
                                                     "Parse error"}})
                    continue
                reqs = req if isinstance(req, list) else [req]
                for r in reqs:
                    await self._handle(r)
        except (asyncio.IncompleteReadError, ConnectionError, WSError):
            pass
        finally:
            self._teardown()

    def _teardown(self) -> None:
        for t in self._pumps.values():
            t.cancel()
        self._pumps.clear()
        try:
            self._event_bus.unsubscribe_all(self.remote)
        except Exception:
            pass

    # ------------------------------------------------------------------
    async def _handle(self, req: dict) -> None:
        rpc_id = req.get("id")
        name = req.get("method", "")
        params = req.get("params") or {}
        if name == "subscribe":
            await self._subscribe(rpc_id, params)
            return
        if name == "unsubscribe":
            await self._unsubscribe(rpc_id, params)
            return
        if name == "unsubscribe_all":
            self._teardown()
            await self._result(rpc_id, {})
            return
        resp = await self.server._call(name, params, rpc_id)
        await self._send_json(resp)

    async def _subscribe(self, rpc_id, params: dict) -> None:
        query_str = params.get("query", "")
        try:
            sub = self._event_bus.subscribe(self.remote, query_str)
        except pubsub.PubSubError as e:
            await self._error(rpc_id, -32603, str(e))
            return
        task = asyncio.create_task(self._pump(rpc_id, query_str, sub))
        self._pumps[query_str] = task
        await self._result(rpc_id, {})

    async def _unsubscribe(self, rpc_id, params: dict) -> None:
        query_str = params.get("query", "")
        task = self._pumps.pop(query_str, None)
        if task is not None:
            task.cancel()
        try:
            self._event_bus.unsubscribe(self.remote, query_str)
        except pubsub.PubSubError as e:
            await self._error(rpc_id, -32603, str(e))
            return
        await self._result(rpc_id, {})

    async def _pump(self, rpc_id, query_str: str,
                    sub: pubsub.Subscription) -> None:
        """Deliver subscription messages as JSON-RPC results carrying the
        subscribe id (reference: ws_handler writes RPCResponse with the
        subscription's original id)."""
        from .core import event_data_json
        try:
            while True:
                msg = await sub.next()
                payload = {
                    "jsonrpc": "2.0",
                    "id": rpc_id,
                    "result": {
                        "query": query_str,
                        "data": event_data_json(msg.data),
                        "events": msg.events,
                    },
                }
                await self._send_json(payload)
        except (pubsub.PubSubError, asyncio.CancelledError):
            pass
        except ConnectionError:
            pass

    # ------------------------------------------------------------------
    async def _result(self, rpc_id, result) -> None:
        await self._send_json({"jsonrpc": "2.0", "id": rpc_id,
                               "result": result})

    async def _error(self, rpc_id, code: int, message: str) -> None:
        await self._send_json({"jsonrpc": "2.0", "id": rpc_id,
                               "error": {"code": code,
                                         "message": message}})

    async def _send_json(self, obj) -> None:
        await self._send_raw(frame(OP_TEXT, json.dumps(obj).encode()))

    async def _send_raw(self, data: bytes) -> None:
        async with self._send_lock:
            self.writer.write(data)
            await self.writer.drain()
