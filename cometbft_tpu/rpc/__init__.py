"""RPC: JSON-RPC 2.0 over HTTP (POST + URI GET) and the method table."""
