"""gRPC data-companion clients.

Reference: rpc/grpc/client/ (Client with block/blockresults/version
services, PrivilegedClient with the pruning service).
"""
from __future__ import annotations

from typing import AsyncIterator

import grpc

from ...wire import encode, decode
from .server import _grpc_addr
from . import pb


class _BaseClient:
    def __init__(self, addr: str):
        from ...abci.grpc import GRPC_OPTIONS
        self._channel = grpc.aio.insecure_channel(
            _grpc_addr(addr), options=GRPC_OPTIONS)

    async def close(self) -> None:
        await self._channel.close()

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        await self.close()

    def _unary(self, service: str, method: str, req_desc, resp_desc):
        return self._channel.unary_unary(
            f"/{service}/{method}",
            request_serializer=lambda m: encode(req_desc, m),
            response_deserializer=lambda b: decode(resp_desc, b))

    def _stream(self, service: str, method: str, req_desc, resp_desc):
        return self._channel.unary_stream(
            f"/{service}/{method}",
            request_serializer=lambda m: encode(req_desc, m),
            response_deserializer=lambda b: decode(resp_desc, b))


class VersionServiceClient(_BaseClient):
    async def get_version(self) -> dict:
        return await self._unary(
            pb.VERSION_SERVICE, "GetVersion",
            pb.GET_VERSION_REQUEST, pb.GET_VERSION_RESPONSE)({})


class BlockServiceClient(_BaseClient):
    async def get_by_height(self, height: int = 0) -> dict:
        """Returns {"block_id": ..., "block": ...} proto dicts."""
        return await self._unary(
            pb.BLOCK_SERVICE, "GetByHeight",
            pb.GET_BY_HEIGHT_REQUEST, pb.GET_BY_HEIGHT_RESPONSE)(
                {"height": height} if height else {})

    async def get_latest_height(self) -> AsyncIterator[int]:
        """Yields committed heights until the stream is cancelled."""
        call = self._stream(
            pb.BLOCK_SERVICE, "GetLatestHeight",
            pb.GET_LATEST_HEIGHT_REQUEST,
            pb.GET_LATEST_HEIGHT_RESPONSE)({})
        async for resp in call:
            yield resp.get("height", 0)


class BlockResultsServiceClient(_BaseClient):
    async def get_block_results(self, height: int = 0) -> dict:
        return await self._unary(
            pb.BLOCK_RESULTS_SERVICE, "GetBlockResults",
            pb.GET_BLOCK_RESULTS_REQUEST,
            pb.GET_BLOCK_RESULTS_RESPONSE)(
                {"height": height} if height else {})


class PruningServiceClient(_BaseClient):
    """Privileged client (reference: rpc/grpc/client/privileged.go)."""

    async def set_block_retain_height(self, height: int) -> None:
        await self._unary(
            pb.PRUNING_SERVICE, "SetBlockRetainHeight",
            pb.SET_BLOCK_RETAIN_HEIGHT_REQUEST,
            pb.SET_BLOCK_RETAIN_HEIGHT_RESPONSE)({"height": height})

    async def get_block_retain_height(self) -> dict:
        return await self._unary(
            pb.PRUNING_SERVICE, "GetBlockRetainHeight",
            pb.GET_BLOCK_RETAIN_HEIGHT_REQUEST,
            pb.GET_BLOCK_RETAIN_HEIGHT_RESPONSE)({})

    async def set_block_results_retain_height(self, height: int) -> None:
        await self._unary(
            pb.PRUNING_SERVICE, "SetBlockResultsRetainHeight",
            pb.SET_BLOCK_RESULTS_RETAIN_HEIGHT_REQUEST,
            pb.SET_BLOCK_RESULTS_RETAIN_HEIGHT_RESPONSE)(
                {"height": height})

    async def get_block_results_retain_height(self) -> int:
        resp = await self._unary(
            pb.PRUNING_SERVICE, "GetBlockResultsRetainHeight",
            pb.GET_BLOCK_RESULTS_RETAIN_HEIGHT_REQUEST,
            pb.GET_BLOCK_RESULTS_RETAIN_HEIGHT_RESPONSE)({})
        return resp.get("pruning_service_retain_height", 0)

    async def set_tx_indexer_retain_height(self, height: int) -> None:
        await self._unary(
            pb.PRUNING_SERVICE, "SetTxIndexerRetainHeight",
            pb.SET_TX_INDEXER_RETAIN_HEIGHT_REQUEST,
            pb.SET_TX_INDEXER_RETAIN_HEIGHT_RESPONSE)(
                {"height": height})

    async def get_tx_indexer_retain_height(self) -> int:
        resp = await self._unary(
            pb.PRUNING_SERVICE, "GetTxIndexerRetainHeight",
            pb.GET_TX_INDEXER_RETAIN_HEIGHT_REQUEST,
            pb.GET_TX_INDEXER_RETAIN_HEIGHT_RESPONSE)({})
        return resp.get("height", 0)

    async def set_block_indexer_retain_height(self, height: int) -> None:
        await self._unary(
            pb.PRUNING_SERVICE, "SetBlockIndexerRetainHeight",
            pb.SET_BLOCK_INDEXER_RETAIN_HEIGHT_REQUEST,
            pb.SET_BLOCK_INDEXER_RETAIN_HEIGHT_RESPONSE)(
                {"height": height})

    async def get_block_indexer_retain_height(self) -> int:
        resp = await self._unary(
            pb.PRUNING_SERVICE, "GetBlockIndexerRetainHeight",
            pb.GET_BLOCK_INDEXER_RETAIN_HEIGHT_REQUEST,
            pb.GET_BLOCK_INDEXER_RETAIN_HEIGHT_RESPONSE)({})
        return resp.get("height", 0)
