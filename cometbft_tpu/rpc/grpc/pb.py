"""Message descriptors for the data-companion gRPC services.

Reference: proto/cometbft/services/{block,block_results,pruning,
version}/v1/*.proto — field numbers and wire kinds mirror those
schemas exactly.
"""
from ...wire.proto import F, Msg
from ...wire.pb import BLOCK, BLOCK_ID, CONSENSUS_PARAMS
from ...wire.abci_pb import EVENT, EXEC_TX_RESULT, VALIDATOR_UPDATE

# -- cometbft.services.version.v1 -------------------------------------------

GET_VERSION_REQUEST = Msg("cometbft.services.version.v1.GetVersionRequest")
GET_VERSION_RESPONSE = Msg(
    "cometbft.services.version.v1.GetVersionResponse",
    F(1, "node", "string"),
    F(2, "abci", "string"),
    F(3, "p2p", "uint64"),
    F(4, "block", "uint64"),
)

# -- cometbft.services.block.v1 ---------------------------------------------

GET_BY_HEIGHT_REQUEST = Msg(
    "cometbft.services.block.v1.GetByHeightRequest",
    F(1, "height", "int64"),
)
GET_BY_HEIGHT_RESPONSE = Msg(
    "cometbft.services.block.v1.GetByHeightResponse",
    F(1, "block_id", "msg", msg=BLOCK_ID),
    F(2, "block", "msg", msg=BLOCK),
)
GET_LATEST_HEIGHT_REQUEST = Msg(
    "cometbft.services.block.v1.GetLatestHeightRequest")
GET_LATEST_HEIGHT_RESPONSE = Msg(
    "cometbft.services.block.v1.GetLatestHeightResponse",
    F(1, "height", "int64"),
)

# -- cometbft.services.block_results.v1 -------------------------------------

GET_BLOCK_RESULTS_REQUEST = Msg(
    "cometbft.services.block_results.v1.GetBlockResultsRequest",
    F(1, "height", "int64"),
)
GET_BLOCK_RESULTS_RESPONSE = Msg(
    "cometbft.services.block_results.v1.GetBlockResultsResponse",
    F(1, "height", "int64"),
    F(2, "tx_results", "msg", msg=EXEC_TX_RESULT, repeated=True),
    F(3, "finalize_block_events", "msg", msg=EVENT, repeated=True),
    F(4, "validator_updates", "msg", msg=VALIDATOR_UPDATE,
      repeated=True),
    F(5, "consensus_param_updates", "msg", msg=CONSENSUS_PARAMS),
    F(6, "app_hash", "bytes"),
)

# -- cometbft.services.pruning.v1 -------------------------------------------


def _set_req(name: str) -> Msg:
    return Msg(f"cometbft.services.pruning.v1.{name}",
               F(1, "height", "uint64"))


def _empty(name: str) -> Msg:
    return Msg(f"cometbft.services.pruning.v1.{name}")


SET_BLOCK_RETAIN_HEIGHT_REQUEST = _set_req("SetBlockRetainHeightRequest")
SET_BLOCK_RETAIN_HEIGHT_RESPONSE = _empty("SetBlockRetainHeightResponse")
GET_BLOCK_RETAIN_HEIGHT_REQUEST = _empty("GetBlockRetainHeightRequest")
GET_BLOCK_RETAIN_HEIGHT_RESPONSE = Msg(
    "cometbft.services.pruning.v1.GetBlockRetainHeightResponse",
    F(1, "app_retain_height", "uint64"),
    F(2, "pruning_service_retain_height", "uint64"),
)
SET_BLOCK_RESULTS_RETAIN_HEIGHT_REQUEST = \
    _set_req("SetBlockResultsRetainHeightRequest")
SET_BLOCK_RESULTS_RETAIN_HEIGHT_RESPONSE = \
    _empty("SetBlockResultsRetainHeightResponse")
GET_BLOCK_RESULTS_RETAIN_HEIGHT_REQUEST = \
    _empty("GetBlockResultsRetainHeightRequest")
GET_BLOCK_RESULTS_RETAIN_HEIGHT_RESPONSE = Msg(
    "cometbft.services.pruning.v1.GetBlockResultsRetainHeightResponse",
    F(1, "pruning_service_retain_height", "uint64"),
)
SET_TX_INDEXER_RETAIN_HEIGHT_REQUEST = \
    _set_req("SetTxIndexerRetainHeightRequest")
SET_TX_INDEXER_RETAIN_HEIGHT_RESPONSE = \
    _empty("SetTxIndexerRetainHeightResponse")
GET_TX_INDEXER_RETAIN_HEIGHT_REQUEST = \
    _empty("GetTxIndexerRetainHeightRequest")
GET_TX_INDEXER_RETAIN_HEIGHT_RESPONSE = Msg(
    "cometbft.services.pruning.v1.GetTxIndexerRetainHeightResponse",
    F(1, "height", "uint64"),
)
SET_BLOCK_INDEXER_RETAIN_HEIGHT_REQUEST = \
    _set_req("SetBlockIndexerRetainHeightRequest")
SET_BLOCK_INDEXER_RETAIN_HEIGHT_RESPONSE = \
    _empty("SetBlockIndexerRetainHeightResponse")
GET_BLOCK_INDEXER_RETAIN_HEIGHT_REQUEST = \
    _empty("GetBlockIndexerRetainHeightRequest")
GET_BLOCK_INDEXER_RETAIN_HEIGHT_RESPONSE = Msg(
    "cometbft.services.pruning.v1.GetBlockIndexerRetainHeightResponse",
    F(1, "height", "uint64"),
)

# -- full gRPC method names --------------------------------------------------

VERSION_SERVICE = "cometbft.services.version.v1.VersionService"
BLOCK_SERVICE = "cometbft.services.block.v1.BlockService"
BLOCK_RESULTS_SERVICE = \
    "cometbft.services.block_results.v1.BlockResultsService"
PRUNING_SERVICE = "cometbft.services.pruning.v1.PruningService"
