"""gRPC data-companion API.

Reference: rpc/grpc/server/services/{versionservice,blockservice,
blockresultservice,pruningservice} and the corresponding
proto/cometbft/services/*/v1 schemas.  Real gRPC on the wire
(grpc.aio with generic handlers); messages are encoded with the
engine's descriptor codec (wire/proto.py), so no generated stubs are
needed.
"""
from .server import GRPCServer
from .client import (VersionServiceClient, BlockServiceClient,
                     BlockResultsServiceClient, PruningServiceClient)

__all__ = [
    "GRPCServer", "VersionServiceClient", "BlockServiceClient",
    "BlockResultsServiceClient", "PruningServiceClient",
]
