"""gRPC data-companion server.

Reference: rpc/grpc/server/server.go (Serve/ServePrivileged) and the
four services under rpc/grpc/server/services/.  Built on grpc.aio
generic handlers — each method is registered by full name with the
engine's descriptor codec as (de)serializer, which keeps the wire
format identical to the reference schemas without generated stubs.
"""
from __future__ import annotations

import asyncio
from typing import Optional

import grpc

from ...libs.log import Logger, new_logger
from ...wire import encode, decode
from ... import version as ver
from . import pb


def _grpc_addr(laddr: str) -> str:
    """tcp://host:port → host:port (grpc target syntax)."""
    if "://" in laddr:
        laddr = laddr.split("://", 1)[1]
    return laddr


class _Handlers(grpc.GenericRpcHandler):
    """Routes /<service>/<method> to registered method handlers."""

    def __init__(self):
        self._methods: dict[str, grpc.RpcMethodHandler] = {}

    def add_unary(self, service: str, method: str, req, resp, fn):
        self._methods[f"/{service}/{method}"] = \
            grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=lambda b, d=req: decode(d, b),
                response_serializer=lambda m, d=resp: encode(d, m))

    def add_server_stream(self, service: str, method: str, req, resp,
                          fn):
        self._methods[f"/{service}/{method}"] = \
            grpc.unary_stream_rpc_method_handler(
                fn,
                request_deserializer=lambda b, d=req: decode(d, b),
                response_serializer=lambda m, d=resp: encode(d, m))

    def service(self, handler_call_details):
        return self._methods.get(handler_call_details.method)


class GRPCServer:
    """One listener exposing a configured subset of the companion
    services.  The pruning service belongs on a separate privileged
    listener (reference: config.go GRPCConfig.Privileged)."""

    def __init__(self, *, block_store=None, state_store=None,
                 event_bus=None, pruner=None,
                 version_service: bool = False,
                 block_service: bool = False,
                 block_results_service: bool = False,
                 pruning_service: bool = False,
                 logger: Optional[Logger] = None):
        self.block_store = block_store
        self.state_store = state_store
        self.event_bus = event_bus
        self.pruner = pruner
        self.logger = logger or new_logger("grpc")
        self._server: Optional[grpc.aio.Server] = None
        self.port: Optional[int] = None

        self._handlers = _Handlers()
        if version_service:
            self._register_version()
        if block_service:
            self._register_block()
        if block_results_service:
            self._register_block_results()
        if pruning_service:
            self._register_pruning()

    # -- lifecycle ---------------------------------------------------------
    async def start(self, laddr: str) -> int:
        # blocks can exceed gRPC's default 4 MiB message cap
        from ...abci.grpc import GRPC_OPTIONS
        self._server = grpc.aio.server(options=GRPC_OPTIONS)
        self._server.add_generic_rpc_handlers((self._handlers,))
        self.port = self._server.add_insecure_port(_grpc_addr(laddr))
        await self._server.start()
        self.logger.info("gRPC server listening", addr=laddr,
                         port=self.port)
        return self.port

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=1.0)
            self._server = None

    # -- version service ---------------------------------------------------
    def _register_version(self) -> None:
        async def get_version(req, ctx):
            return {"node": ver.CMT_SEM_VER, "abci": ver.ABCI_SEM_VER,
                    "p2p": ver.P2P_PROTOCOL,
                    "block": ver.BLOCK_PROTOCOL}
        self._handlers.add_unary(
            pb.VERSION_SERVICE, "GetVersion",
            pb.GET_VERSION_REQUEST, pb.GET_VERSION_RESPONSE,
            get_version)

    # -- block service -----------------------------------------------------
    def _register_block(self) -> None:
        async def get_by_height(req, ctx):
            height = req.get("height", 0)
            store = self.block_store
            if height == 0:
                height = store.height
            if height < store.base or height > store.height:
                await ctx.abort(
                    grpc.StatusCode.NOT_FOUND,
                    f"height {height} not in store "
                    f"[{store.base},{store.height}]")
            block = store.load_block(height)
            meta = store.load_block_meta(height)
            if block is None or meta is None:
                await ctx.abort(grpc.StatusCode.NOT_FOUND,
                                f"no block at height {height}")
            return {"block_id": meta.block_id.to_proto(),
                    "block": block.to_proto()}

        async def get_latest_height(req, ctx):
            """Long-lived stream of committed heights (reference:
            blockservice GetLatestHeight)."""
            from ...types import events as ev
            sub = self.event_bus.subscribe(
                f"grpc-latest-height-{id(ctx)}",
                ev.EVENT_QUERY_NEW_BLOCK_HEADER, out_capacity=16)
            try:
                h = self.block_store.height
                if h > 0:
                    yield {"height": h}
                while True:
                    msg = await sub.next()
                    yield {"height": msg.data.payload["header"].height}
            finally:
                self.event_bus.unsubscribe_all(
                    f"grpc-latest-height-{id(ctx)}")

        self._handlers.add_unary(
            pb.BLOCK_SERVICE, "GetByHeight",
            pb.GET_BY_HEIGHT_REQUEST, pb.GET_BY_HEIGHT_RESPONSE,
            get_by_height)
        self._handlers.add_server_stream(
            pb.BLOCK_SERVICE, "GetLatestHeight",
            pb.GET_LATEST_HEIGHT_REQUEST, pb.GET_LATEST_HEIGHT_RESPONSE,
            get_latest_height)

    # -- block results service ---------------------------------------------
    def _register_block_results(self) -> None:
        async def get_block_results(req, ctx):
            height = req.get("height", 0)
            if height == 0:
                height = self.block_store.height
            if height < 0 or height > self.block_store.height:
                await ctx.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                f"height {height} out of range")
            resp = self.state_store.load_finalize_block_response(height)
            if resp is None:
                await ctx.abort(grpc.StatusCode.NOT_FOUND,
                                f"no results for height {height}")
            from ...state.store import _fbr_to_proto
            d = _fbr_to_proto(resp)
            return {"height": height,
                    "tx_results": d.get("tx_results", []),
                    "finalize_block_events": d.get("events", []),
                    "validator_updates": d.get("validator_updates", []),
                    **({"consensus_param_updates":
                        d["consensus_param_updates"]}
                       if d.get("consensus_param_updates") else {}),
                    "app_hash": d.get("app_hash", b"")}

        self._handlers.add_unary(
            pb.BLOCK_RESULTS_SERVICE, "GetBlockResults",
            pb.GET_BLOCK_RESULTS_REQUEST, pb.GET_BLOCK_RESULTS_RESPONSE,
            get_block_results)

    # -- pruning service (privileged) --------------------------------------
    def _register_pruning(self) -> None:
        def _setter(set_fn):
            async def handler(req, ctx):
                try:
                    set_fn(req.get("height", 0))
                except ValueError as e:
                    await ctx.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                    str(e))
                return {}
            return handler

        p = self.pruner
        svc = pb.PRUNING_SERVICE
        add = self._handlers.add_unary

        add(svc, "SetBlockRetainHeight",
            pb.SET_BLOCK_RETAIN_HEIGHT_REQUEST,
            pb.SET_BLOCK_RETAIN_HEIGHT_RESPONSE,
            _setter(p.set_companion_retain_height))

        async def get_block_retain(req, ctx):
            return {"app_retain_height":
                    p.get_application_retain_height(),
                    "pruning_service_retain_height":
                    p.get_companion_retain_height()}
        add(svc, "GetBlockRetainHeight",
            pb.GET_BLOCK_RETAIN_HEIGHT_REQUEST,
            pb.GET_BLOCK_RETAIN_HEIGHT_RESPONSE, get_block_retain)

        add(svc, "SetBlockResultsRetainHeight",
            pb.SET_BLOCK_RESULTS_RETAIN_HEIGHT_REQUEST,
            pb.SET_BLOCK_RESULTS_RETAIN_HEIGHT_RESPONSE,
            _setter(p.set_abci_results_retain_height))

        async def get_results_retain(req, ctx):
            return {"pruning_service_retain_height":
                    p.get_abci_results_retain_height()}
        add(svc, "GetBlockResultsRetainHeight",
            pb.GET_BLOCK_RESULTS_RETAIN_HEIGHT_REQUEST,
            pb.GET_BLOCK_RESULTS_RETAIN_HEIGHT_RESPONSE,
            get_results_retain)

        add(svc, "SetTxIndexerRetainHeight",
            pb.SET_TX_INDEXER_RETAIN_HEIGHT_REQUEST,
            pb.SET_TX_INDEXER_RETAIN_HEIGHT_RESPONSE,
            _setter(p.set_tx_indexer_retain_height))

        async def get_tx_indexer_retain(req, ctx):
            return {"height": p.get_tx_indexer_retain_height()}
        add(svc, "GetTxIndexerRetainHeight",
            pb.GET_TX_INDEXER_RETAIN_HEIGHT_REQUEST,
            pb.GET_TX_INDEXER_RETAIN_HEIGHT_RESPONSE,
            get_tx_indexer_retain)

        add(svc, "SetBlockIndexerRetainHeight",
            pb.SET_BLOCK_INDEXER_RETAIN_HEIGHT_REQUEST,
            pb.SET_BLOCK_INDEXER_RETAIN_HEIGHT_RESPONSE,
            _setter(p.set_block_indexer_retain_height))

        async def get_block_indexer_retain(req, ctx):
            return {"height": p.get_block_indexer_retain_height()}
        add(svc, "GetBlockIndexerRetainHeight",
            pb.GET_BLOCK_INDEXER_RETAIN_HEIGHT_REQUEST,
            pb.GET_BLOCK_INDEXER_RETAIN_HEIGHT_RESPONSE,
            get_block_indexer_retain)
