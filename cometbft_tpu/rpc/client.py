"""RPC clients: JSON-RPC over HTTP + WebSocket event subscriptions.

Reference: rpc/client/http (Client + wsEvents) — the client library the
light provider, statesync state provider, and e2e tests depend on.
Includes the JSON -> typed parsers that invert rpc/core's response
serializers (hex hashes, base64 bytes, stringified int64s).
"""
from __future__ import annotations

import asyncio
import base64
import json
from typing import AsyncIterator, Optional
from urllib.parse import urlsplit

from ..types.block import Header, ConsensusVersion, LightBlock, SignedHeader
from ..types.block_id import BlockID
from ..types.commit import Commit, CommitSig
from ..types.part_set import PartSetHeader
from ..types.timestamp import Timestamp
from ..types.validator import Validator
from ..types.validator_set import ValidatorSet
from ..types import genesis as genesis_types


class RPCClientError(Exception):
    pass


# --- JSON -> typed parsers (inverse of rpc/core serializers) ----------------

def block_id_from_json(d: dict) -> BlockID:
    parts = d.get("parts") or {}
    return BlockID(
        hash=bytes.fromhex(d.get("hash", "") or ""),
        part_set_header=PartSetHeader(
            total=int(parts.get("total", 0)),
            hash=bytes.fromhex(parts.get("hash", "") or "")))


def header_from_json(d: dict) -> Header:
    v = d.get("version") or {}
    return Header(
        version=ConsensusVersion(block=int(v.get("block", 0)),
                                 app=int(v.get("app", 0))),
        chain_id=d.get("chain_id", ""),
        height=int(d.get("height", 0)),
        time=Timestamp.from_rfc3339(d["time"]),
        last_block_id=block_id_from_json(d.get("last_block_id") or {}),
        last_commit_hash=bytes.fromhex(d.get("last_commit_hash", "")),
        data_hash=bytes.fromhex(d.get("data_hash", "")),
        validators_hash=bytes.fromhex(d.get("validators_hash", "")),
        next_validators_hash=bytes.fromhex(
            d.get("next_validators_hash", "")),
        consensus_hash=bytes.fromhex(d.get("consensus_hash", "")),
        app_hash=bytes.fromhex(d.get("app_hash", "")),
        last_results_hash=bytes.fromhex(d.get("last_results_hash", "")),
        evidence_hash=bytes.fromhex(d.get("evidence_hash", "")),
        proposer_address=bytes.fromhex(d.get("proposer_address", "")),
    )


def commit_from_json(d: dict):
    if "aggregate_signature" in d:
        # aggregate-commit chains (docs/aggregate_commits.md);
        # non-canonical bitmaps fail at the parse boundary exactly as
        # the proto decoder rejects them — a masked decode would hash
        # differently from what the server sent
        from ..libs.bits import BitArray
        from ..types.commit import AggregateCommit
        count = int(d.get("signer_count", 0))
        ba = BitArray.from_le_bytes(
            base64.b64decode(d.get("signers", "") or ""), count)
        return AggregateCommit(
            height=int(d.get("height", 0)),
            round=int(d.get("round", 0)),
            block_id=block_id_from_json(d.get("block_id") or {}),
            signers=ba,
            signature=base64.b64decode(
                d.get("aggregate_signature", "")))
    sigs = []
    for s in d.get("signatures", []):
        sig = s.get("signature")
        sigs.append(CommitSig(
            block_id_flag=int(s.get("block_id_flag", 0)),
            validator_address=bytes.fromhex(
                s.get("validator_address", "") or ""),
            timestamp=Timestamp.from_rfc3339(s["timestamp"])
            if s.get("timestamp") else Timestamp.zero(),
            signature=base64.b64decode(sig) if sig else b""))
    return Commit(
        height=int(d.get("height", 0)),
        round=int(d.get("round", 0)),
        block_id=block_id_from_json(d.get("block_id") or {}),
        signatures=sigs)


def validator_set_from_json(vals: list) -> ValidatorSet:
    out = []
    for v in vals:
        pub = genesis_types.pub_key_from_json(v["pub_key"])
        val = Validator(
            address=bytes.fromhex(v["address"]),
            pub_key=pub,
            voting_power=int(v["voting_power"]),
            proposer_priority=int(v.get("proposer_priority", 0)))
        out.append(val)
    # rebuild through the constructor (reference http provider does
    # types.NewValidatorSet too): proposer priorities are recomputed, which
    # is safe — the validator-set hash covers only pubkey/power
    return ValidatorSet(out)


# --- HTTP client -------------------------------------------------------------

class HTTPClient:
    """JSON-RPC 2.0 over HTTP POST (reference: rpc/client/http)."""

    def __init__(self, address: str, timeout: float = 10.0):
        """address: 'http://host:port' or 'tcp://host:port'."""
        u = urlsplit(address.replace("tcp://", "http://"))
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 26657
        self.timeout = timeout
        self._id = 0

    async def call(self, method: str, **params):
        self._id += 1
        body = json.dumps({"jsonrpc": "2.0", "id": self._id,
                           "method": method,
                           "params": _encode_params(params)}).encode()
        req = (f"POST / HTTP/1.1\r\nHost: {self.host}\r\n"
               f"Content-Type: application/json\r\n"
               f"Content-Length: {len(body)}\r\n"
               f"Connection: close\r\n\r\n").encode() + body
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), self.timeout)
        try:
            writer.write(req)
            await writer.drain()
            raw = await asyncio.wait_for(reader.read(-1), self.timeout)
        finally:
            writer.close()
        header, _, payload = raw.partition(b"\r\n\r\n")
        status = header.split(b" ", 2)[1:2]
        if not status or status[0] != b"200":
            raise RPCClientError(f"HTTP error: {header[:120]!r}")
        resp = json.loads(payload)
        if resp.get("error"):
            e = resp["error"]
            raise RPCClientError(
                f"{e.get('message')} ({e.get('code')}): {e.get('data')}")
        return resp.get("result")

    # -- typed helpers ----------------------------------------------------
    async def status(self) -> dict:
        return await self.call("status")

    async def health(self) -> dict:
        return await self.call("health")

    async def abci_query(self, path: str, data: bytes,
                         height: int = 0, prove: bool = False) -> dict:
        return await self.call("abci_query", path=path,
                               data=data.hex(), height=str(height),
                               prove=prove)

    async def broadcast_tx_sync(self, tx: bytes) -> dict:
        return await self.call("broadcast_tx_sync",
                               tx=base64.b64encode(tx).decode())

    async def broadcast_tx_async(self, tx: bytes) -> dict:
        return await self.call("broadcast_tx_async",
                               tx=base64.b64encode(tx).decode())

    async def broadcast_tx_commit(self, tx: bytes) -> dict:
        return await self.call("broadcast_tx_commit",
                               tx=base64.b64encode(tx).decode())

    async def block(self, height: int = 0) -> dict:
        return await self.call("block", height=str(height))

    async def commit(self, height: int = 0
                     ) -> tuple[SignedHeader, bool]:
        res = await self.call("commit", height=str(height))
        sh = res["signed_header"]
        return (SignedHeader(header=header_from_json(sh["header"]),
                             commit=commit_from_json(sh["commit"])),
                bool(res.get("canonical")))

    async def validators(self, height: int = 0) -> ValidatorSet:
        """Pages through /validators to assemble the full set
        (reference: light provider paging)."""
        vals: list = []
        page = 1
        while True:
            res = await self.call("validators", height=str(height),
                                  page=str(page), per_page="100")
            vals.extend(res.get("validators", []))
            if len(vals) >= int(res.get("total", len(vals))) or \
                    not res.get("validators"):
                break
            page += 1
        return validator_set_from_json(vals)

    async def light_block(self, height: int = 0):
        """One-round-trip signed header + validator set from the
        lightserve route (docs/light_proofs.md)."""
        from ..types.block import LightBlock
        res = await self.call("light_block", height=str(height))
        lb = res["light_block"]
        sh = lb["signed_header"]
        return LightBlock(
            signed_header=SignedHeader(
                header=header_from_json(sh["header"]),
                commit=commit_from_json(sh["commit"])),
            validator_set=validator_set_from_json(
                lb["validator_set"]["validators"]))

    async def genesis(self) -> dict:
        return await self.call("genesis")

    async def consensus_params(self, height: int = 0) -> dict:
        return await self.call("consensus_params", height=str(height))

    async def tx(self, hash_: bytes) -> dict:
        return await self.call("tx", hash=hash_.hex())


def _encode_params(params: dict) -> dict:
    out = {}
    for k, v in params.items():
        if isinstance(v, bytes):
            v = base64.b64encode(v).decode()
        out[k] = v
    return out


# --- WebSocket client --------------------------------------------------------

class WSClient:
    """WebSocket JSON-RPC client with subscriptions (reference:
    rpc/client/http wsEvents)."""

    def __init__(self, address: str):
        u = urlsplit(address.replace("tcp://", "http://")
                     .replace("ws://", "http://"))
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or 26657
        self._id = 0
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._recv_task: Optional[asyncio.Task] = None
        self._pending: dict[object, asyncio.Future] = {}
        self._subs: dict[object, asyncio.Queue] = {}

    async def connect(self) -> None:
        import os
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port)
        key = base64.b64encode(os.urandom(16)).decode()
        self._writer.write(
            (f"GET /websocket HTTP/1.1\r\nHost: {self.host}\r\n"
             f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
             f"Sec-WebSocket-Key: {key}\r\n"
             f"Sec-WebSocket-Version: 13\r\n\r\n").encode())
        await self._writer.drain()
        status = await self._reader.readline()
        if b"101" not in status:
            raise RPCClientError(f"ws handshake failed: {status!r}")
        while True:
            line = await self._reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
        self._recv_task = asyncio.create_task(self._recv_loop())

    async def close(self) -> None:
        if self._recv_task:
            self._recv_task.cancel()
        if self._writer:
            try:
                self._writer.close()
            except Exception:
                pass

    async def _recv_loop(self) -> None:
        from .ws import OP_CLOSE, OP_PING, OP_TEXT, frame, read_message

        async def on_control(op, payload):
            if op == OP_PING:
                await self._send_raw(frame(OP_PONG, payload, mask=True))

        try:
            while True:
                op, data = await read_message(self._reader, on_control)
                if op == OP_CLOSE:
                    return
                if op != OP_TEXT:
                    continue
                msg = json.loads(data)
                rpc_id = msg.get("id")
                if rpc_id in self._subs and "result" in msg and \
                        isinstance(msg["result"], dict) and \
                        "query" in msg["result"]:
                    self._subs[rpc_id].put_nowait(msg["result"])
                    continue
                fut = self._pending.pop(rpc_id, None)
                if fut is not None and not fut.done():
                    if msg.get("error"):
                        fut.set_exception(RPCClientError(
                            str(msg["error"])))
                    else:
                        fut.set_result(msg.get("result"))
        except (asyncio.CancelledError, asyncio.IncompleteReadError,
                ConnectionError):
            pass
        finally:
            # connection gone: fail every caller still awaiting a reply
            # and wake subscription readers with a sentinel error
            err = RPCClientError("websocket connection closed")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()
            for queue in self._subs.values():
                queue.put_nowait(_WS_CLOSED)
            self._subs.clear()

    async def _send_raw(self, data: bytes) -> None:
        self._writer.write(data)
        await self._writer.drain()

    async def call(self, method: str, **params):
        from .ws import OP_TEXT, frame
        self._id += 1
        rpc_id = self._id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rpc_id] = fut
        body = json.dumps({"jsonrpc": "2.0", "id": rpc_id,
                           "method": method, "params": params}).encode()
        await self._send_raw(frame(OP_TEXT, body, mask=True))
        return await fut

    async def subscribe(self, query: str) -> "WsSubscription":
        """Subscribe; returned object yields event payloads."""
        self._id += 1
        rpc_id = self._id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rpc_id] = fut
        queue: asyncio.Queue = asyncio.Queue()
        self._subs[rpc_id] = queue
        from .ws import OP_TEXT, frame
        body = json.dumps({"jsonrpc": "2.0", "id": rpc_id,
                           "method": "subscribe",
                           "params": {"query": query}}).encode()
        await self._send_raw(frame(OP_TEXT, body, mask=True))
        await fut
        return WsSubscription(self, rpc_id, query, queue)

    async def unsubscribe(self, query: str) -> None:
        await self.call("unsubscribe", query=query)


_WS_CLOSED = object()


class WsSubscription:
    def __init__(self, client: WSClient, rpc_id, query: str,
                 queue: asyncio.Queue):
        self.client = client
        self.rpc_id = rpc_id
        self.query = query
        self._queue = queue

    async def next(self, timeout: Optional[float] = None) -> dict:
        if timeout is None:
            item = await self._queue.get()
        else:
            item = await asyncio.wait_for(self._queue.get(), timeout)
        if item is _WS_CLOSED:
            raise RPCClientError("websocket connection closed")
        return item

    def __aiter__(self) -> AsyncIterator[dict]:
        return self

    async def __anext__(self) -> dict:
        item = await self._queue.get()
        if item is _WS_CLOSED:
            raise StopAsyncIteration
        return item
