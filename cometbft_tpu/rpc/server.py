"""JSON-RPC 2.0 server over HTTP.

Reference: rpc/jsonrpc/server/ (http_json_handler.go POST dispatch,
http_uri_handler.go GET-with-query-params), rpc/core/routes.go (method
table), rpc/core/env.go (the environment of store/mempool/consensus
references the methods close over).  asyncio-native minimal HTTP/1.1 —
the RPC surface, not a general web server.
"""
from __future__ import annotations

import asyncio
import base64
import json
from typing import Optional
from urllib.parse import parse_qsl, urlsplit

from ..config import RPCConfig
from ..libs.log import new_logger
from . import core


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(message)
        self.code = code
        self.message = message
        self.data = data


class RPCServer:
    def __init__(self, node, config: RPCConfig, routes=None):
        """`routes` overrides the method table (used by the light
        verifying proxy, which has no local node)."""
        self.node = node
        self.config = config
        self.logger = new_logger("rpc")
        self.env = core.Environment(node) if node is not None else None
        self.routes = routes if routes is not None \
            else core.routes(self.env)
        self._server: Optional[asyncio.base_events.Server] = None
        self.listen_addr = ""
        self._ws_counter = 0

    async def start(self) -> None:
        addr = self.config.laddr.replace("tcp://", "")
        host, port = addr.rsplit(":", 1)
        self._server = await asyncio.start_server(
            self._handle_conn, host or "127.0.0.1", int(port))
        sock = self._server.sockets[0].getsockname()
        self.listen_addr = f"{sock[0]}:{sock[1]}"
        self.logger.info("RPC listening", addr=self.listen_addr)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()

    @property
    def port(self) -> int:
        return int(self.listen_addr.rsplit(":", 1)[1])

    # ------------------------------------------------------------------
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                request_line = await reader.readline()
                if not request_line:
                    return
                try:
                    method, target, _ = \
                        request_line.decode().strip().split(" ", 2)
                except ValueError:
                    return
                headers = {}
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = line.decode().partition(":")
                    headers[k.strip().lower()] = v.strip()
                if http_method_is_metrics(method, target):
                    payload, ctype = self._render_metrics(target)
                    writer.write(
                        b"HTTP/1.1 200 OK\r\n"
                        b"Content-Type: " + ctype + b"\r\n"
                        b"Content-Length: " +
                        str(len(payload)).encode() + b"\r\n"
                        b"Connection: keep-alive\r\n\r\n" + payload)
                    await writer.drain()
                    if headers.get("connection", "").lower() == "close":
                        break
                    continue
                if headers.get("upgrade", "").lower() == "websocket":
                    # reference: ws_handler.go — the /websocket endpoint
                    from .ws import WsSession
                    self._ws_counter += 1
                    peer = writer.get_extra_info("peername")
                    remote = f"{peer}#{self._ws_counter}"
                    await WsSession(self, reader, writer, remote).run(
                        headers)
                    return
                body = b""
                clen = int(headers.get("content-length", 0) or 0)
                if clen:
                    if clen > self.config.max_body_bytes:
                        return
                    body = await reader.readexactly(clen)
                resp = await self._dispatch(method, target, body)
                payload = json.dumps(resp).encode()
                writer.write(
                    b"HTTP/1.1 200 OK\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: " +
                    str(len(payload)).encode() + b"\r\n"
                    b"Connection: keep-alive\r\n\r\n" + payload)
                await writer.drain()
                if headers.get("connection", "").lower() == "close":
                    break
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _render_metrics(self, target: str) -> tuple[bytes, bytes]:
        """The Prometheus exposition page: the node registry merged
        with the process-global DEFAULT (crypto batch-verify /
        kernel-dispatch histograms, breaker state — families fed below
        the node seam).  ``?exemplars=1`` switches to OpenMetrics with
        per-bucket trace-height exemplars."""
        reg = getattr(self.node, "metrics_registry", None)
        if reg is None:
            return b"# metrics disabled\n", b"text/plain; version=0.0.4"
        from ..libs import metrics as libmetrics
        try:
            params = dict(parse_qsl(urlsplit(target).query))
        except ValueError:
            params = {}
        exemplars = params.get("exemplars", "") in ("1", "true")
        payload = libmetrics.render_merged(
            reg, libmetrics.DEFAULT, exemplars=exemplars).encode()
        if exemplars:
            # OpenMetrics requires the explicit EOF terminator —
            # conforming parsers reject a page without it as truncated
            return payload + b"# EOF\n", \
                b"application/openmetrics-text; version=1.0.0"
        return payload, b"text/plain; version=0.0.4"

    async def _dispatch(self, http_method: str, target: str,
                        body: bytes) -> dict:
        if http_method == "POST":
            try:
                req = json.loads(body or b"{}")
            except (json.JSONDecodeError, UnicodeDecodeError) as e:
                # invalid UTF-8 raises UnicodeDecodeError, not
                # JSONDecodeError — both are the client's parse error,
                # not a server crash (found by the input fuzzer)
                return _err_response(None, -32700,
                                     "Parse error", str(e))
            if isinstance(req, list):
                if not req:
                    # JSON-RPC 2.0: an empty batch is itself invalid
                    # and gets a single error object, not []
                    return _err_response(None, -32600,
                                         "Invalid request",
                                         "empty batch")
                return [await self._call_one(r) for r in req]
            return await self._call_one(req)
        # URI over GET: /method?param=value
        try:
            parts = urlsplit(target)
        except ValueError as e:
            # e.g. "//[" -> "Invalid IPv6 URL" (found by the fuzzer)
            return _err_response(None, -32700, "Parse error", str(e))
        name = parts.path.lstrip("/")
        if not name:
            return _err_response(
                None, -32601, "Method not found",
                "available: " + ", ".join(sorted(self.routes)))
        params = {k: _parse_uri_value(v)
                  for k, v in parse_qsl(parts.query)}
        return await self._call(name, params, rpc_id=-1)

    async def _call_one(self, req: dict) -> dict:
        if not isinstance(req, dict):
            # valid JSON that isn't a request object (e.g. `1`,
            # `"str"`, or such an element inside a batch) — JSON-RPC
            # Invalid Request, not a server crash (found by the fuzzer)
            return _err_response(None, -32600, "Invalid request",
                                 "request must be an object")
        rpc_id = req.get("id")
        name = req.get("method", "")
        if not isinstance(name, str):
            # "method" may be any JSON value on the wire (the fuzzer
            # sent a dict, which is unhashable and crashed the route
            # lookup) — JSON-RPC Invalid Request, not a server error
            return _err_response(rpc_id, -32600, "Invalid request",
                                 "method must be a string")
        params = req.get("params")
        if params is None:
            params = {}
        if isinstance(params, list):
            return _err_response(rpc_id, -32602,
                                 "Invalid params",
                                 "positional params not supported")
        if not isinstance(params, dict):
            return _err_response(rpc_id, -32602, "Invalid params",
                                 "params must be an object")
        return await self._call(name, params, rpc_id)

    async def _call(self, name: str, params: dict, rpc_id) -> dict:
        fn = self.routes.get(name)
        if fn is None:
            return _err_response(
                rpc_id, -32601, "Method not found",
                "available: " + ", ".join(sorted(self.routes)))
        try:
            result = await fn(**params)
        except RPCError as e:
            return _err_response(rpc_id, e.code, e.message, e.data)
        except TypeError as e:
            return _err_response(rpc_id, -32602, "Invalid params",
                                 str(e))
        except Exception as e:
            # correlate the client-visible error with the server log
            # line via a trace id (reference: internal/rpctrace — "ask
            # the operator about error <uuid>" without leaking
            # internals to the caller)
            import uuid
            trace = uuid.uuid4().hex[:16]
            self.logger.error("RPC method failed", method=name,
                              err=str(e), trace=trace,
                              exc_info=True)
            return _err_response(
                rpc_id, -32603, "Internal error",
                f"error trace {trace} (see server log)")
        return {"jsonrpc": "2.0", "id": rpc_id, "result": result}


def http_method_is_metrics(method: str, target: str) -> bool:
    """GET /metrics — the Prometheus exposition endpoint (reference:
    node/node.go prometheusSrv + instrumentation config)."""
    return method == "GET" and target.split("?", 1)[0] == "/metrics"


def _err_response(rpc_id, code: int, message: str,
                  data: str = "") -> dict:
    return {"jsonrpc": "2.0", "id": rpc_id,
            "error": {"code": code, "message": message, "data": data}}


def _parse_uri_value(v: str):
    """URI params: 0x-hex → bytes-as-hex-string, quoted strings
    unquoted and tagged as raw (reference: http_uri_handler parsing —
    a quoted []byte param is the raw string content, not base64)."""
    if v.startswith('"') and v.endswith('"'):
        return core.UriString(v[1:-1])
    return v
