"""RPC method implementations.

Reference: rpc/core/ — env.go (the Environment), routes.go (the method
table), {status,blocks,mempool,abci,consensus,net}.go.  JSON shapes
follow the reference's response schemas (hex block hashes, base64 tx
bytes, stringified int64s).
"""
from __future__ import annotations

import base64
from typing import Optional

from ..abci import types as abci
from ..mempool.mempool import InvalidTxError, MempoolError, TxInCacheError
from ..types import genesis
from ..types.tx import tx_hash


class Environment:
    """Reference: rpc/core/env.go — references into the node."""

    def __init__(self, node):
        self.node = node

    @property
    def block_store(self):
        return self.node.block_store

    @property
    def state_store(self):
        return self.node.state_store

    @property
    def mempool(self):
        return self.node.mempool

    @property
    def consensus(self):
        return self.node.consensus_state


def routes(env: Environment) -> dict:
    """Reference: rpc/core/routes.go:15."""
    return {
        "health": lambda: _health(env),
        "status": lambda: _status(env),
        "net_info": lambda: _net_info(env),
        "genesis": lambda: _genesis(env),
        "genesis_chunked": lambda chunk="0":
            _genesis_chunked(env, chunk),
        "abci_info": lambda: _abci_info(env),
        "abci_query": lambda path="", data="", height="0",
        prove=False: _abci_query(env, path, data, height, prove),
        "broadcast_tx_sync": lambda tx="":
            _broadcast_tx_sync(env, tx),
        "broadcast_tx_async": lambda tx="":
            _broadcast_tx_async(env, tx),
        "broadcast_tx_commit": lambda tx="":
            _broadcast_tx_commit(env, tx),
        "unconfirmed_txs": lambda limit="30":
            _unconfirmed_txs(env, limit),
        "num_unconfirmed_txs": lambda: _num_unconfirmed_txs(env),
        "block": lambda height="0": _block(env, height),
        "block_by_hash": lambda hash="": _block_by_hash(env, hash),
        # lightserve: the proof-serving read surface (ROADMAP item 3;
        # cometbft_tpu/lightserve/, docs/light_proofs.md)
        "light_block": lambda height="0": _light_block(env, height),
        "multiproof": lambda height="0", indices="":
            _multiproof(env, height, indices),
        "abci_query_batch": lambda path="", data="", height="0",
        prove=False: _abci_query_batch(env, path, data, height, prove),
        "header": lambda height="0": _header(env, height),
        "header_by_hash": lambda hash="":
            _header_by_hash(env, hash),
        "check_tx": lambda tx="": _check_tx(env, tx),
        "unconfirmed_tx": lambda hash="":
            _unconfirmed_tx(env, hash),
        "block_results": lambda height="0": _block_results(env, height),
        "commit": lambda height="0": _commit(env, height),
        "blockchain": lambda minHeight="0", maxHeight="0":
            _blockchain(env, minHeight, maxHeight),
        "validators": lambda height="0", page="1", per_page="30":
            _validators(env, height, page, per_page),
        "consensus_state": lambda: _consensus_state(env),
        "dump_consensus_state": lambda:
            _dump_consensus_state(env),
        # flight recorder (libs/tracing.py): the per-height span
        # timeline every perf PR is judged with
        "trace": lambda height="0", category="", limit="0":
            _trace(env, height, category, limit),
        "consensus_params": lambda height="0":
            _consensus_params(env, height),
        "tx": lambda hash="", prove=False: _tx(env, hash),
        "tx_search": lambda query="", page="1", per_page="30",
        order_by="asc": _tx_search(env, query, page, per_page),
        "block_search": lambda query="", page="1", per_page="30",
        order_by="asc": _block_search(env, query, page, per_page),
        "broadcast_evidence": lambda evidence="":
            _broadcast_evidence(env, evidence),
        # data-companion pruning service (reference: rpc/grpc/server/
        # services/pruningservice — served here over JSON-RPC, the
        # engine's single RPC surface)
        "pruning_set_block_retain_height": lambda height="0":
            _pruning_set_retain(env, height),
        "pruning_get_block_retain_height": lambda:
            _pruning_get_retain(env),
        # control API — served only with rpc.unsafe (reference:
        # routes.go AddUnsafeRoutes); every handler re-checks the
        # config so the gate can't be bypassed by table drift
        "dial_seeds": lambda seeds="":
            _unsafe_dial_seeds(env, seeds),
        "dial_peers": lambda peers="", persistent=False,
        unconditional=False, private=False:
            _unsafe_dial_peers(env, peers, persistent, private),
        "unsafe_flush_mempool": lambda:
            _unsafe_flush_mempool(env),
    }


async def _health(env):
    """Readiness/lag plane (docs/observability.md): what a load
    balancer in front of the replica tier — or the QA soak gates —
    polls instead of scraping Prometheus.  Height lag is measured
    against the best height any peer has advertised (consensus
    round states while in consensus, the blocksync pool while
    syncing); the p95s are computed in-process from the live
    histograms."""
    node = env.node
    height = env.block_store.height
    best_peer = 0
    cr = getattr(node, "consensus_reactor", None)
    if cr is not None:
        for ps in list(cr._peer_states.values()):
            # prs.height is the height the peer is WORKING on; its
            # committed head is one behind
            best_peer = max(best_peer, ps.prs.height - 1)
    catching_up = bool(getattr(cr, "wait_sync", False))
    br = getattr(node, "blocksync_reactor", None)
    if br is not None and br.pool is not None:
        best_peer = max(best_peer, br.pool.max_peer_height())
    lag = max(0, best_peer - height)
    sw = getattr(node, "switch", None)
    n_peers = sw.num_peers() if sw is not None else 0
    mp = getattr(node, "mempool", None)
    barrier_p95 = 0.0
    cs = getattr(node, "consensus_state", None)
    if cs is not None:
        barrier_p95 = cs.metrics \
            .pipeline_barrier_wait_seconds.quantile(0.95)
    loop_lag_p95 = 0.0
    hm = getattr(node, "health_metrics", None)
    if hm is not None:
        loop_lag_p95 = hm.event_loop_lag_seconds.quantile(0.95)
    if catching_up:
        status = "syncing"
    elif lag > 2:
        status = "lagging"
    else:
        status = "ok"
    return {
        "status": status,
        "height": str(height),
        "best_peer_height": str(best_peer),
        "height_lag": str(lag),
        "catching_up": catching_up,
        "n_peers": str(n_peers),
        "mempool_txs": str(mp.size() if mp is not None else 0),
        "mempool_bytes": str(
            mp.size_bytes() if mp is not None else 0),
        "pipeline_barrier_wait_p95_s": round(barrier_p95, 6),
        "event_loop_lag_p95_s": round(loop_lag_p95, 6),
    }


async def _status(env):
    return env.node.status()


async def _net_info(env):
    sw = env.node.switch
    return {
        "listening": bool(sw.listen_addr),
        "listeners": [sw.listen_addr],
        "n_peers": str(sw.num_peers()),
        "peers": [
            {"node_info": {"id": p.id,
                           "moniker": p.node_info.moniker,
                           "network": p.node_info.network},
             "is_outbound": p.outbound,
             "remote_ip": p.remote_addr.rsplit(":", 1)[0]}
            for p in sw.peers.values()
        ],
    }


async def _genesis(env):
    import json as _json
    return {"genesis": _json.loads(env.node.genesis_doc.to_json())}


_GENESIS_CHUNK_SIZE = 16 * 1024 * 1024   # reference: 16 MB chunks


async def _genesis_chunked(env, chunk):
    """Reference: rpc/core/net.go GenesisChunked — the genesis JSON
    split into 16 MB base64 chunks so large genesis docs fit in one
    JSON-RPC response each.  Chunks are computed once per node (the
    genesis doc is immutable) and cached on the environment."""
    from .server import RPCError
    chunks = getattr(env, "_genesis_chunks", None)
    if chunks is None:
        raw = env.node.genesis_doc.to_json().encode()
        chunks = [raw[i:i + _GENESIS_CHUNK_SIZE]
                  for i in range(0, len(raw),
                                 _GENESIS_CHUNK_SIZE)] or [b""]
        env._genesis_chunks = chunks
    try:
        cid = int(chunk)
    except (TypeError, ValueError):
        raise RPCError(-32602, f"invalid chunk id {chunk!r}")
    if cid < 0 or cid >= len(chunks):
        raise RPCError(
            -32603, f"chunk id {cid} out of range [0, {len(chunks)})")
    return {"chunk": str(cid), "total": str(len(chunks)),
            "data": base64.b64encode(chunks[cid]).decode()}


async def _abci_info(env):
    res = await env.node.app_conns.query.info(abci.InfoRequest())
    return {"response": {
        "data": res.data, "version": res.version,
        "app_version": str(res.app_version),
        "last_block_height": str(res.last_block_height),
        "last_block_app_hash": base64.b64encode(
            res.last_block_app_hash).decode(),
    }}


async def _abci_query(env, path, data, height, prove):
    raw = _decode_hex_or_str(data)
    res = await env.node.app_conns.query.query(abci.QueryRequest(
        data=raw, path=path, height=int(height),
        prove=_parse_bool(prove)))
    return {"response": {
        "code": res.code, "log": res.log, "info": res.info,
        "index": str(res.index),
        "key": base64.b64encode(res.key).decode(),
        "value": base64.b64encode(res.value).decode(),
        "height": str(res.height), "codespace": res.codespace,
    }}


def _check_tx_result(tx: bytes, res) -> dict:
    return {
        "code": res.code, "data": base64.b64encode(res.data).decode(),
        "log": res.log, "codespace": res.codespace,
        "hash": tx_hash(tx).hex().upper(),
    }


async def _broadcast_tx_sync(env, tx):
    raw = _decode_tx(tx)
    try:
        res = await env.mempool.check_tx(raw)
    except InvalidTxError as e:
        return {"code": e.code, "data": "", "log": str(e),
                "codespace": "", "hash": tx_hash(raw).hex().upper()}
    except TxInCacheError:
        from .server import RPCError
        raise RPCError(-32603, "tx already exists in cache")
    except MempoolError as e:
        from .server import RPCError
        raise RPCError(-32603, str(e))
    return _check_tx_result(raw, res)


async def _broadcast_tx_async(env, tx):
    import asyncio as _asyncio
    raw = _decode_tx(tx)

    async def _bg():
        try:
            await env.mempool.check_tx(raw)
        except MempoolError:
            pass
    _asyncio.get_running_loop().create_task(_bg())
    return {"code": 0, "data": "", "log": "", "codespace": "",
            "hash": tx_hash(raw).hex().upper()}


async def _broadcast_tx_commit(env, tx):
    """CheckTx, then wait for the tx to land in a block (reference:
    rpc/core/mempool.go BroadcastTxCommit via event subscription)."""
    import asyncio as _asyncio
    raw = _decode_tx(tx)
    key = tx_hash(raw)
    sub = env.node.event_bus.subscribe(
        f"rpc-tx-{key.hex()[:16]}",
        f"tm.event = 'Tx' AND tx.hash = '{key.hex().upper()}'")
    try:
        try:
            check = await env.mempool.check_tx(raw)
        except InvalidTxError as e:
            return {"check_tx": {"code": e.code, "log": str(e)},
                    "tx_result": {}, "hash": key.hex().upper(),
                    "height": "0"}
        timeout = env.node.config.rpc \
            .timeout_broadcast_tx_commit_ns / 1e9
        try:
            msg = await _asyncio.wait_for(sub.next(), timeout)
        except _asyncio.TimeoutError:
            from .server import RPCError
            raise RPCError(-32603,
                           "timed out waiting for tx to be included "
                           "in a block")
        payload = msg.data.payload
        res = payload["result"]
        return {
            "check_tx": _check_tx_result(raw, check),
            "tx_result": {
                "code": res.code,
                "data": base64.b64encode(res.data).decode(),
                "log": res.log,
                "gas_wanted": str(res.gas_wanted),
                "gas_used": str(res.gas_used),
            },
            "hash": key.hex().upper(),
            "height": str(payload["height"]),
        }
    finally:
        try:
            env.node.event_bus.unsubscribe_all(
                f"rpc-tx-{key.hex()[:16]}")
        except Exception:
            pass


async def _pruning_set_retain(env, height):
    pruner = getattr(env.node, "pruner", None)
    if pruner is None:
        from .server import RPCError
        raise RPCError(-32603, "pruner unavailable")
    pruner.companion_enabled = True
    try:
        pruner.set_companion_retain_height(int(height))
    except ValueError as e:
        from .server import RPCError
        raise RPCError(-32602, str(e))
    return {}


async def _pruning_get_retain(env):
    pruner = getattr(env.node, "pruner", None)
    if pruner is None:
        from .server import RPCError
        raise RPCError(-32603, "pruner unavailable")
    return {
        "app_retain_height": str(
            pruner.get_application_retain_height()),
        "pruning_service_retain_height": str(
            pruner.get_companion_retain_height()),
    }


async def _broadcast_evidence(env, evidence):
    """Ingest wire-encoded evidence into the pool (reference:
    rpc/core/evidence.go BroadcastEvidence; used by the light client's
    report_evidence path)."""
    from ..types.evidence import evidence_from_proto_wrapped
    from ..wire import pb as _pb, decode as _decode
    raw = base64.b64decode(evidence)
    ev = evidence_from_proto_wrapped(_decode(_pb.EVIDENCE, raw))
    pool = getattr(env.node, "evidence_pool", None)
    if pool is None:
        from .server import RPCError
        raise RPCError(-32603, "evidence pool unavailable")
    pool.add_evidence(ev)
    return {"hash": ev.hash().hex().upper()}


async def _unconfirmed_txs(env, limit):
    txs = env.mempool.reap_max_txs(int(limit))
    return {
        "n_txs": str(len(txs)),
        "total": str(env.mempool.size()),
        "total_bytes": str(env.mempool.size_bytes()),
        "txs": [base64.b64encode(t).decode() for t in txs],
    }


async def _num_unconfirmed_txs(env):
    return {"n_txs": str(env.mempool.size()),
            "total": str(env.mempool.size()),
            "total_bytes": str(env.mempool.size_bytes())}


def _normalize_height(env, height) -> int:
    h = int(height)
    if h <= 0:
        return env.block_store.height
    return h


async def _cached(env, method: str, height: int, extra, build):
    """Serve ``method`` at ``height`` from the lightserve response
    cache when possible; otherwise build and (when the height is
    strictly below the tip, i.e. immutable) insert.  ``extra`` is the
    hashable remainder of the request key."""
    cache = getattr(env.node, "lightserve_cache", None) \
        if env.node is not None else None
    if cache is None:
        return await build()
    hit = cache.get(method, height, extra)
    if hit is not None:
        return hit
    res = await build()
    cache.put(method, height, extra, res,
              latest_height=env.block_store.height)
    return res


async def _block(env, height):
    h = _normalize_height(env, height)
    return await _cached(env, "block", h, (),
                         lambda: _build_block(env, h))


async def _build_block(env, h):
    block = env.block_store.load_block(h)
    meta = env.block_store.load_block_meta(h)
    if block is None or meta is None:
        from .server import RPCError
        raise RPCError(-32603, f"block at height {h} not found")
    return {"block_id": _block_id_json(meta.block_id),
            "block": _block_json(block)}


async def _light_block(env, height):
    from ..lightserve import core as lightserve
    h = _normalize_height(env, height)
    return await _cached(env, "light_block", h, (),
                         lambda: lightserve.light_block(env, h))


async def _multiproof(env, height, indices):
    from ..lightserve import core as lightserve
    h = _normalize_height(env, height)
    idx = tuple(sorted(set(lightserve.parse_indices(indices))))
    return await _cached(env, "multiproof", h, idx,
                         lambda: lightserve.tx_multiproof(env, h, idx))


async def _abci_query_batch(env, path, data, height, prove):
    from ..lightserve import core as lightserve

    def build():
        return lightserve.abci_query_batch(env, path, data, height,
                                           prove)
    try:
        h = int(height)
    except (TypeError, ValueError):
        h = 0
    if h <= 0 or not _parse_bool(prove):
        # height 0 = latest: mutable, never cached.  Unproven batches
        # fan out per key against whatever state the app serves —
        # also not immutable — while a proven batch at an explicit
        # height is pinned to that height's committed statetree
        # version, so it can be cached like any settled response.
        return await build()
    keys = tuple(k.hex() for k in lightserve._parse_keys(data))
    return await _cached(env, "abci_query_batch", h,
                         (str(path), keys), build)


async def _block_by_hash(env, hash):
    raw = _decode_hex_or_str(hash)
    block = env.block_store.load_block_by_hash(raw)
    meta = env.block_store.load_block_meta_by_hash(raw)
    if block is None or meta is None:
        from .server import RPCError
        raise RPCError(-32603, "block not found")
    return {"block_id": _block_id_json(meta.block_id),
            "block": _block_json(block)}


async def _header(env, height):
    """Reference: rpc/core/blocks.go Header."""
    h = _normalize_height(env, height)
    meta = env.block_store.load_block_meta(h)
    if meta is None:
        from .server import RPCError
        raise RPCError(-32603, f"header at height {h} not found")
    return {"header": _header_json(meta.header)}


async def _header_by_hash(env, hash):
    """Reference: rpc/core/blocks.go HeaderByHash."""
    raw = _decode_hex_or_str(hash)
    meta = env.block_store.load_block_meta_by_hash(raw)
    if meta is None:
        from .server import RPCError
        raise RPCError(-32603, "header not found")
    return {"header": _header_json(meta.header)}


async def _check_tx(env, tx):
    """Run CheckTx against the app without adding the tx to the
    mempool (reference: rpc/core/mempool.go CheckTx)."""
    raw = _decode_tx(tx)
    res = await env.node.app_conns.mempool.check_tx(
        abci.CheckTxRequest(tx=raw, type=abci.CHECK_TX_TYPE_CHECK))
    return {
        "code": res.code,
        "data": base64.b64encode(res.data).decode(),
        "log": res.log, "info": res.info,
        "gas_wanted": str(res.gas_wanted),
        "gas_used": str(res.gas_used),
        "events": _events_json(res.events),
        "codespace": res.codespace,
    }


async def _unconfirmed_tx(env, hash):
    """Reference: rpc/core/mempool.go UnconfirmedTx."""
    raw = _decode_hex_or_str(hash)
    tx = env.mempool.get_tx_by_hash(raw)
    if tx is None:
        from .server import RPCError
        raise RPCError(-32603, "tx not found in mempool")
    return {"tx": base64.b64encode(tx).decode()}


async def _block_results(env, height):
    h = _normalize_height(env, height)
    resp = env.state_store.load_finalize_block_response(h)
    if resp is None:
        from .server import RPCError
        raise RPCError(-32603, f"no results for height {h}")
    return {
        "height": str(h),
        "txs_results": [
            {"code": r.code,
             "data": base64.b64encode(r.data).decode(),
             "log": r.log, "gas_wanted": str(r.gas_wanted),
             "gas_used": str(r.gas_used),
             "events": _events_json(r.events)}
            for r in resp.tx_results],
        "finalize_block_events": _events_json(resp.events),
        "validator_updates": [
            {"pub_key_type": v.pub_key_type,
             "pub_key_bytes": base64.b64encode(
                 v.pub_key_bytes).decode(),
             "power": str(v.power)}
            for v in resp.validator_updates],
        "app_hash": resp.app_hash.hex().upper(),
    }


async def _commit(env, height):
    h = _normalize_height(env, height)
    # cache-safe: only heights below the tip are inserted (put
    # refuses the rest), and below the tip the commit is canonical
    return await _cached(env, "commit", h, (),
                         lambda: _build_commit(env, h))


async def _build_commit(env, h):
    meta = env.block_store.load_block_meta(h)
    commit = env.block_store.load_block_commit(h)
    canonical = True
    if commit is None:
        commit = env.block_store.load_seen_commit(h)
        canonical = False
    if meta is None or commit is None:
        from .server import RPCError
        raise RPCError(-32603, f"commit for height {h} not found")
    return {
        "signed_header": {
            "header": _header_json(meta.header),
            "commit": _commit_json(commit),
        },
        "canonical": canonical,
    }


async def _blockchain(env, min_height, max_height):
    base, height = env.block_store.base, env.block_store.height
    min_h = max(int(min_height) or base, base)
    max_h = min(int(max_height) or height, height)
    metas = []
    for h in range(max_h, min_h - 1, -1):
        m = env.block_store.load_block_meta(h)
        if m is not None:
            metas.append({
                "block_id": _block_id_json(m.block_id),
                "block_size": str(m.block_size),
                "header": _header_json(m.header),
                "num_txs": str(m.num_txs),
            })
    return {"last_height": str(height), "block_metas": metas}


async def _validators(env, height, page, per_page):
    h = _normalize_height(env, height)
    vals = env.state_store.load_validators(h)
    page_i, per = max(1, int(page)), min(100, int(per_page))
    start = (page_i - 1) * per
    sel = vals.validators[start:start + per]
    return {
        "block_height": str(h),
        "validators": [
            {"address": v.address.hex().upper(),
             "pub_key": genesis.pub_key_to_json(v.pub_key),
             "voting_power": str(v.voting_power),
             "proposer_priority": str(v.proposer_priority)}
            for v in sel],
        "count": str(len(sel)),
        "total": str(vals.size()),
    }


async def _consensus_state(env):
    rs = env.consensus.rs
    return {"round_state": {
        "height/round/step":
            f"{rs.height}/{rs.round}/{rs.step}",
        "start_time": rs.start_time.rfc3339(),
        "proposal_block_hash":
            rs.proposal_block.hash().hex().upper()
            if rs.proposal_block else "",
        "locked_block_hash":
            rs.locked_block.hash().hex().upper()
            if rs.locked_block else "",
        "valid_block_hash":
            rs.valid_block.hash().hex().upper()
            if rs.valid_block else "",
    }}


async def _trace(env, height, category, limit):
    """Flight-recorder timeline (libs/tracing.py): spans + instant
    events from the per-category ring buffers, strictly ordered by
    monotonic timestamp.  ?height=H keeps one height's events,
    ?category=consensus|crypto|p2p|mempool|abci keeps one ring,
    ?limit=N keeps the newest N."""
    from ..libs import tracing
    try:
        h = int(height or 0)
    except (TypeError, ValueError):
        h = 0
    try:
        lim = int(limit or 0)
    except (TypeError, ValueError):
        lim = 0
    events = tracing.snapshot(height=h if h > 0 else None,
                              category=str(category)
                              if category else None,
                              limit=lim)
    r = tracing.recorder()
    r.refresh_anchor()
    return {
        "enabled": tracing.enabled(),
        "count": len(events),
        "node": r.node_id,
        # (monotonic_ns, wall_ns) clock-anchor pairs: what lets
        # tools/fleet_report.py place this node's monotonic
        # timeline on a cluster-wide wall clock
        "anchors": [[str(m), str(w)] for m, w in r.anchors],
        # int64s ride as strings, the surface-wide convention
        "events": [{**e, "ts_ns": str(e["ts_ns"]),
                    "dur_ns": str(e["dur_ns"]),
                    "height": str(e["height"])} for e in events],
    }


def _vote_set_summary(vs) -> dict:
    if vs is None:
        return {}
    return {"bit_array": str(vs.bit_array()),
            "voting_power": str(vs.sum)}


async def _dump_consensus_state(env):
    """Full round state + what we believe each peer's round state is
    (reference: rpc/core/consensus.go DumpConsensusState)."""
    rs = env.consensus.rs
    round_state = {
        "height": str(rs.height), "round": rs.round,
        "step": rs.step_name(),
        "start_time": rs.start_time.rfc3339(),
        "commit_time": rs.commit_time.rfc3339(),
        "validators": {
            "validators": [
                {"address": v.address.hex().upper(),
                 "voting_power": str(v.voting_power),
                 "proposer_priority": str(v.proposer_priority)}
                for v in rs.validators.validators]
            if rs.validators else [],
            "proposer": {"address":
                         rs.validators.get_proposer()
                         .address.hex().upper()}
            if rs.validators and rs.validators.validators else {},
        },
        "proposal_block_hash":
            rs.proposal_block.hash().hex().upper()
            if rs.proposal_block else "",
        "locked_round": rs.locked_round,
        "locked_block_hash":
            rs.locked_block.hash().hex().upper()
            if rs.locked_block else "",
        "valid_round": rs.valid_round,
        "valid_block_hash":
            rs.valid_block.hash().hex().upper()
            if rs.valid_block else "",
        "commit_round": rs.commit_round,
        "votes": [
            {"round": r,
             "prevotes": _vote_set_summary(
                 rs.votes.prevotes(r)),
             "precommits": _vote_set_summary(
                 rs.votes.precommits(r))}
            for r in (sorted(rs.votes._round_vote_sets)
                      if rs.votes else [])],
        "last_commit": _vote_set_summary(rs.last_commit),
    }
    peers = []
    for p in env.node.switch.peers.values():
        ps = p.data.get("consensus_peer_state")
        if ps is None:
            continue
        prs = ps.prs
        peers.append({
            "node_address": p.remote_addr,
            "peer_state": {"round_state": {
                "height": str(prs.height), "round": prs.round,
                "step": prs.step,
                "proposal": prs.proposal,
                "proposal_pol_round": prs.proposal_pol_round,
                "prevotes": str(prs.prevotes or ""),
                "precommits": str(prs.precommits or ""),
                "last_commit_round": prs.last_commit_round,
                "catchup_commit_round": prs.catchup_commit_round,
            }},
        })
    return {"round_state": round_state, "peers": peers}


def _require_unsafe(env) -> None:
    if not env.node.config.rpc.unsafe:
        from .server import RPCError
        raise RPCError(
            -32601, "unsafe RPC commands disabled "
            "(enable with rpc.unsafe)")


async def _unsafe_dial_seeds(env, seeds):
    """Reference: rpc/core/net.go UnsafeDialSeeds."""
    _require_unsafe(env)
    addrs = [s for s in (seeds.split(",")
                         if isinstance(seeds, str) else seeds) if s]
    if not addrs:
        from .server import RPCError
        raise RPCError(-32602, "no seeds provided")
    env.node.switch.dial_peers_async(addrs, persistent=False)
    return {"log": "Dialing seeds in progress. "
                   "See /net_info for details"}


async def _unsafe_dial_peers(env, peers, persistent, private):
    """Reference: rpc/core/net.go UnsafeDialPeers.  (unconditional
    is accepted for wire compatibility but has no effect: the switch
    enforces no inbound peer cap to bypass.)"""
    _require_unsafe(env)
    addrs = [s for s in (peers.split(",")
                         if isinstance(peers, str) else peers) if s]
    if not addrs:
        from .server import RPCError
        raise RPCError(-32602, "no peers provided")
    if _parse_bool(private):
        if not all("@" in a for a in addrs):
            from .server import RPCError
            raise RPCError(
                -32602, "private peers must be id@host:port "
                "(privacy is keyed on the node id)")
        env.node.switch.private_ids.update(
            a.split("@", 1)[0] for a in addrs)
    env.node.switch.dial_peers_async(
        addrs, persistent=_parse_bool(persistent))
    return {"log": "Dialing peers in progress. "
                   "See /net_info for details"}


async def _unsafe_flush_mempool(env):
    """Reference: rpc/core/mempool.go UnsafeFlushMempool."""
    _require_unsafe(env)
    env.mempool.flush()
    return {}


async def _consensus_params(env, height):
    h = _normalize_height(env, height)
    params = env.state_store.load_consensus_params(h)
    return {"block_height": str(h), "consensus_params": {
        "block": {"max_bytes": str(params.block.max_bytes),
                  "max_gas": str(params.block.max_gas)},
        "evidence": {
            "max_age_num_blocks":
                str(params.evidence.max_age_num_blocks),
            "max_age_duration":
                str(params.evidence.max_age_duration_ns),
            "max_bytes": str(params.evidence.max_bytes)},
        "validator": {"pub_key_types":
                      list(params.validator.pub_key_types)},
    }}


def _tx_result_json(tr) -> dict:
    from ..types.tx import tx_hash
    return {
        "hash": tx_hash(tr.tx).hex().upper(),
        "height": str(tr.height),
        "index": tr.index,
        "tx_result": {
            "code": tr.result.code,
            "data": base64.b64encode(tr.result.data).decode(),
            "log": tr.result.log,
            "gas_wanted": str(tr.result.gas_wanted),
            "gas_used": str(tr.result.gas_used),
            "events": _events_json(tr.result.events),
        },
        "tx": base64.b64encode(tr.tx).decode(),
    }


async def _tx(env, hash):
    from .server import RPCError
    if env.node.tx_indexer is None:
        raise RPCError(-32603, "transaction indexing is disabled")
    raw = hash if isinstance(hash, bytes) else (
        bytes.fromhex(hash[2:]) if hash.startswith("0x")
        else bytes.fromhex(hash))
    tr = env.node.tx_indexer.get(raw)
    if tr is None:
        raise RPCError(-32603, f"tx {hash} not found")
    return _tx_result_json(tr)


async def _tx_search(env, query, page, per_page):
    from ..libs.pubsub import Query
    from .server import RPCError
    if env.node.tx_indexer is None:
        raise RPCError(-32603, "transaction indexing is disabled")
    hashes = env.node.tx_indexer.search(Query(query))
    page_i, per = max(1, int(page)), min(100, int(per_page))
    sel = hashes[(page_i - 1) * per:page_i * per]
    txs = [env.node.tx_indexer.get(h) for h in sel]
    return {"txs": [_tx_result_json(t) for t in txs if t],
            "total_count": str(len(hashes))}


async def _block_search(env, query, page, per_page):
    from ..libs.pubsub import Query
    from .server import RPCError
    if env.node.block_indexer is None:
        raise RPCError(-32603, "block indexing is disabled")
    heights = env.node.block_indexer.search(Query(query))
    page_i, per = max(1, int(page)), min(100, int(per_page))
    sel = heights[(page_i - 1) * per:page_i * per]
    blocks = []
    for h in sel:
        meta = env.block_store.load_block_meta(h)
        block = env.block_store.load_block(h)
        if meta and block:
            blocks.append({"block_id": _block_id_json(meta.block_id),
                           "block": _block_json(block)})
    return {"blocks": blocks, "total_count": str(len(heights))}


# ---------------------------------------------------------------------------
# JSON shaping helpers


def _block_id_json(bid) -> dict:
    return {"hash": bid.hash.hex().upper(),
            "parts": {"total": bid.part_set_header.total,
                      "hash": bid.part_set_header.hash.hex().upper()}}


def _header_json(h) -> dict:
    return {
        "version": {"block": str(h.version.block),
                    "app": str(h.version.app)},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": h.time.rfc3339(),
        "last_block_id": _block_id_json(h.last_block_id),
        "last_commit_hash": h.last_commit_hash.hex().upper(),
        "data_hash": h.data_hash.hex().upper(),
        "validators_hash": h.validators_hash.hex().upper(),
        "next_validators_hash": h.next_validators_hash.hex().upper(),
        "consensus_hash": h.consensus_hash.hex().upper(),
        "app_hash": h.app_hash.hex().upper(),
        "last_results_hash": h.last_results_hash.hex().upper(),
        "evidence_hash": h.evidence_hash.hex().upper(),
        "proposer_address": h.proposer_address.hex().upper(),
    }


def _commit_json(c) -> dict:
    from ..types.commit import AggregateCommit
    if isinstance(c, AggregateCommit):
        # aggregate-commit chains (docs/aggregate_commits.md): one
        # BLS signature + signer bitmap instead of per-val signatures
        return {
            "height": str(c.height), "round": c.round,
            "block_id": _block_id_json(c.block_id),
            "signer_count": c.size(),
            "signers": base64.b64encode(c.signers_bytes()).decode(),
            "aggregate_signature":
                base64.b64encode(c.signature).decode(),
        }
    return {
        "height": str(c.height), "round": c.round,
        "block_id": _block_id_json(c.block_id),
        "signatures": [
            {"block_id_flag": s.block_id_flag,
             "validator_address": s.validator_address.hex().upper(),
             "timestamp": s.timestamp.rfc3339(),
             "signature": base64.b64encode(s.signature).decode()
             if s.signature else None}
            for s in c.signatures],
    }


def _block_json(b) -> dict:
    return {
        "header": _header_json(b.header),
        "data": {"txs": [base64.b64encode(t).decode()
                         for t in b.data.txs]},
        "evidence": {"evidence": []},
        "last_commit": _commit_json(b.last_commit)
        if b.last_commit is not None else None,
    }


def _events_json(events) -> list:
    return [{"type": e.type, "attributes": [
        {"key": a.key, "value": a.value, "index": a.index}
        for a in e.attributes]} for e in events or []]


class UriString(str):
    """A quoted URI GET parameter.  The reference's URI handler treats
    a quoted value as the raw string content — `tx="name=satoshi"`
    submits the bytes `name=satoshi` — while JSON-RPC POST []byte
    params are base64 (rpc/jsonrpc/server/http_uri_handler.go,
    nonJSONStringToArg).  The server tags quoted URI params with this
    type so decoders keep the two wire conventions apart."""


def _decode_tx(tx) -> bytes:
    """Txs arrive base64 (JSON-RPC), 0x-hex (URI), or as a quoted
    raw URI string."""
    if isinstance(tx, bytes):
        return tx
    if isinstance(tx, UriString):
        return str(tx).encode()
    if tx.startswith("0x"):
        return bytes.fromhex(tx[2:])
    return base64.b64decode(tx)


def _decode_hex_or_str(v) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, UriString):
        return str(v).encode()
    if v.startswith("0x"):
        return bytes.fromhex(v[2:])
    return v.encode()


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).lower() in ("true", "1")


def event_data_json(ev) -> dict:
    """EventData -> the ws subscription payload (reference: the typed
    TMEventData JSON in rpc/core/events).  Best-effort typed rendering of
    the common event kinds; round-state events carry their summary dict."""
    kind = getattr(ev, "kind", "")
    payload = getattr(ev, "payload", None)
    out: dict = {"type": f"tendermint/event/{kind or 'Unknown'}"}
    value: dict = {}
    try:
        if kind == "NewBlock" and isinstance(payload, dict):
            block = payload.get("block")
            if block is not None:
                value = {"block": _block_json(block),
                         "block_id": _block_id_json(
                             payload.get("block_id"))}
        elif kind == "NewBlockHeader" and isinstance(payload, dict):
            value = {"header": _header_json(payload["header"])}
        elif kind == "Tx" and isinstance(payload, dict):
            res = payload.get("result")
            value = {
                "height": str(payload.get("height", 0)),
                "index": payload.get("index", 0),
                "tx": base64.b64encode(payload.get("tx", b"")).decode(),
                "result": {
                    "code": res.code,
                    "data": base64.b64encode(res.data).decode(),
                    "log": res.log,
                    "gas_wanted": str(res.gas_wanted),
                    "gas_used": str(res.gas_used),
                    "events": _events_json(res.events),
                } if res is not None else None,
            }
        elif isinstance(payload, dict):
            value = {k: v for k, v in payload.items()
                     if isinstance(v, (str, int, float, bool, type(None)))}
    except Exception:  # noqa: BLE001 — events must never kill the pump
        value = {}
    out["value"] = value
    return out
