"""Supervised single-thread workers for off-event-loop compute.

The 1-vCPU QA rig's profile (QA_r08) shows signature-verification
stalls stacking behind the p2p recv routine: a synchronous 10k-sig
batch verify freezes the ENTIRE node for the duration because there
is exactly one event loop.  The fix is structural — CPU-heavy verify
work runs on a dedicated worker thread whose native kernels release
the GIL, and the event loop only ever awaits a future.

``SupervisedWorker`` is deliberately smaller than a generic pool:

  * exactly ONE persistent thread — verification is serialized by
    construction, so two concurrent bursts cannot double the node's
    CPU demand (on the 1-vCPU rig an unbounded pool would just trade
    event-loop stalls for scheduler thrash);
  * every submitted task is timed from submit to start
    (``<ns>_<sub>_queue_wait_seconds``) and the pending depth is
    exported as a gauge — the queue REVEALS overload instead of
    absorbing it silently;
  * a task exception is captured into the returned future AND logged
    by the worker (callers of advisory work often discard the future;
    a swallowed crash must still be visible), and the worker thread
    itself survives — the supervision contract the node's async tasks
    get from libs/supervisor.py, ported to a thread.

Not a replacement for asyncio.to_thread: tasks here are expected to
release the GIL (native batch verify, pairing products), which is
what makes the off-loop move a real win on a single core — the event
loop keeps getting scheduled while the kernel runs in C.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Callable, Optional

from . import metrics as libmetrics
from .log import Logger, new_logger

_QUEUE_WAIT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                       0.05, 0.1, 0.25, 0.5, 1.0, 2.5)


class SupervisedWorker:
    """One named worker thread with task-queue metrics and crash
    logging.  ``submit(fn, *args)`` returns a concurrent Future;
    tasks run in submission order on the single thread."""

    def __init__(self, worker_name: str, subsystem: str = "crypto",
                 logger: Optional[Logger] = None,
                 registry: Optional[libmetrics.Registry] = None):
        self._name = worker_name
        self._logger = logger or new_logger("workers")
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._depth = 0
        self._depth_lock = threading.Lock()
        self._stopped = False
        reg = registry or libmetrics.DEFAULT
        wait_hist = reg.histogram(
            subsystem, "verify_queue_wait_seconds",
            "Time a task submitted to a verification worker waited "
            "in its queue before starting, by worker.",
            labels=("worker",), buckets=_QUEUE_WAIT_BUCKETS)
        depth_gauge = reg.gauge(
            subsystem, "verify_executor_depth",
            "Tasks queued or running on a verification worker, by "
            "worker.", labels=("worker",))
        # one child per worker, bound at construction: worker_name is
        # hard-coded at the few construction sites (bftlint
        # reviewed-bounded label name)
        self._wait_hist = wait_hist.with_labels(worker_name)
        self._depth_gauge = depth_gauge.with_labels(worker_name)
        self._thread = threading.Thread(
            target=self._run, name=f"worker-{worker_name}",
            daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    def submit(self, fn: Callable, *args) -> Future:
        """Queue ``fn(*args)``; the future resolves with its result or
        exception.  Raises RuntimeError after ``stop()``."""
        if self._stopped:
            raise RuntimeError(f"worker {self._name} is stopped")
        fut: Future = Future()
        with self._depth_lock:
            self._depth += 1
            self._depth_gauge.set(self._depth)
        self._q.put((fut, fn, args, time.perf_counter()))
        return fut

    def depth(self) -> int:
        return self._depth

    def stop(self, wait: bool = True) -> None:
        """Drain-and-join: queued tasks still run (verification
        futures someone awaits must resolve), then the thread exits."""
        if self._stopped:
            return
        self._stopped = True
        self._q.put(None)
        if wait:
            self._thread.join(timeout=30)

    # ------------------------------------------------------------------
    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                # drain-before-exit: a submit() racing stop() can
                # enqueue BEHIND the sentinel (the _stopped check and
                # the q.put are not atomic); those futures must still
                # resolve — the stop() contract
                while True:
                    try:
                        item = self._q.get_nowait()
                    except queue.Empty:
                        return
                    if item is not None:
                        self._run_task(item)
            self._run_task(item)

    def _run_task(self, item) -> None:
        fut, fn, args, t_submit = item
        self._wait_hist.observe(time.perf_counter() - t_submit)
        if fut.set_running_or_notify_cancel():
            try:
                fut.set_result(fn(*args))
            except BaseException as e:  # noqa: BLE001 — supervised:
                # captured into the future AND logged (advisory
                # callers drop futures; the crash must be visible)
                self._logger.error(
                    "verify worker task failed",
                    worker=self._name, exc_info=True)
                try:
                    fut.set_exception(e)
                except InvalidStateError:
                    pass        # future cancelled while running
        with self._depth_lock:
            self._depth -= 1
            self._depth_gauge.set(self._depth)


__all__ = ["SupervisedWorker"]
