"""Pub/sub with a query language, feeding RPC subscribers and the indexer.

Reference: libs/pubsub/pubsub.go (Server :93) + libs/pubsub/query (the
gogll-generated grammar).  Queries are conjunctions of conditions over
event tags:

    tm.event = 'NewBlock' AND tx.height > 5 AND account.name CONTAINS 'igor'

Operators: =, <, <=, >, >=, CONTAINS, EXISTS.  Values: single-quoted
strings, numbers, dates (treated as strings here).  Tags are multi-valued
(one event key can carry several values, e.g. several tx senders).
"""
from __future__ import annotations

import asyncio
import re
from datetime import datetime, timezone
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

# "<date>T<time>.<frac><tz-or-nothing>" — fraction capped to
# microseconds for python 3.10's fromisoformat
_FRAC_RE = re.compile(r"^([^.]+)\.(\d+)(.*)$")


class PubSubError(Exception):
    pass


class QueryError(PubSubError):
    pass


def _tokenize(s: str) -> list[tuple[str, str]]:
    """Tokens: ("str", text) for 'quoted' literals (escapes honoured,
    may contain AND/spaces), ("op", =|<|<=|>|>=), ("word", text) for
    keys, AND, CONTAINS, EXISTS, DATE, TIME and bare values."""
    tokens: list[tuple[str, str]] = []
    i, n = 0, len(s)
    while i < n:
        c = s[i]
        if c.isspace():
            i += 1
            continue
        if c == "'":
            j, buf = i + 1, []
            while j < n and s[j] != "'":
                if s[j] == "\\" and j + 1 < n:
                    buf.append(s[j + 1])
                    j += 2
                else:
                    buf.append(s[j])
                    j += 1
            if j >= n:
                raise QueryError(f"unterminated string in {s!r}")
            tokens.append(("str", "".join(buf)))
            i = j + 1
            continue
        if c in "<>=":
            if s[i:i + 2] in ("<=", ">="):
                tokens.append(("op", s[i:i + 2]))
                i += 2
            else:
                tokens.append(("op", c))
                i += 1
            continue
        j = i
        while j < n and not s[j].isspace() and s[j] not in "<>='":
            j += 1
        tokens.append(("word", s[i:j]))
        i = j
    return tokens


def _parse_time_like(raw: str):
    """RFC3339 timestamp or yyyy-mm-dd date → aware datetime, else
    None (reference: query grammar TIME/DATE literals)."""
    txt = raw.strip()
    if txt.endswith("Z"):
        txt = txt[:-1] + "+00:00"
    # python < 3.11 fromisoformat accepts only 3- or 6-digit
    # fractional seconds; RFC3339 emitters produce 1-9 digits (a
    # nanosecond field with trailing zeros trimmed) — normalize to 6
    m = _FRAC_RE.match(txt)
    if m:
        txt = f"{m.group(1)}.{(m.group(2) + '000000')[:6]}{m.group(3)}"
    try:
        dt = datetime.fromisoformat(txt)
    except ValueError:
        return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt


def _parse_value(raw: str):
    if raw.startswith("'") and raw.endswith("'"):
        return raw[1:-1]
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    return raw


def _as_number(v) -> Optional[float]:
    if isinstance(v, (int, float)):
        return float(v)
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


@dataclass(frozen=True)
class Condition:
    key: str
    op: str
    value: Any = None

    def matches_value(self, ev_val: str) -> bool:
        op = self.op
        if op == "EXISTS":
            return True
        if op == "CONTAINS":
            return str(self.value) in ev_val
        if isinstance(self.value, datetime):
            # DATE/TIME literal: the event value must parse as a
            # timestamp too
            t = _parse_time_like(ev_val)
            if t is None:
                return False
            v = self.value
            return {"=": t == v, "<": t < v, "<=": t <= v,
                    ">": t > v, ">=": t >= v}[op]
        if op == "=":
            n, m = _as_number(self.value), _as_number(ev_val)
            if n is not None and m is not None:
                return n == m
            return str(self.value) == ev_val
        n, m = _as_number(self.value), _as_number(ev_val)
        if n is None or m is None:
            # fall back to lexicographic comparison for strings
            a, b = ev_val, str(self.value)
            return {"<": a < b, "<=": a <= b,
                    ">": a > b, ">=": a >= b}[op]
        return {"<": m < n, "<=": m <= n, ">": m > n, ">=": m >= n}[op]


class Query:
    """Conjunction of conditions; matches event tag maps."""

    def __init__(self, query_str: str):
        self.query_str = query_str.strip()
        self.conditions: list[Condition] = []
        if not self.query_str:
            return
        toks = _tokenize(self.query_str)
        i = 0
        while i < len(toks):
            kind, key = toks[i]
            if kind != "word":
                raise QueryError(
                    f"expected key, got {key!r} in {query_str!r}")
            i += 1
            if i >= len(toks):
                raise QueryError(f"missing operator in {query_str!r}")
            kind, op = toks[i]
            op_up = op.upper()
            i += 1
            if kind == "word" and op_up == "EXISTS":
                self.conditions.append(Condition(key, "EXISTS"))
            elif kind == "op" or (kind == "word" and
                                  op_up == "CONTAINS"):
                if i >= len(toks):
                    raise QueryError(f"missing value in {query_str!r}")
                vkind, vtext = toks[i]
                i += 1
                if vkind == "str":
                    value: Any = vtext
                elif vtext.upper() in ("DATE", "TIME"):
                    # DATE yyyy-mm-dd / TIME RFC3339 literal
                    if i >= len(toks):
                        raise QueryError(
                            f"missing {vtext} literal in {query_str!r}")
                    _, raw = toks[i]
                    i += 1
                    value = _parse_time_like(raw)
                    if value is None:
                        raise QueryError(
                            f"bad {vtext} literal {raw!r}")
                else:
                    value = _parse_value(vtext)
                self.conditions.append(
                    Condition(key, "CONTAINS" if op_up == "CONTAINS"
                              else op, value))
            else:
                raise QueryError(
                    f"expected operator, got {op!r} in {query_str!r}")
            if i < len(toks):
                kind, word = toks[i]
                if kind != "word" or word.upper() != "AND":
                    raise QueryError(
                        f"expected AND, got {word!r} in {query_str!r}")
                i += 1
                if i >= len(toks):
                    raise QueryError(
                        f"dangling AND in {query_str!r}")

    def matches(self, events: dict[str, list[str]]) -> bool:
        """events: composite key ("type.attr") → list of values."""
        for cond in self.conditions:
            vals = events.get(cond.key)
            if not vals:
                return False
            if not any(cond.matches_value(v) for v in vals):
                return False
        return True

    def __str__(self) -> str:
        return self.query_str

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and \
            self.query_str == other.query_str

    def __hash__(self) -> int:
        return hash(self.query_str)


EMPTY_QUERY = Query("")


@dataclass
class Message:
    data: Any
    events: dict[str, list[str]] = field(default_factory=dict)


_CANCEL_SENTINEL = object()


class Subscription:
    """A subscriber's message stream (reference: pubsub.Subscription;
    its Canceled channel wakes blocked readers — here a sentinel message
    does)."""

    def __init__(self, out_capacity: int = 100):
        # +1 slot so the cancel sentinel always fits
        self._queue: asyncio.Queue = asyncio.Queue(out_capacity + 1)
        self._capacity = out_capacity
        self._canceled: Optional[str] = None

    @property
    def canceled(self) -> Optional[str]:
        return self._canceled

    def cancel(self, reason: str) -> None:
        if self._canceled is None:
            self._canceled = reason
            # wake any reader blocked in next()
            self._queue.put_nowait(_CANCEL_SENTINEL)

    async def next(self) -> Message:
        if self._canceled:
            raise PubSubError(f"subscription canceled: {self._canceled}")
        msg = await self._queue.get()
        if msg is _CANCEL_SENTINEL:
            raise PubSubError(f"subscription canceled: {self._canceled}")
        return msg

    def try_put(self, msg: Message) -> bool:
        if self._canceled or self._queue.qsize() >= self._capacity:
            return False
        self._queue.put_nowait(msg)
        return True


class Server:
    """In-process pub/sub server (reference: pubsub.Server :93).

    Subscriptions are keyed by (subscriber, query).  Publishing is
    synchronous fan-out; a full subscriber queue cancels that
    subscription (the reference's non-buffered semantics surface
    slow-subscriber errors the same way).
    """

    def __init__(self):
        self._subs: dict[tuple[str, str], tuple[Query, Subscription]] = {}

    def subscribe(self, subscriber: str, query: Query | str,
                  out_capacity: int = 100) -> Subscription:
        if isinstance(query, str):
            query = Query(query)
        key = (subscriber, query.query_str)
        if key in self._subs:
            raise PubSubError("already subscribed")
        sub = Subscription(out_capacity)
        self._subs[key] = (query, sub)
        return sub

    def unsubscribe(self, subscriber: str, query: Query | str) -> None:
        qs = query.query_str if isinstance(query, Query) else \
            Query(query).query_str
        key = (subscriber, qs)
        if key not in self._subs:
            raise PubSubError("subscription not found")
        _, sub = self._subs.pop(key)
        sub.cancel("unsubscribed")

    def unsubscribe_all(self, subscriber: str) -> None:
        keys = [k for k in self._subs if k[0] == subscriber]
        if not keys:
            raise PubSubError("subscription not found")
        for k in keys:
            _, sub = self._subs.pop(k)
            sub.cancel("unsubscribed")

    def num_clients(self) -> int:
        return len({k[0] for k in self._subs})

    def num_client_subscriptions(self, subscriber: str) -> int:
        return sum(1 for k in self._subs if k[0] == subscriber)

    def publish(self, data: Any,
                events: Optional[dict[str, list[str]]] = None) -> None:
        events = events or {}
        msg = Message(data, events)
        dead = []
        for key, (query, sub) in self._subs.items():
            if query.matches(events):
                if not sub.try_put(msg):
                    sub.cancel("out of capacity")
                    dead.append(key)
        for key in dead:
            self._subs.pop(key, None)
