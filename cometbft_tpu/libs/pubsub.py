"""Pub/sub with a query language, feeding RPC subscribers and the indexer.

Reference: libs/pubsub/pubsub.go (Server :93) + libs/pubsub/query (the
gogll-generated grammar).  Queries are conjunctions of conditions over
event tags:

    tm.event = 'NewBlock' AND tx.height > 5 AND account.name CONTAINS 'igor'

Operators: =, <, <=, >, >=, CONTAINS, EXISTS.  Values: single-quoted
strings, numbers, dates (treated as strings here).  Tags are multi-valued
(one event key can carry several values, e.g. several tx senders).
"""
from __future__ import annotations

import asyncio
import re
from dataclasses import dataclass, field
from typing import Any, Callable, Optional


class PubSubError(Exception):
    pass


class QueryError(PubSubError):
    pass


_COND_RE = re.compile(
    r"\s*(?P<key>[\w.\-/]+)\s*"
    r"(?P<op>=|<=|>=|<|>|CONTAINS|EXISTS)\s*"
    r"(?P<val>'(?:[^'\\]|\\.)*'|[\w.\-:+TZ]+)?\s*$",
    re.IGNORECASE)


def _parse_value(raw: str):
    if raw.startswith("'") and raw.endswith("'"):
        return raw[1:-1]
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    return raw


def _as_number(v) -> Optional[float]:
    if isinstance(v, (int, float)):
        return float(v)
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


@dataclass(frozen=True)
class Condition:
    key: str
    op: str
    value: Any = None

    def matches_value(self, ev_val: str) -> bool:
        op = self.op
        if op == "EXISTS":
            return True
        if op == "CONTAINS":
            return str(self.value) in ev_val
        if op == "=":
            n, m = _as_number(self.value), _as_number(ev_val)
            if n is not None and m is not None:
                return n == m
            return str(self.value) == ev_val
        n, m = _as_number(self.value), _as_number(ev_val)
        if n is None or m is None:
            # fall back to lexicographic comparison for dates/strings
            a, b = ev_val, str(self.value)
            return {"<": a < b, "<=": a <= b,
                    ">": a > b, ">=": a >= b}[op]
        return {"<": m < n, "<=": m <= n, ">": m > n, ">=": m >= n}[op]


class Query:
    """Conjunction of conditions; matches event tag maps."""

    def __init__(self, query_str: str):
        self.query_str = query_str.strip()
        self.conditions: list[Condition] = []
        if not self.query_str:
            return
        for part in re.split(r"\s+AND\s+", self.query_str,
                             flags=re.IGNORECASE):
            m = _COND_RE.match(part)
            if not m:
                raise QueryError(f"invalid condition {part!r}")
            op = m.group("op").upper()
            raw_val = m.group("val")
            if op == "EXISTS":
                if raw_val:
                    raise QueryError(f"EXISTS takes no value: {part!r}")
                self.conditions.append(Condition(m.group("key"), op))
            else:
                if raw_val is None:
                    raise QueryError(f"missing value in {part!r}")
                self.conditions.append(Condition(
                    m.group("key"), op, _parse_value(raw_val)))

    def matches(self, events: dict[str, list[str]]) -> bool:
        """events: composite key ("type.attr") → list of values."""
        for cond in self.conditions:
            vals = events.get(cond.key)
            if not vals:
                return False
            if not any(cond.matches_value(v) for v in vals):
                return False
        return True

    def __str__(self) -> str:
        return self.query_str

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and \
            self.query_str == other.query_str

    def __hash__(self) -> int:
        return hash(self.query_str)


EMPTY_QUERY = Query("")


@dataclass
class Message:
    data: Any
    events: dict[str, list[str]] = field(default_factory=dict)


_CANCEL_SENTINEL = object()


class Subscription:
    """A subscriber's message stream (reference: pubsub.Subscription;
    its Canceled channel wakes blocked readers — here a sentinel message
    does)."""

    def __init__(self, out_capacity: int = 100):
        # +1 slot so the cancel sentinel always fits
        self._queue: asyncio.Queue = asyncio.Queue(out_capacity + 1)
        self._capacity = out_capacity
        self._canceled: Optional[str] = None

    @property
    def canceled(self) -> Optional[str]:
        return self._canceled

    def cancel(self, reason: str) -> None:
        if self._canceled is None:
            self._canceled = reason
            # wake any reader blocked in next()
            self._queue.put_nowait(_CANCEL_SENTINEL)

    async def next(self) -> Message:
        if self._canceled:
            raise PubSubError(f"subscription canceled: {self._canceled}")
        msg = await self._queue.get()
        if msg is _CANCEL_SENTINEL:
            raise PubSubError(f"subscription canceled: {self._canceled}")
        return msg

    def try_put(self, msg: Message) -> bool:
        if self._canceled or self._queue.qsize() >= self._capacity:
            return False
        self._queue.put_nowait(msg)
        return True


class Server:
    """In-process pub/sub server (reference: pubsub.Server :93).

    Subscriptions are keyed by (subscriber, query).  Publishing is
    synchronous fan-out; a full subscriber queue cancels that
    subscription (the reference's non-buffered semantics surface
    slow-subscriber errors the same way).
    """

    def __init__(self):
        self._subs: dict[tuple[str, str], tuple[Query, Subscription]] = {}

    def subscribe(self, subscriber: str, query: Query | str,
                  out_capacity: int = 100) -> Subscription:
        if isinstance(query, str):
            query = Query(query)
        key = (subscriber, query.query_str)
        if key in self._subs:
            raise PubSubError("already subscribed")
        sub = Subscription(out_capacity)
        self._subs[key] = (query, sub)
        return sub

    def unsubscribe(self, subscriber: str, query: Query | str) -> None:
        qs = query.query_str if isinstance(query, Query) else \
            Query(query).query_str
        key = (subscriber, qs)
        if key not in self._subs:
            raise PubSubError("subscription not found")
        _, sub = self._subs.pop(key)
        sub.cancel("unsubscribed")

    def unsubscribe_all(self, subscriber: str) -> None:
        keys = [k for k in self._subs if k[0] == subscriber]
        if not keys:
            raise PubSubError("subscription not found")
        for k in keys:
            _, sub = self._subs.pop(k)
            sub.cancel("unsubscribed")

    def num_clients(self) -> int:
        return len({k[0] for k in self._subs})

    def num_client_subscriptions(self, subscriber: str) -> int:
        return sum(1 for k in self._subs if k[0] == subscriber)

    def publish(self, data: Any,
                events: Optional[dict[str, list[str]]] = None) -> None:
        events = events or {}
        msg = Message(data, events)
        dead = []
        for key, (query, sub) in self._subs.items():
            if query.matches(events):
                if not sub.try_put(msg):
                    sub.cancel("out of capacity")
                    dead.append(key)
        for key in dead:
            self._subs.pop(key, None)
