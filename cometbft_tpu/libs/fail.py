"""Fail points: env-indexed crash injection for crash-consistency tests.

Reference: internal/fail/fail.go:28 — `fail.Fail()` calls are sprinkled
through the commit path; when the environment variable FAIL_TEST_INDEX
equals the running call index, the process exits immediately (no cleanup,
no flushing — a real crash).  Replay tests iterate every index and assert
the node recovers at each boundary.
"""
from __future__ import annotations

import os

ENV_VAR = "FAIL_TEST_INDEX"

_target = int(os.environ.get(ENV_VAR, "-1") or "-1")
_counter = 0


def fail() -> None:
    """Crash the process if this is the FAIL_TEST_INDEX-th call."""
    global _counter
    if _target < 0:
        return
    if _counter == _target:
        os._exit(99)                      # hard exit: no atexit, no flush
    _counter += 1


def call_count() -> int:
    return _counter


def reset(target: int = -1) -> None:
    """Test hook: re-arm in-process."""
    global _target, _counter
    _target = target
    _counter = 0
