"""Service lifecycle discipline: start/stop/quit, idempotent, resettable.

Reference: libs/service/service.go — BaseService with OnStart/OnStop hooks,
atomic started/stopped flags, Quit channel. Here the quit channel is an
asyncio.Event and services may own asyncio tasks.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from .log import Logger, nop_logger


class AlreadyStartedError(RuntimeError):
    pass


class AlreadyStoppedError(RuntimeError):
    pass


class Service:
    """Base service. Subclasses override on_start / on_stop.

    Mirrors the invariants of the reference BaseService: Start is one-shot
    (error if started or stopped), Stop flips the quit event exactly once.
    """

    def __init__(self, name: str = "", logger: Optional[Logger] = None):
        self.name = name or type(self).__name__
        self.logger = logger or nop_logger()
        self._started = False
        self._stopped = False
        self._quit = asyncio.Event()
        self._tasks: list[asyncio.Task] = []

    # -- lifecycle ---------------------------------------------------------
    @property
    def is_running(self) -> bool:
        return self._started and not self._stopped

    def set_logger(self, logger: Logger) -> None:
        self.logger = logger

    async def start(self) -> None:
        if self._started:
            raise AlreadyStartedError(self.name)
        if self._stopped:
            raise AlreadyStoppedError(self.name)
        # flip the flag before awaiting so a concurrent start() cannot pass
        # the guard (reference BaseService uses an atomic CAS)
        self._started = True
        self.logger.debug("service start", service=self.name)
        try:
            await self.on_start()
        except BaseException:
            self._started = False
            raise

    async def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        self.logger.debug("service stop", service=self.name)
        self._quit.set()
        await self.on_stop()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass
            except Exception as e:
                self.logger.error("background task died", service=self.name,
                                  task=t.get_name(), err=repr(e))
        self._tasks.clear()

    async def wait(self) -> None:
        """Block until the service is stopped."""
        await self._quit.wait()

    def spawn(self, coro, name: str = "") -> asyncio.Task:
        """Track a background task; cancelled on stop (goroutine analog)."""
        t = asyncio.create_task(coro, name=f"{self.name}/{name}")
        self._tasks.append(t)
        return t

    # -- hooks -------------------------------------------------------------
    async def on_start(self) -> None:  # pragma: no cover - default
        pass

    async def on_stop(self) -> None:  # pragma: no cover - default
        pass
