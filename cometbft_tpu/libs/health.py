"""Liveness-plane sampling: the event-loop lag histogram.

ISSUE 14's perf-lab stall measurement (``verify_event_loop_stall``)
proved the event loop is the scarce resource on a validator — every
reactor, the RPC server, and the consensus state machine share it.
This module turns that lab-only measurement into an always-on live
metric: a supervised sampler sleeps for a fixed interval and observes
how much later than scheduled it actually woke
(``cometbft_node_event_loop_lag_seconds``).  A loop stalled by a
blocking call or GC pause shows up here within one interval, and
``/health`` serves the p95 so the replica tier's load balancer
(ROADMAP item 4) and the soak gates (item 5) can shed to a healthier
node without scraping Prometheus.

The sampler costs one timer wakeup per interval (default 250 ms — 4
observations/s) and is spawned under the node supervisor, so it dies
with the node and restarts if it crashes.
"""
from __future__ import annotations

import asyncio

from .metrics import Histogram, Registry

# lag buckets: a healthy loop wakes within single-digit milliseconds;
# the tail we care about (blocking verify dispatch, GC, snapshot I/O)
# lives in the 10ms-2.5s range
_LAG_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5)


class Metrics:
    """Node-liveness metric family (subsystem ``node``)."""

    def __init__(self, registry: Registry):
        self.event_loop_lag_seconds: Histogram = registry.histogram(
            "node", "event_loop_lag_seconds",
            "Observed oversleep of a fixed-interval sampler on the "
            "node event loop: wakeup_actual - wakeup_scheduled.",
            buckets=_LAG_BUCKETS)


class LoopLagSampler:
    """Fixed-interval oversleep sampler.

    ``await asyncio.sleep(dt)`` never returns early; any extra delay
    is time the loop spent running other callbacks past their
    deadline — the same gap-sampling model as perf_lab's
    ``verify_event_loop_stall`` ticker, at a cadence cheap enough to
    leave on in production."""

    def __init__(self, metrics: Metrics,
                 interval_s: float = 0.25):
        self.metrics = metrics
        self.interval_s = max(0.001, float(interval_s))

    async def run(self) -> None:
        loop = asyncio.get_running_loop()
        interval = self.interval_s
        hist = self.metrics.event_loop_lag_seconds
        last = loop.time()
        while True:
            await asyncio.sleep(interval)
            now = loop.time()
            hist.observe(max(0.0, now - last - interval))
            last = now
