"""Prometheus-style metrics: registry + text exposition.

Reference: libs/metrics (go-kit metrics with a Prometheus provider) and
the per-package metrics.go files (internal/consensus/metrics.go:190,
mempool, p2p, state, blocksync, statesync, proxy).  Served at /metrics
by the instrumentation listener (node/node.go prometheusSrv).
"""
from __future__ import annotations

import threading
import time
from typing import Optional, Sequence


def _fmt_labels(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


_MEMO_MAX = 1024


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str,
                 label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._children: dict[tuple, "_Metric"] = {}
        self._memo: dict[tuple, "_Metric"] = {}
        self._lock = threading.Lock()

    def with_labels(self, *values: str):
        # hot path: with_labels runs per gossip message in the p2p
        # send/recv routines — the raw-tuple memo skips the per-call
        # str() normalization and lock (dict reads are GIL-atomic;
        # writes happen only under the lock below).  Only all-str
        # tuples are memoized: that is the actual hot-path shape, and
        # it keeps equal-but-differently-typed values (1 vs "1") from
        # creating duplicate memo entries for one child; the memo is
        # FIFO-bounded like the vote memos so peer-controlled label
        # values cannot grow it without bound.
        try:
            child = self._memo.get(values)
        except TypeError:           # unhashable label value
            child, memoizable = None, False
        else:
            memoizable = all(type(v) is str for v in values)
        if child is not None:
            return child
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected {len(self.label_names)} label "
                f"values, got {len(values)}")
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child(key)
                self._children[key] = child
            if memoizable:
                if len(self._memo) >= _MEMO_MAX:
                    self._memo.pop(next(iter(self._memo)))
                self._memo[values] = child
            return child

    def _new_child(self, key: tuple):  # pragma: no cover - abstract
        raise NotImplementedError

    def _samples(self):  # -> list[(labels, value)]
        raise NotImplementedError

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        for suffix, labels, value in self._samples():
            lines.append(
                f"{self.name}{suffix}{labels} {_fmt_value(value)}")
        return "\n".join(lines)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str,
                 label_names: Sequence[str] = ()):
        super().__init__(name, help_, label_names)
        self._value = 0.0

    def _new_child(self, key):
        return Counter(self.name, self.help)

    def add(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError("counters only go up")
        self._value += v

    inc = add

    @property
    def value(self) -> float:
        return self._value

    def _samples(self):
        if self.label_names:
            return [("", _fmt_labels(self.label_names, k), c._value)
                    for k, c in sorted(self._children.items())]
        return [("", "", self._value)]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_: str,
                 label_names: Sequence[str] = ()):
        super().__init__(name, help_, label_names)
        self._value = 0.0

    def _new_child(self, key):
        return Gauge(self.name, self.help)

    def set(self, v: float) -> None:
        self._value = float(v)

    def add(self, v: float = 1.0) -> None:
        self._value += v

    def sub(self, v: float = 1.0) -> None:
        self._value -= v

    @property
    def value(self) -> float:
        return self._value

    def _samples(self):
        if self.label_names:
            return [("", _fmt_labels(self.label_names, k), g._value)
                    for k, g in sorted(self._children.items())]
        return [("", "", self._value)]


_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                    5.0, 10.0)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help_: str,
                 label_names: Sequence[str] = (),
                 buckets: Sequence[float] = _DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * len(self.buckets)
        self._sum = 0.0
        self._count = 0

    def _new_child(self, key):
        return Histogram(self.name, self.help, buckets=self.buckets)

    def observe(self, v: float) -> None:
        self._sum += v
        self._count += 1
        for i, b in enumerate(self.buckets):
            if v <= b:
                self._counts[i] += 1

    def _child_samples(self, labels_prefix: str):
        out = []
        cum = 0
        for b, c in zip(self.buckets, self._counts):
            cum = c
            le = _fmt_value(b)
            if labels_prefix:
                lab = labels_prefix[:-1] + f',le="{le}"}}'
            else:
                lab = f'{{le="{le}"}}'
            out.append(("_bucket", lab, cum))
        inf_lab = (labels_prefix[:-1] + ',le="+Inf"}') \
            if labels_prefix else '{le="+Inf"}'
        out.append(("_bucket", inf_lab, self._count))
        out.append(("_sum", labels_prefix, self._sum))
        out.append(("_count", labels_prefix, self._count))
        return out

    def _samples(self):
        if self.label_names:
            out = []
            for k, h in sorted(self._children.items()):
                out.extend(h._child_samples(
                    _fmt_labels(self.label_names, k)))
            return out
        return self._child_samples("")


class Registry:
    def __init__(self, namespace: str = "cometbft"):
        self.namespace = namespace
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _register(self, m: _Metric) -> _Metric:
        with self._lock:
            if m.name in self._metrics:
                return self._metrics[m.name]
            self._metrics[m.name] = m
            return m

    def counter(self, subsystem: str, name: str, help_: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._register(Counter(
            f"{self.namespace}_{subsystem}_{name}", help_, labels))

    def gauge(self, subsystem: str, name: str, help_: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge(
            f"{self.namespace}_{subsystem}_{name}", help_, labels))

    def histogram(self, subsystem: str, name: str, help_: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = _DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._register(Histogram(
            f"{self.namespace}_{subsystem}_{name}", help_, labels,
            buckets))

    def render(self) -> str:
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: m.name)
        return "\n".join(m.render() for m in metrics) + "\n"


# The process-global registry (reference: the Prometheus default
# registerer); nodes may also construct private registries in tests.
DEFAULT = Registry()


class Timer:
    """Context manager observing elapsed seconds into a Histogram."""

    def __init__(self, hist: Histogram):
        self.hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self._t0)
        return False
