"""Prometheus-style metrics: registry + text exposition (metrics v2).

Reference: libs/metrics (go-kit metrics with a Prometheus provider) and
the per-package metrics.go files (internal/consensus/metrics.go:190,
mempool, p2p, state, blocksync, statesync, proxy).  Served at /metrics
by the instrumentation listener (node/node.go prometheusSrv).

v2 additions (the "metrics v2 + perf lab" layer):
  * Prometheus-text-format-correct exposition — label values and HELP
    text are escaped per the exposition format spec, so a peer moniker
    containing a quote or newline cannot break a scrape;
  * histogram trace exemplars — every bucket remembers its most recent
    observation together with the flight-recorder height in progress
    (libs/tracing.py ``current_height``), so a p99 outlier in a scrape
    links straight to ``/trace?height=H``.  Exemplars ride the
    OpenMetrics ``# {...}`` syntax and are OFF in the default render
    (plain text-format scrapers reject them) — pass ``exemplars=True``
    (``GET /metrics?exemplars=1``);
  * bounded label cardinality — a metric family never materializes
    more than ``max_children`` label sets; excess label values (e.g.
    peer-controlled ids under churn) collapse into one ``overflow``
    series instead of growing the registry without bound;
  * ``Registry.collect()`` — machine-readable family descriptors
    (name, kind, help, labels, live series) feeding the generated
    metrics catalog in docs/observability.md and the tier-1
    cardinality/help guard;
  * ``render_merged()`` — one exposition page over several registries
    (the node registry + the process-global DEFAULT that the crypto
    layer's backend-dispatch histograms live on).
"""
from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

from . import tracing


def _escape_label_value(v: str) -> str:
    """Exposition-format label escaping: backslash, double-quote and
    newline (in that order — escaping the escape char first)."""
    return v.replace("\\", "\\\\").replace('"', '\\"') \
            .replace("\n", "\\n")


def _escape_help(h: str) -> str:
    """HELP lines escape backslash and newline only."""
    return h.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape_label_value(v)}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _fmt_exemplar(ex) -> str:
    """OpenMetrics exemplar: ``# {labels} value timestamp``."""
    value, ts, labels = ex
    inner = ",".join(f'{k}="{_escape_label_value(str(v))}"'
                     for k, v in labels.items())
    return f" # {{{inner}}} {_fmt_value(value)} {ts:.3f}"


_MEMO_MAX = 1024
# Hard ceiling on label sets per family: beyond this, new label values
# collapse into one "overflow" series.  Peer-controlled label values
# (peer ids under churn, lane names from a byzantine app) therefore
# cannot grow a family without bound — the tier-1 cardinality guard
# (tests/test_metrics_contract.py) locks this invariant.
_CHILDREN_MAX = 2048
_OVERFLOW = "overflow"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str,
                 label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self.max_children = _CHILDREN_MAX
        self._children: dict[tuple, "_Metric"] = {}
        self._memo: dict[tuple, "_Metric"] = {}
        self._lock = threading.Lock()

    def with_labels(self, *values: str):
        # hot path: with_labels runs per gossip message in the p2p
        # send/recv routines — the raw-tuple memo skips the per-call
        # str() normalization and lock (dict reads are GIL-atomic;
        # writes happen only under the lock below).  Only all-str
        # tuples are memoized: that is the actual hot-path shape, and
        # it keeps equal-but-differently-typed values (1 vs "1") from
        # creating duplicate memo entries for one child; the memo is
        # FIFO-bounded like the vote memos so peer-controlled label
        # values cannot grow it without bound.
        try:
            child = self._memo.get(values)
        except TypeError:           # unhashable label value
            child, memoizable = None, False
        else:
            memoizable = all(type(v) is str for v in values)
        if child is not None:
            return child
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected {len(self.label_names)} label "
                f"values, got {len(values)}")
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= self.max_children:
                    # cardinality ceiling: collapse into the shared
                    # overflow series rather than growing unboundedly
                    key = tuple(_OVERFLOW
                                for _ in self.label_names)
                    child = self._children.get(key)
                if child is None:
                    child = self._new_child(key)
                    self._children[key] = child
            if memoizable:
                if len(self._memo) >= _MEMO_MAX:
                    self._memo.pop(next(iter(self._memo)))
                self._memo[values] = child
            return child

    def _new_child(self, key: tuple):  # pragma: no cover - abstract
        raise NotImplementedError

    def _samples(self):  # -> list[(suffix, labels, value, exemplar)]
        raise NotImplementedError

    def series_count(self) -> int:
        return len(self._children) if self.label_names else 1

    def describe(self) -> dict:
        """Family descriptor for Registry.collect()."""
        return {"name": self.name, "kind": self.kind,
                "help": self.help, "labels": list(self.label_names),
                "series": self.series_count()}

    def render(self, exemplars: bool = False) -> str:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.kind}"]
        for suffix, labels, value, ex in self._samples():
            tail = _fmt_exemplar(ex) if exemplars and ex else ""
            lines.append(
                f"{self.name}{suffix}{labels} "
                f"{_fmt_value(value)}{tail}")
        return "\n".join(lines)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str,
                 label_names: Sequence[str] = ()):
        super().__init__(name, help_, label_names)
        self._value = 0.0

    def _new_child(self, key):
        return Counter(self.name, self.help)

    def add(self, v: float = 1.0) -> None:
        if v < 0:
            raise ValueError("counters only go up")
        self._value += v

    inc = add

    @property
    def value(self) -> float:
        return self._value

    def _samples(self):
        if self.label_names:
            return [("", _fmt_labels(self.label_names, k), c._value,
                     None)
                    for k, c in sorted(self._children.items())]
        return [("", "", self._value, None)]

    def render(self, exemplars: bool = False) -> str:
        if not exemplars:
            return super().render()
        # OpenMetrics mode (the exemplar page): counter sample names
        # MUST carry the _total suffix and the family name drops it —
        # a conforming parser rejects the page otherwise
        family = self.name[:-len("_total")] \
            if self.name.endswith("_total") else self.name
        lines = [f"# HELP {family} {_escape_help(self.help)}",
                 f"# TYPE {family} counter"]
        for _suffix, labels, value, _ex in self._samples():
            lines.append(f"{family}_total{labels} "
                         f"{_fmt_value(value)}")
        return "\n".join(lines)


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_: str,
                 label_names: Sequence[str] = ()):
        super().__init__(name, help_, label_names)
        self._value = 0.0

    def _new_child(self, key):
        return Gauge(self.name, self.help)

    def set(self, v: float) -> None:
        self._value = float(v)

    def add(self, v: float = 1.0) -> None:
        self._value += v

    def sub(self, v: float = 1.0) -> None:
        self._value -= v

    @property
    def value(self) -> float:
        return self._value

    def _samples(self):
        if self.label_names:
            return [("", _fmt_labels(self.label_names, k), g._value,
                     None)
                    for k, g in sorted(self._children.items())]
        return [("", "", self._value, None)]


_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                    5.0, 10.0)


class Histogram(_Metric):
    """Prometheus-correct cumulative histogram.

    ``observe`` feeds ``_bucket``/``_sum``/``_count``; each bucket also
    remembers its latest observation as an OpenMetrics exemplar
    annotated with the flight-recorder height in progress, linking a
    scrape outlier to ``/trace?height=H``."""

    kind = "histogram"

    def __init__(self, name: str, help_: str,
                 label_names: Sequence[str] = (),
                 buckets: Sequence[float] = _DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * len(self.buckets)
        self._sum = 0.0
        self._count = 0
        # per-bucket (value, unix_ts, labels) — index len(buckets) is
        # the +Inf bucket
        self._exemplars: dict[int, tuple] = {}

    def _new_child(self, key):
        return Histogram(self.name, self.help, buckets=self.buckets)

    def observe(self, v: float,
                exemplar: Optional[dict] = None) -> None:
        self._sum += v
        self._count += 1
        idx = len(self.buckets)        # +Inf unless a bucket matches
        for i, b in enumerate(self.buckets):
            if v <= b:
                self._counts[i] += 1
                if i < idx:
                    idx = i
        if exemplar is None:
            # trace exemplar: stamp the height the consensus machine
            # is working on so the observation links to /trace
            h = tracing.recorder().current_height
            if h:
                exemplar = {"trace_height": h}
        if exemplar:
            # exemplar timestamps are exposition metadata — OpenMetrics
            # requires wall clock — not interval arithmetic
            # bftlint: disable=monotonic-clock
            self._exemplars[idx] = (v, time.time(), exemplar)

    def quantile(self, q: float) -> float:
        """Estimate the q-quantile (0 < q <= 1) by linear
        interpolation over the cumulative bucket counts — the same
        estimate Prometheus' histogram_quantile() would give a
        scraper, computed in-process so ``/health`` can serve a p95
        without a metrics pipeline.  Returns 0.0 with no samples; the
        +Inf bucket clamps to the largest finite bound (observations
        past the last bucket are unbounded, so the estimate is a
        floor there, not a value)."""
        if self._count == 0:
            return 0.0
        rank = q * self._count
        prev_bound, prev_cum = 0.0, 0
        for i, b in enumerate(self.buckets):
            cum = self._counts[i]
            if cum >= rank:
                width = cum - prev_cum
                if width <= 0:
                    return b
                return prev_bound + (b - prev_bound) * \
                    (rank - prev_cum) / width
            prev_bound, prev_cum = b, cum
        return self.buckets[-1] if self.buckets else 0.0

    def _child_samples(self, labels_prefix: str):
        out = []
        for i, b in enumerate(self.buckets):
            c = self._counts[i]
            le = _fmt_value(b)
            if labels_prefix:
                lab = labels_prefix[:-1] + f',le="{le}"}}'
            else:
                lab = f'{{le="{le}"}}'
            out.append(("_bucket", lab, c, self._exemplars.get(i)))
        inf_lab = (labels_prefix[:-1] + ',le="+Inf"}') \
            if labels_prefix else '{le="+Inf"}'
        out.append(("_bucket", inf_lab, self._count,
                    self._exemplars.get(len(self.buckets))))
        out.append(("_sum", labels_prefix, self._sum, None))
        out.append(("_count", labels_prefix, self._count, None))
        return out

    def _samples(self):
        if self.label_names:
            out = []
            for k, h in sorted(self._children.items()):
                out.extend(h._child_samples(
                    _fmt_labels(self.label_names, k)))
            return out
        return self._child_samples("")


class Registry:
    def __init__(self, namespace: str = "cometbft"):
        self.namespace = namespace
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _register(self, m: _Metric) -> _Metric:
        with self._lock:
            if m.name in self._metrics:
                return self._metrics[m.name]
            self._metrics[m.name] = m
            return m

    def counter(self, subsystem: str, name: str, help_: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._register(Counter(
            f"{self.namespace}_{subsystem}_{name}", help_, labels))

    def gauge(self, subsystem: str, name: str, help_: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge(
            f"{self.namespace}_{subsystem}_{name}", help_, labels))

    def histogram(self, subsystem: str, name: str, help_: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = _DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._register(Histogram(
            f"{self.namespace}_{subsystem}_{name}", help_, labels,
            buckets))

    def collect(self) -> list[dict]:
        """Sorted family descriptors — the generated metrics catalog
        (docs/observability.md) and the tier-1 cardinality/help guard
        read the registry through this."""
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: m.name)
        return [m.describe() for m in metrics]

    def families(self) -> list[_Metric]:
        with self._lock:
            return sorted(self._metrics.values(),
                          key=lambda m: m.name)

    def render(self, exemplars: bool = False) -> str:
        return "\n".join(m.render(exemplars=exemplars)
                         for m in self.families()) + "\n"


def render_merged(*registries: Registry,
                  exemplars: bool = False) -> str:
    """One exposition page over several registries (node registry
    first, then e.g. the process-global DEFAULT).  A family name
    already emitted is skipped so the page never carries duplicate
    TYPE lines."""
    seen: set[str] = set()
    out: list[str] = []
    for reg in registries:
        if reg is None:
            continue
        for m in reg.families():
            if m.name in seen:
                continue
            seen.add(m.name)
            out.append(m.render(exemplars=exemplars))
    return "\n".join(out) + "\n"


# The process-global registry (reference: the Prometheus default
# registerer); nodes may also construct private registries in tests.
# The crypto layer's batch-verify histograms and the TPU-dispatch
# breaker state live here (they have no node context) — the node's
# /metrics endpoint merges this registry in via render_merged().
DEFAULT = Registry()


class Timer:
    """Context manager observing elapsed seconds into a Histogram."""

    def __init__(self, hist: Histogram):
        self.hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self._t0)
        return False
