"""Flight recorder: node-wide span tracing with crash-dump timelines.

Always-on, near-zero-overhead attribution of where a height's
wall-clock goes.  Monotonic-clock spans and instant events are written
to fixed-size per-category ring buffers (the flight recorder) — no
I/O, no allocation beyond one tuple per event, bounded memory.  The
committee-based-consensus measurement line of work (PAPERS.md) showed
per-step latency attribution is what separates signature cost from
gossip/tally cost; this module bakes that attribution into the node so
every later perf PR is judged against the same timeline.

Readers:
  * the ``/trace`` JSON-RPC endpoint (rpc/core.py) — live timeline,
    filterable by height/category;
  * ``/debug/pprof/trace`` on the pprof listener (libs/pprof.py);
  * automatic crash dumps: the supervisor give-up path and the nemesis
    safety-assertion failure both call :func:`dump`, leaving a JSON
    flight record next to the node's data (the black box);
  * ``tools/trace_report.py`` — per-height gossip/verify/execute/commit
    breakdown rendered from a dump.

Disabled mode compiles to a no-op: ``span()`` returns a shared inert
context manager and ``instant()`` returns immediately — the benchmark
guard in tests/test_tracing.py holds the disabled path under 1µs per
call.  Category enables and the ring size come from
``instrumentation.trace_*`` (config.py), wired by the node.

Events are tuples ``(ts_ns, dur_ns, name, height, attrs)`` on a
``deque(maxlen=size)`` per category; ``time.monotonic_ns()`` is the
only clock, so timelines are immune to wall-clock steps and strictly
ordered within a process.

Clock anchors: monotonic timestamps are process-local, so two nodes'
timelines cannot be compared directly.  The recorder keeps a bounded
list of periodically refreshed ``(monotonic_ns, wall_ns)`` anchor
pairs — sampled together, refreshed passively whenever an event is
recorded past the anchor interval — exposed in every dump and at the
``/trace`` RPC.  ``tools/fleet_report.py`` fits offset + drift from
the pairs and merges N nodes' dumps onto one wall timeline (the
cluster critical path the committee-consensus measurement papers
decompose).  Wall time is never used for interval arithmetic here;
anchors are alignment metadata, the same boundary class as the pex
addrbook save/load conversion.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

# canonical categories (free-form strings are accepted; these are the
# ones the node emits and the report understands)
CONSENSUS = "consensus"
CRYPTO = "crypto"
P2P = "p2p"
MEMPOOL = "mempool"
ABCI = "abci"
SUPERVISOR = "supervisor"
NEMESIS = "nemesis"

CATEGORIES = (CONSENSUS, CRYPTO, P2P, MEMPOOL, ABCI, SUPERVISOR,
              NEMESIS)

now_ns = time.monotonic_ns


class Recorder:
    """Per-category ring buffers + dump machinery.

    The module-global instance behind :func:`span`/:func:`instant` is
    what the node wires; tests may construct private recorders."""

    #: bound on the anchor list; old middle anchors are evicted but the
    #: very first is kept so drift fits retain the longest baseline
    ANCHORS_MAX = 64

    def __init__(self, buffer_size: int = 4096, enabled: bool = True,
                 categories: Optional[str] = None,
                 dump_dir: str = "", node_id: str = "",
                 anchor_interval_s: float = 30.0):
        self.buffer_size = max(1, int(buffer_size))
        self.enabled = enabled
        # None = every category; else the enabled set
        self.categories: Optional[frozenset] = (
            frozenset(c.strip() for c in categories.split(",")
                      if c.strip())
            if isinstance(categories, str) and categories.strip()
            else (frozenset(categories) if categories else None))
        self.dump_dir = dump_dir
        self.node_id = node_id
        self.last_dump_path = ""
        # (monotonic_ns, wall_ns) pairs for cross-node alignment; the
        # first is taken here so even a dump written in the first
        # interval carries one.  time.time_ns is sampled ONLY to pair
        # with a monotonic reading — never for interval arithmetic.
        self.anchor_interval_ns = max(1, int(anchor_interval_s * 1e9))
        self.anchors: list[tuple[int, int]] = []
        self._next_anchor_ns = 0
        self.refresh_anchor(force=True)
        # best-effort height context: the consensus step machine
        # stamps the height in progress, and events recorded without
        # an explicit height (crypto dispatches, p2p frames, abci
        # calls) inherit it — that is what makes "/trace?height=H" a
        # complete per-height timeline rather than consensus-only
        self.current_height = 0
        self._rings: dict[str, deque] = {}
        self._dump_seq = 0
        self._lock = threading.Lock()

    # -- hot path ----------------------------------------------------
    def enabled_for(self, category: str) -> bool:
        return self.enabled and (self.categories is None or
                                 category in self.categories)

    def _ring(self, category: str) -> deque:
        ring = self._rings.get(category)
        if ring is None:
            # rare path; the lock only guards ring creation — appends
            # ride the GIL (deque.append is atomic)
            with self._lock:
                ring = self._rings.get(category)
                if ring is None:
                    ring = deque(maxlen=self.buffer_size)
                    self._rings[category] = ring
        return ring

    def record(self, category: str, name: str, start_ns: int,
               end_ns: int, height: int,
               attrs: Optional[dict]) -> None:
        self._ring(category).append(
            (start_ns, end_ns - start_ns, name,
             height or self.current_height, attrs))
        if end_ns >= self._next_anchor_ns:
            self.refresh_anchor()

    def record_instant(self, category: str, name: str, height: int,
                       attrs: Optional[dict]) -> None:
        ts = now_ns()
        self._ring(category).append(
            (ts, 0, name, height or self.current_height, attrs))
        if ts >= self._next_anchor_ns:
            self.refresh_anchor()

    def refresh_anchor(self, force: bool = False) -> None:
        """Sample a fresh (monotonic_ns, wall_ns) pair.  Driven
        passively from the record paths — one int comparison per event
        — so a recorder that sees traffic keeps current anchors with
        no timer task; idle recorders still hold their construction
        anchor."""
        mono = now_ns()
        if not force and mono < self._next_anchor_ns:
            return
        self._next_anchor_ns = mono + self.anchor_interval_ns
        self.anchors.append((mono, time.time_ns()))
        if len(self.anchors) > self.ANCHORS_MAX:
            # keep the first (longest drift baseline) and the newest
            del self.anchors[1]

    # -- readers -----------------------------------------------------
    def snapshot(self, height: Optional[int] = None,
                 category: Optional[str] = None,
                 limit: int = 0) -> list[dict]:
        """Merged timeline, strictly ordered by monotonic timestamp.
        ``height`` keeps only events stamped with that height;
        ``category`` keeps one ring; ``limit`` keeps the newest N."""
        out = []
        for cat, ring in list(self._rings.items()):
            if category is not None and cat != category:
                continue
            for ts, dur, name, h, attrs in list(ring):
                if height is not None and h != height:
                    continue
                ev = {"ts_ns": ts, "dur_ns": dur, "category": cat,
                      "name": name, "height": h}
                if attrs:
                    ev["attrs"] = attrs
                out.append(ev)
        out.sort(key=lambda e: (e["ts_ns"], e["dur_ns"]))
        if limit > 0:
            out = out[-limit:]
        return out

    def clear(self) -> None:
        with self._lock:
            self._rings.clear()

    # -- the black box -----------------------------------------------
    def resolved_dump_dir(self) -> str:
        """Where automatic dumps land.  A node wires its data dir (or
        the explicit ``instrumentation.dump_dir``); a bare recorder —
        unit tests, tools, library embedders that never call
        configure() — falls back to $COMETBFT_TPU_DUMP_DIR, then the
        system temp dir.  Never the process CWD: supervisor give-up
        dumps from test runs used to litter the repository root."""
        if self.dump_dir:
            return self.dump_dir
        env = os.environ.get("COMETBFT_TPU_DUMP_DIR", "")
        if env:
            return env
        import tempfile
        return tempfile.gettempdir()

    def dump(self, reason: str = "", path: str = "",
             extra: Optional[dict] = None) -> str:
        """Write the whole flight record to a JSON file and return its
        path.  Never raises — a failing dump must not mask the crash
        being dumped; returns "" on failure."""
        try:
            with self._lock:
                self._dump_seq += 1
                seq = self._dump_seq
            if not path:
                slug = "".join(c if c.isalnum() or c in "-_" else "-"
                               for c in reason)[:48] or "flight"
                path = os.path.join(
                    self.resolved_dump_dir(),
                    f"flight-{os.getpid()}-{seq:03d}-{slug}.json")
            self.refresh_anchor(force=True)
            record = {
                "reason": reason,
                "wall_time": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "monotonic_ns": now_ns(),
                "pid": os.getpid(),
                "node": self.node_id,
                "anchors": [list(a) for a in self.anchors],
                "extra": extra or {},
                "events": self.snapshot(),
            }
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp, path)
            self.last_dump_path = path
            return path
        except Exception:
            return ""


# the process-global recorder (the node configures it; tests may swap
# their own via set_recorder)
_R = Recorder()


class _NopSpan:
    """Shared inert context manager for the disabled path."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def note(self, **attrs) -> None:
        pass


_NOP = _NopSpan()


class _Span:
    __slots__ = ("_r", "cat", "name", "height", "attrs", "t0")

    def __init__(self, r: Recorder, cat: str, name: str, height: int,
                 attrs: Optional[dict]):
        self._r = r
        self.cat = cat
        self.name = name
        self.height = height
        self.attrs = attrs
        self.t0 = 0

    def __enter__(self):
        self.t0 = now_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            a = self.attrs or {}
            a["error"] = exc_type.__name__
            self.attrs = a
        self._r.record(self.cat, self.name, self.t0, now_ns(),
                       self.height, self.attrs)
        return False

    def note(self, **attrs) -> None:
        """Attach attributes discovered mid-span."""
        if self.attrs is None:
            self.attrs = {}
        self.attrs.update(attrs)


# ---------------------------------------------------------------------
# module-level API — what the instrumented call sites use

def span(category: str, name: str, height: int = 0, **attrs):
    """Context manager recording a monotonic span on exit.  When the
    category (or tracing) is disabled this is a no-op."""
    r = _R
    if not r.enabled or (r.categories is not None and
                         category not in r.categories):
        return _NOP
    return _Span(r, category, name, height, attrs or None)


def instant(category: str, name: str, height: int = 0,
            **attrs) -> None:
    """Record a zero-duration point event."""
    r = _R
    if not r.enabled or (r.categories is not None and
                         category not in r.categories):
        return
    r.record_instant(category, name, height, attrs or None)


def record_span(category: str, name: str, start_ns: int,
                end_ns: Optional[int] = None, height: int = 0,
                **attrs) -> None:
    """Record a span whose start was captured by the caller (e.g. the
    consensus step tracker, which learns a step ended only when the
    next one begins)."""
    r = _R
    if not r.enabled or (r.categories is not None and
                         category not in r.categories):
        return
    r.record(category, name, start_ns,
             end_ns if end_ns is not None else now_ns(), height,
             attrs or None)


def set_height(height: int) -> None:
    """Stamp the height in progress (consensus step machine) so
    height-less events inherit it."""
    _R.current_height = height


def enabled(category: str = "") -> bool:
    return _R.enabled_for(category) if category else _R.enabled


def snapshot(height: Optional[int] = None,
             category: Optional[str] = None,
             limit: int = 0) -> list[dict]:
    return _R.snapshot(height=height, category=category, limit=limit)


def dump(reason: str = "", path: str = "",
         extra: Optional[dict] = None) -> str:
    return _R.dump(reason=reason, path=path, extra=extra)


def clear() -> None:
    _R.clear()


def configure(enabled: bool = True, buffer_size: int = 4096,
              categories: Optional[str] = None,
              dump_dir: str = "", node_id: str = "",
              anchor_interval_s: float = 30.0) -> Recorder:
    """(Re)configure the process-global recorder — called by the node
    from instrumentation.trace_* config.  Existing rings are dropped
    so the new buffer size takes effect."""
    global _R
    _R = Recorder(buffer_size=buffer_size, enabled=enabled,
                  categories=categories, dump_dir=dump_dir,
                  node_id=node_id,
                  anchor_interval_s=anchor_interval_s)
    return _R


def refresh_anchor(force: bool = False) -> None:
    """Take a fresh clock anchor on the process-global recorder."""
    _R.refresh_anchor(force=force)


def anchors() -> list[tuple[int, int]]:
    return list(_R.anchors)


def recorder() -> Recorder:
    return _R


def set_recorder(r: Recorder) -> Recorder:
    """Test seam: install a private recorder; returns the old one."""
    global _R
    old, _R = _R, r
    return old
