"""Circuit breaker: closed → open → half-open, plus a latched-open
terminal state for non-transient faults.

Built for the TPU kernel dispatch path (crypto/batch.py): a failed
Pallas compile on a non-TPU accelerator is deterministic per process,
so re-attempting it per batch burns seconds of compile time on every
commit (ADVICE r5 #1).  The breaker classifies that as non-transient
and LATCHES open — the fallback path is taken forever, no re-probe.
Transient faults (pooled-TPU hiccups, timeouts) open the breaker for
``reset_timeout_s`` and then admit a single half-open probe.

State is exported as a gauge on whatever metrics registry the caller
wires in, so a degraded node is visible at /metrics.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

from . import metrics as libmetrics
from .log import Logger, nop_logger

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"
LATCHED_OPEN = "latched_open"

STATE_CODES = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2, LATCHED_OPEN: 3}


class Metrics:
    def __init__(self, registry: Optional[libmetrics.Registry] = None):
        m = registry if registry is not None else libmetrics.Registry()
        self.state = m.gauge(
            "breaker", "state",
            "Circuit state (0 closed, 1 open, 2 half-open, "
            "3 latched-open).", labels=("breaker",))
        self.failures = m.counter(
            "breaker", "failures_total",
            "Failures recorded against the circuit.",
            labels=("breaker",))
        self.transitions = m.counter(
            "breaker", "transitions_total",
            "State transitions of the circuit.",
            labels=("breaker", "state"))


class CircuitBreaker:
    """``allow()`` gates the protected call; the caller reports the
    outcome with ``record_success()`` / ``record_failure(latch=...)``.

    * closed: calls flow; ``failure_threshold`` consecutive failures
      open the circuit.
    * open: calls are refused until ``reset_timeout_s`` has elapsed,
      then ONE probe is admitted (→ half-open).
    * half-open: the probe's outcome closes or re-opens the circuit;
      concurrent calls are refused while the probe is in flight.
    * latched-open: terminal.  ``record_failure(latch=True)`` marks
      the fault non-transient; the circuit never re-probes.

    The clock is injectable for deterministic tests.
    """

    def __init__(self, name: str, failure_threshold: int = 1,
                 reset_timeout_s: float = 30.0,
                 monotonic: Callable[[], float] = time.monotonic,
                 metrics: Optional[Metrics] = None,
                 logger: Optional[Logger] = None):
        self.name = name
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout_s = reset_timeout_s
        self._monotonic = monotonic
        self.metrics = metrics if metrics is not None else Metrics()
        self.logger = logger if logger is not None else nop_logger()
        self._state = CLOSED
        self._failures = 0         # consecutive, while closed
        self._opened_at = 0.0
        self._probe_in_flight = False
        self.metrics.state.with_labels(self.name).set(
            STATE_CODES[CLOSED])

    # ------------------------------------------------------------------
    @property
    def state(self) -> str:
        return self._state

    def _transition(self, state: str) -> None:
        if state == self._state:
            return
        self.logger.info("breaker transition", breaker=self.name,
                         from_=self._state, to=state)
        self._state = state
        self.metrics.state.with_labels(self.name).set(
            STATE_CODES[state])
        self.metrics.transitions.with_labels(self.name, state).inc()

    # ------------------------------------------------------------------
    def allow(self) -> bool:
        """True when the protected call may proceed.  In half-open,
        exactly one caller gets True per probe window."""
        if self._state == CLOSED:
            return True
        if self._state == LATCHED_OPEN:
            return False
        if self._state == OPEN:
            if self._monotonic() - self._opened_at >= \
                    self.reset_timeout_s:
                self._transition(HALF_OPEN)
                self._probe_in_flight = True
                return True
            return False
        # HALF_OPEN: admit a single probe at a time
        if not self._probe_in_flight:
            self._probe_in_flight = True
            return True
        return False

    def record_success(self) -> None:
        if self._state == LATCHED_OPEN:
            return
        self._failures = 0
        self._probe_in_flight = False
        self._transition(CLOSED)

    def record_failure(self, latch: bool = False) -> None:
        self.metrics.failures.with_labels(self.name).inc()
        self._probe_in_flight = False
        if self._state == LATCHED_OPEN:
            return
        if latch:
            self._transition(LATCHED_OPEN)
            return
        if self._state == HALF_OPEN:
            self._opened_at = self._monotonic()
            self._transition(OPEN)
            return
        self._failures += 1
        if self._failures >= self.failure_threshold:
            self._opened_at = self._monotonic()
            self._failures = 0
            self._transition(OPEN)
