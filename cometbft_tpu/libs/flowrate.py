"""Token-bucket flow control + transfer-rate monitoring.

Reference: internal/flowrate (Monitor: sliding-window rate measurement;
Limit: blocks until the caller may transfer n bytes at the target rate).
Used by MConnection to cap per-connection send/recv throughput
(p2p/transport/tcp/conn/connection.go:27-44 consts; config
p2p.send_rate / p2p.recv_rate, 5 MB/s defaults).
"""
from __future__ import annotations

import asyncio
import time


class RateLimiter:
    """Async token bucket: `take(n)` waits until n bytes fit the rate.

    rate = bytes/second; burst = bucket depth (defaults to one second's
    worth, mirroring flowrate's windowing).  rate <= 0 disables limiting.
    """

    def __init__(self, rate: float, burst: float = 0.0):
        self.rate = float(rate)
        self.burst = float(burst) if burst > 0 else max(self.rate, 1.0)
        self._tokens = self.burst
        self._last = time.monotonic()
        # rate measurement (flowrate.Monitor's job)
        self._total = 0
        self._window_start = self._last
        self._window_bytes = 0
        self._measured_rate = 0.0

    def _refill(self) -> None:
        now = time.monotonic()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now

    async def take(self, n: int) -> None:
        """Account n bytes, sleeping as needed to hold the target rate."""
        self._account(n)
        if self.rate <= 0:
            return
        self._refill()
        self._tokens -= n
        if self._tokens < 0:
            # sleep until the deficit refills
            await asyncio.sleep(-self._tokens / self.rate)

    def try_take(self, n: int) -> bool:
        """Non-blocking: True (and accounted) if n bytes fit now."""
        if self.rate <= 0:
            self._account(n)
            return True
        self._refill()
        if self._tokens < n:
            return False
        self._tokens -= n
        self._account(n)
        return True

    # -- monitoring -------------------------------------------------------
    def _account(self, n: int) -> None:
        self._total += n
        now = time.monotonic()
        if now - self._window_start >= 1.0:
            self._measured_rate = self._window_bytes / \
                (now - self._window_start)
            self._window_start = now
            self._window_bytes = 0
        self._window_bytes += n

    @property
    def total(self) -> int:
        return self._total

    @property
    def measured_rate(self) -> float:
        """Bytes/s over the last completed window."""
        return self._measured_rate
