"""Length-delimited frame IO shared by the socket protocols.

Reference: libs/protoio — uvarint-length-prefixed messages, used by the
ABCI socket protocol (abci/types/messages.go) and the privval remote
signer (privval/msgs.go).
"""
from __future__ import annotations

import asyncio
from typing import Optional, Type

from ..wire.proto import encode_uvarint  # single canonical encoder

__all__ = ["encode_uvarint", "read_delimited", "write_delimited"]


async def read_delimited(reader: asyncio.StreamReader, max_size: int,
                         exc_type: Type[Exception]) -> Optional[bytes]:
    """One uvarint-length-delimited frame; None on clean EOF at a frame
    boundary; raises exc_type on oversize/malformed/torn frames."""
    prefix = b""
    size = 0
    shift = 0
    while True:
        b = await reader.read(1)
        if not b:
            if prefix:
                raise exc_type("EOF inside length prefix")
            return None
        prefix += b
        size |= (b[0] & 0x7F) << shift
        shift += 7
        if b[0] < 0x80:
            break
        if len(prefix) > 10:
            raise exc_type("length prefix too long")
    if size > max_size:
        raise exc_type(f"message too large: {size}")
    return await reader.readexactly(size)


def write_delimited(payload: bytes) -> bytes:
    """Frame bytes for a payload (caller writes them)."""
    return encode_uvarint(len(payload)) + payload
