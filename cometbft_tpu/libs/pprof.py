"""Live profiling endpoint (reference: the pprof HTTP server gated by
`instrumentation.pprof_laddr`, config/config.go:488-490, started in
node/node.go pprofSrv).

The Go runtime's pprof surface maps onto the asyncio runtime:

    /debug/pprof/            index
    /debug/pprof/tasks       every asyncio task + current stack
                             (goroutine dump analog)
    /debug/pprof/threads     OS thread stacks
    /debug/pprof/heap        tracemalloc top allocations (?start=1
                             begins recording, ?stop=1 stops), plus
                             gc counters
    /debug/pprof/profile     cProfile for ?seconds=N (default 5),
                             pstats text sorted by cumulative time

Serves on its own listener like the reference — profiling must stay
reachable when the RPC listener is saturated.
"""
from __future__ import annotations

import asyncio
import gc
import io
import sys
import traceback
from typing import Optional

from .log import new_logger

logger = new_logger("pprof")


def _tasks_dump() -> str:
    out = [f"asyncio tasks: {len(asyncio.all_tasks())}\n"]
    for t in sorted(asyncio.all_tasks(), key=lambda t: t.get_name()):
        out.append(f"\n--- task {t.get_name()!r} "
                   f"{'(done)' if t.done() else ''}\n")
        buf = io.StringIO()
        t.print_stack(file=buf)
        out.append(buf.getvalue())
    return "".join(out)


def _threads_dump() -> str:
    out = []
    frames = sys._current_frames()
    import threading
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in frames.items():
        out.append(f"\n--- thread {names.get(ident, '?')} "
                   f"({ident})\n")
        out.append("".join(traceback.format_stack(frame)))
    return "".join(out)


def _heap_dump(start: bool, stop: bool) -> str:
    import tracemalloc
    out = [f"gc counts: {gc.get_count()}  objects: "
           f"{len(gc.get_objects())}\n"]
    if stop:
        if tracemalloc.is_tracing():
            tracemalloc.stop()
        out.append("tracemalloc stopped\n")
        return "".join(out)
    if not tracemalloc.is_tracing():
        # tracing adds real per-allocation overhead on a validator:
        # it must be an explicit operator decision, never a side
        # effect of a monitoring probe touching the endpoint
        if start:
            tracemalloc.start()
            out.append("tracemalloc started — allocations recorded "
                       "from now on; request again for a snapshot\n")
        else:
            out.append("tracemalloc not running; pass ?start=1 to "
                       "begin recording allocations\n")
        return "".join(out)
    snap = tracemalloc.take_snapshot()
    out.append("top allocations by line:\n")
    for stat in snap.statistics("lineno")[:40]:
        out.append(f"  {stat}\n")
    return "".join(out)


async def _profile_dump(seconds: float) -> str:
    import cProfile
    import pstats
    prof = cProfile.Profile()
    try:
        prof.enable()
    except ValueError:
        # another profiler (e.g. a concurrent /profile request) owns
        # the hook — report it instead of dropping the connection
        return ("profiler busy: another profiling session is "
                "active; retry when it completes\n")
    try:
        await asyncio.sleep(min(seconds, 120.0))
    finally:
        prof.disable()
    buf = io.StringIO()
    pstats.Stats(prof, stream=buf).sort_stats("cumulative") \
        .print_stats(60)
    return buf.getvalue()


def _trace_dump(write_file: bool) -> str:
    """Flight-recorder dump hook (libs/tracing.py): the whole span
    timeline as JSON; ?dump=1 also writes a flight-record file to the
    configured dump dir and reports its path."""
    import json as _json

    from . import tracing
    out = {"enabled": tracing.enabled(),
           "events": tracing.snapshot()}
    if write_file:
        out["dump_path"] = tracing.dump(reason="pprof_request")
    return _json.dumps(out, indent=1) + "\n"


_INDEX = """pprof endpoints (asyncio runtime):
/debug/pprof/tasks     asyncio task dump (goroutine analog)
/debug/pprof/threads   OS thread stacks
/debug/pprof/heap      tracemalloc allocations (?start=1 begins
                         recording, ?stop=1 stops)
/debug/pprof/profile   CPU profile, ?seconds=N (default 5)
/debug/pprof/trace     flight-recorder timeline (?dump=1 writes a
                         flight-record file too)
"""


class PprofServer:
    """Reference: node/node.go pprofSrv."""

    def __init__(self, listen_addr: str):
        # "host:port" or ":port"
        addr = listen_addr.replace("tcp://", "")
        host, _, port = addr.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.listen_addr = ""
        self._server: Optional[asyncio.base_events.Server] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        sock = self._server.sockets[0].getsockname()
        self.listen_addr = f"{sock[0]}:{sock[1]}"
        logger.info("pprof listening", addr=self.listen_addr)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request_line = await reader.readline()
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
            parts = request_line.decode().split(" ")
            target = parts[1] if len(parts) > 1 else "/"
            path, _, query = target.partition("?")
            params = dict(
                kv.split("=", 1) for kv in query.split("&")
                if "=" in kv)
            if path in ("/debug/pprof", "/debug/pprof/"):
                body = _INDEX
            elif path == "/debug/pprof/tasks":
                body = _tasks_dump()
            elif path == "/debug/pprof/threads":
                body = _threads_dump()
            elif path == "/debug/pprof/heap":
                body = _heap_dump(params.get("start") == "1",
                                  params.get("stop") == "1")
            elif path == "/debug/pprof/trace":
                body = _trace_dump(params.get("dump") == "1")
            elif path == "/debug/pprof/profile":
                try:
                    seconds = float(params.get("seconds", "5"))
                except ValueError:
                    seconds = 5.0
                body = await _profile_dump(seconds)
            else:
                writer.write(b"HTTP/1.1 404 Not Found\r\n"
                             b"Content-Length: 0\r\n\r\n")
                await writer.drain()
                return
            payload = body.encode()
            writer.write(
                b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: text/plain; charset=utf-8\r\n"
                b"Content-Length: " + str(len(payload)).encode() +
                b"\r\nConnection: close\r\n\r\n" + payload)
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError,
                ValueError):
            pass
        finally:
            try:
                writer.close()
            except OSError:
                pass
