"""BitArray: gossiped vote bitmaps.

Reference: internal/bits/bit_array.go — fixed-size bit array with
set/get, copy, bitwise ops, random-true-index picking (used by consensus
gossip to choose which vote to send a peer).
"""
from __future__ import annotations

import random
from typing import Optional


class BitArray:
    __slots__ = ("bits", "_elems")

    def __init__(self, bits: int):
        if bits < 0:
            raise ValueError("negative bits")
        self.bits = bits
        self._elems = 0  # int bitmap, bit i == index i

    @classmethod
    def from_indices(cls, bits: int, indices) -> "BitArray":
        ba = cls(bits)
        for i in indices:
            ba.set_index(i, True)
        return ba

    def size(self) -> int:
        return self.bits

    def get_index(self, i: int) -> bool:
        if i < 0 or i >= self.bits:
            return False
        return bool((self._elems >> i) & 1)

    def set_index(self, i: int, v: bool) -> bool:
        if i < 0 or i >= self.bits:
            return False
        if v:
            self._elems |= (1 << i)
        else:
            self._elems &= ~(1 << i)
        return True

    def copy(self) -> "BitArray":
        ba = BitArray(self.bits)
        ba._elems = self._elems
        return ba

    def or_(self, other: "BitArray") -> "BitArray":
        """Union; result size is the larger (reference: Or)."""
        ba = BitArray(max(self.bits, other.bits))
        ba._elems = self._elems | other._elems
        return ba

    def and_(self, other: "BitArray") -> "BitArray":
        ba = BitArray(min(self.bits, other.bits))
        mask = (1 << ba.bits) - 1
        ba._elems = self._elems & other._elems & mask
        return ba

    def not_(self) -> "BitArray":
        ba = BitArray(self.bits)
        ba._elems = ~self._elems & ((1 << self.bits) - 1)
        return ba

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other (reference: Sub)."""
        ba = BitArray(self.bits)
        mask = (1 << self.bits) - 1
        ba._elems = self._elems & ~(other._elems) & mask
        return ba

    def is_empty(self) -> bool:
        return self._elems == 0

    def is_full(self) -> bool:
        return self.bits > 0 and self._elems == (1 << self.bits) - 1

    def true_indices(self) -> list[int]:
        e, out, i = self._elems, [], 0
        while e:
            if e & 1:
                out.append(i)
            e >>= 1
            i += 1
        return out

    def pick_random(self) -> Optional[int]:
        """A uniformly random true index, or None (reference: PickRandom)."""
        idxs = self.true_indices()
        if not idxs:
            return None
        return random.choice(idxs)

    def update(self, other: "BitArray") -> None:
        """Copy other's bits into self (reference: Update)."""
        mask = (1 << self.bits) - 1
        self._elems = other._elems & mask

    def __eq__(self, other) -> bool:
        return (isinstance(other, BitArray) and self.bits == other.bits and
                self._elems == other._elems)

    def __str__(self) -> str:
        s = "".join("x" if self.get_index(i) else "_"
                    for i in range(self.bits))
        return f"BA{{{self.bits}:{s}}}"

    def to_proto(self) -> dict:
        # libs/bits proto: {bits: int64, elems: repeated uint64}
        elems = []
        e = self._elems
        for _ in range((self.bits + 63) // 64):
            elems.append(e & ((1 << 64) - 1))
            e >>= 64
        d: dict = {}
        if self.bits:
            d["bits"] = self.bits
        if elems:
            d["elems"] = elems
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "BitArray":
        ba = cls(d.get("bits", 0))
        e = 0
        for i, w in enumerate(d.get("elems", [])):
            e |= w << (64 * i)
        ba._elems = e & ((1 << ba.bits) - 1) if ba.bits else 0
        return ba
