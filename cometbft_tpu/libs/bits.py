"""BitArray: gossiped vote bitmaps.

Reference: internal/bits/bit_array.go — fixed-size bit array with
set/get, copy, bitwise ops, random-true-index picking (used by consensus
gossip to choose which vote to send a peer).
"""
from __future__ import annotations

import random
from typing import Optional


# bit positions set in each byte value (true_indices fast path)
_BYTE_BITS = tuple(tuple(i for i in range(8) if b >> i & 1)
                   for b in range(256))


class BitArray:
    __slots__ = ("bits", "_elems")

    def __init__(self, bits: int):
        if bits < 0:
            raise ValueError("negative bits")
        self.bits = bits
        self._elems = 0  # int bitmap, bit i == index i

    @classmethod
    def from_indices(cls, bits: int, indices) -> "BitArray":
        ba = cls(bits)
        for i in indices:
            ba.set_index(i, True)
        return ba

    def size(self) -> int:
        return self.bits

    def get_index(self, i: int) -> bool:
        if i < 0 or i >= self.bits:
            return False
        return bool((self._elems >> i) & 1)

    def set_index(self, i: int, v: bool) -> bool:
        if i < 0 or i >= self.bits:
            return False
        if v:
            self._elems |= (1 << i)
        else:
            self._elems &= ~(1 << i)
        return True

    def copy(self) -> "BitArray":
        ba = BitArray(self.bits)
        ba._elems = self._elems
        return ba

    def or_(self, other: "BitArray") -> "BitArray":
        """Union; result size is the larger (reference: Or)."""
        ba = BitArray(max(self.bits, other.bits))
        ba._elems = self._elems | other._elems
        return ba

    def and_(self, other: "BitArray") -> "BitArray":
        ba = BitArray(min(self.bits, other.bits))
        mask = (1 << ba.bits) - 1
        ba._elems = self._elems & other._elems & mask
        return ba

    def not_(self) -> "BitArray":
        ba = BitArray(self.bits)
        ba._elems = ~self._elems & ((1 << self.bits) - 1)
        return ba

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other (reference: Sub)."""
        ba = BitArray(self.bits)
        mask = (1 << self.bits) - 1
        ba._elems = self._elems & ~(other._elems) & mask
        return ba

    def is_empty(self) -> bool:
        return self._elems == 0

    def is_full(self) -> bool:
        return self.bits > 0 and self._elems == (1 << self.bits) - 1

    def true_indices(self) -> list[int]:
        # one to_bytes + per-byte table walk: the bit-shift and
        # lowest-set-bit loops are both O(bits^2/64) on big dense
        # ints (every shift/xor rewrites the whole bignum) —
        # aggregate-commit bitmaps hit this at 10k validators per
        # verification
        e = self._elems
        if not e:
            return []
        out: list[int] = []
        for base, byte in enumerate(
                e.to_bytes((self.bits + 7) // 8, "little")):
            if byte:
                start = base * 8
                out.extend(start + i for i in _BYTE_BITS[byte])
        return out

    def popcount(self) -> int:
        return bin(self._elems).count("1")

    def highest_true_index(self) -> int:
        """Index of the highest set bit, or -1 when empty."""
        return self._elems.bit_length() - 1

    def pick_random(self) -> Optional[int]:
        """A uniformly random true index, or None (reference: PickRandom)."""
        idxs = self.true_indices()
        if not idxs:
            return None
        return random.choice(idxs)

    def update(self, other: "BitArray") -> None:
        """Copy other's bits into self (reference: Update)."""
        mask = (1 << self.bits) - 1
        self._elems = other._elems & mask

    def __eq__(self, other) -> bool:
        return (isinstance(other, BitArray) and self.bits == other.bits and
                self._elems == other._elems)

    def __str__(self) -> str:
        s = "".join("x" if self.get_index(i) else "_"
                    for i in range(self.bits))
        return f"BA{{{self.bits}:{s}}}"

    def to_le_bytes(self) -> bytes:
        """Canonical little-endian packing: (bits+7)//8 bytes, byte i
        bit j = index 8i+j, padding bits zero (the aggregate-commit
        signer-bitmap wire layout)."""
        return self._elems.to_bytes((self.bits + 7) // 8, "little")

    @classmethod
    def from_le_bytes(cls, raw: bytes, bits: int) -> "BitArray":
        """Inverse of to_le_bytes; rejects non-canonical input (wrong
        length or padding bits set) so two wire encodings can never
        decode to one value."""
        if bits < 0:
            raise ValueError("negative bits")
        if len(raw) != (bits + 7) // 8:
            raise ValueError(
                f"bitmap length {len(raw)} != canonical "
                f"{(bits + 7) // 8} for {bits} bits")
        elems = int.from_bytes(raw, "little")
        if elems >> bits:
            raise ValueError("bitmap has padding bits set")
        ba = cls(bits)
        ba._elems = elems
        return ba

    def to_proto(self) -> dict:
        # libs/bits proto: {bits: int64, elems: repeated uint64}
        elems = []
        e = self._elems
        for _ in range((self.bits + 63) // 64):
            elems.append(e & ((1 << 64) - 1))
            e >>= 64
        d: dict = {}
        if self.bits:
            d["bits"] = self.bits
        if elems:
            d["elems"] = elems
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "BitArray":
        ba = cls(d.get("bits", 0))
        e = 0
        for i, w in enumerate(d.get("elems", [])):
            e |= w << (64 * i)
        ba._elems = e & ((1 << ba.bits) - 1) if ba.bits else 0
        return ba
