"""Structured logging with per-module level filters.

Reference: libs/log/logger.go (slog-based structured logger),
libs/log/filter.go (per-module level filtering).
"""
from __future__ import annotations

import logging
import sys
import time
from typing import Any


class Logger:
    """Key-value structured logger, mirroring the reference's log.Logger
    interface (Debug/Info/Error + With for bound context)."""

    __slots__ = ("_logger", "_ctx")

    def __init__(self, logger: logging.Logger, ctx: dict[str, Any] | None = None):
        self._logger = logger
        self._ctx = ctx or {}

    def with_(self, **kv: Any) -> "Logger":
        return Logger(self._logger, {**self._ctx, **kv})

    def _fmt(self, msg: str, kv: dict[str, Any]) -> str:
        items = {**self._ctx, **kv}
        if not items:
            return msg
        kvs = " ".join(f"{k}={_render(v)}" for k, v in items.items())
        return f"{msg} {kvs}"

    def debug(self, msg: str, **kv: Any) -> None:
        if self._logger.isEnabledFor(logging.DEBUG):
            exc_info = kv.pop("exc_info", None)
            self._logger.debug(self._fmt(msg, kv), exc_info=exc_info)

    def info(self, msg: str, **kv: Any) -> None:
        if self._logger.isEnabledFor(logging.INFO):
            exc_info = kv.pop("exc_info", None)
            self._logger.info(self._fmt(msg, kv), exc_info=exc_info)

    def warn(self, msg: str, **kv: Any) -> None:
        exc_info = kv.pop("exc_info", None)
        self._logger.warning(self._fmt(msg, kv), exc_info=exc_info)

    def error(self, msg: str, **kv: Any) -> None:
        # exc_info is a directive for the underlying logger (log the
        # active traceback), not a structured field
        exc_info = kv.pop("exc_info", None)
        self._logger.error(self._fmt(msg, kv), exc_info=exc_info)


def _render(v: Any) -> str:
    if isinstance(v, bytes):
        return v.hex().upper()[:16] or "''"
    s = str(v)
    if " " in s:
        return repr(s)
    return s


_configured = False


def _configure_root(level: int = logging.INFO) -> None:
    global _configured
    if _configured:
        return
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter("%(asctime)s %(levelname).1s %(name)s: %(message)s"))
    root = logging.getLogger("cometbft")
    root.addHandler(h)
    root.setLevel(level)
    root.propagate = False
    _configured = True


def new_logger(module: str = "main", level: str | int | None = None,
               **ctx: Any) -> Logger:
    """Get a structured logger for a module.  level=None inherits the root
    level; an explicit level (here or via set_module_level) sticks because
    default-level calls never touch the logger's level."""
    _configure_root()
    lg = logging.getLogger(f"cometbft.{module}")
    if level is not None:
        if isinstance(level, str):
            level = getattr(logging, level.upper())
        lg.setLevel(level)
    return Logger(lg, ctx)


def nop_logger() -> Logger:
    lg = logging.getLogger("cometbft.nop")
    if not lg.handlers:
        lg.addHandler(logging.NullHandler())
        lg.setLevel(logging.CRITICAL + 1)
        lg.propagate = False
    return Logger(lg)


def set_module_level(module: str, level: str) -> None:
    """Per-module level filter (reference: libs/log/filter.go)."""
    logging.getLogger(f"cometbft.{module}").setLevel(getattr(logging, level.upper()))


def set_level(level: str) -> None:
    """Set the root cometbft logger level (config: base.log_level)."""
    _configure_root()
    logging.getLogger("cometbft").setLevel(
        getattr(logging, level.upper(), logging.INFO))
