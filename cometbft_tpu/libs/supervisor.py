"""Task supervision: one-for-one restart of crashed asyncio loops.

Background loops spawned with a bare ``loop.create_task(...)`` die
silently on the first uncaught exception — the reactor keeps running
but its gossip/sync/dial loop is simply gone.  The reference codebase
leans on Go's panic-crashes-the-process discipline; here the analog is
an Erlang-style one-for-one supervisor: every reactor/switch loop is
spawned through a Supervisor, an uncaught exception restarts that loop
with exponential backoff + jitter, and a bounded restart budget turns
a hot crash loop into a loud, metered give-up instead of a silent
spin.  Crash/restart/give-up counts are exported on the node's
metrics registry.

The clock, sleep, and jitter RNG are injectable so tests can assert
the exact backoff schedule deterministically.
"""
from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass
from typing import Callable, Optional

from . import metrics as libmetrics
from .log import Logger, new_logger


@dataclass(frozen=True)
class RestartPolicy:
    """One-for-one restart policy for a supervised loop.

    ``max_restarts`` crashes inside a sliding ``window_s`` exhaust the
    budget: the loop is abandoned (loudly — log + give-up metric +
    callback).  A loop that stays healthy longer than the window earns
    its budget back, and the backoff exponent resets with it.
    """
    max_restarts: int = 5
    window_s: float = 60.0
    backoff_base_s: float = 0.1
    backoff_max_s: float = 10.0
    jitter: float = 0.1            # fraction of the delay, uniform
    restart_on_success: bool = False   # normal return ends supervision


DEFAULT_POLICY = RestartPolicy()


class Metrics:
    """Supervisor metric family (reference idiom: per-package
    metrics.go fed from one shared registry)."""

    def __init__(self, registry: Optional[libmetrics.Registry] = None):
        m = registry if registry is not None else libmetrics.Registry()
        # labeled by the loop KIND (e.g. "consensus_gossip_votes"),
        # never by peer id: peer-derived label values are
        # peer-controlled and would grow the family without bound
        self.crashes = m.counter(
            "supervisor", "crashes_total",
            "Uncaught exceptions in supervised loops.",
            labels=("supervisor", "task"))
        self.restarts = m.counter(
            "supervisor", "restarts_total",
            "Restarts of supervised loops after a crash.",
            labels=("supervisor", "task"))
        self.giveups = m.counter(
            "supervisor", "giveups_total",
            "Supervised loops abandoned after exhausting their "
            "restart budget.",
            labels=("supervisor", "task"))
        self.live = m.gauge(
            "supervisor", "live_tasks",
            "Currently supervised loops.", labels=("supervisor",))


class SupervisedTask:
    """Handle for one supervised loop.

    Quacks enough like an asyncio.Task for the call sites that used to
    hold one: ``cancel()`` stops the loop for good (no restart), and
    ``await handle`` joins the runner.
    """

    def __init__(self, name: str, kind: str):
        self.name = name
        self.kind = kind
        self.restarts = 0
        self.gave_up = False
        self.last_error: Optional[BaseException] = None
        self.crash_times: list[float] = []
        self._runner: Optional[asyncio.Task] = None

    @property
    def runner(self) -> Optional[asyncio.Task]:
        return self._runner

    def cancel(self) -> None:
        if self._runner is not None:
            self._runner.cancel()

    def done(self) -> bool:
        return self._runner is None or self._runner.done()

    async def wait(self) -> None:
        if self._runner is not None:
            try:
                await self._runner
            except asyncio.CancelledError:
                pass

    def __await__(self):
        if self._runner is None:
            async def _done():
                return None
            return _done().__await__()
        return self._runner.__await__()

    def __repr__(self) -> str:
        return f"SupervisedTask({self.name}, restarts={self.restarts})"


class Supervisor:
    """One-for-one supervisor owning a set of loops.

    ``monotonic``/``sleep``/``rng`` are injectable for deterministic
    tests (fake clock, recorded backoff schedule, seeded jitter).
    """

    def __init__(self, name: str, logger: Optional[Logger] = None,
                 metrics: Optional[Metrics] = None, *,
                 monotonic: Optional[Callable[[], float]] = None,
                 sleep: Optional[Callable] = None,
                 rng: Optional[random.Random] = None):
        self.name = name
        self.logger = logger if logger is not None else \
            new_logger(f"supervisor.{name}")
        self.metrics = metrics if metrics is not None else Metrics()
        self._monotonic = monotonic
        self._sleep = sleep if sleep is not None else asyncio.sleep
        self._rng = rng if rng is not None else random.Random()
        self._tasks: list[SupervisedTask] = []

    # ------------------------------------------------------------------
    def _now(self) -> float:
        if self._monotonic is not None:
            return self._monotonic()
        return asyncio.get_event_loop().time()

    def backoff(self, n_crashes_in_window: int,
                policy: RestartPolicy) -> float:
        """Delay before the restart following the n-th windowed crash
        (1-based): capped exponential plus uniform jitter."""
        d = min(policy.backoff_base_s * (2 ** (n_crashes_in_window - 1)),
                policy.backoff_max_s)
        return d * (1.0 + policy.jitter * self._rng.random())

    def note_crash(self, kind: str, exc: BaseException) -> None:
        """Meter a crash in a loop the supervisor does not own (e.g.
        the asyncio.Server-driven accept path) so it is never silent."""
        self.metrics.crashes.with_labels(self.name, kind).inc()
        self.logger.error("unsupervised loop crashed", task=kind,
                          err=repr(exc))

    # ------------------------------------------------------------------
    def spawn(self, factory: Callable, name: str = "",
              kind: str = "",
              policy: Optional[RestartPolicy] = None,
              on_crash: Optional[Callable] = None,
              on_giveup: Optional[Callable] = None) -> SupervisedTask:
        """Supervise ``factory`` — a zero-arg callable returning a
        fresh coroutine per (re)start.  ``kind`` labels metrics (keep
        it low-cardinality); ``name`` is the per-instance log/display
        name."""
        st = SupervisedTask(
            name or getattr(factory, "__name__", "task"),
            kind or name or "task")
        st._runner = asyncio.get_running_loop().create_task(
            self._run(st, factory, policy or DEFAULT_POLICY,
                      on_crash, on_giveup),
            name=f"{self.name}/{st.name}")
        self._tasks.append(st)
        self.metrics.live.with_labels(self.name).add(1)
        return st

    async def stop(self) -> None:
        tasks, self._tasks = self._tasks, []
        for st in tasks:
            st.cancel()
        for st in tasks:
            await st.wait()

    def live_count(self) -> int:
        return sum(1 for st in self._tasks if not st.done())

    # ------------------------------------------------------------------
    async def _run(self, st: SupervisedTask, factory: Callable,
                   policy: RestartPolicy,
                   on_crash: Optional[Callable],
                   on_giveup: Optional[Callable]) -> None:
        try:
            while True:
                try:
                    await factory()
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — that's the job
                    st.last_error = e
                    self.metrics.crashes.with_labels(
                        self.name, st.kind).inc()
                    self.logger.error("supervised loop crashed",
                                      task=st.name, err=repr(e))
                    self._notify(on_crash, st, e)
                    now = self._now()
                    st.crash_times = [
                        t for t in st.crash_times
                        if now - t <= policy.window_s]
                    st.crash_times.append(now)
                    if len(st.crash_times) > policy.max_restarts:
                        st.gave_up = True
                        self.metrics.giveups.with_labels(
                            self.name, st.kind).inc()
                        self.logger.error(
                            "supervised loop abandoned: restart "
                            "budget exhausted", task=st.name,
                            restarts=st.restarts, err=repr(e))
                        self._dump_flight_record(st, e)
                        self._notify(on_giveup, st, e)
                        return
                    st.restarts += 1
                    self.metrics.restarts.with_labels(
                        self.name, st.kind).inc()
                    delay = self.backoff(len(st.crash_times), policy)
                    self.logger.info("restarting supervised loop",
                                     task=st.name, attempt=st.restarts,
                                     delay_s=round(delay, 4))
                    await self._sleep(delay)
                else:
                    if not policy.restart_on_success:
                        return
                    await self._sleep(policy.backoff_base_s)
        finally:
            self.metrics.live.with_labels(self.name).sub(1)
            # drop our handle so peer-churn supervisors don't
            # accumulate dead SupervisedTasks (and their last_error
            # tracebacks) forever; stop() snapshots first, so this is
            # a no-op there
            try:
                self._tasks.remove(st)
            except ValueError:
                pass

    def _dump_flight_record(self, st: SupervisedTask,
                            exc: BaseException) -> None:
        """A give-up is the node's 'black box moment': dump the flight
        recorder (libs/tracing.py) so the timeline leading into the
        crash loop survives.  Never lets a dump failure mask the
        give-up itself."""
        try:
            from . import tracing
            tracing.instant(tracing.SUPERVISOR, "giveup",
                            supervisor=self.name, task=st.name,
                            err=repr(exc)[:200])
            path = tracing.dump(
                reason=f"supervisor_giveup_{self.name}_{st.kind}",
                extra={"supervisor": self.name, "task": st.name,
                       "kind": st.kind, "restarts": st.restarts,
                       "error": repr(exc)})
            if path:
                self.logger.error("flight record dumped", path=path)
        except Exception:  # noqa: BLE001 — best-effort black box
            pass

    def _notify(self, cb: Optional[Callable], st: SupervisedTask,
                exc: BaseException) -> None:
        if cb is None:
            return
        try:
            cb(st, exc)
        except Exception as e:  # noqa: BLE001 — callbacks must not kill us
            self.logger.error("supervisor callback failed",
                              task=st.name, err=repr(e))
