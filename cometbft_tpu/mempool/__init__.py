"""Mempool: pending transactions with priority lanes."""
from .mempool import (
    CListMempool, Mempool, MempoolError, NopMempool, TxCache,
)

__all__ = ["CListMempool", "Mempool", "MempoolError", "NopMempool",
           "TxCache"]
