"""CListMempool: the concurrent pool with app-defined priority lanes.

Reference: mempool/clist_mempool.go:34 — per-lane lists, CheckTx through
the mempool ABCI connection, LRU dedup cache (cache.go), recheck after
commit, interleaved-weighted-round-robin reaping (iterators.go IWRR),
TxsAvailable notification; mempool/mempool.go:27 (interface);
nop_mempool.go (disabled variant).

Tx validity (incl. signatures) is the APP's job via CheckTx — the pool
itself never inspects tx contents (SURVEY §2.5 note).
"""
from __future__ import annotations

import abc
import asyncio
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from ..abci import types as abci
from ..config import MempoolConfig
from ..libs import tracing
from ..libs.log import Logger, new_logger
from ..types.tx import compute_proto_size_overhead, tx_key


class MempoolError(Exception):
    pass


class TxInCacheError(MempoolError):
    pass


class MempoolFullError(MempoolError):
    pass


class InvalidTxError(MempoolError):
    def __init__(self, code: int, log: str = ""):
        super().__init__(f"tx rejected by CheckTx: code {code} {log}")
        self.code = code


class TxCache:
    """LRU of recently seen tx keys (reference: mempool/cache.go)."""

    def __init__(self, size: int):
        self._size = size
        self._m: OrderedDict[bytes, None] = OrderedDict()

    def push(self, key: bytes) -> bool:
        """Returns False if already present."""
        if key in self._m:
            self._m.move_to_end(key)
            return False
        self._m[key] = None
        if len(self._m) > self._size:
            self._m.popitem(last=False)
        return True

    def remove(self, key: bytes) -> None:
        self._m.pop(key, None)

    def has(self, key: bytes) -> bool:
        return key in self._m

    def keys(self) -> list:
        return list(self._m.keys())

    def __len__(self) -> int:
        return len(self._m)

    def reset(self) -> None:
        self._m.clear()


@dataclass
class MempoolTx:
    tx: bytes
    key: bytes
    height: int          # height at which the tx was last validated
    gas_wanted: int
    lane: str
    senders: set = field(default_factory=set)
    seq: int = 0         # global FIFO sequence for cross-lane ordering
    # app-reported state keys the tx's validity depends on
    # (CheckTxResponse.recheck_keys); empty = unattributed, so the
    # bounded-age watermark alone schedules its rechecks
    recheck_keys: frozenset = frozenset()


class Mempool(abc.ABC):
    """Reference: mempool/mempool.go Mempool interface (:27-100)."""

    @abc.abstractmethod
    async def check_tx(self, tx: bytes, sender: str = ""
                       ) -> abci.CheckTxResponse: ...

    @abc.abstractmethod
    def reap_max_bytes_max_gas(self, max_bytes: int,
                               max_gas: int) -> list[bytes]: ...

    @abc.abstractmethod
    async def update(self, height: int, txs: Sequence[bytes],
                     tx_results: Sequence[abci.ExecTxResult],
                     pre_check=None, post_check=None) -> None: ...

    def lock(self) -> None: ...

    def unlock(self) -> None: ...

    def pre_update(self) -> None: ...

    async def flush_app_conn(self) -> None: ...

    def size(self) -> int:
        return 0

    def size_bytes(self) -> int:
        return 0


class CListMempool(Mempool):
    def __init__(self, config: MempoolConfig, proxy_app,
                 lanes: Optional[dict[str, int]] = None,
                 default_lane: str = "",
                 height: int = 0,
                 logger: Optional[Logger] = None,
                 metrics=None):
        """proxy_app: the mempool ABCI connection.  lanes: lane id →
        priority from the app's InfoResponse; empty → single implicit
        lane (priority 0)."""
        if lanes and not default_lane:
            raise MempoolError("lanes set but no default lane")
        if lanes and default_lane not in lanes:
            raise MempoolError("default lane not in lane list")
        from .metrics import Metrics
        self.metrics = metrics if metrics is not None else Metrics()
        self.config = config
        self.proxy_app = proxy_app
        self.logger = logger if logger is not None else \
            new_logger("mempool")
        self.lanes = dict(lanes or {"": 0})
        self.default_lane = default_lane if lanes else ""
        # per-lane insertion-ordered maps: key -> MempoolTx
        self._lane_txs: dict[str, OrderedDict[bytes, MempoolTx]] = {
            lane: OrderedDict() for lane in self.lanes}
        # per-lane byte totals, maintained incrementally: lane_sizes
        # feeds the metrics updater on EVERY add/evict, and a rescan
        # there measured ~19% of a saturated node's CPU (O(pool) per
        # added tx — QA_r05.json profile_top)
        self._lane_bytes: dict[str, int] = {
            lane: 0 for lane in self.lanes}
        self.cache = TxCache(config.cache_size)
        self.height = height
        self._seq = 0
        self._size_bytes = 0
        self._size_count = 0
        # commit-time exclusion: while locked, check_tx waits so no tx
        # can slip in unvalidated between FinalizeBlock and recheck
        self._unlocked = asyncio.Event()
        self._unlocked.set()
        self._txs_available: Optional[asyncio.Event] = None
        self._notified_txs_available = False
        self._recheck_cursor: Optional[int] = None
        # tx keys admitted while a commit cycle raced their in-flight
        # CheckTx: revalidated unconditionally by the next update()
        # (the FinalizeBlock↔recheck admission-gap fix)
        self._pending_recheck: set[bytes] = set()
        # broadcast wakeup for per-peer gossip routines: replaced on
        # every append so any number of waiters can block on it (the
        # clist-wait analog, reference internal/clist/clist.go:95-104)
        self._gossip_wake = asyncio.Event()
        # bounded (seq, key) append log: per-peer gossip cursors and
        # the per-salt short-id maps read "what arrived since seq S"
        # from here in O(new) instead of rescanning every lane per
        # wire message (the QA_r08 profile showed the O(pool) walk in
        # _receive_have at ~2.3 ms/message at a 2.5k-tx pool)
        self._append_log: list = []
        # highest seq the log has DROPPED (trim/flush); a cursor at
        # or above it can still be served from the log.  -1 = nothing
        # ever dropped, so even the from-the-beginning cursor works
        self._log_start_seq = -1

    # ------------------------------------------------------------------
    def enable_txs_available(self) -> None:
        self._txs_available = asyncio.Event()

    def txs_available(self) -> asyncio.Event:
        if self._txs_available is None:
            raise MempoolError("txs_available not enabled")
        return self._txs_available

    def _notify_txs_available(self) -> None:
        if self.size() == 0:
            return
        if self._txs_available is not None and \
                not self._notified_txs_available:
            self._notified_txs_available = True
            self._txs_available.set()

    def _wake_gossip(self) -> None:
        ev = self._gossip_wake
        self._gossip_wake = asyncio.Event()
        ev.set()

    async def wait_for_change(self, last_seq: int,
                              timeout: float = 1.0) -> None:
        """Block until the append sequence advances past last_seq or
        the fallback timeout elapses — gossip routines park here
        instead of polling (VERDICT r3 #5: no steady-state busy-poll
        under zero load)."""
        ev = self._gossip_wake            # capture BEFORE the seq check
        if self._seq != last_seq:
            return
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            pass

    # ------------------------------------------------------------------
    def lock(self) -> None:
        """Block new check_tx admissions (reference: Mempool.Lock held
        across app Commit + Update)."""
        self._unlocked.clear()

    def unlock(self) -> None:
        self._unlocked.set()

    def pre_update(self) -> None:
        pass

    async def flush_app_conn(self) -> None:
        await self.proxy_app.flush()

    def size(self) -> int:
        # O(1): called on every CheckTx (_check_full), every metrics
        # update, and every gossip bound — a lane scan here was
        # measurable in the QA_r07 profile
        return self._size_count

    def size_bytes(self) -> int:
        return self._size_bytes

    def lane_sizes(self, lane: str) -> tuple[int, int]:
        d = self._lane_txs.get(lane, {})
        return len(d), self._lane_bytes.get(lane, 0)

    def contains(self, key: bytes) -> bool:
        return any(key in d for d in self._lane_txs.values())

    def get_tx_by_hash(self, h: bytes) -> Optional[bytes]:
        for d in self._lane_txs.values():
            e = d.get(h)
            if e is not None:
                return e.tx
        return None

    _APPEND_LOG_MAX = 65536

    def keys_appended_after(self, cursor: int) -> Optional[list]:
        """Tx keys appended with seq > cursor, in append order — the
        O(new) feed for gossip cursors and short-id maps.  Returns
        None when the bounded log no longer reaches back to cursor
        (caller falls back to a full pool scan).  Keys whose txs have
        since committed/evicted still appear; callers resolve through
        the live pool (gossip) or tolerate stale entries (short-id
        maps, where a stale hit only suppresses a useless re-pull)."""
        if cursor < self._log_start_seq:
            return None
        log = self._append_log
        # cursors trail the tip by a handful of appends in steady
        # state: walk back from the right
        i = len(log)
        while i > 0 and log[i - 1][0] > cursor:
            i -= 1
        return [k for _, k in log[i:]]

    def get_entry(self, key: bytes) -> Optional[MempoolTx]:
        for d in self._lane_txs.values():
            e = d.get(key)
            if e is not None:
                return e
        return None

    def add_sender(self, key: bytes, sender: str) -> None:
        """Record that a peer holds this tx (it advertised or sent
        it) so gossip never echoes the tx back at it."""
        if not sender:
            return
        for d in self._lane_txs.values():
            e = d.get(key)
            if e is not None:
                e.senders.add(sender)
                return

    def flush(self) -> None:
        """Remove everything (reference: Flush)."""
        for d in self._lane_txs.values():
            d.clear()
        for lane in self._lane_bytes:
            self._lane_bytes[lane] = 0
        self._size_bytes = 0
        self._size_count = 0
        self._pending_recheck.clear()
        self._append_log.clear()
        self._log_start_seq = self._seq
        self.cache.reset()

    # ------------------------------------------------------------------
    async def check_tx(self, tx: bytes, sender: str = ""
                       ) -> abci.CheckTxResponse:
        """Validate a tx via the app and add it to the pool.

        Reference: CheckTx (:347) + handleCheckTxResponse (:407)."""
        if len(tx) > self.config.max_tx_bytes:
            raise MempoolError(
                f"tx too large: {len(tx)} > {self.config.max_tx_bytes}")
        # wait out any in-progress commit/update cycle
        while not self._unlocked.is_set():
            await self._unlocked.wait()
        # dedup BEFORE the capacity math: under gossip most
        # deliveries are duplicates (every peer forwards the same
        # txs), and the QA_r07 profile showed the dup path paying
        # the full admission bookkeeping per call
        key = tx_key(tx)
        if not self.cache.push(key):
            # record the extra sender for dedup/gossip routing
            for d in self._lane_txs.values():
                e = d.get(key)
                if e is not None and sender:
                    e.senders.add(sender)
            self.metrics.already_received_txs.add()
            raise TxInCacheError("tx already exists in cache")
        try:
            self._check_full(len(tx))
        except MempoolError:
            self.cache.remove(key)
            raise
        checked_at = self.height
        try:
            import time as _time
            _t0 = _time.perf_counter()
            with tracing.span(tracing.MEMPOOL, "checktx",
                              height=self.height, bytes=len(tx)):
                res = await self.proxy_app.check_tx(
                    abci.CheckTxRequest(
                        tx=tx, type=abci.CHECK_TX_TYPE_CHECK))
            self.metrics.checktx_duration_seconds.observe(
                _time.perf_counter() - _t0)
        except Exception:
            self.cache.remove(key)
            raise
        if res.code != abci.CODE_TYPE_OK:
            if not self.config.keep_invalid_txs_in_cache:
                self.cache.remove(key)
            self.metrics.failed_txs.add()
            raise InvalidTxError(res.code, res.log)
        try:
            lane = self._resolve_lane(res.lane_id)
            self._add_tx(tx, key, res.gas_wanted, lane, sender,
                         getattr(res, "recheck_keys", None))
        except MempoolError:
            # a tx never admitted to the pool must not stay cached, or
            # it becomes unsubmittable until LRU eviction
            self.cache.remove(key)
            raise
        # the FinalizeBlock↔recheck gap (the old :150 note): the gate
        # above ran BEFORE the CheckTx await, so a commit cycle that
        # started during the call validated this tx against pre-block
        # state AND already ran its recheck pass without us.  Mark the
        # entry so the NEXT update()'s recheck slice revalidates it
        # unconditionally — key overlap and the watermark may both
        # miss it.  No retry loop here: under sub-second block
        # intervals a validate-retry could chase the tip forever.
        if not self._unlocked.is_set() or self.height != checked_at:
            # pointless (and unbounded) when recheck is disabled —
            # nothing would ever drain the set
            if self.config.recheck and self.contains(key):
                self._pending_recheck.add(key)
                self.metrics.checktx_revalidations.add()
        return res

    def _resolve_lane(self, lane_id: str) -> str:
        if not lane_id:
            return self.default_lane
        if lane_id not in self.lanes:
            raise MempoolError(f"app assigned unknown lane {lane_id!r}")
        return lane_id

    def _check_full(self, tx_size: int) -> None:
        if self.size() >= self.config.size or \
                self._size_bytes + tx_size > self.config.max_txs_bytes:
            self.metrics.rejected_txs.add()
            raise MempoolFullError(
                f"mempool is full: {self.size()} txs, "
                f"{self._size_bytes} bytes")

    def _add_tx(self, tx: bytes, key: bytes, gas_wanted: int,
                lane: str, sender: str,
                recheck_keys=None) -> None:
        if self.contains(key):
            return
        # capacity may have changed across the CheckTx await
        # (reference: isFull re-check in handleCheckTxResponse)
        self._check_full(len(tx))
        self._seq += 1
        entry = MempoolTx(tx=tx, key=key, height=self.height,
                          gas_wanted=gas_wanted, lane=lane,
                          senders={sender} if sender else set(),
                          seq=self._seq,
                          recheck_keys=frozenset(recheck_keys or ()))
        self._lane_txs[lane][key] = entry
        self._append_log.append((self._seq, key))
        if len(self._append_log) > self._APPEND_LOG_MAX:
            drop = len(self._append_log) // 4
            self._log_start_seq = self._append_log[drop - 1][0]
            del self._append_log[:drop]
        self._size_count += 1
        self._size_bytes += len(tx)
        self._lane_bytes[lane] = \
            self._lane_bytes.get(lane, 0) + len(tx)
        self.metrics.tx_size_bytes.observe(len(tx))
        self.metrics.update_sizes(self)
        self.logger.debug("Added tx", lane=lane,
                          tx=key.hex().upper()[:12])
        self._notify_txs_available()
        self._wake_gossip()

    def remove_tx_by_key(self, key: bytes) -> None:
        for d in self._lane_txs.values():
            e = d.pop(key, None)
            if e is not None:
                self._size_count -= 1
                self._size_bytes -= len(e.tx)
                self._lane_bytes[e.lane] = \
                    self._lane_bytes.get(e.lane, 0) - len(e.tx)
                return
        raise MempoolError("transaction not found in mempool")

    # ------------------------------------------------------------------
    def _iwrr_order(self) -> list[MempoolTx]:
        """Interleaved weighted round-robin across lanes by priority
        (reference: iterators.go IWRRIterator)."""
        queues = {lane: list(d.values())
                  for lane, d in self._lane_txs.items() if d}
        if not queues:
            return []
        out: list[MempoolTx] = []
        # each full round grants each lane `priority` slots, interleaved
        while queues:
            for lane in sorted(queues,
                               key=lambda ln: -self.lanes.get(ln, 0)):
                weight = max(1, self.lanes.get(lane, 0))
                q = queues.get(lane)
                if q is None:
                    continue
                take = min(weight, len(q))
                out.extend(q[:take])
                del q[:take]
                if not q:
                    del queues[lane]
        return out

    def reap_max_bytes_max_gas(self, max_bytes: int,
                               max_gas: int) -> list[bytes]:
        """Reference: ReapMaxBytesMaxGas (:690)."""
        txs: list[bytes] = []
        total_bytes = 0
        total_gas = 0
        for e in self._iwrr_order():
            # budget the proto-encoded size (per-tx tag + length varint),
            # not the raw bytes — reference ReapMaxBytesMaxGas uses
            # ComputeProtoSizeForTxs so the encoded block stays under
            # the consensus max_bytes peers enforce
            nb = total_bytes + len(e.tx) + \
                compute_proto_size_overhead(len(e.tx))
            if max_bytes > -1 and nb > max_bytes:
                break
            ng = total_gas + e.gas_wanted
            if max_gas > -1 and ng > max_gas:
                break
            txs.append(e.tx)
            total_bytes, total_gas = nb, ng
        return txs

    def reap_max_txs(self, n: int) -> list[bytes]:
        order = self._iwrr_order()
        if n < 0:
            n = len(order)
        return [e.tx for e in order[:n]]

    def iter_entries(self) -> list[MempoolTx]:
        """Gossip order: same IWRR order the reaper uses."""
        return self._iwrr_order()

    # ------------------------------------------------------------------
    async def update(self, height: int, txs: Sequence[bytes],
                     tx_results: Sequence[abci.ExecTxResult],
                     pre_check: Optional[Callable] = None,
                     post_check: Optional[Callable] = None) -> None:
        """Remove committed txs, then recheck the invalidated slice.

        Reference: Update (:767) — caller must hold the mempool lock
        (BlockExecutor.commit does).  Incremental recheck
        (docs/pipeline.md): the committed block's app-reported
        ``recheck_keys`` select which pooled txs could have been
        invalidated; everything else is revalidated on the bounded-age
        watermark instead of after every block."""
        self.height = height
        self._notified_txs_available = False
        if self._txs_available is not None:
            self._txs_available.clear()
        touched: set[bytes] = set()
        unattributed_commit = False
        for tx, res in zip(txs, tx_results):
            key = tx_key(tx)
            if res.code == abci.CODE_TYPE_OK:
                self.cache.push(key)   # committed: keep in cache forever
                rk = getattr(res, "recheck_keys", None)
                if rk:
                    touched.update(rk)
                else:
                    # a state-changing tx the app didn't attribute:
                    # key targeting is unsound for this block, fall
                    # back to rechecking every attributed entry too
                    unattributed_commit = True
            elif not self.config.keep_invalid_txs_in_cache:
                self.cache.remove(key)
            try:
                self.remove_tx_by_key(key)
            except MempoolError:
                pass
        if self.config.recheck and self.size() > 0:
            import time as _time
            t0 = _time.perf_counter()
            if self.config.recheck_incremental:
                due = self._recheck_slice(height, touched,
                                          unattributed_commit)
                if self._pending_recheck:
                    seen = {e.key for e in due}
                    for d in self._lane_txs.values():
                        for e in d.values():
                            if e.key in self._pending_recheck and \
                                    e.key not in seen:
                                due.append(e)
            else:
                due = [e for d in self._lane_txs.values()
                       for e in d.values()]
            skipped = self.size() - len(due)
            if skipped:
                self.metrics.recheck_skipped_txs.add(skipped)
            if due:
                with tracing.span(tracing.MEMPOOL, "recheck",
                                  height=height, txs=len(due),
                                  skipped=skipped):
                    await self._recheck_entries(due)
            dt = _time.perf_counter() - t0
            self.metrics.recheck_duration_seconds.set(dt)
            self.metrics.recheck_pass_duration_seconds.observe(dt)
        # every commit settles the raced-admission flags — the due
        # slice above consumed them; with recheck disabled or an
        # empty pool there is nothing left they could select
        self._pending_recheck.clear()
        self.metrics.update_sizes(self)
        self._notify_txs_available()

    def _recheck_slice(self, height: int, touched: set,
                       unattributed_commit: bool) -> list[MempoolTx]:
        """The pooled txs the committed block could have invalidated:
        key overlap where the app attributes state keys, plus every
        entry whose last validation is recheck_max_age_blocks old (the
        watermark bounds staleness for unattributed txs and apps, and
        for validity that depends on non-key state like height)."""
        max_age = self.config.recheck_max_age_blocks
        due: list[MempoolTx] = []
        for d in self._lane_txs.values():
            for e in d.values():
                if height - e.height >= max_age:
                    due.append(e)
                elif e.recheck_keys and (
                        unattributed_commit or
                        not touched.isdisjoint(e.recheck_keys)):
                    due.append(e)
        return due

    async def _recheck_entries(self, entries: list[MempoolTx]) -> None:
        """Re-validate the given entries at the new height (reference:
        recheckTxs + handleRecheckTxResponse :618), batching CheckTx
        through the async client — the socket transport pipelines the
        whole chunk in flight instead of paying a round trip per tx."""
        batch = max(1, self.config.recheck_batch_size)
        for i in range(0, len(entries), batch):
            chunk = entries[i:i + batch]
            results = await asyncio.gather(
                *(self.proxy_app.check_tx(abci.CheckTxRequest(
                    tx=e.tx, type=abci.CHECK_TX_TYPE_RECHECK))
                  for e in chunk),
                return_exceptions=True)
            for e, res in zip(chunk, results):
                if isinstance(res, BaseException):
                    raise res
                self.metrics.recheck_times.add()
                if res.code != abci.CODE_TYPE_OK:
                    removed = self._lane_txs.get(e.lane, {}) \
                        .pop(e.key, None)
                    if removed is not None:
                        self._size_count -= 1
                        self._size_bytes -= len(e.tx)
                        self._lane_bytes[e.lane] = \
                            self._lane_bytes.get(e.lane, 0) - len(e.tx)
                        self.metrics.evicted_txs.add()
                    if not self.config.keep_invalid_txs_in_cache:
                        self.cache.remove(e.key)
                else:
                    # revalidated: reset the watermark clock
                    e.height = self.height


class NopMempool(Mempool):
    """Disabled mempool (reference: nop_mempool.go)."""

    async def check_tx(self, tx: bytes, sender: str = ""):
        raise MempoolError("mempool is disabled")

    def reap_max_bytes_max_gas(self, max_bytes: int,
                               max_gas: int) -> list[bytes]:
        return []

    async def update(self, height, txs, tx_results, pre_check=None,
                     post_check=None) -> None:
        pass
