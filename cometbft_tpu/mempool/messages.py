"""Mempool gossip messages: flood txs + have/want reconciliation.

Wire: cometbft.mempool.v2.Message extends the reference Txs oneof with
two reconciliation arms (docs/gossip.md):

  * TxHave — "here is what I hold": a batch of short salted tx-hash
    ids.  Ids are the first ``SHORT_ID_LEN`` bytes of
    ``sha256(salt || tx_key)`` and ride as ONE concatenated bytes blob
    (no per-id tag/length overhead: 256 ids = 2 KiB + envelope).
  * TxWant — "send me these": the subset of a peer's advertised ids
    the receiver could not resolve against its pool + dedup cache.

The salt is carried explicitly so receivers can diff against ANY
advertiser.  Policy (reactor.py) derives it from the chain height
epoch, so nodes near the same height agree on it and short ids stay
comparable across peers — that is what lets the in-flight want
tracker dedup pulls of the same tx from many advertisers.  An
engineered 2^32-work collision only suppresses a pull under ONE salt:
epoch rotation, per-summary self-collision rotation (the sender
re-salts a batch whose own ids collide), and the compact-block /
full-part fallback all bound the damage to a delay.

Old peers negotiate none of this: the capability string
``txrecon/1`` must appear in both handshake NodeInfos or the link
speaks plain flooded Txs.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass

from ..wire.proto import F, Msg, decode, encode

FEATURE_TXRECON = "txrecon/1"

# bytes per short id: 8 bytes keeps the natural collision rate at
# ~n^2/2^65 (immeasurable at any real pool size) while an advert
# costs 1/32nd of the raw txs it summarizes at 256 B/tx
SHORT_ID_LEN = 8

TXS = Msg("cometbft.mempool.v2.Txs",
          F(1, "txs", "bytes", repeated=True))
TX_HAVE = Msg("cometbft.mempool.v2.TxHave",
              F(1, "salt", "bytes"),
              F(2, "ids", "bytes"))
TX_WANT = Msg("cometbft.mempool.v2.TxWant",
              F(1, "salt", "bytes"),
              F(2, "ids", "bytes"))
MESSAGE = Msg("cometbft.mempool.v2.Message",
              F(1, "txs", "msg", msg=TXS),
              F(2, "tx_have", "msg", msg=TX_HAVE),
              F(3, "tx_want", "msg", msg=TX_WANT))


@dataclass
class TxsMessage:
    txs: list

    TYPE = "txs"


@dataclass
class TxHaveMessage:
    salt: bytes
    ids: list          # list[bytes], each SHORT_ID_LEN long

    TYPE = "tx_have"


@dataclass
class TxWantMessage:
    salt: bytes
    ids: list

    TYPE = "tx_want"


def short_id(salt: bytes, key: bytes) -> bytes:
    """One short salted id (the bulk path is short_ids)."""
    return hashlib.sha256(salt + key).digest()[:SHORT_ID_LEN]


def short_ids(salt: bytes, keys: list) -> list:
    """Short ids for many tx keys, batched through the native sha256
    path when available (summary build + diff at a 5k-tx pool is a
    perf-lab benchmark: gossip_reconcile_roundtrip)."""
    from ..crypto._native_loader import batched_hashes
    items = [salt + k for k in keys]
    hashes = batched_hashes("sha256_many", items)
    if hashes is None:
        hashes = [hashlib.sha256(it).digest() for it in items]
    return [h[:SHORT_ID_LEN] for h in hashes]


def _split_ids(blob: bytes) -> list:
    n = len(blob) // SHORT_ID_LEN
    return [blob[i * SHORT_ID_LEN:(i + 1) * SHORT_ID_LEN]
            for i in range(n)]


def encode_mempool(msg) -> bytes:
    if isinstance(msg, TxsMessage):
        d = {"txs": {"txs": list(msg.txs)}}
    elif isinstance(msg, TxHaveMessage):
        d = {"tx_have": {"salt": msg.salt,
                         "ids": b"".join(msg.ids)}}
    elif isinstance(msg, TxWantMessage):
        d = {"tx_want": {"salt": msg.salt,
                         "ids": b"".join(msg.ids)}}
    else:
        raise ValueError(f"cannot encode mempool message {type(msg)}")
    return encode(MESSAGE, d)


def decode_mempool(raw: bytes):
    d = decode(MESSAGE, raw)
    if "txs" in d:
        return TxsMessage(txs=list(d["txs"].get("txs", [])))
    if "tx_have" in d:
        n = d["tx_have"]
        return TxHaveMessage(salt=n.get("salt", b""),
                             ids=_split_ids(n.get("ids", b"")))
    if "tx_want" in d:
        n = d["tx_want"]
        return TxWantMessage(salt=n.get("salt", b""),
                             ids=_split_ids(n.get("ids", b"")))
    raise ValueError(f"unknown mempool message {sorted(d)}")
