"""Mempool reactor: transaction gossip.

Reference: mempool/reactor.go — one per-peer goroutine walking the lane
iterators, Receive → TryAddTx; senders tracked so a tx never bounces
straight back to where it came from.  Wire: cometbft.mempool.v2.Txs
inside Message (proto/cometbft/mempool/v2/types.proto).
"""
from __future__ import annotations

import asyncio
from typing import Optional

from ..config import MempoolConfig
from ..libs.log import Logger
from ..libs.supervisor import RestartPolicy
from ..p2p.conn import ChannelDescriptor
from ..p2p.switch import Peer, Reactor
from ..wire.proto import F, Msg, encode, decode
from .mempool import CListMempool, MempoolError

MEMPOOL_CHANNEL = 0x30

TXS = Msg("cometbft.mempool.v2.Txs",
          F(1, "txs", "bytes", repeated=True))
MESSAGE = Msg("cometbft.mempool.v2.Message",
              F(1, "txs", "msg", msg=TXS))


class MempoolReactor(Reactor):
    def __init__(self, mempool: CListMempool, config: MempoolConfig,
                 logger: Optional[Logger] = None):
        super().__init__("MEMPOOL")
        self.mempool = mempool
        self.config = config
        if logger is not None:
            self.logger = logger
        self._gossip_tasks: dict[str, object] = {}  # SupervisedTask

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=MEMPOOL_CHANNEL, priority=5,
                                  send_queue_capacity=1000)]

    async def add_peer(self, peer: Peer) -> None:
        if not self.config.broadcast:
            return

        def _stop_peer_on_giveup(st, exc):
            # supervised one-shot teardown (AST-checked invariant)
            if self.switch is not None:
                self.supervisor.spawn(
                    lambda: self.switch.stop_peer(peer, repr(exc)),
                    name=f"stop_peer:{peer.id[:12]}",
                    kind="stop_peer")

        self._gossip_tasks[peer.id] = self.supervisor.spawn(
            lambda: self._gossip_routine(peer),
            name=f"mempool_gossip:{peer.id[:12]}",
            kind="mempool_gossip",
            policy=RestartPolicy(max_restarts=3, window_s=30.0,
                                 backoff_base_s=0.05,
                                 backoff_max_s=1.0),
            on_giveup=_stop_peer_on_giveup)

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        t = self._gossip_tasks.pop(peer.id, None)
        if t is not None:
            t.cancel()

    async def receive(self, chan_id: int, peer: Peer,
                      msg_bytes: bytes) -> None:
        """Reference: reactor.go Receive → TryAddTx."""
        try:
            d = decode(MESSAGE, msg_bytes)
        except Exception as e:
            self.logger.error("bad mempool message", err=str(e))
            return
        for tx in (d.get("txs") or {}).get("txs", []):
            try:
                await self.mempool.check_tx(tx, sender=peer.id)
            except MempoolError:
                pass   # dupes/invalid/full are not peer faults

    # gossip batching: many small txs per wire message instead of one
    # — at 256 B txs the per-message overhead (proto envelope,
    # MConnection framing, a latency-relay hop, a recv wakeup) was the
    # dominant cost, and the 16-node QA rig's ingestion was gossip-
    # bound once the pipelined engine stopped being commit-bound
    _BATCH_TXS = 64
    _BATCH_BYTES = 32 * 1024

    async def _gossip_routine(self, peer: Peer) -> None:
        """Send txs the peer hasn't seen, batched, advancing a
        sequence cursor so an unchanged pool costs nothing per tick
        (reference: per-peer broadcastTxRoutine over persistent lane
        iterators)."""
        sent: set[bytes] = set()
        last_seq = -1
        try:
            while True:
                if self.mempool._seq == last_seq:
                    # fallback-timeout wakeup with no append since the
                    # last scan: don't re-walk a large quiet pool
                    await self.mempool.wait_for_change(last_seq)
                    continue
                send_failed = False
                batch: list = []
                batch_bytes = 0

                def flush_batch() -> bool:
                    nonlocal batch, batch_bytes
                    if not batch:
                        return True
                    ok = peer.send(MEMPOOL_CHANNEL, encode(
                        MESSAGE,
                        {"txs": {"txs": [e.tx for e in batch]}}))
                    if ok:
                        sent.update(e.key for e in batch)
                    batch = []
                    batch_bytes = 0
                    return ok

                for d in self.mempool._lane_txs.values():
                    for e in list(d.values()):
                        if e.key in sent or peer.id in e.senders:
                            continue
                        batch.append(e)
                        batch_bytes += len(e.tx)
                        if len(batch) >= self._BATCH_TXS or \
                                batch_bytes >= self._BATCH_BYTES:
                            if not flush_batch():
                                send_failed = True
                                break
                    if send_failed:
                        break
                if not send_failed and not flush_batch():
                    send_failed = True
                last_seq = self.mempool._seq
                # bound the dedup set by live pool content
                if len(sent) > 4 * max(1, self.mempool.size()):
                    live = {e.key for d in
                            self.mempool._lane_txs.values()
                            for e in d.values()}
                    sent &= live
                if send_failed:
                    # peer send-queue backpressure: retry on a timer;
                    # reset the cursor so the retry actually rescans
                    await asyncio.sleep(0.05)
                    last_seq = -1
                else:
                    # park until the pool appends (clist-wait analog);
                    # the call returns immediately if _seq already
                    # moved during the scan above
                    await self.mempool.wait_for_change(last_seq)
        except asyncio.CancelledError:
            raise
        # crashes propagate to the supervisor (bounded restart, then
        # drop the peer on give-up)
