"""Mempool reactor: transaction gossip.

Reference: mempool/reactor.go — one per-peer goroutine walking the lane
iterators, Receive → TryAddTx; senders tracked so a tx never bounces
straight back to where it came from.  Wire: cometbft.mempool.v2
Message (mempool/messages.py).

Two gossip planes (docs/gossip.md):

  * flood (reference behavior) — push every tx the peer hasn't seen,
    batched.  Used for peers that did not negotiate ``txrecon/1``.
  * have/want reconciliation — advertise short salted tx-hash
    summaries (TxHave); the peer diffs them against its pool + dedup
    cache and pulls only what it misses (TxWant → Txs).  Brand-new
    LOCAL txs are still pushed in full to ~recon_push_peers peers so
    first-hop latency doesn't pay an advertise/pull round trip.
    Bytes on the wire stop scaling with peer count: N-1 peers send a
    tx's 8-byte id instead of its body, and the QA profile's ~90%
    duplicate CheckTx deliveries collapse into id lookups.

Want tracking is single-writer: the reactor's receive path and the
supervised sweep routine both run on the event loop and every
mutation of the in-flight table goes through ``_WantTracker``'s
methods (the same owner discipline PeerState grew in
consensus/reactor.py).
"""
from __future__ import annotations

import asyncio
import hashlib
from collections import OrderedDict
from typing import Optional

from ..config import MempoolConfig
from ..libs import tracing
from ..libs.log import Logger
from ..libs.supervisor import RestartPolicy
from ..p2p.conn import ChannelDescriptor
from ..p2p.switch import Peer, Reactor
from .mempool import CListMempool, MempoolError, TxInCacheError
from .messages import (
    FEATURE_TXRECON, TxHaveMessage, TxWantMessage,
    TxsMessage, decode_mempool, encode_mempool, short_ids,
)

MEMPOOL_CHANNEL = 0x30

# re-exported for callers that built raw flood messages against the
# pre-reconciliation reactor (tests, tools)
from .messages import MESSAGE, TXS  # noqa: E402,F401


class _ShortMap:
    """My pool's keys under one advertiser salt: short id -> tx key.

    Extended incrementally via the pool's append sequence; entries
    are never removed when a tx commits (the dedup cache still knows
    the tx, and a stale hit only suppresses a useless re-pull), but
    the map is rebuilt from the live pool when it outgrows it."""

    __slots__ = ("cursor", "m")

    def __init__(self):
        self.cursor = -1
        self.m: dict[bytes, bytes] = {}


class _WantEntry:
    __slots__ = ("salt", "sid", "asked", "ts", "tries", "advertisers")

    def __init__(self, salt: bytes, sid: bytes, asked: str, ts: float):
        self.salt = salt
        self.sid = sid
        self.asked = asked          # peer currently pulled from
        self.ts = ts                # when the current want was sent
        self.tries = 1
        self.advertisers = [asked]  # every peer that announced the id


class _WantTracker:
    """In-flight pulls keyed by (salt, short id) with per-peer
    attribution.  Single writer: the reactor's event-loop callbacks.
    All mutation goes through these methods so the invariants (bounded
    size, advertiser dedup, monotone tries) live in one place."""

    MAX_WANTS = 32_768

    def __init__(self):
        self._m: dict[tuple, _WantEntry] = {}
        # live salt -> entry count, so active_salts() is O(#salts)
        # per call instead of an O(table) scan per received Txs
        # message (the table bound is 32k)
        self._salt_counts: dict[bytes, int] = {}

    def __len__(self) -> int:
        return len(self._m)

    def get(self, salt: bytes, sid: bytes) -> Optional[_WantEntry]:
        return self._m.get((salt, sid))

    def active_salts(self) -> list:
        return list(self._salt_counts)

    def _salt_dec(self, salt: bytes) -> None:
        n = self._salt_counts.get(salt, 0) - 1
        if n <= 0:
            self._salt_counts.pop(salt, None)
        else:
            self._salt_counts[salt] = n

    def open(self, salt: bytes, sid: bytes, peer_id: str,
             now: float) -> Optional[_WantEntry]:
        """Record a new in-flight want; None when the table is full
        (the tx still arrives via flood peers / compact blocks)."""
        if len(self._m) >= self.MAX_WANTS:
            return None
        w = _WantEntry(salt, sid, peer_id, now)
        self._m[(salt, sid)] = w
        self._salt_counts[salt] = self._salt_counts.get(salt, 0) + 1
        return w

    def note_advertiser(self, w: _WantEntry, peer_id: str) -> None:
        if peer_id not in w.advertisers:
            w.advertisers.append(peer_id)

    def resolve(self, salt: bytes, sid: bytes) -> bool:
        if self._m.pop((salt, sid), None) is None:
            return False
        self._salt_dec(salt)
        return True

    def drop(self, w: _WantEntry) -> None:
        if self._m.pop((w.salt, w.sid), None) is not None:
            self._salt_dec(w.salt)

    def reissue(self, w: _WantEntry, peer_id: str, now: float) -> None:
        w.asked = peer_id
        w.ts = now
        w.tries += 1

    def expired(self, now: float, timeout_s: float) -> list:
        return [w for w in self._m.values()
                if now - w.ts >= timeout_s]


class MempoolReactor(Reactor):
    def __init__(self, mempool: CListMempool, config: MempoolConfig,
                 logger: Optional[Logger] = None):
        super().__init__("MEMPOOL")
        self.mempool = mempool
        self.config = config
        if logger is not None:
            self.logger = logger
        self._gossip_tasks: dict[str, object] = {}  # SupervisedTask
        # --- reconciliation state (owner: the event loop via the
        # methods below; docs/gossip.md) ----------------------------
        self._recon_peers: dict[str, Peer] = {}
        self._wants = _WantTracker()
        self._short_maps: "OrderedDict[bytes, _ShortMap]" = \
            OrderedDict()
        self._salt_bump = 0          # bumped on summary self-collision
        self._salt_cache: tuple = (None, b"")
        self._sweep_task = None
        # token bucket for NEW-salt map builds: each unseen salt costs
        # a full-pool rehash, so a peer spamming random salts could
        # burn CPU; beyond the budget its adverts are dropped (the
        # tx still arrives via other advertisers / the push path)
        self._salt_build_tokens = 16.0
        self._salt_build_last = 0.0

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=MEMPOOL_CHANNEL, priority=5,
                                  send_queue_capacity=1000)]

    def get_features(self) -> list[str]:
        return [FEATURE_TXRECON] \
            if getattr(self.config, "gossip_reconciliation", False) \
            else []

    def _peer_recon(self, peer: Peer) -> bool:
        """Both sides negotiated have/want gossip on this link."""
        if not getattr(self.config, "gossip_reconciliation", False):
            return False
        has = getattr(peer, "has_feature", None)
        return bool(has and has(FEATURE_TXRECON))

    async def add_peer(self, peer: Peer) -> None:
        if not self.config.broadcast:
            return

        def _stop_peer_on_giveup(st, exc):
            # supervised one-shot teardown (AST-checked invariant)
            if self.switch is not None:
                self.supervisor.spawn(
                    lambda: self.switch.stop_peer(peer, repr(exc)),
                    name=f"stop_peer:{peer.id[:12]}",
                    kind="stop_peer")

        if self._peer_recon(peer):
            self._recon_peers[peer.id] = peer
            self._ensure_sweeper()
        self._gossip_tasks[peer.id] = self.supervisor.spawn(
            lambda: self._gossip_routine(peer),
            name=f"mempool_gossip:{peer.id[:12]}",
            kind="mempool_gossip",
            policy=RestartPolicy(max_restarts=3, window_s=30.0,
                                 backoff_base_s=0.05,
                                 backoff_max_s=1.0),
            on_giveup=_stop_peer_on_giveup)

    async def remove_peer(self, peer: Peer, reason: str) -> None:
        self._recon_peers.pop(peer.id, None)
        t = self._gossip_tasks.pop(peer.id, None)
        if t is not None:
            t.cancel()

    # ------------------------------------------------------------------
    # receive path

    async def receive(self, chan_id: int, peer: Peer,
                      msg_bytes: bytes) -> None:
        """Reference: reactor.go Receive → TryAddTx, extended with the
        TxHave/TxWant reconciliation arms."""
        try:
            msg = decode_mempool(msg_bytes)
        except Exception as e:
            self.logger.error("bad mempool message", err=str(e))
            return
        # one peer-attributed instant per wire message (not per tx):
        # bounded by the p2p recv rate, and what lets fleet_report
        # attribute reconciliation chatter to links
        if isinstance(msg, TxsMessage):
            tracing.instant(tracing.MEMPOOL, "txs_recv",
                            txs=len(msg.txs), peer=peer.id[:12],
                            chan=chan_id)
            await self._receive_txs(msg, peer)
        elif isinstance(msg, TxHaveMessage):
            tracing.instant(tracing.MEMPOOL, "have_recv",
                            ids=len(msg.ids), peer=peer.id[:12],
                            chan=chan_id)
            self._receive_have(msg, peer)
        elif isinstance(msg, TxWantMessage):
            tracing.instant(tracing.MEMPOOL, "want_recv",
                            ids=len(msg.ids), peer=peer.id[:12],
                            chan=chan_id)
            self._receive_want(msg, peer)

    async def _receive_txs(self, msg: TxsMessage, peer: Peer) -> None:
        m = self.mempool.metrics
        useful = 0
        for tx in msg.txs:
            m.gossip_txs_received.add()
            try:
                await self.mempool.check_tx(tx, sender=peer.id)
                useful += len(tx)
            except TxInCacheError:
                m.gossip_txs_duplicate.add()
            except MempoolError:
                pass   # invalid/full are not peer faults
        recv = m.gossip_txs_received.value
        if recv:
            m.duplicate_delivery_ratio.set(
                m.gossip_txs_duplicate.value / recv)
        if useful and self.switch is not None:
            # the single claimed mempool channel — bounded like
            # touch_channel's ch_id
            ch_id = f"{MEMPOOL_CHANNEL:#x}"
            self.switch.metrics.message_useful_bytes_total \
                .with_labels(ch_id).add(useful)
        if msg.txs and len(self._wants):
            self._settle_wants(msg.txs)

    def _settle_wants(self, txs: list) -> None:
        """Arrived txs clear their in-flight want entries under every
        active salt (the salts present in the tracker are a handful —
        neighboring epochs plus rotation bumps).  Hashing is batched
        through the native sha256 path: per-tx hashlib calls here
        were measurable at QA batch sizes."""
        from ..types.tx import hash_each
        salts = self._wants.active_salts()
        if not salts:
            return
        keys = hash_each(txs)
        for salt in salts:
            for sid in short_ids(salt, keys):
                self._wants.resolve(salt, sid)

    def _allow_salt_build(self, salt: bytes) -> bool:
        """Rate-limit full-pool rehashes for salts we have no map for
        (~1.6 builds/s sustained, burst 16): honest peers converge on
        the epoch salt, so only a salt-spamming peer ever hits this."""
        if salt in self._short_maps:
            return True
        now = asyncio.get_running_loop().time()
        self._salt_build_tokens = min(
            16.0, self._salt_build_tokens +
            (now - self._salt_build_last) * 1.6)
        self._salt_build_last = now
        if self._salt_build_tokens < 1.0:
            return False
        self._salt_build_tokens -= 1.0
        return True

    def _receive_have(self, msg: TxHaveMessage, peer: Peer) -> None:
        """Diff the advertised ids against pool + dedup cache; pull
        what's missing, dedup pulls through the in-flight tracker."""
        if not self._peer_recon(peer):
            return
        if not self._allow_salt_build(msg.salt):
            return
        sm = self._short_map(msg.salt)
        m = self.mempool.metrics
        now = asyncio.get_running_loop().time()
        wants: list[bytes] = []
        for sid in msg.ids:
            key = sm.m.get(sid)
            if key is not None:
                # we hold (or held) it: remember the peer as a sender
                # so neither plane ever echoes the tx back at it
                self.mempool.add_sender(key, peer.id)
                continue
            w = self._wants.get(msg.salt, sid)
            if w is not None:
                self._wants.note_advertiser(w, peer.id)
                continue
            if self._wants.open(msg.salt, sid, peer.id, now) is None:
                continue            # tracker full; fall back to flood
            wants.append(sid)
        if wants:
            m.recon_wants_sent.add(len(wants))
            cap = self.config.recon_advert_max_ids
            for i in range(0, len(wants), cap):
                peer.send(MEMPOOL_CHANNEL, encode_mempool(
                    TxWantMessage(salt=msg.salt,
                                  ids=wants[i:i + cap])))

    def _receive_want(self, msg: TxWantMessage, peer: Peer) -> None:
        """Serve a pull: resolve the short ids under the salt WE
        advertised with and push the full txs back, batched."""
        if not self._peer_recon(peer):
            # same gate as _receive_have: an unnegotiated peer must
            # not reach the salt-map machinery at all — its wants
            # would drain the shared new-salt token bucket and starve
            # honest adverts
            return
        if not self._allow_salt_build(msg.salt):
            return
        sm = self._short_map(msg.salt)
        self.mempool.metrics.recon_wants_received.add(len(msg.ids))
        batch: list[bytes] = []
        batch_bytes = 0
        for sid in msg.ids:
            key = sm.m.get(sid)
            tx = self.mempool.get_tx_by_hash(key) \
                if key is not None else None
            if tx is None:
                continue            # committed/evicted since advertised
            batch.append(tx)
            batch_bytes += len(tx)
            if len(batch) >= self._BATCH_TXS or \
                    batch_bytes >= self._BATCH_BYTES:
                peer.send(MEMPOOL_CHANNEL,
                          encode_mempool(TxsMessage(batch)))
                batch, batch_bytes = [], 0
        if batch:
            peer.send(MEMPOOL_CHANNEL,
                      encode_mempool(TxsMessage(batch)))

    # ------------------------------------------------------------------
    # reconciliation: salts and short-id maps

    _SHORT_MAPS_MAX = 4

    def _current_salt(self) -> bytes:
        """Epoch salt shared by nodes near the same height (see
        mempool/messages.py), plus this node's rotation bump."""
        epoch = self.mempool.height // max(
            1, self.config.recon_salt_epoch_blocks)
        tag = (epoch, self._salt_bump)
        if self._salt_cache[0] != tag:
            self._salt_cache = (tag, hashlib.sha256(
                b"cometbft/txrecon/1" +
                epoch.to_bytes(8, "big") +
                self._salt_bump.to_bytes(4, "big")).digest()[:8])
        return self._salt_cache[1]

    def _rotate_salt(self) -> None:
        self._salt_bump += 1
        self.mempool.metrics.recon_salt_rotations.add()

    def _short_map(self, salt: bytes) -> _ShortMap:
        sm = self._short_maps.get(salt)
        if sm is None:
            sm = _ShortMap()
            self._short_maps[salt] = sm
            while len(self._short_maps) > self._SHORT_MAPS_MAX:
                self._short_maps.popitem(last=False)
            # seed from the dedup cache: a fresh map (new salt epoch)
            # built from the live pool alone would not know committed
            # txs, so every advertiser of a just-committed tx would
            # trigger a full-body re-pull that check_tx then rejects
            # — one wasted round trip per advertiser, straight into
            # the gated duplicate ratio.  One batched hash pass over
            # the (bounded) cache, already rate-limited by the
            # new-salt token bucket.
            cached = self.mempool.cache.keys()
            if cached:
                for sid, key in zip(short_ids(salt, cached), cached):
                    sm.m[sid] = key
        else:
            self._short_maps.move_to_end(salt)
        if sm.cursor != self.mempool._seq:
            # O(new) via the append log; full-pool walk only when the
            # cursor predates the bounded log (fresh map, long idle)
            fresh = self.mempool.keys_appended_after(sm.cursor)
            if fresh is None:
                fresh = [e.key
                         for d in self.mempool._lane_txs.values()
                         for e in d.values() if e.seq > sm.cursor]
            if fresh:
                for sid, key in zip(short_ids(salt, fresh), fresh):
                    sm.m[sid] = key
            sm.cursor = self.mempool._seq
        # bound: stale (committed) entries are useful — they answer
        # adverts for txs the dedup cache still knows — until the
        # map dwarfs live pool + cache combined; the rebuild keeps
        # both sources
        bound = max(8192, 2 * (max(1, self.mempool.size()) +
                               len(self.mempool.cache)))
        if len(sm.m) > bound:
            keep = [e.key for d in self.mempool._lane_txs.values()
                    for e in d.values()]
            keep += self.mempool.cache.keys()
            sm.m = dict(zip(short_ids(salt, keep), keep))
        return sm

    # ------------------------------------------------------------------
    # want-timeout sweep: refetch from another advertiser

    def _ensure_sweeper(self) -> None:
        if self._sweep_task is not None and \
                not self._sweep_task.done():
            return
        self._sweep_task = self.supervisor.spawn(
            lambda: self._want_sweep_routine(),
            name="mempool_want_sweep", kind="mempool_want_sweep",
            policy=RestartPolicy(max_restarts=10, window_s=60.0,
                                 backoff_base_s=0.1,
                                 backoff_max_s=2.0))

    async def _want_sweep_routine(self) -> None:
        timeout_s = self.config.recon_want_timeout_ns / 1e9
        try:
            while True:
                await asyncio.sleep(max(0.05, timeout_s / 2))
                self.sweep_wants(
                    asyncio.get_running_loop().time(), timeout_s)
        except asyncio.CancelledError:
            raise

    def sweep_wants(self, now: float, timeout_s: float) -> None:
        """Expire stale pulls: re-ask the next live advertiser, drop
        the entry once every advertiser has been tried (the tx still
        arrives via compact-block fallback or a later advert)."""
        m = self.mempool.metrics
        regroup: dict[str, dict[bytes, list]] = {}
        for w in self._wants.expired(now, timeout_s):
            candidates = [p for p in w.advertisers
                          if p in self._recon_peers]
            if not candidates or w.tries > len(w.advertisers) + 1:
                self._wants.drop(w)
                m.recon_want_expired.add()
                continue
            nxt = None
            for off in range(len(candidates)):
                c = candidates[(w.tries + off) % len(candidates)]
                if c != w.asked or len(candidates) == 1:
                    nxt = c
                    break
            if nxt is None:
                nxt = candidates[0]
            self._wants.reissue(w, nxt, now)
            m.recon_want_refetches.add()
            regroup.setdefault(nxt, {}).setdefault(
                w.salt, []).append(w.sid)
        cap = self.config.recon_advert_max_ids
        for peer_id, by_salt in regroup.items():
            peer = self._recon_peers.get(peer_id)
            if peer is None:
                continue
            for salt, sids in by_salt.items():
                # same message-size bound as the first-pull path: a
                # mass expiry (peer death with thousands in flight)
                # must not land as one table-sized TxWant
                for i in range(0, len(sids), cap):
                    peer.send(MEMPOOL_CHANNEL, encode_mempool(
                        TxWantMessage(salt=salt,
                                      ids=sids[i:i + cap])))

    # ------------------------------------------------------------------
    # gossip routines

    # gossip batching: many small txs per wire message instead of one
    # — at 256 B txs the per-message overhead (proto envelope,
    # MConnection framing, a latency-relay hop, a recv wakeup) was the
    # dominant cost, and the 16-node QA rig's ingestion was gossip-
    # bound once the pipelined engine stopped being commit-bound
    _BATCH_TXS = 64
    _BATCH_BYTES = 32 * 1024

    async def _gossip_routine(self, peer: Peer) -> None:
        if self._peer_recon(peer):
            await self._recon_gossip_routine(peer)
        else:
            await self._flood_gossip_routine(peer)

    def _fresh_entries(self, cursor: int, peer_id: str,
                       handled: set) -> list:
        """Pool entries appended after ``cursor`` that this peer may
        still need.  The per-peer cursor is the backpressure resume
        point: a send-queue stall retries its own unsent remainder
        and scans forward from here — the old ``last_seq = -1`` reset
        re-walked (and re-batched) the entire pool on every stall.
        Steady state reads the mempool's bounded append log (O(new));
        a cursor older than the log falls back to the full scan."""
        keys = self.mempool.keys_appended_after(cursor)
        if keys is None:
            return [e for d in self.mempool._lane_txs.values()
                    for e in list(d.values())
                    if e.seq > cursor and e.key not in handled and
                    peer_id not in e.senders]
        out = []
        seen: set[bytes] = set()
        for k in keys:
            if k in seen or k in handled:
                continue
            seen.add(k)
            e = self.mempool.get_entry(k)
            if e is not None and e.seq > cursor and \
                    peer_id not in e.senders:
                out.append(e)
        return out

    def _push_fast_path(self, key: bytes, peer_id: str) -> bool:
        """Deterministic per-(tx, peer) lottery choosing ~K of the
        recon peers a brand-new local tx is pushed to in full."""
        k = self.config.recon_push_peers
        if k <= 0:
            return False
        n = len(self._recon_peers)
        if n <= k:
            return True
        h = int.from_bytes(hashlib.sha256(
            key + peer_id.encode()).digest()[:2], "big")
        return h < (65536 * k) // n

    async def _recon_gossip_routine(self, peer: Peer) -> None:
        """Advertise short-id summaries of pool entries the peer
        hasn't seen; push brand-new local txs in full to ~K peers
        (the first-hop fast path).  Same cursor/parking/backpressure
        shape as the flood routine."""
        advertised: set[bytes] = set()
        pending: list = []      # unsent remainder of a stalled pass
        cursor = -1             # highest pool seq already scanned
        m = self.mempool.metrics
        try:
            while True:
                if not pending and self.mempool._seq == cursor:
                    await self.mempool.wait_for_change(cursor)
                    continue
                scan_seq = self.mempool._seq
                todo = pending
                pending = []
                if scan_seq != cursor:
                    todo = todo + self._fresh_entries(
                        cursor, peer.id, advertised)
                    cursor = scan_seq
                push: list = []
                push_bytes = 0
                have: list = []

                def flush_push() -> bool:
                    nonlocal push, push_bytes
                    if not push:
                        return True
                    ok = peer.send(MEMPOOL_CHANNEL, encode_mempool(
                        TxsMessage([e.tx for e in push])))
                    if ok:
                        advertised.update(e.key for e in push)
                        m.recon_pushed_txs.add(len(push))
                        push, push_bytes = [], 0
                    return ok

                def flush_have() -> bool:
                    nonlocal have
                    if not have:
                        return True
                    # self-collision check: two distinct pool keys
                    # colliding under the current salt would make the
                    # summary ambiguous — rotate and re-derive
                    # (satellite test: short-hash collision)
                    keys = [e.key for e in have]
                    for _ in range(4):
                        salt = self._current_salt()
                        sids = short_ids(salt, keys)
                        if len(set(sids)) == len(keys):
                            break
                        self._rotate_salt()
                    ok = peer.send(MEMPOOL_CHANNEL, encode_mempool(
                        TxHaveMessage(salt=salt, ids=sids)))
                    if ok:
                        advertised.update(keys)
                        have = []
                    return ok

                fail_idx = -1
                for i, e in enumerate(todo):
                    if e.key in advertised or \
                            peer.id in e.senders or \
                            not self.mempool.contains(e.key):
                        continue    # sent meanwhile / committed
                    if not e.senders and \
                            self._push_fast_path(e.key, peer.id):
                        push.append(e)
                        push_bytes += len(e.tx)
                        if len(push) >= self._BATCH_TXS or \
                                push_bytes >= self._BATCH_BYTES:
                            if not flush_push():
                                fail_idx = i + 1
                                break
                    else:
                        have.append(e)
                        if len(have) >= \
                                self.config.recon_advert_max_ids:
                            if not flush_have():
                                fail_idx = i + 1
                                break
                if fail_idx < 0 and not flush_push():
                    fail_idx = len(todo)
                if fail_idx < 0 and not flush_have():
                    fail_idx = len(todo)
                if fail_idx >= 0:
                    # peer send-queue backpressure: keep the unsent
                    # batches + unvisited tail and retry on a timer —
                    # the cursor already covers this pass, so the
                    # retry never re-walks the pool
                    pending = push + have + todo[fail_idx:]
                    await asyncio.sleep(0.05)
                    continue
                # bound the dedup set by live pool content
                if len(advertised) > 4 * max(1, self.mempool.size()):
                    live = {e.key for d in
                            self.mempool._lane_txs.values()
                            for e in d.values()}
                    advertised &= live
                await self.mempool.wait_for_change(cursor)
        except asyncio.CancelledError:
            raise
        # crashes propagate to the supervisor (bounded restart — the
        # fresh routine's cursor=-1 rescan re-covers anything the
        # lost pending list held — then drop the peer on give-up)

    async def _flood_gossip_routine(self, peer: Peer) -> None:
        """Send txs the peer hasn't seen, batched, advancing a
        sequence cursor so an unchanged pool costs nothing per tick
        (reference: per-peer broadcastTxRoutine over persistent lane
        iterators).  The fallback plane for peers that did not
        negotiate ``txrecon/1``."""
        sent: set[bytes] = set()
        pending: list = []      # unsent remainder of a stalled pass
        cursor = -1             # highest pool seq already scanned
        try:
            while True:
                if not pending and self.mempool._seq == cursor:
                    # fallback-timeout wakeup with no append since the
                    # last scan: don't re-walk a large quiet pool
                    await self.mempool.wait_for_change(cursor)
                    continue
                scan_seq = self.mempool._seq
                todo = pending
                pending = []
                if scan_seq != cursor:
                    todo = todo + self._fresh_entries(
                        cursor, peer.id, sent)
                    cursor = scan_seq
                batch: list = []
                batch_bytes = 0

                def flush_batch() -> bool:
                    nonlocal batch, batch_bytes
                    if not batch:
                        return True
                    ok = peer.send(MEMPOOL_CHANNEL, encode_mempool(
                        TxsMessage([e.tx for e in batch])))
                    if ok:
                        sent.update(e.key for e in batch)
                        batch, batch_bytes = [], 0
                    return ok

                fail_idx = -1
                for i, e in enumerate(todo):
                    if e.key in sent or peer.id in e.senders or \
                            not self.mempool.contains(e.key):
                        continue    # sent meanwhile / committed
                    batch.append(e)
                    batch_bytes += len(e.tx)
                    if len(batch) >= self._BATCH_TXS or \
                            batch_bytes >= self._BATCH_BYTES:
                        if not flush_batch():
                            fail_idx = i + 1
                            break
                if fail_idx < 0 and not flush_batch():
                    fail_idx = len(todo)
                if fail_idx >= 0:
                    # peer send-queue backpressure: keep the unsent
                    # batch + unvisited tail and retry on a timer —
                    # the cursor already covers this pass, so the
                    # retry never re-walks the pool (the old
                    # ``last_seq = -1`` reset rescanned and rebatched
                    # the whole pool per stall)
                    pending = batch + todo[fail_idx:]
                    await asyncio.sleep(0.05)
                    continue
                # bound the dedup set by live pool content
                if len(sent) > 4 * max(1, self.mempool.size()):
                    live = {e.key for d in
                            self.mempool._lane_txs.values()
                            for e in d.values()}
                    sent &= live
                # park until the pool appends (clist-wait analog);
                # the call returns immediately if _seq already moved
                # during the scan above
                await self.mempool.wait_for_change(cursor)
        except asyncio.CancelledError:
            raise
        # crashes propagate to the supervisor (bounded restart — the
        # fresh routine's cursor=-1 rescan re-covers anything the
        # lost pending list held — then drop the peer on give-up)
