"""Mempool metrics (reference: mempool/metrics.go + metrics.gen.go —
same names/labels so dashboards port)."""
from __future__ import annotations

from typing import Optional

from ..libs import metrics as libmetrics


class Metrics:
    def __init__(self, registry: Optional[libmetrics.Registry] = None):
        m = registry if registry is not None else libmetrics.Registry()
        self.size = m.gauge(
            "mempool", "size",
            "Number of uncommitted transactions in the mempool.")
        self.size_bytes = m.gauge(
            "mempool", "size_bytes",
            "Total size of the mempool in bytes.")
        self.lane_size = m.gauge(
            "mempool", "lane_size",
            "Number of txs in a lane.", labels=("lane",))
        self.lane_bytes = m.gauge(
            "mempool", "lane_bytes",
            "Bytes in a lane.", labels=("lane",))
        self.tx_size_bytes = m.histogram(
            "mempool", "tx_size_bytes",
            "Histogram of transaction sizes in bytes.",
            buckets=(16, 64, 256, 1024, 4096, 16384, 65536, 262144,
                     1048576))
        self.failed_txs = m.counter(
            "mempool", "failed_txs",
            "Number of failed transactions.")
        self.rejected_txs = m.counter(
            "mempool", "rejected_txs",
            "Number of rejected transactions (mempool full / too "
            "large).")
        self.evicted_txs = m.counter(
            "mempool", "evicted_txs",
            "Number of evicted transactions.")
        self.recheck_times = m.counter(
            "mempool", "recheck_times",
            "Number of times transactions were rechecked in the "
            "mempool.")
        self.recheck_duration_seconds = m.gauge(
            "mempool", "recheck_duration_seconds",
            "Duration of the last recheck pass.")
        # metrics v2: latency distributions for the two mempool hot
        # paths — per-CheckTx app round-trips and whole recheck passes
        # (the last-value gauge above stays for reference parity)
        self.checktx_duration_seconds = m.histogram(
            "mempool", "checktx_duration_seconds",
            "Histogram of CheckTx app round-trip latency in seconds.",
            buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1,
                     0.5, 1.0, 5.0))
        self.recheck_pass_duration_seconds = m.histogram(
            "mempool", "recheck_pass_duration_seconds",
            "Histogram of full post-commit recheck pass duration in "
            "seconds.",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.5,
                     5.0, 10.0))
        self.already_received_txs = m.counter(
            "mempool", "already_received_txs",
            "Number of duplicate transaction receptions (cache "
            "hits).")
        # incremental recheck (docs/pipeline.md)
        self.recheck_skipped_txs = m.counter(
            "mempool", "recheck_skipped_txs",
            "Pooled transactions the incremental recheck proved "
            "untouched by the committed block and skipped.")
        self.checktx_revalidations = m.counter(
            "mempool", "checktx_revalidations",
            "CheckTx calls re-issued because a commit cycle raced "
            "the in-flight validation (the FinalizeBlock-to-recheck "
            "admission gap).")
        # reconciliation gossip (docs/gossip.md): the duplicate-
        # delivery ratio is the first-class gated number — the
        # fraction of peer-delivered txs the dedup cache had already
        # seen.  Flood gossip ran at ~90% in the 16-node QA rig; the
        # have/want plane is gated at <= 50% — at most 2
        # deliveries per tx per node on average (tools/qa.py).
        self.gossip_txs_received = m.counter(
            "mempool", "gossip_txs_received",
            "Transactions delivered by peer gossip, duplicates "
            "included.")
        self.gossip_txs_duplicate = m.counter(
            "mempool", "gossip_txs_duplicate",
            "Peer-delivered transactions the dedup cache had "
            "already seen.")
        self.duplicate_delivery_ratio = m.gauge(
            "mempool", "duplicate_delivery_ratio",
            "gossip_txs_duplicate / gossip_txs_received, cumulative "
            "— the redundancy of the tx gossip plane.")
        self.recon_wants_sent = m.counter(
            "mempool", "recon_wants_sent",
            "Short ids pulled from peers (TxWant) after a summary "
            "diff found them missing.")
        self.recon_wants_received = m.counter(
            "mempool", "recon_wants_received",
            "Short ids peers pulled from this node.")
        self.recon_want_refetches = m.counter(
            "mempool", "recon_want_refetches",
            "In-flight wants re-issued to another advertiser after "
            "the want timeout.")
        self.recon_want_expired = m.counter(
            "mempool", "recon_want_expired",
            "In-flight wants dropped with no advertiser left to "
            "retry.")
        self.recon_pushed_txs = m.counter(
            "mempool", "recon_pushed_txs",
            "Brand-new local transactions pushed in full to the "
            "fast-path peer subset.")
        self.recon_salt_rotations = m.counter(
            "mempool", "recon_salt_rotations",
            "Summary salt rotations forced by a short-id "
            "self-collision.")

    def update_sizes(self, mempool) -> None:
        self.size.set(mempool.size())
        self.size_bytes.set(mempool.size_bytes())
        for lane in getattr(mempool, "_lane_txs", {}):
            n, b = mempool.lane_sizes(lane)
            self.lane_size.with_labels(lane).set(n)
            self.lane_bytes.with_labels(lane).set(b)
