"""Testnet manifests, random generation, setup, and an in-process
runner with perturbations and invariant checks.

Reference: test/e2e/pkg/manifest.go (the TOML manifest schema),
test/e2e/generator (random sampling of the config space for nightly
runs), test/e2e/runner (setup.go writes per-node homes; start.go,
perturb.go, wait.go drive the net; tests assert invariants).  The
docker-compose layer is replaced by in-process `Node` objects on real
localhost sockets — same protocols end to end, no containers.
"""
from __future__ import annotations

import asyncio
import dataclasses
import json
import os
import random
import socket
from dataclasses import dataclass, field
from typing import Optional

# -- manifest schema ---------------------------------------------------------

PERTURBATIONS = ("kill", "restart", "pause")
MODES = ("validator", "full")


@dataclass
class ManifestNode:
    """Reference: manifest.go ManifestNode."""
    mode: str = "validator"            # validator | full
    # height at which the node joins (0 = from genesis); late joiners
    # exercise blocksync (reference: StartAt)
    start_at: int = 0
    key_type: str = "ed25519"
    db_backend: str = "memdb"
    # perturbations applied mid-run (reference: perturb.go)
    perturb: list[str] = field(default_factory=list)
    # reference: RetainBlocks drives app retain height
    retain_blocks: int = 0
    send_no_load: bool = False
    # emulated-latency zone (reference: latency_emulation.go — tc/
    # netem between zones; here a TCP relay adds the delay per link)
    zone: str = ""


@dataclass
class Manifest:
    """Reference: manifest.go Manifest (the supported subset)."""
    chain_id: str = "e2e-net"
    initial_height: int = 1
    key_type: str = "ed25519"
    abci_protocol: str = "builtin"     # builtin | builtin_unsync
    disable_pex: bool = False
    # target load during the run
    load_tx_rate: int = 40
    load_tx_size: int = 200
    nodes: dict[str, ManifestNode] = field(default_factory=dict)
    # node name -> voting power (defaults: validators at 100)
    validators: dict[str, int] = field(default_factory=dict)
    # one-way link latency between zones, "zoneA:zoneB" -> ms
    # (reference: manifest zones + latency_emulation.go)
    latency_ms: dict[str, int] = field(default_factory=dict)
    # artificial ABCI call delays in ms (reference: manifest
    # prepare_proposal_delay etc.)
    prepare_proposal_delay_ms: int = 0
    process_proposal_delay_ms: int = 0
    check_tx_delay_ms: int = 0
    finalize_block_delay_ms: int = 0
    # duplicate-vote evidences to inject mid-run over RPC
    # (reference: manifest.go Evidence + runner/evidence.go)
    evidence: int = 0

    def link_delay_s(self, za: str, zb: str) -> float:
        if not za or not zb or za == zb:
            return 0.0
        ms = self.latency_ms.get(f"{za}:{zb}",
                                 self.latency_ms.get(f"{zb}:{za}", 0))
        return ms / 1000.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Manifest":
        nodes = {name: ManifestNode(**nd)
                 for name, nd in (d.get("nodes") or {}).items()}
        kw = {k: v for k, v in d.items() if k != "nodes"}
        m = cls(**kw)
        m.nodes = nodes
        return m

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")

    @classmethod
    def load(cls, path: str) -> "Manifest":
        with open(path) as f:
            return cls.from_dict(json.load(f))


def generate(seed: int = 0, max_nodes: int = 4) -> Manifest:
    """Randomly sample the testnet config space (reference:
    test/e2e/generator/generate.go)."""
    # scramble the seed: consecutive small seeds otherwise share
    # their first Mersenne draws and sample near-identical configs
    rng = random.Random((seed * 2654435761 + 97) % 2 ** 32)
    n_vals = rng.randint(2, max(2, max_nodes - 1))
    n_full = rng.randint(0, max(0, max_nodes - n_vals))
    m = Manifest(
        chain_id=f"gen-{seed}",
        key_type=rng.choice(["ed25519", "secp256k1"]),
        abci_protocol=rng.choice(["builtin", "builtin_unsync"]),
        disable_pex=rng.random() < 0.25,
        load_tx_rate=rng.choice([20, 40, 80]),
        load_tx_size=rng.choice([128, 256, 1024]),
    )
    for i in range(n_vals):
        node = ManifestNode(mode="validator",
                            key_type=m.key_type,
                            db_backend=rng.choice(["memdb", "sqlite"]))
        # perturb at most one validator so the net keeps quorum
        if i == n_vals - 1 and n_vals > 2 and rng.random() < 0.5:
            node.perturb = [rng.choice(PERTURBATIONS)]
        m.nodes[f"validator{i:02d}"] = node
        m.validators[f"validator{i:02d}"] = rng.choice([50, 100])
    for i in range(n_full):
        m.nodes[f"full{i:02d}"] = ManifestNode(
            mode="full", key_type=m.key_type,
            start_at=rng.choice([0, 3]))
    # sometimes spread the net over two latency zones
    if rng.random() < 0.3:
        zones = ["zone-a", "zone-b"]
        for i, nm in enumerate(m.nodes.values()):
            nm.zone = zones[i % 2]
        m.latency_ms["zone-a:zone-b"] = rng.choice([50, 100, 200])
    # sometimes mimic app computation time
    if rng.random() < 0.3:
        m.finalize_block_delay_ms = rng.choice([20, 50])
        m.check_tx_delay_ms = rng.choice([0, 5])
    # sometimes inject byzantine evidence mid-run
    if rng.random() < 0.25:
        m.evidence = rng.choice([1, 2, 4])
    return m


# -- setup (reference: runner/setup.go) --------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@dataclass
class RelaySpec:
    """One latency-emulation relay: listens on `port`, forwards to
    the target with a one-way delay (reference: tc/netem in
    latency_emulation.go, externalized as a TCP relay)."""
    port: int
    target_host: str
    target_port: int
    delay_s: float


def setup(manifest: Manifest, outdir: str
          ) -> tuple[dict[str, "object"], list[RelaySpec]]:
    """Write per-node homes (keys, genesis, config overrides with
    pre-allocated ports and persistent-peer wiring).  Returns
    (node name -> Config, latency relays to run).  With zone
    latencies configured, a node's persistent-peers entries point at
    per-link relays; PEX is disabled in that case so gossiped real
    addresses don't bypass the emulated links."""
    from ..config import Config
    from ..p2p.key import NodeKey
    from ..privval import FilePV
    from ..types.genesis import GenesisDoc, GenesisValidator
    from ..types.timestamp import Timestamp

    use_latency = bool(manifest.latency_ms)
    cfgs: dict[str, Config] = {}
    pvs: dict[str, object] = {}
    node_ids: dict[str, str] = {}
    p2p_ports: dict[str, int] = {}
    for name, nm in manifest.nodes.items():
        home = os.path.join(outdir, name)
        cfg = Config()
        cfg.base.home = home
        cfg.base.moniker = name
        cfg.base.db_backend = nm.db_backend
        p2p_port, rpc_port = _free_port(), _free_port()
        cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
        cfg.p2p.pex = not manifest.disable_pex and not use_latency
        cfg.p2p.allow_duplicate_ip = True
        cfg.consensus.timeout_commit_ns = 50_000_000
        cfg.blocksync.enable = True
        os.makedirs(os.path.join(home, "config"), exist_ok=True)
        os.makedirs(os.path.join(home, "data"), exist_ok=True)
        pv = FilePV.load_or_generate(
            cfg.base.path(cfg.base.priv_validator_key_file),
            cfg.base.path(cfg.base.priv_validator_state_file),
            key_type=nm.key_type)
        nk = NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
        node_ids[name] = nk.id
        p2p_ports[name] = p2p_port
        cfgs[name] = cfg
        pvs[name] = pv
    doc = GenesisDoc(
        chain_id=manifest.chain_id,
        genesis_time=Timestamp.now(),
        initial_height=manifest.initial_height,
        validators=[GenesisValidator(
            address=b"", pub_key=pvs[name].get_pub_key(),
            power=manifest.validators.get(name, 100))
            for name, nm in manifest.nodes.items()
            if nm.mode == "validator"],
    )
    # genesis must permit the net's key type or the first validator
    # UPDATE (e.g. equivocation punishment) halts consensus
    # (reference: runner/setup.go:169 sets PubKeyTypes = [KeyType])
    doc.consensus_params.validator.pub_key_types = \
        [manifest.key_type]
    relays: list[RelaySpec] = []
    for name, cfg in cfgs.items():
        doc.save_as(cfg.base.path(cfg.base.genesis_file))
        peers = []
        for other, other_port in p2p_ports.items():
            # dial only "later" nodes: one direction per pair, so
            # slow links can't race both ends into mutually-rejected
            # duplicate connections (the reverse direction is covered
            # by the other node's inbound accept)
            if other <= name:
                continue
            delay = manifest.link_delay_s(
                manifest.nodes[name].zone, manifest.nodes[other].zone)
            port = other_port
            if delay > 0:
                port = _free_port()
                relays.append(RelaySpec(
                    port=port, target_host="127.0.0.1",
                    target_port=other_port, delay_s=delay))
            peers.append(f"{node_ids[other]}@127.0.0.1:{port}")
        cfg.p2p.persistent_peers = ",".join(peers)
    return cfgs, relays


class Relay:
    """A running latency relay: the listening server plus its live
    connection handlers (so close() actually tears everything down)."""

    def __init__(self):
        self.server = None
        self.tasks: set = set()

    def close(self) -> None:
        if self.server is not None:
            self.server.close()
        for t in list(self.tasks):
            t.cancel()

    async def wait_closed(self) -> None:
        if self.server is not None:
            await self.server.wait_closed()
        # handler/pump tasks were cancelled by close(): wait them out
        # so loop teardown never sees pending relay tasks
        if self.tasks:
            await asyncio.gather(*list(self.tasks),
                                 return_exceptions=True)


async def start_relay(spec: RelaySpec) -> Relay:
    """Run one latency relay.  Bytes are delivered delay_s after they
    arrive WITHOUT throttling bandwidth (a per-direction delivery
    queue, like netem's constant delay)."""
    relay = Relay()

    async def handle(reader, writer):
        try:
            tr, tw = await asyncio.open_connection(
                spec.target_host, spec.target_port)
        except OSError:
            writer.close()
            return

        async def pump(src, dst):
            loop = asyncio.get_running_loop()
            queue: asyncio.Queue = asyncio.Queue()

            async def deliver():
                while True:
                    at, data = await queue.get()
                    if data is None:
                        break
                    now = loop.time()
                    if at > now:
                        await asyncio.sleep(at - now)
                    try:
                        dst.write(data)
                        await dst.drain()
                    except (ConnectionError, OSError):
                        break

            task = loop.create_task(deliver())
            try:
                while True:
                    data = await src.read(65536)
                    if not data:
                        break
                    queue.put_nowait(
                        (loop.time() + spec.delay_s, data))
            except (ConnectionError, OSError):
                pass
            finally:
                queue.put_nowait((0, None))
                await task
                try:
                    dst.close()
                except OSError:
                    pass

        await asyncio.gather(pump(reader, tw), pump(tr, writer))

    async def tracked_handle(reader, writer):
        task = asyncio.current_task()
        relay.tasks.add(task)
        try:
            await handle(reader, writer)
        except asyncio.CancelledError:
            for w in (writer,):
                try:
                    w.close()
                except OSError:
                    pass
            raise
        finally:
            relay.tasks.discard(task)

    relay.server = await asyncio.start_server(tracked_handle,
                                              "127.0.0.1", spec.port)
    return relay


async def inject_evidence(manifest: Manifest, cfgs: dict,
                          endpoint: str, count: int) -> list[str]:
    """Forge `count` duplicate-vote evidences signed by a real
    validator's key and submit them over RPC (reference:
    runner/evidence.go — generates conflicting precommits against a
    recent height and broadcasts them).  Returns evidence hashes."""
    import base64

    from ..privval import FilePV
    from ..rpc.client import HTTPClient
    from ..types import canonical
    from ..types.block_id import BlockID
    from ..types.evidence import DuplicateVoteEvidence
    from ..types.part_set import PartSetHeader
    from ..types.vote import Vote
    from ..wire import encode as wencode, pb as wpb

    # byzantine validators: rotate across the manifest's validators
    # (reference: evidence.go targets different validators per
    # evidence, and a block carrying several offences by ONE
    # validator exercises a different app path than several offenders)
    val_names = [name for name, nm in manifest.nodes.items()
                 if nm.mode == "validator"]
    pvs = {}
    for name in val_names:
        cfg = cfgs[name]
        pvs[name] = FilePV.load_or_generate(
            cfg.base.path(cfg.base.priv_validator_key_file),
            cfg.base.path(cfg.base.priv_validator_state_file))

    cli = HTTPClient(endpoint, timeout=30.0)
    st = await cli.status()
    tip = int(st["sync_info"]["latest_block_height"])
    total_power = sum(manifest.validators.get(name, 100)
                      for name in val_names)
    vals = await cli.validators(max(1, tip - 2))
    index_by_addr = {v.address: i
                     for i, v in enumerate(vals.validators)}
    per_val = {}
    for name in val_names:
        addr = pvs[name].get_pub_key().address()
        if addr not in index_by_addr:
            raise ValueError(
                f"validator {name} (addr {addr.hex()[:12]}) not in "
                f"the set at height {max(1, tip - 2)}")
        per_val[name] = (addr, index_by_addr[addr],
                         manifest.validators.get(name, 100))

    hashes = []
    for j in range(count):
        val_name = val_names[j % len(val_names)]
        pv = pvs[val_name]
        addr, val_index, val_power = per_val[val_name]
        # heights may clamp together on a young chain, so the forged
        # block ids vary per evidence — identical evidence would be
        # deduped by the pool and never reach the requested count
        h = max(1, tip - 2 - j)
        sh, _ = await cli.commit(h)          # exact header time
        votes = []
        # a < b block-id order via the leading byte; the j suffix
        # keeps evidences distinct at any count without byte overflow
        for lead in (b"\x01", b"\x02"):
            bid = lead + j.to_bytes(31, "big")
            v = Vote(type=canonical.PRECOMMIT_TYPE, height=h, round=0,
                     block_id=BlockID(
                         hash=bid,
                         part_set_header=PartSetHeader(1, bid)),
                     timestamp=sh.header.time,
                     validator_address=addr,
                     validator_index=val_index)
            # sign directly with the raw key: FilePV would (rightly)
            # refuse the second, conflicting signature
            v.signature = pv.priv_key.sign(
                v.sign_bytes(manifest.chain_id))
            votes.append(v)
        ev = DuplicateVoteEvidence(
            vote_a=votes[0], vote_b=votes[1],
            total_voting_power=total_power,
            validator_power=val_power,
            timestamp=sh.header.time)
        raw = wencode(wpb.EVIDENCE, ev.to_proto_wrapped())
        res = await cli.call(
            "broadcast_evidence",
            evidence=base64.b64encode(raw).decode())
        hashes.append(res["hash"])
    return hashes


# -- runner (reference: runner/{start,perturb,wait}.go) ----------------------

@dataclass
class RunReport:
    target_height: int = 0
    heights: dict[str, int] = field(default_factory=dict)
    load_sent: int = 0
    load_accepted: int = 0
    perturbed: list[str] = field(default_factory=list)
    mismatches: list[str] = field(default_factory=list)
    evidence_injected: list[str] = field(default_factory=list)
    evidence_committed: int = 0
    # seconds from first boot until every node reached target_height
    # (excludes load-drain/teardown; the benchmark-comparable number)
    reached_target_s: float = 0.0


async def run_manifest(manifest: Manifest, outdir: str,
                       target_height: int = 8,
                       timeout_s: float = 90.0) -> RunReport:
    """Boot every node, inject load, apply perturbations once the net
    is past the halfway height, wait for target_height everywhere,
    then check cross-node block-hash/app-hash invariants
    (reference: runner/main.go stage order; tests/block_test.go)."""
    from ..node.node import Node
    from ..rpc.client import HTTPClient
    from . import loadtime

    cfgs, relay_specs = setup(manifest, outdir)
    nodes: dict[str, Node] = {}
    report = RunReport(target_height=target_height)
    load_task: Optional[asyncio.Task] = None
    relay_servers: list[Relay] = []

    def _apply_delays(node: Node) -> None:
        delays = {
            "prepare_proposal":
                manifest.prepare_proposal_delay_ms / 1000.0,
            "process_proposal":
                manifest.process_proposal_delay_ms / 1000.0,
            "check_tx": manifest.check_tx_delay_ms / 1000.0,
            "finalize_block":
                manifest.finalize_block_delay_ms / 1000.0,
        }
        if any(delays.values()) and \
                hasattr(node.app, "abci_delays"):
            node.app.abci_delays = delays

    try:
        boot_t0 = asyncio.get_event_loop().time()
        for r in relay_specs:
            relay_servers.append(await start_relay(r))
        # start_at=0 nodes boot now; late joiners wait for the height
        for name, cfg in cfgs.items():
            if manifest.nodes[name].start_at == 0:
                nodes[name] = Node(cfg)
                _apply_delays(nodes[name])
                await nodes[name].start()
        if not nodes:
            raise ValueError(
                "manifest needs at least one node with start_at=0")

        first = next(iter(nodes.values()))
        endpoint = f"http://{first._rpc_server.listen_addr}"

        load_res = loadtime.LoadResult(experiment_id="")

        async def _load():
            nonlocal load_res
            load_res = await loadtime.generate(
                [endpoint], rate=manifest.load_tx_rate,
                connections=1, duration_s=timeout_s / 3,
                size=manifest.load_tx_size, method="async")

        load_task = asyncio.get_running_loop().create_task(_load())

        async def wait_height(h: int, budget: float) -> None:
            deadline = asyncio.get_running_loop().time() + budget
            while asyncio.get_running_loop().time() < deadline:
                if all(n.height >= h for n in nodes.values()):
                    return
                await asyncio.sleep(0.05)
            raise TimeoutError(
                f"heights {[n.height for n in nodes.values()]} "
                f"< {h} after {budget}s")

        await wait_height(target_height // 2, timeout_s / 3)

        # late joiners enter mid-run and must blocksync to catch up
        for name, cfg in cfgs.items():
            if name not in nodes:
                nodes[name] = Node(cfg)
                _apply_delays(nodes[name])
                await nodes[name].start()

        # perturbations (reference: perturb.go — one node at a time)
        for name, nm in manifest.nodes.items():
            for p in nm.perturb:
                report.perturbed.append(f"{name}:{p}")
                # kill/restart/pause all stop the node and boot a
                # fresh one on the same durable stores (pause maps to
                # a short stop: asyncio tasks can't be frozen the way
                # docker pause freezes a process)
                await nodes[name].stop()
                await asyncio.sleep(0.2 if p != "pause" else 1.0)
                nodes[name] = Node(cfgs[name])
                _apply_delays(nodes[name])
                await nodes[name].start()

        # evidence stage (reference: runner/evidence.go InjectEvidence)
        if manifest.evidence > 0:
            report.evidence_injected = await inject_evidence(
                manifest, cfgs, endpoint, manifest.evidence)

        await wait_height(target_height, timeout_s / 2)
        report.reached_target_s = \
            asyncio.get_event_loop().time() - boot_t0

        # wait for injected evidence to land in committed blocks
        if report.evidence_injected:
            deadline = asyncio.get_event_loop().time() + timeout_s / 4
            want = len(report.evidence_injected)
            ref_node = next(iter(nodes.values()))
            seen = 0
            scanned = manifest.initial_height - 1
            while asyncio.get_event_loop().time() < deadline:
                # incremental: only newly committed blocks each tick
                while scanned < ref_node.height:
                    scanned += 1
                    blk = ref_node.block_store.load_block(scanned)
                    if blk is not None:
                        seen += len(blk.evidence)
                report.evidence_committed = seen
                if seen >= want:
                    break
                await asyncio.sleep(0.1)
    finally:
        if load_task is not None:
            await load_task
        report.load_sent = load_res.sent
        report.load_accepted = load_res.accepted
        for name, n in nodes.items():
            report.heights[name] = n.height
            try:
                await n.stop()
            except Exception:
                pass
        for srv in relay_servers:
            srv.close()
        for srv in relay_servers:
            await srv.wait_closed()

    # invariants on the durable stores: identical block ids and app
    # hashes at every common height (reference: tests/block_test.go,
    # app_test.go)
    ref_name = next(iter(nodes))
    ref = nodes[ref_name]
    for h in range(manifest.initial_height, target_height + 1):
        want = ref.block_store.load_block_meta(h)
        if want is None:
            report.mismatches.append(f"{ref_name} missing meta @{h}")
            continue
        for name, n in nodes.items():
            got = n.block_store.load_block_meta(h)
            if got is None:
                continue            # pruned or still syncing
            if got.block_id.hash != want.block_id.hash:
                report.mismatches.append(
                    f"{name}@{h}: block hash mismatch")
            if got.header.app_hash != want.header.app_hash:
                report.mismatches.append(
                    f"{name}@{h}: app hash mismatch")
    return report
