"""Runnable BASELINE benchmark configs.

BASELINE.md lists five reproduction configs; #1 (the live 4-validator
kvstore testnet) is `tools/manifest.py` + `cometbft_tpu.cmd load`,
and this module packages the verification-workload ones:

  #2  BatchVerifier microbench at 64 / 1k / 10k ed25519 sigs
  #3  light-client skipping verification, large validator set
  #4  consensus replay: per-height VoteSet tally + Commit verify
  #5  stress: large mixed-key commit + bls12381 aggregate path

Run:  python -m cometbft_tpu.tools.benchmarks [--full] [--config N]
Each config prints one JSON line.  --full uses the BASELINE sizes
(1k/10k); the default sizes finish in seconds on a laptop CPU.
"""
from __future__ import annotations

import argparse
import json
import time


def _now() -> float:
    return time.perf_counter()



def _make_valset(privs):
    """ValidatorSet sorted the consensus way, with privkeys re-paired
    to the sorted order (shared by configs #3/#4/#5)."""
    from ..types.validator_set import Validator, ValidatorSet

    vals = [Validator.new(p.pub_key(), 10) for p in privs]
    pairs = sorted(zip(vals, privs),
                   key=lambda vp: (-vp[0].voting_power,
                                   vp[0].address))
    vals = [p[0] for p in pairs]
    privs = [p[1] for p in pairs]
    return ValidatorSet(vals), privs


def _signed_commit(chain_id, vset, privs, height, bid,
                   base_s=1700000000):
    """Commit with one real precommit signature per validator."""
    from ..types import canonical
    from ..types.commit import (BLOCK_ID_FLAG_COMMIT, Commit,
                                CommitSig)
    from ..types.timestamp import Timestamp
    from ..types.vote import Vote

    sigs = []
    for i, (val, priv) in enumerate(zip(vset.validators, privs)):
        ts = Timestamp(base_s + height, i)
        v = Vote(type=canonical.PRECOMMIT_TYPE, height=height,
                 round=0, block_id=bid, timestamp=ts,
                 validator_address=val.address, validator_index=i)
        sigs.append(CommitSig(block_id_flag=BLOCK_ID_FLAG_COMMIT,
                              validator_address=val.address,
                              timestamp=ts,
                              signature=priv.sign(
                                  v.sign_bytes(chain_id))))
    return Commit(height=height, round=0, block_id=bid,
                  signatures=sigs)


def config2_batch_verify(sizes=(64, 1024, 10_000)) -> dict:
    """Reference seam: crypto/ed25519 BatchVerifier ->
    types/validation.go verifyCommitBatch."""
    from ..crypto import batch, ed25519

    results = {}
    for n in sizes:
        privs = [ed25519.gen_priv_key() for _ in range(n)]
        items = []
        for i, p in enumerate(privs):
            msg = b"vote-%d" % i
            items.append((p.pub_key(), msg, p.sign(msg)))
        bv = batch.create_batch_verifier(items[0][0])
        for pub, msg, sig in items:
            bv.add(pub, msg, sig)
        t0 = _now()
        ok, mask = bv.verify()
        dt = (_now() - t0) * 1000
        assert ok and all(mask)
        results[str(n)] = round(dt, 2)
    return {"config": 2, "metric": "batch_verify_ms_by_size",
            "backend": batch.get_backend(),
            "results_ms": results}


def config3_light_client(n_vals=1000, hops=4) -> dict:
    """Reference: light/verifier.go VerifyNonAdjacent with a large
    valset (BASELINE config #3: 1k-validator SignedHeader chain)."""
    from ..crypto import ed25519
    from ..light.verifier import DEFAULT_TRUST_LEVEL, verify
    from ..types.block import Header, SignedHeader
    from ..types.block_id import BlockID
    from ..types.part_set import PartSetHeader
    from ..types.timestamp import Timestamp

    chain_id = "light-bench"
    vset, privs = _make_valset(
        [ed25519.gen_priv_key() for _ in range(n_vals)])

    def signed_header(height: int) -> SignedHeader:
        hdr = Header(chain_id=chain_id, height=height,
                     time=Timestamp(1700000000 + height, 0),
                     validators_hash=vset.hash(),
                     next_validators_hash=vset.hash(),
                     proposer_address=vset.validators[0].address)
        bid = BlockID(hash=hdr.hash(),
                      part_set_header=PartSetHeader(1, b"\x11" * 32))
        return SignedHeader(
            header=hdr,
            commit=_signed_commit(chain_id, vset, privs, height, bid))

    trusted = signed_header(1)
    targets = [signed_header(1 + 10 * (i + 1)) for i in range(hops)]
    now = Timestamp(1700000600, 0)
    t0 = _now()
    for sh in targets:
        verify(trusted, vset, sh, vset,
               365 * 24 * 3600 * 10 ** 9, now, 10 ** 9,
               DEFAULT_TRUST_LEVEL)
    dt = (_now() - t0) * 1000
    return {"config": 3, "metric": "light_skipping_verify_ms_per_hop",
            "validators": n_vals, "hops": hops,
            "value_ms": round(dt / hops, 2)}


def config4_replay_tally(n_vals=150, heights=10) -> dict:
    """Reference: per-height VoteSet tally (vote_set.go AddVote with
    per-vote verify) + Commit verify (BASELINE config #4's hot
    work, without the disk WAL)."""
    from ..crypto import ed25519
    from ..types import canonical
    from ..types.block_id import BlockID
    from ..types.part_set import PartSetHeader
    from ..types.timestamp import Timestamp
    from ..types.validation import verify_commit
    from ..types.vote import Vote
    from ..types.vote_set import VoteSet

    chain_id = "replay-bench"
    vset, privs = _make_valset(
        [ed25519.gen_priv_key() for _ in range(n_vals)])

    tally_ms = []
    commit_ms = []
    for h in range(1, heights + 1):
        bid = BlockID(hash=bytes([h]) * 32,
                      part_set_header=PartSetHeader(1, b"\x07" * 32))
        votes = []
        for i, (val, priv) in enumerate(zip(vset.validators, privs)):
            ts = Timestamp(1700000000 + h, i)
            v = Vote(type=canonical.PRECOMMIT_TYPE, height=h, round=0,
                     block_id=bid, timestamp=ts,
                     validator_address=val.address,
                     validator_index=i)
            v.signature = priv.sign(v.sign_bytes(chain_id))
            votes.append(v)
        vs = VoteSet(chain_id, h, 0, canonical.PRECOMMIT_TYPE, vset)
        t0 = _now()
        for v in votes:
            vs.add_vote(v)
        tally_ms.append((_now() - t0) * 1000)
        commit = vs.make_extended_commit().to_commit()
        t0 = _now()
        verify_commit(chain_id, vset, bid, h, commit)
        commit_ms.append((_now() - t0) * 1000)
    return {"config": 4, "metric": "replay_per_height_ms",
            "validators": n_vals, "heights": heights,
            "tally_ms_p50": round(sorted(tally_ms)[len(tally_ms) // 2],
                                  2),
            "commit_verify_ms_p50": round(
                sorted(commit_ms)[len(commit_ms) // 2], 2)}


def config5_mixed_stress(n_vals=1000, n_bls=64) -> dict:
    """Reference: BASELINE config #5 — mixed-key commit verify (batch
    gate must disengage) + bls12381 aggregate verification."""
    from ..crypto import bls12381, ed25519, secp256k1
    from ..types.block_id import BlockID
    from ..types.part_set import PartSetHeader
    from ..types.validation import verify_commit

    chain_id = "stress-bench"
    privs = []
    for i in range(n_vals):
        if i % 3 == 0:
            privs.append(secp256k1.gen_priv_key())
        elif i % 7 == 0:
            privs.append(bls12381.gen_priv_key_from_secret(
                b"bench-%d" % i))
        else:
            privs.append(ed25519.gen_priv_key())
    vset, privs = _make_valset(privs)
    assert not vset.all_keys_have_same_type()
    bid = BlockID(hash=b"\x55" * 32,
                  part_set_header=PartSetHeader(1, b"\x66" * 32))
    commit = _signed_commit(chain_id, vset, privs, 9, bid)
    t0 = _now()
    verify_commit(chain_id, vset, bid, 9, commit)
    mixed_ms = (_now() - t0) * 1000

    # bls aggregate: n_bls distinct messages, one aggregate signature
    bls_privs = [bls12381.gen_priv_key_from_secret(b"agg-%d" % i)
                 for i in range(n_bls)]
    msgs = [b"block-%d" % i for i in range(n_bls)]
    agg = bls12381.aggregate_signatures(
        [p.sign(m) for p, m in zip(bls_privs, msgs)])
    pks = [p.pub_key() for p in bls_privs]
    t0 = _now()
    ok = bls12381.aggregate_verify(pks, msgs, agg)
    bls_ms = (_now() - t0) * 1000
    assert ok
    return {"config": 5, "metric": "mixed_stress",
            "validators": n_vals, "bls_aggregate_size": n_bls,
            "mixed_commit_verify_ms": round(mixed_ms, 1),
            "bls_aggregate_verify_ms": round(bls_ms, 1)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="BASELINE benchmark configs #2-#5")
    ap.add_argument("--config", type=int, default=0,
                    choices=[0, 2, 3, 4, 5],
                    help="run a single config (2-5); 0 = all. "
                         "Config #1 (live testnet) is tools/"
                         "manifest.py + `cometbft_tpu.cmd load`.")
    ap.add_argument("--full", action="store_true",
                    help="BASELINE sizes (1k light valset, 10k batch)")
    args = ap.parse_args(argv)
    runs = {
        2: lambda: config2_batch_verify(
            (64, 1024, 10_000) if args.full else (64, 256)),
        3: lambda: config3_light_client(
            1000 if args.full else 100),
        4: lambda: config4_replay_tally(150, 10 if args.full else 3),
        5: lambda: config5_mixed_stress(
            10_000 if args.full else 200,
            256 if args.full else 16),
    }
    for n, fn in runs.items():
        if args.config in (0, n):
            print(json.dumps(fn()))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
