"""Opportunistic TPU prober: sample the pooled chip all round, persist
every measurement the moment it lands.

The pooled TPU backend in this environment ("axon") is claimable only
in rare windows — four consecutive rounds of a single blocking 600 s
wait inside bench.py produced a timeout artifact every time even
though the pool DID answer mid-round at least once (VERDICT r4 weak
#1).  The fix is structural:

  * a daemon (``python -m cometbft_tpu.tools.tpu_probe``) runs for the
    whole round, attempting a SHORT claim every few minutes in a child
    process it can kill;
  * the moment a claim lands, the child runs the AOT-exported kernels
    (``ops/exported/`` — zero tracing, the committed artifacts exist
    precisely for this) and appends each measurement to
    ``BENCH_CACHE.json`` IMMEDIATELY — value, shape bucket, kernel,
    git rev, timestamp — because the pool has vanished mid-window
    before;
  * ``bench.py`` folds the cache into the official artifact, labeled
    live vs cached, so a successful device measurement taken at ANY
    point in the round is never lost.

Claim-conflict discipline: only one process may dial the pool at a
time (a second concurrent claim wedges both).  Children take an
exclusive flock on ``.tpu_claim.lock``; ``bench.py`` stops the daemon
via ``.tpu_probe_stop`` before its own attempts.
"""
from __future__ import annotations

import fcntl
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _cache_path() -> str:
    # overridable so smoke tests never pollute the round's artifact
    return os.environ.get("COMETBFT_TPU_PROBE_CACHE",
                          os.path.join(REPO, "BENCH_CACHE.json"))
LOCK_PATH = os.path.join(REPO, ".tpu_claim.lock")
STOP_PATH = os.path.join(REPO, ".tpu_probe_stop")
PID_PATH = os.path.join(REPO, ".tpu_probe.pid")
WORKLOAD_PATH = os.path.join(REPO, ".probe_workload.npz")

N = 10_000
MSG_LEN = 110


def _log(*a):
    print(f"[probe {time.strftime('%H:%M:%S')}]", *a, file=sys.stderr,
          flush=True)


def _git_rev() -> str:
    try:
        return subprocess.run(
            ["git", "-C", REPO, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def append_records(recs: list[dict]) -> None:
    """Append measurement records to BENCH_CACHE.json atomically
    (flock + tmp/rename) — probe children and bench.py both write."""
    if not recs:
        return
    path = _cache_path()
    lock = open(path + ".lock", "w")
    try:
        fcntl.flock(lock, fcntl.LOCK_EX)
        data = {"records": []}
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            pass
        data.setdefault("records", []).extend(recs)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1)
        os.replace(tmp, path)
    finally:
        fcntl.flock(lock, fcntl.LOCK_UN)
        lock.close()


def read_records() -> list[dict]:
    try:
        with open(_cache_path()) as f:
            return json.load(f).get("records", [])
    except (OSError, ValueError):
        return []


# --- workload ---------------------------------------------------------------

def load_or_make_workload(n: int = N):
    """10k (pub, msg, sig) triples, generated once per round and cached
    on disk (keygen costs ~10 s; probe windows are precious)."""
    import numpy as np
    try:
        z = np.load(WORKLOAD_PATH)
        pubs, msgs, sigs = z["pubs"], z["msgs"], z["sigs"]
        if len(pubs) >= n:
            return [(pubs[i].tobytes(), msgs[i].tobytes(),
                     sigs[i].tobytes()) for i in range(n)]
    except (OSError, ValueError, KeyError):
        pass        # missing or corrupt (e.g. a writer was SIGKILLed)
    import secrets
    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey,
        )
        from cryptography.hazmat.primitives.serialization import (
            Encoding, PublicFormat,
        )

        def gen():
            sk = Ed25519PrivateKey.generate()
            return (sk.public_key().public_bytes(
                Encoding.Raw, PublicFormat.Raw), sk.sign)
    except ImportError:
        # containers without `cryptography`: the repo's own signer
        # (same wire format; slower keygen, paid once per cache)
        from ..crypto import ed25519 as _e

        def gen():
            sk = _e.gen_priv_key()
            return sk.pub_key().bytes(), sk.sign
    base = secrets.token_bytes(MSG_LEN - 8)
    items = []
    for i in range(n):
        pub, sign = gen()
        msg = base + i.to_bytes(8, "little")
        items.append((pub, msg, sign(msg)))
    if n < N:
        # never let a small (smoke) workload overwrite the full 10k
        # cache — regenerating it inside a claimed window costs ~10 s
        return items
    tmp = f"{WORKLOAD_PATH}.{os.getpid()}.tmp"
    try:
        with open(tmp, "wb") as f:
            np.savez(f,
                     pubs=np.frombuffer(
                         b"".join(p for p, _, _ in items),
                         np.uint8).reshape(n, 32),
                     msgs=np.frombuffer(
                         b"".join(m for _, m, _ in items),
                         np.uint8).reshape(n, MSG_LEN),
                     sigs=np.frombuffer(
                         b"".join(s for _, _, s in items),
                         np.uint8).reshape(n, 64))
        os.replace(tmp, WORKLOAD_PATH)     # atomic: no torn readers
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return items


def openssl_baseline_ms(items, sample: int = 1000) -> float:
    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PublicKey,
        )

        def check(pub, msg, sig):
            Ed25519PublicKey.from_public_bytes(pub).verify(sig, msg)
    except ImportError:
        from ..crypto.ed25519 import Ed25519PubKey

        def check(pub, msg, sig):
            assert Ed25519PubKey(pub).verify_signature(msg, sig)
    sub = items[:sample]
    t0 = time.perf_counter()
    for pub, msg, sig in sub:
        check(pub, msg, sig)
    return (time.perf_counter() - t0) * 1000.0 * (len(items) / len(sub))


# --- the measurement suite (runs inside a claimed child) --------------------

def _measure_suite(smoke: bool = False) -> int:
    """Claim the backend, then measure — persisting after EVERY step.

    Order is most-important-first (the pool can vanish mid-window):
    pallas device-only @10240 (validates the r4b carry rework + the
    10240 bucket), pallas @16384 (direct comparison to r4's measured
    116 ms), e2e verify_batch, xla @10240, then microbenches.
    """
    import numpy as np

    marker = os.environ.get("COMETBFT_TPU_PROBE_MARKER")
    rev = _git_rev()
    t0 = time.perf_counter()
    import jax
    import jax.numpy as jnp

    if smoke:
        # JAX_PLATFORMS conflicts with this environment's
        # sitecustomize TPU-plugin hook (see tests/conftest.py);
        # post-import config.update never dials the pool
        jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()                      # blocks until claimed
    claim_s = time.perf_counter() - t0
    if marker:
        with open(marker, "w") as f:
            f.write(str(os.getpid()))
    plat_raw = devs[0].platform
    # the pooled chip may register under the plugin's name ("axon")
    # rather than "tpu" — anything that isn't the host CPU is the
    # remote chip, and records normalize to "tpu" so one rare window
    # is never discarded over a label
    plat = "cpu" if plat_raw == "cpu" else "tpu"
    _log(f"claimed backend in {claim_s:.1f}s: {devs}")

    n_items = 64 if smoke else N

    def base_rec(**kw):
        r = {"ts": time.strftime("%Y-%m-%dT%H:%M:%S"), "git_rev": rev,
             "platform": plat, "platform_raw": plat_raw,
             "claim_s": round(claim_s, 1), "n": n_items}
        r.update(kw)
        return r

    if plat != "tpu" and not smoke:
        append_records([base_rec(metric="claim_nontpu",
                                 note=f"backend={plat}; suite skipped")])
        return 0

    items = load_or_make_workload(n_items)
    base_ms = openssl_baseline_ms(items, min(n_items, 1000))
    append_records([base_rec(metric="openssl_baseline",
                             value_ms=round(base_ms, 1))])

    from ..ops import ed25519_jax as ej
    from ..ops import aot

    def time_fn(fn, reps=5):
        fn()                                   # warm (compile/load)
        ts = []
        for _ in range(reps):
            t = time.perf_counter()
            fn()
            ts.append((time.perf_counter() - t) * 1000.0)
        return float(np.median(ts)), [round(t, 1) for t in ts]

    # device-only kernel dispatches over the AOT artifacts; compiled
    # pallas only runs on TPU, so smoke (CPU) covers the xla kernel
    buckets = [64] if smoke else [10240, 16384]
    kernels = ([("xla", buckets)] if smoke else
               [("pallas", buckets), ("xla", buckets[:1])])
    prepped = {}
    for m in buckets:
        prepped[m] = ej.prep_arrays(items, m)
    for kernel, ms in kernels:
        for m in ms:
            a_b, r_b, s_w8, k_w8, pre_bad = prepped[m]
            da, dr = jnp.asarray(a_b), jnp.asarray(r_b)
            ds, dk = jnp.asarray(s_w8), jnp.asarray(k_w8)
            for d in (da, dr, ds, dk):
                d.block_until_ready()
            exp = aot.load(kernel, m)
            used_aot = (exp is not None and plat == "tpu"
                        and "tpu" in getattr(exp, "platforms", ()))

            def live_dispatch(kernel=kernel, da=da, dr=dr, ds=ds,
                              dk=dk):
                if kernel == "pallas":
                    np.asarray(ej._pallas_verify_packed(
                        da, dr, ds, dk, kernel="pallas"))
                else:
                    np.asarray(ej._jit_verify_packed(da, dr, ds, dk))

            if used_aot:
                try:
                    np.asarray(exp.call(da, dr, ds, dk))

                    def dispatch(exp=exp, da=da, dr=dr, ds=ds, dk=dk):
                        np.asarray(exp.call(da, dr, ds, dk))
                except Exception as e:
                    # e.g. the backend registers as "axon" and the
                    # export refuses the platform: fall back to live
                    # jit rather than burning the window
                    _log(f"AOT call failed ({e!r:.120}); live jit")
                    used_aot = False
                    dispatch = live_dispatch
            else:
                dispatch = live_dispatch
            try:
                t_first = time.perf_counter()
                med, runs = time_fn(dispatch)
                first_s = round(time.perf_counter() - t_first
                                - sum(runs) / 1000.0, 1)
                append_records([base_rec(
                    metric=f"{kernel}_device_only", bucket=m,
                    value_ms=round(med, 2), runs=runs, aot=used_aot,
                    first_call_s=first_s,
                    baseline_cpu_ms=round(base_ms, 1))])
                _log(f"{kernel}@{m} device-only {med:.1f} ms "
                     f"(aot={used_aot}, first={first_s}s)")
            except Exception as e:
                append_records([base_rec(
                    metric=f"{kernel}_device_only", bucket=m,
                    error=repr(e)[:300])])
                _log(f"{kernel}@{m} failed: {e!r}")

    # e2e: full production path (prep + transfer + kernel + mask).
    # Two arms per kernel (ISSUE 14): the tiled+overlapped pipeline
    # (host_prep of tile i+1 runs under JAX async dispatch of tile i)
    # and the monolithic single dispatch (tile pinned above n), with
    # the measured overlap ratio read from the pipeline's histogram —
    # this is the number the next claimed window must produce on a
    # real chip (the CPU backend can only prove plumbing, not
    # overlap).  AOT coverage of the tile bucket is checked first so
    # a missing artifact never burns the window tracing a tile shape.
    from ..crypto.pipeline import overlap_histogram, tile_size
    missing = aot.missing_tile_artifacts("xla")
    if missing:
        append_records([base_rec(metric="tile_artifacts_missing",
                                 buckets=missing)])
        _log(f"tile buckets without AOT artifacts: {missing}")
    tile = 64 if smoke else tile_size()
    # the monolithic arm pins single-dispatch by raising the tile to
    # the TOP pad bucket — verify_batch's tile is bucket-clamped, so
    # a workload above 16384 sigs would silently run the pipelined
    # path in BOTH arms and mislabel a claimed window's records
    assert n_items <= 16384, \
        "monolithic arm unpinnable above the top pad bucket"
    for kernel in (("xla",) if smoke else ("pallas", "xla")):
        os.environ["COMETBFT_TPU_KERNEL"] = kernel
        try:
            ok, mask = ej.verify_batch(items)
            if not ok:
                raise AssertionError(
                    f"workload must verify; mask false at "
                    f"{[i for i, v in enumerate(mask) if not v][:5]}")
            for arm, t in (("monolithic", max(n_items, 16384)),
                           ("pipelined", tile)):
                os.environ["COMETBFT_TPU_VERIFY_TILE"] = str(t)
                ohist = overlap_histogram()
                o_sum, o_cnt = ohist._sum, ohist._count
                med, runs = time_fn(lambda: ej.verify_batch(items))
                rec = base_rec(
                    metric=f"{kernel}_e2e_{arm}",
                    value_ms=round(med, 2), runs=runs, tile=t,
                    baseline_cpu_ms=round(base_ms, 1),
                    vs_baseline=round(base_ms / med, 2))
                if ohist._count > o_cnt:
                    rec["overlap_ratio"] = round(
                        (ohist._sum - o_sum) / (ohist._count - o_cnt),
                        3)
                append_records([rec])
                _log(f"{kernel} e2e {arm} {med:.1f} ms "
                     f"({base_ms/med:.1f}x, "
                     f"overlap={rec.get('overlap_ratio')})")
                if arm == "monolithic":
                    # keep the historical series comparable
                    append_records([base_rec(
                        metric=f"{kernel}_e2e",
                        value_ms=round(med, 2), runs=runs,
                        baseline_cpu_ms=round(base_ms, 1),
                        vs_baseline=round(base_ms / med, 2))])
        except Exception as e:
            append_records([base_rec(metric=f"{kernel}_e2e",
                                     error=repr(e)[:300])])
            _log(f"{kernel} e2e failed: {e!r}")
        finally:
            os.environ.pop("COMETBFT_TPU_VERIFY_TILE", None)
    os.environ.pop("COMETBFT_TPU_KERNEL", None)

    # correctness spot-check through the production dispatch: one
    # corrupted signature must be attributed exactly
    try:
        bad_items = list(items[:min(256, len(items))])
        pub, msg, sig = bad_items[7]
        bad_items[7] = (pub, msg, sig[:8] + bytes([sig[8] ^ 1])
                        + sig[9:])
        ok, mask = ej.verify_batch(bad_items)
        good = (not ok) and (not mask[7]) and all(
            mask[i] for i in range(len(bad_items)) if i != 7)
        append_records([base_rec(metric="mask_attribution",
                                 value_ms=0.0, passed=bool(good))])
    except Exception as e:
        append_records([base_rec(metric="mask_attribution",
                                 error=repr(e)[:300])])

    # per-primitive microbenches (floor analysis) — best-effort
    try:
        from ..ops import microbench
        recs = microbench.run_suite(base_rec, smoke=smoke)
        _log(f"microbench: {len(recs)} records")
    except Exception as e:
        _log(f"microbench skipped: {e!r}")
    return 0


# --- parent-side attempt / daemon -------------------------------------------

def attempt_once(claim_timeout: float = 150.0,
                 measure_budget: float = 900.0,
                 smoke: bool = False,
                 ignore_stop: bool = False) -> bool:
    """Spawn a measurement child; kill it unless it claims the backend
    within claim_timeout (the marker file extends the deadline to
    measure_budget).  Returns True if the child claimed."""
    marker = os.path.join(REPO, f".tpu_probe_marker.{os.getpid()}")
    try:
        os.unlink(marker)
    except OSError:
        pass
    env = dict(os.environ, COMETBFT_TPU_PROBE_MARKER=marker)
    env.pop("JAX_PLATFORMS", None)      # must see the real backend
    argv = [sys.executable, "-m", "cometbft_tpu.tools.tpu_probe",
            "--child"]
    if smoke:
        argv.append("--smoke")
    lock = open(LOCK_PATH, "w")
    got_lock = False
    t_lock = time.monotonic()
    while time.monotonic() - t_lock < claim_timeout:
        try:
            fcntl.flock(lock, fcntl.LOCK_EX | fcntl.LOCK_NB)
            got_lock = True                 # one pool dialer at a time
            break
        except OSError:
            time.sleep(2.0)
    if not got_lock:
        # another child is mid-measure; its records land in the cache
        _log("claim lock busy; skipping this attempt")
        lock.close()
        return False
    try:
        p = subprocess.Popen(argv, env=env, cwd=REPO,
                             stdout=sys.stderr, stderr=sys.stderr,
                             start_new_session=True)
        t0 = time.monotonic()
        claimed = False
        while p.poll() is None:
            if not claimed and os.path.exists(marker):
                claimed = True
                _log("child claimed the backend; extending deadline")
            limit = measure_budget if claimed else claim_timeout
            if time.monotonic() - t0 > limit:
                _log(f"killing child after {limit:.0f}s "
                     f"(claimed={claimed})")
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except OSError:
                    p.kill()
                p.wait()
                break
            if (os.path.exists(STOP_PATH) and not claimed
                    and not ignore_stop):
                _log("stop requested; killing unclaimed child")
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except OSError:
                    p.kill()
                p.wait()
                break
            time.sleep(2.0)
        # the child may claim and exit within one poll interval (fast
        # suites): re-read the marker before the finally unlinks it
        claimed = claimed or os.path.exists(marker)
        return claimed
    finally:
        try:
            os.unlink(marker)
        except OSError:
            pass
        fcntl.flock(lock, fcntl.LOCK_UN)
        lock.close()


def request_stop(wait_s: float = 120.0) -> None:
    """Ask a running daemon to exit (used by bench.py before its own
    claim attempts); waits for the pid file to clear."""
    with open(STOP_PATH, "w") as f:
        f.write("stop")
    t0 = time.monotonic()
    while time.monotonic() - t0 < wait_s:
        try:
            with open(PID_PATH) as f:
                pid = int(f.read().strip())
            os.kill(pid, 0)                  # still alive?
        except (OSError, ValueError):
            return
        time.sleep(2.0)
    # daemon still up (likely mid-measure): leave it — its child holds
    # the claim lock, and our own attempt will block on that lock


def daemon_main(interval: float = 240.0, claim_timeout: float = 150.0,
                measure_budget: float = 900.0,
                max_age_s: float = 10.5 * 3600) -> int:
    try:
        os.unlink(STOP_PATH)
    except OSError:
        pass
    with open(PID_PATH, "w") as f:
        f.write(str(os.getpid()))
    _log(f"daemon up (pid {os.getpid()}), interval {interval:.0f}s")
    t0 = time.monotonic()
    successes = 0
    try:
        while True:
            if os.path.exists(STOP_PATH):
                _log("stop file present; exiting")
                return 0
            if time.monotonic() - t0 > max_age_s:
                _log("max age reached; exiting")
                return 0
            claimed = attempt_once(claim_timeout, measure_budget)
            if claimed:
                successes += 1
                # after a successful suite, slow down: repeats only
                # sharpen medians
                interval = max(interval, 900.0)
            # sleep in small steps so stop stays responsive
            slept = 0.0
            while slept < interval:
                if os.path.exists(STOP_PATH):
                    _log("stop file present; exiting")
                    return 0
                time.sleep(5.0)
                slept += 5.0
    finally:
        try:
            os.unlink(PID_PATH)
        except OSError:
            pass


def main(argv: list[str]) -> int:
    if "--child" in argv:
        return _measure_suite(smoke="--smoke" in argv)
    if "--once" in argv:
        # manual one-shots must not be self-killed by a stop file left
        # behind by an earlier bench.py run
        return 0 if attempt_once(smoke="--smoke" in argv,
                                 ignore_stop=True) else 1
    return daemon_main()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
