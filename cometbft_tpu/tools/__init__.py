"""Operational tooling: load generation, latency reports, testnet
manifests (reference: test/loadtime, test/e2e/runner, test/e2e/pkg)."""
