"""Load generation and latency reporting.

Reference: test/loadtime — the `load` tool paces timestamped
transactions into a running network over c connections at r tx/s
(payload/payload.go: "a=" + hex(encoded payload) so the kvstore only
ever stores one key), and the `report` tool reads committed blocks
back, matches payloads by experiment id, and reports latency
statistics (report/report.go).  Block-interval statistics mirror
test/e2e/runner/benchmark.go (avg/stddev/min/max production time).
"""
from __future__ import annotations

import asyncio
import json
import math
import secrets
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

_KEY_PREFIX = b"a="
MAX_PAYLOAD_SIZE = 4 * 1024 * 1024


def payload_bytes(experiment_id: str, size: int = 256, rate: int = 0,
                  connections: int = 0,
                  now_ns: Optional[int] = None) -> bytes:
    """One timestamped tx (reference: payload.NewBytes).  The tx is
    kvstore-compatible: a single "a" key whose value is the
    hex-encoded payload, padded with random hex up to `size`."""
    if size > MAX_PAYLOAD_SIZE:
        raise ValueError(f"size {size} too large")
    body = {
        "id": experiment_id,
        "time_ns": time.time_ns() if now_ns is None else now_ns,
        "rate": rate,
        "connections": connections,
    }
    raw = json.dumps(body, separators=(",", ":")).encode().hex()
    tx = _KEY_PREFIX + raw.encode()
    if len(tx) < size:
        # random hex padding outside the JSON (split by '.')
        pad = size - len(tx) - 1
        tx += b"." + secrets.token_hex((pad + 1) // 2)[:pad].encode()
    return tx


def payload_from_tx(tx: bytes) -> Optional[dict]:
    """Reference: payload.FromBytes — None if not a load payload."""
    if not tx.startswith(_KEY_PREFIX):
        return None
    body = tx[len(_KEY_PREFIX):].split(b".", 1)[0]
    try:
        return json.loads(bytes.fromhex(body.decode()))
    except (ValueError, json.JSONDecodeError):
        return None


@dataclass
class LoadResult:
    experiment_id: str
    sent: int = 0
    accepted: int = 0
    errors: int = 0
    dropped: int = 0            # pacing ticks skipped at the cap
    duration_s: float = 0.0


async def generate(endpoints: list[str], *, rate: int = 100,
                   connections: int = 1, duration_s: float = 10.0,
                   size: int = 256,
                   experiment_id: Optional[str] = None,
                   method: str = "sync",
                   max_in_flight: int = 0) -> LoadResult:
    """Open-loop pacing of `rate` tx/s total across `connections`
    workers per endpoint for `duration_s`.

    Reference behavior: test/loadtime/cmd/load main.go — the
    cometbft-load-test transactors maintain the REQUESTED rate with
    concurrent in-flight requests.  (VERDICT r4 weak #3: the old
    worker awaited each RPC round trip inside its pacing loop, so
    offered load capped at connections x 1/RTT — ~13 tx/s on the QA
    net — no matter the requested rate, and the engine's saturation
    point was never measured.)

    Each pacing tick fires the send as its OWN task; completions are
    harvested asynchronously.  `max_in_flight` bounds outstanding
    requests per worker (default sized to rate x client-timeout so the
    bound only binds when the endpoint is badly behind); a tick that
    finds the window full is counted in `dropped`, so offered load is
    always visible as sent + dropped ≈ rate x duration.  A stalled
    event loop catches up by sending immediately until the schedule is
    level again, preserving the offered average."""
    from ..rpc.client import HTTPClient

    exp_id = experiment_id or uuid.uuid4().hex[:16]
    res = LoadResult(experiment_id=exp_id)
    start = time.monotonic()
    deadline = start + duration_s
    n_workers = max(1, connections) * len(endpoints)
    per_worker_interval = n_workers / max(1, rate)
    timeout = 10.0
    cap = max_in_flight or max(
        8, math.ceil(timeout * rate / n_workers) + 4)

    async def send_one(cli) -> None:
        tx = payload_bytes(exp_id, size=size, rate=rate,
                           connections=connections)
        try:
            if method == "async":
                r = await cli.broadcast_tx_async(tx)
            else:
                r = await cli.broadcast_tx_sync(tx)
            if int(r.get("code", 0)) == 0:
                res.accepted += 1
            else:
                res.errors += 1
        except Exception:
            res.errors += 1

    async def worker(endpoint: str, widx: int) -> None:
        cli = HTTPClient(endpoint, timeout=timeout)
        tasks: set[asyncio.Task] = set()
        # stagger workers across the pacing interval
        await asyncio.sleep(per_worker_interval * widx / n_workers)
        next_at = time.monotonic()
        while time.monotonic() < deadline:
            if len(tasks) >= cap:
                res.dropped += 1
            else:
                res.sent += 1
                t = asyncio.create_task(send_one(cli))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
            next_at += per_worker_interval
            delay = next_at - time.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)
        if tasks:
            await asyncio.wait(set(tasks), timeout=timeout + 2.0)
        for t in list(tasks):
            t.cancel()

    await asyncio.gather(*(worker(ep, i)
                           for i, ep in enumerate(
                               ep for ep in endpoints
                               for _ in range(max(1, connections)))))
    res.duration_s = time.monotonic() - start
    return res


async def null_sink(delay_s: float = 0.0):
    """Minimal JSON-RPC-over-HTTP sink (one request per connection —
    the client sends Connection: close and reads to EOF).  delay_s
    stalls each response, letting tests prove pacing is decoupled
    from completion.  Returns the asyncio server; the port is
    server.sockets[0].getsockname()[1]."""

    async def handle(reader, writer):
        try:
            hdr = await reader.readuntil(b"\r\n\r\n")
            clen = 0
            for line in hdr.split(b"\r\n"):
                if line.lower().startswith(b"content-length:"):
                    clen = int(line.split(b":", 1)[1])
            if clen:
                await reader.readexactly(clen)
            if delay_s:
                await asyncio.sleep(delay_s)
            body = b'{"jsonrpc":"2.0","id":1,"result":{"code":0}}'
            writer.write(
                b"HTTP/1.1 200 OK\r\nContent-Type: application/json"
                b"\r\nContent-Length: " + str(len(body)).encode()
                + b"\r\n\r\n" + body)
            await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError,
                OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    return await asyncio.start_server(handle, "127.0.0.1", 0)


async def selfcheck(rate: int = 200, duration_s: float = 3.0,
                    connections: int = 2) -> dict:
    """Verify the generator actually OFFERS the requested rate against
    a null JSON-RPC sink (VERDICT r4 #3: offered-vs-requested must be
    provable independent of the engine under test).  Returns
    {requested, sent, dropped, offered_ratio}; run before a QA series
    so a generator regression can never masquerade as an engine
    saturation point."""
    server = await null_sink()
    port = server.sockets[0].getsockname()[1]
    try:
        res = await generate([f"http://127.0.0.1:{port}"], rate=rate,
                             connections=connections,
                             duration_s=duration_s, method="sync")
    finally:
        server.close()
        await server.wait_closed()
    requested = int(rate * duration_s)
    return {"requested": requested, "sent": res.sent,
            "accepted": res.accepted, "dropped": res.dropped,
            "offered_ratio": round(
                (res.sent + res.dropped) / max(1, requested), 3)}


# ---------------------------------------------------------------------------
# reporting

@dataclass
class Stats:
    count: int = 0
    min_s: float = 0.0
    max_s: float = 0.0
    avg_s: float = 0.0
    stddev_s: float = 0.0
    p50_s: float = 0.0
    p90_s: float = 0.0
    p99_s: float = 0.0

    @classmethod
    def from_samples(cls, xs: list[float]) -> "Stats":
        if not xs:
            return cls()
        s = sorted(xs)

        def pct(p: float) -> float:
            return s[min(len(s) - 1, int(p * len(s)))]
        avg = sum(s) / len(s)
        var = sum((x - avg) ** 2 for x in s) / len(s)
        return cls(count=len(s), min_s=s[0], max_s=s[-1], avg_s=avg,
                   stddev_s=math.sqrt(var), p50_s=pct(0.50),
                   p90_s=pct(0.90), p99_s=pct(0.99))

    def to_dict(self) -> dict:
        return {k: (round(v, 6) if isinstance(v, float) else v)
                for k, v in self.__dict__.items()}


@dataclass
class Report:
    experiment_id: str = ""
    latency: Stats = field(default_factory=Stats)
    block_interval: Stats = field(default_factory=Stats)
    negative_latencies: int = 0
    heights: int = 0

    def to_dict(self) -> dict:
        return {"experiment_id": self.experiment_id,
                "heights": self.heights,
                "negative_latencies": self.negative_latencies,
                "latency": self.latency.to_dict(),
                "block_interval": self.block_interval.to_dict()}


def _parse_block_time(raw: str) -> float:
    from ..libs.pubsub import _parse_time_like
    dt = _parse_time_like(raw)
    if dt is None:
        raise ValueError(f"bad block time {raw!r}")
    return dt.timestamp()


async def report(endpoint: str, experiment_id: Optional[str] = None,
                 from_height: int = 0,
                 to_height: int = 0) -> Report:
    """Scan committed blocks over RPC, extract load payloads, compute
    tx latency (block time - payload time) and block-interval stats
    (reference: loadtime/report/report.go + runner/benchmark.go)."""
    import base64

    from ..rpc.client import HTTPClient

    cli = HTTPClient(endpoint, timeout=30.0)
    st = await cli.status()
    base = int(st["sync_info"]["earliest_block_height"] or 1)
    tip = int(st["sync_info"]["latest_block_height"])
    lo = max(base, from_height or base)
    hi = min(tip, to_height or tip)
    rep = Report(experiment_id=experiment_id or "")
    lat: list[float] = []
    times: list[float] = []
    for h in range(lo, hi + 1):
        res = await cli.block(h)
        block = res["block"]
        bt = _parse_block_time(block["header"]["time"])
        times.append(bt)
        for tx64 in block["data"].get("txs", []):
            p = payload_from_tx(base64.b64decode(tx64))
            if p is None:
                continue
            if experiment_id and p.get("id") != experiment_id:
                continue
            if not rep.experiment_id:
                rep.experiment_id = p.get("id", "")
            d = bt - p.get("time_ns", 0) / 1e9
            if d < 0:
                rep.negative_latencies += 1
            lat.append(d)
    rep.heights = max(0, hi - lo + 1)
    rep.latency = Stats.from_samples(lat)
    rep.block_interval = Stats.from_samples(
        [b - a for a, b in zip(times, times[1:])])
    return rep
