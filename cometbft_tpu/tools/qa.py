"""QA scale run: a 15+ node, 100+ validator live net under staged
load, with a kill/restart perturbation and a statesync late joiner.

Reference: docs/references/qa/method.md + CometBFT-QA-v1.md (the
200-node / 175-validator DigitalOcean saturation study, scaled to one
host) and test/e2e/runner/benchmark.go (block-interval stats).  The
run records a tx/s saturation table + latency quantiles per load
window into QA_r{N}.json; docs/QA.md carries the narrative.

Shape of the net (single host, in-process asyncio nodes):
- 12 live validators (power 100 each) + 3 full nodes across three
  latency zones (50/100/150 ms one-way links)
- 90 "remote" validators in the genesis set with power 1 and mixed
  key types (ed25519/secp256k1) that never come online: every commit
  carries a 102-slot signature array, so commit verification runs at
  the 100+ validator width the reference QA exercises, while quorum
  rests with the live 12 (1200 of 1290 power)
- one statesync late joiner that bootstraps from a snapshot mid-run

Run:  python -m cometbft_tpu.tools.qa [--quick]
"""
from __future__ import annotations

import asyncio
import json
import os
import statistics
import tempfile
import time
from dataclasses import dataclass, field
from typing import Optional

from ..config import Config
from ..crypto import ed25519, secp256k1
from ..libs.log import new_logger
from ..p2p.key import NodeKey
from ..privval import FilePV
from ..types.genesis import GenesisDoc, GenesisValidator
from ..types.timestamp import Timestamp

logger = new_logger("qa")

ZONES = ["zone-a", "zone-b", "zone-c"]
ZONE_LATENCY_MS = {"zone-a:zone-b": 50, "zone-a:zone-c": 100,
                   "zone-b:zone-c": 150}


@dataclass
class WindowResult:
    rate: int
    duration_s: float
    sent: int = 0
    accepted: int = 0
    dropped: int = 0            # open-loop ticks held by the cap
    stalled: bool = False       # net could not advance 2 blocks after
    committed: int = 0          # the window: past saturation
    tx_per_s: float = 0.0
    latency_p50_s: float = 0.0
    latency_p90_s: float = 0.0
    latency_max_s: float = 0.0
    # per-window resource series (process mode; reference QA method
    # tables: CometBFT-QA-v1.md:318-334 record RSS/CPU per node)
    rss_avg_mb: float = 0.0
    rss_max_mb: float = 0.0
    cpu_total_pct: float = 0.0
    fds_max: int = 0
    mempool_avg: float = 0.0
    mempool_max: int = 0
    # fraction of peer-delivered txs the dedup cache had already seen
    # during this window, summed over all nodes (ISSUE 12: the gated
    # redundancy number of the tx gossip plane; flood ran ~0.9)
    dup_ratio: float = -1.0
    gossip_txs: int = 0


@dataclass
class QAReport:
    nodes: int = 0
    validators_total: int = 0
    validators_live: int = 0
    windows: list[WindowResult] = field(default_factory=list)
    saturation_rate: int = 0
    # generator self-check against a null sink (offered ~= requested
    # must hold independent of the engine under test)
    offered_check: dict = field(default_factory=dict)
    # commit signature width actually flowing through verification
    commit_sigs_avg: float = 0.0
    commit_sigs_min: int = 0
    commit_sigs_heights: int = 0
    # top hot-path entries from node 0's cProfile during the highest-
    # rate window (libs/pprof.py /debug/pprof/profile)
    profile_top: list = field(default_factory=list)
    block_interval_avg_s: float = 0.0
    block_interval_std_s: float = 0.0
    block_interval_min_s: float = 0.0
    block_interval_max_s: float = 0.0
    final_height: int = 0
    perturbation: str = ""
    perturbed_recovered: bool = False
    statesync_joiner_height: int = 0
    # cumulative duplicate-delivery ratio over the whole run (ISSUE
    # 12 acceptance: flood gossip ran ~0.9; gated <= 0.50 — at most
    # 2 deliveries per tx per node on average)
    dup_ratio_overall: float = -1.0
    # compact-block protocol totals scraped at run end (proc mode):
    # sent / reconstructed prove the fast path ran, misses +
    # mismatches prove the full-part fallback was exercised in-run
    # (ISSUE 12 acceptance)
    compact_blocks: dict = field(default_factory=dict)
    # cluster critical-path metrics from the fleet collector's
    # artifact (ISSUE 19; -1 = not measured): p95 time from proposal
    # first-sent to 2/3 prevote power arriving at a node, and the max
    # inter-node commit skew observed at any height
    fleet_path: str = ""
    prevote_t23_p95_s: float = -1.0
    commit_skew_max_s: float = -1.0
    mismatches: list[str] = field(default_factory=list)
    notes: list[str] = field(default_factory=list)
    # stages that ran but failed their objective (e.g. a statesync
    # joiner that never caught up): a degraded scenario must be
    # explicit in the artifact — QA_r05's second run recorded
    # `statesync_joiner_height: 0`, which reads like success unless
    # you know the field's zero value (ISSUE 9 satellite)
    degraded: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        import dataclasses
        return dataclasses.asdict(self)


# every port this run has handed out: the bind-then-close pattern can
# yield the same port twice across many rapid allocations (observed as
# a relay bind EADDRINUSE on the 70-relay full-scale run)
_USED_PORTS: set = set()


def _free_port() -> int:
    import socket
    while True:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        if p not in _USED_PORTS:
            _USED_PORTS.add(p)
            return p


def _mk_cfg(root: str, name: str, zone: str) -> Config:
    home = os.path.join(root, name)
    cfg = Config()
    cfg.base.home = home
    cfg.base.moniker = name
    cfg.base.db_backend = "memdb"
    cfg.p2p.laddr = f"tcp://127.0.0.1:{_free_port()}"
    cfg.rpc.laddr = f"tcp://127.0.0.1:{_free_port()}"
    cfg.p2p.allow_duplicate_ip = True
    cfg.p2p.pex = False          # fixed topology under latency relays
    cfg.consensus.timeout_commit_ns = 200_000_000
    # ISSUE 10 rig configuration: pipelined commit + incremental
    # recheck are the defaults; adaptive timeouts are off by default
    # product-wide but ON for the QA rig — deriving propose/vote
    # timeouts from the measured quorum delay is half the block-
    # interval story QA_r07 measures against QA_r05
    cfg.consensus.adaptive_timeouts = True
    # empty blocks at most every 2 s: at pipelined sub-second
    # intervals, 16 time-shared processes otherwise burn the core
    # committing empty blocks between load windows
    cfg.consensus.create_empty_blocks_interval_ns = 2_000_000_000
    cfg.mempool.size = 20_000
    # 16 KiB wire packets (reference default 1 KiB): a 512 KiB part
    # fallback at 1 KiB packets pays 512 framing + AEAD passes per
    # link — pure per-packet python overhead on a time-shared core.
    # The reconciliation plane made big blocks cheap to PROPOSE
    # (compact form); this makes the remaining full-part traffic
    # cheap to carry (ISSUE 12 block-size escalation).
    cfg.p2p.max_packet_msg_payload_size = 16384
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    return cfg


def _ghost_validators(n: int) -> list[GenesisValidator]:
    """Validators in the set that never come online — mixed key types
    so the commit verification path sees a heterogeneous 100+ slot
    array (BASELINE config #5's shape)."""
    out = []
    for i in range(n):
        if i % 2 == 0:
            pub = ed25519.gen_priv_key().pub_key()
        else:
            pub = secp256k1.gen_priv_key().pub_key()
        out.append(GenesisValidator(address=b"", pub_key=pub, power=1))
    return out


def _link_port(zones: dict, relay_specs: list, a: str, b: str,
               target_port: int) -> int:
    """Port for a->b traffic: direct when same zone, else through a
    latency relay matching the zone pair (manifest.py pattern)."""
    from .manifest import RelaySpec
    za, zb = zones.get(a, ZONES[0]), zones.get(b, ZONES[0])
    key = f"{za}:{zb}" if f"{za}:{zb}" in ZONE_LATENCY_MS \
        else f"{zb}:{za}"
    ms = ZONE_LATENCY_MS.get(key, 0) if za != zb else 0
    if ms == 0:
        return target_port
    port = _free_port()
    relay_specs.append(RelaySpec(
        port=port, target_host="127.0.0.1",
        target_port=target_port, delay_s=ms / 1000.0))
    return port


def _setup_net(outdir: str, n_validators: int, n_full: int,
               ghosts: int, report: "QAReport",
               single_zone: bool = False, peer_degree: int = 0,
               max_block_bytes: int = 262144):
    """Everything both QA modes share before boot: per-node homes and
    keys, the mixed-key genesis with ghost validators, the topology
    (full mesh over inter-zone latency relays by default;
    single_zone=True drops the WAN emulation and peer_degree=k bounds
    each node to ring+skip neighbors — the sig-scale stage uses both,
    where the deliverable is signature width, not WAN behavior, and
    363 relay links across 33 time-shared processes starve the core).

    Returns (names, zones, cfgs, joiner_cfg, node_ids, p2p_port,
    relay_specs); cfgs have persistent_peers filled in."""
    names = [f"validator{i:02d}" for i in range(n_validators)] + \
            [f"full{i:02d}" for i in range(n_full)]
    zones = {name: ZONES[0] if single_zone
             else ZONES[i % len(ZONES)]
             for i, name in enumerate(names)}
    cfgs = {name: _mk_cfg(outdir, name, zones[name])
            for name in names}
    joiner_cfg = _mk_cfg(outdir, "joiner", ZONES[0])

    pvs = {}
    for name in names + ["joiner"]:
        cfg = cfgs.get(name, joiner_cfg)
        pvs[name] = FilePV.generate(
            cfg.base.path(cfg.base.priv_validator_key_file),
            cfg.base.path(cfg.base.priv_validator_state_file))
        NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
    vals = [GenesisValidator(address=b"",
                             pub_key=pvs[n].get_pub_key(), power=100)
            for n in names[:n_validators]]
    vals += _ghost_validators(ghosts)
    doc = GenesisDoc(chain_id="qa-net", genesis_time=Timestamp.now(),
                     validators=vals)
    doc.consensus_params.validator.pub_key_types = [
        "ed25519", "secp256k1"]
    doc.consensus_params.feature.pbts_enable_height = 1
    # bound proposals under backlog: with 256 B load txs and the 4 MB
    # default, a single post-saturation proposal reaps the entire
    # queue — a block too big to gossip through the latency relays
    # before the propose timeout, so rounds churn while the backlog
    # (and the next proposal) keeps growing.  128 KiB ≈ 450 txs kept
    # rounds bounded for the serial engine; with pipelined commits
    # and timeouts that adapt to the measured gossip delay the rig
    # carries 256 KiB ≈ 900 txs per block (ISSUE 10) — operators
    # size real chains the same way.  With the reconciliation data
    # plane (ISSUE 12) a proposal's bytes stop scaling with peer
    # count — compact-capable peers receive skeleton + tx hashes and
    # rebuild from their pools — and the adaptive timeouts absorb
    # whatever gossip delay remains, so the rate-100+ acceptance run
    # escalates past this cap (run_qa_procs(max_block_bytes=...)).
    doc.consensus_params.block.max_bytes = max_block_bytes
    doc.consensus_params.evidence.max_bytes = 32768
    report.validators_total = len(vals)
    report.validators_live = n_validators
    report.nodes = len(names) + 1

    node_ids = {}
    for name in names + ["joiner"]:
        cfg = cfgs.get(name, joiner_cfg)
        doc.save_as(cfg.base.path(cfg.base.genesis_file))
        node_ids[name] = NodeKey.load_or_gen(
            cfg.base.path(cfg.base.node_key_file)).id

    relay_specs: list = []
    p2p_port = {name: int(cfgs[name].p2p.laddr.rsplit(":", 1)[1])
                for name in names}
    n = len(names)
    for i, name in enumerate(names):
        if peer_degree and n > peer_degree:
            # ring + doubling skips: connected, diameter O(log n)
            offs = {1, 2}
            k = 4
            while k < n and len(offs) < peer_degree:
                offs.add(k)
                k *= 2
            targets = [names[(i + o) % n] for o in sorted(offs)]
        else:
            targets = names[i + 1:]
        peers = []
        for other in targets:
            if other == name:
                continue
            peers.append(
                f"{node_ids[other]}@127.0.0.1:"
                f"{_link_port(zones, relay_specs, name, other, p2p_port[other])}")
        cfgs[name].p2p.persistent_peers = ",".join(peers)
    return names, zones, cfgs, joiner_cfg, node_ids, p2p_port, \
        relay_specs


def _note_saturation(report: "QAReport", w: "WindowResult",
                     rate: float) -> None:
    """Saturation rule (one place): the highest offered rate whose
    committed throughput still tracks >= 80% of it — and whose window
    did not stall (a net that needs minutes to advance after the
    window is past saturation even if the backlog commits)."""
    if not w.stalled and w.tx_per_s >= 0.8 * rate:
        report.saturation_rate = rate


async def _selfcheck_generator(report: "QAReport", rate: int) -> None:
    """Prove the generator offers the requested rate against a null
    sink BEFORE the run (VERDICT r4 #3) — a generator regression must
    never read as an engine saturation point."""
    from . import loadtime
    report.offered_check = await loadtime.selfcheck(
        rate=rate, duration_s=2.0)
    logger.info("load generator self-check",
                **report.offered_check)


# BLOCK_ID_FLAG_COMMIT / _NIL: slots that carry a real signature (the
# width the batch verification path actually processes) — the single
# definition both QA modes share
_PRESENT_SIG_FLAGS = (2, 3)


def _count_commit_sigs(signatures: list) -> int:
    """Non-absent signatures in a commit's 102-slot array (JSON
    form)."""
    return sum(1 for s in signatures
               if s is not None
               and s.get("block_id_flag") in _PRESENT_SIG_FLAGS)


async def _sample_commit_sigs(report: "QAReport", cli,
                              final_height: int) -> None:
    """Per-block verified-signature counts over sampled heights
    (VERDICT r4 #5: the QA report must state how many real signatures
    each commit carries through the batch path)."""
    counts = []
    for h in range(2, final_height + 1, max(1, final_height // 40)):
        try:
            c = await cli.call("commit", height=str(h))
            sigs = c["signed_header"]["commit"]["signatures"]
            counts.append(_count_commit_sigs(sigs))
        except Exception:
            continue
    if counts:
        report.commit_sigs_avg = round(
            sum(counts) / len(counts), 1)
        report.commit_sigs_min = min(counts)
        report.commit_sigs_heights = len(counts)


def _configure_joiner(joiner_cfg: Config, endpoints: list,
                      trust_height: int, trust_hash: str,
                      node_ids: dict, p2p_port: dict,
                      names: list) -> None:
    """Statesync late-joiner config (one place): light-client trust
    anchored 8 blocks back, first two nodes as RPC providers, first
    four as peers."""
    joiner_cfg.statesync.enable = True
    joiner_cfg.statesync.rpc_servers = [endpoints[0], endpoints[1]]
    joiner_cfg.statesync.trust_height = trust_height
    joiner_cfg.statesync.trust_hash = trust_hash
    joiner_cfg.statesync.discovery_time_ns = int(2e9)
    joiner_cfg.p2p.persistent_peers = ",".join(
        f"{node_ids[n]}@127.0.0.1:{p2p_port[n]}"
        for n in names[:4])


def _record_intervals(report: "QAReport", secs: list) -> None:
    """Block-interval stats (benchmark.go:15-24) from a sorted list
    of block timestamps in seconds."""
    intervals = [b - a for a, b in zip(secs, secs[1:])]
    if intervals:
        report.block_interval_avg_s = statistics.mean(intervals)
        report.block_interval_std_s = (
            statistics.pstdev(intervals)
            if len(intervals) > 1 else 0.0)
        report.block_interval_min_s = min(intervals)
        report.block_interval_max_s = max(intervals)


async def run_qa(outdir: str, n_validators: int = 12, n_full: int = 3,
                 ghosts: int = 90,
                 rates: tuple = (10, 25, 50, 100, 200),
                 window_s: float = 15.0) -> QAReport:
    from ..abci.kvstore import KVStoreApplication
    from ..db import new_db
    from ..node.node import Node
    from ..rpc.client import HTTPClient
    from . import loadtime
    from .manifest import Relay, start_relay

    report = QAReport()
    names, zones, cfgs, joiner_cfg, node_ids, p2p_port, relay_specs = \
        _setup_net(outdir, n_validators, n_full, ghosts, report)

    nodes: dict[str, Node] = {}
    relays: list[Relay] = []
    joiner: Optional[Node] = None
    try:
        for spec in relay_specs:
            relays.append(await start_relay(spec))
        for name in names:
            app = KVStoreApplication(
                db=new_db("app", "memdb",
                          cfgs[name].base.path("data")),
                snapshot_interval=5)
            nodes[name] = Node(cfgs[name], app=app)
            await nodes[name].start()
        logger.info("net booted", nodes=len(nodes),
                    relays=len(relays))

        endpoints = [f"http://{nodes[n]._rpc_server.listen_addr}"
                     for n in names[:3]]
        ref = nodes[names[0]]

        async def wait_height(h: int, budget: float,
                              who=None) -> None:
            pool = who if who is not None else list(nodes.values())
            deadline = time.monotonic() + budget
            while time.monotonic() < deadline:
                if all(n.height >= h for n in pool):
                    return
                await asyncio.sleep(0.1)
            raise TimeoutError(
                f"net stuck: {[n.height for n in pool]} < {h}")

        await wait_height(2, 120.0)
        await _selfcheck_generator(report, max(rates))

        def _inproc_gossip_counters() -> tuple:
            recv = dup = 0.0
            for n in nodes.values():
                m = n.mempool.metrics
                recv += m.gossip_txs_received.value
                dup += m.gossip_txs_duplicate.value
            return recv, dup

        # --- load windows at increasing rates -----------------------
        for wi, rate in enumerate(rates):
            dup0 = _inproc_gossip_counters()
            res = await loadtime.generate(
                endpoints, rate=rate, connections=2,
                duration_s=window_s, size=256, method="async",
                max_in_flight=16)
            # let the tail commit; a net that cannot advance 2 blocks
            # is past saturation — record the window and stop
            # escalating instead of failing the whole run
            stalled = False
            h0 = ref.height
            try:
                await wait_height(h0 + 2, 60.0, who=[ref])
            except TimeoutError:
                stalled = True
            rep = await loadtime.report(
                endpoints[0], experiment_id=res.experiment_id)
            w = WindowResult(
                rate=rate, duration_s=window_s, sent=res.sent,
                accepted=res.accepted, dropped=res.dropped,
                stalled=stalled, committed=rep.latency.count,
                tx_per_s=rep.latency.count / window_s,
                latency_p50_s=rep.latency.p50_s,
                latency_p90_s=rep.latency.p90_s,
                latency_max_s=rep.latency.max_s)
            _apply_dup_window(w, dup0, _inproc_gossip_counters())
            report.windows.append(w)
            logger.info("load window done", rate=rate,
                        committed=w.committed,
                        tx_s=round(w.tx_per_s, 1),
                        p50=round(w.latency_p50_s, 3),
                        dup_ratio=w.dup_ratio,
                        stalled=stalled)
            _note_saturation(report, w, rate)
            if stalled:
                logger.info("net past saturation; stopping the ladder",
                            rate=rate)
                break

            if wi == 1:
                # --- perturbation between windows: kill/restart one
                # validator (reference: perturb.go)
                victim = names[n_validators - 1]
                report.perturbation = f"{victim}:kill-restart"
                await nodes[victim].stop()
                await asyncio.sleep(0.5)
                app = KVStoreApplication(
                    db=new_db("app", "memdb",
                              cfgs[victim].base.path("data")),
                    snapshot_interval=5)
                nodes[victim] = Node(cfgs[victim], app=app)
                await nodes[victim].start()
                h = ref.height
                await wait_height(h + 2, 120.0,
                                  who=[nodes[victim]])
                report.perturbed_recovered = True
                logger.info("perturbed node recovered",
                            victim=victim)

        # --- statesync late joiner ----------------------------------
        # non-fatal, like the procs mode: a joiner that cannot catch a
        # loaded box within budget (e.g. after a stalled ladder broke
        # out with backlog) must not void the recorded windows
        cli = HTTPClient(endpoints[0], timeout=30.0)
        try:
            th = max(1, ref.height - 8)
            blk = await cli.call("block", height=str(th))
            _configure_joiner(joiner_cfg, endpoints, th,
                              blk["block_id"]["hash"], node_ids,
                              p2p_port, names)
            app = KVStoreApplication(
                db=new_db("app", "memdb",
                          joiner_cfg.base.path("data")),
                snapshot_interval=5)
            joiner = Node(joiner_cfg, app=app)
            await joiner.start()
            target = ref.height
            await wait_height(target, 180.0, who=[joiner])
            report.statesync_joiner_height = joiner.height
            logger.info("statesync joiner caught up",
                        height=joiner.height)
        except Exception as e:
            logger.error("joiner stage failed", err=repr(e))
            report.notes.append(f"joiner-stage: {e!r:.120}")
            report.degraded.append("statesync_joiner")

        report.final_height = ref.height
        _gate_dup_ratio(report, _inproc_gossip_counters())

        # --- commit signature width over sampled heights ------------
        counts = []
        step = max(1, report.final_height // 40)
        for h in range(2, report.final_height + 1, step):
            blk = ref.block_store.load_block(h)
            if blk is None:
                continue
            lc = blk.last_commit
            if hasattr(lc, "signers"):       # AggregateCommit
                counts.append(lc.signers.popcount())
            else:
                counts.append(sum(
                    1 for s in lc.signatures
                    if s.block_id_flag in _PRESENT_SIG_FLAGS))
        if counts:
            report.commit_sigs_avg = round(sum(counts) / len(counts), 1)
            report.commit_sigs_min = min(counts)
            report.commit_sigs_heights = len(counts)

        # --- block interval stats (benchmark.go:15-24) --------------
        times = []
        for h in range(2, ref.height + 1):
            meta = ref.block_store.load_block_meta(h)
            if meta is not None:
                times.append(meta.header.time.unix_ns() / 1e9)
        _record_intervals(report, times)

        # --- invariants ---------------------------------------------
        for h in range(1, report.final_height + 1):
            want = ref.block_store.load_block_meta(h)
            if want is None:
                continue
            for name, n in list(nodes.items()) + [("joiner", joiner)]:
                got = n.block_store.load_block_meta(h)
                if got is None:
                    continue
                if got.block_id.hash != want.block_id.hash:
                    report.mismatches.append(
                        f"{name}@{h}: block hash mismatch")
                if got.header.app_hash != want.header.app_hash:
                    report.mismatches.append(
                        f"{name}@{h}: app hash mismatch")
    finally:
        for n in list(nodes.values()) + ([joiner] if joiner else []):
            try:
                await n.stop()
            except Exception:
                pass
        for r in relays:
            r.close()
        for r in relays:
            await r.wait_closed()
    return report


# --------------------------------------------------------------------------
# process mode: every node is a separate OS process (real GC/scheduler/
# fd isolation), sampled with psutil — the reference QA method's shape
# (docs/references/qa/method.md; resource tables CometBFT-QA-v1.md).

class _Sampler:
    """2 s psutil sampler over the node subprocesses."""

    def __init__(self, procs: dict):
        import psutil
        self._psutil = psutil
        self.procs = procs
        self.samples: list[tuple] = []     # (t, name, rss, cpu, fds)
        self._task: Optional[asyncio.Task] = None
        self._ps: dict = {}
        for name, proc in procs.items():
            try:
                p = psutil.Process(proc.pid)
                p.cpu_percent(None)        # prime the cpu counter
                self._ps[name] = p
            except psutil.Error:
                pass

    def track(self, name: str, proc) -> None:
        try:
            p = self._psutil.Process(proc.pid)
            p.cpu_percent(None)
            self._ps[name] = p
        except self._psutil.Error:
            pass

    async def _run(self, interval: float) -> None:
        while True:
            t = time.monotonic()
            for name, p in list(self._ps.items()):
                try:
                    with p.oneshot():
                        self.samples.append(
                            (t, name,
                             p.memory_info().rss,
                             p.cpu_percent(None),
                             p.num_fds()))
                except self._psutil.Error:
                    pass                   # process died/restarting
            await asyncio.sleep(interval)

    def start(self, interval: float = 2.0) -> None:
        self._task = asyncio.get_running_loop().create_task(
            self._run(interval))

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()

    def window_stats(self, t0: float, t1: float) -> dict:
        sel = [s for s in self.samples if t0 <= s[0] <= t1]
        if not sel:
            return {}
        rss = [s[2] for s in sel]
        # total CPU: sum of simultaneous per-process readings / ticks
        ticks = sorted({round(s[0], 1) for s in sel})
        cpu_by_tick = {}
        for s in sel:
            cpu_by_tick.setdefault(round(s[0], 1), 0.0)
            cpu_by_tick[round(s[0], 1)] += s[3]
        return {
            "rss_avg_mb": sum(rss) / len(rss) / 1e6,
            "rss_max_mb": max(rss) / 1e6,
            "cpu_total_pct": (sum(cpu_by_tick.values()) /
                              max(1, len(ticks))),
            "fds_max": max(s[4] for s in sel),
        }


def _write_node_overrides(cfg: Config) -> None:
    from ..confix import save_overrides
    save_overrides(cfg.base.home, {
        "base": {"moniker": cfg.base.moniker, "db_backend": "memdb",
                 "log_level": "error", "proxy_app": "kvstore"},
        "p2p": {"laddr": cfg.p2p.laddr,
                "persistent_peers": cfg.p2p.persistent_peers,
                "max_packet_msg_payload_size":
                    cfg.p2p.max_packet_msg_payload_size,
                "allow_duplicate_ip": True, "pex": False},
        "rpc": {"laddr": cfg.rpc.laddr},
        "instrumentation": {
            "pprof_listen_addr":
                cfg.instrumentation.pprof_listen_addr},
        "consensus": {
            "timeout_commit_ns": cfg.consensus.timeout_commit_ns,
            "pipeline_commit": cfg.consensus.pipeline_commit,
            "compact_blocks": cfg.consensus.compact_blocks,
            "vote_batch_max": cfg.consensus.vote_batch_max,
            "adaptive_timeouts": cfg.consensus.adaptive_timeouts,
            "adaptive_timeout_floor_ns":
                cfg.consensus.adaptive_timeout_floor_ns,
            "adaptive_timeout_ceiling_ns":
                cfg.consensus.adaptive_timeout_ceiling_ns,
            "create_empty_blocks_interval_ns":
                cfg.consensus.create_empty_blocks_interval_ns},
        "mempool": {
            "size": cfg.mempool.size,
            "recheck_incremental": cfg.mempool.recheck_incremental,
            "recheck_max_age_blocks":
                cfg.mempool.recheck_max_age_blocks,
            "gossip_reconciliation":
                cfg.mempool.gossip_reconciliation,
            "recon_push_peers": cfg.mempool.recon_push_peers},
        "statesync": {
            "enable": cfg.statesync.enable,
            "rpc_servers": list(cfg.statesync.rpc_servers or []),
            "trust_height": cfg.statesync.trust_height,
            "trust_hash": cfg.statesync.trust_hash,
            "discovery_time_ns": cfg.statesync.discovery_time_ns,
        },
    })


_PRCTL = None                     # resolved lazily, in the parent


def _spawn_node(home: str):
    import subprocess
    import sys
    env = dict(os.environ)
    env["COMETBFT_TPU_CRYPTO_BACKEND"] = "cpu"
    # hard-clear any inherited platform pin (this environment exports
    # JAX_PLATFORMS=axon): a QA node child must never dial the pooled
    # TPU — with the pin inherited, children stalled claiming it and
    # consensus churned at height 1 for the whole run
    env["JAX_PLATFORMS"] = ""
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo_root + os.pathsep + \
        env.get("PYTHONPATH", "")

    # resolve libc.prctl in the PARENT: importing/loading inside the
    # post-fork pre-exec window can deadlock on runtime locks held by
    # other threads (asyncio executor/getaddrinfo threads are live
    # when the victim restart and joiner spawns happen)
    global _PRCTL
    if _PRCTL is None:
        import ctypes
        try:
            _PRCTL = ctypes.CDLL("libc.so.6").prctl
        except OSError:
            _PRCTL = False

    def _die_with_parent():
        # a coordinator killed with SIGKILL never reaches its finally
        # block; leaked node processes then poison the NEXT run (CPU
        # contention + same chain-id p2p noise — observed as height-1
        # round churn).  PR_SET_PDEATHSIG ties each child's life to
        # the coordinator's.
        if _PRCTL:
            _PRCTL(1, 9)                  # PR_SET_PDEATHSIG, SIGKILL

    return subprocess.Popen(
        [sys.executable, "-m", "cometbft_tpu.cmd", "--home", home,
         "start"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        env=env, cwd=repo_root, preexec_fn=_die_with_parent)


async def _fetch_profile(pprof_port: int, seconds: int = 30) -> list:
    """Top cumulative-time lines from the node's live cProfile
    endpoint (libs/pprof.py), trimmed for the report."""
    import urllib.request

    def _get():
        url = (f"http://127.0.0.1:{pprof_port}/debug/pprof/profile"
               f"?seconds={seconds}")
        with urllib.request.urlopen(url, timeout=seconds + 30) as r:
            return r.read().decode(errors="replace")
    try:
        text = await asyncio.to_thread(_get)
    except Exception as e:
        return [f"profile fetch failed: {e!r}"]
    lines = [ln.rstrip() for ln in text.splitlines()]
    # keep the stats header + the first ~25 rows of the table
    out = []
    for ln in lines:
        if len(out) >= 30:
            break
        if ln.strip():
            out.append(ln)
    return out


# --------------------------------------------------------------------------
# fleet collector (docs/observability.md): periodic /trace + /health
# scrapes across every node streamed into one run-level artifact, so
# a finished (or crashed) run always has the cross-node evidence
# tools/fleet_report.py needs — not just the one node that failed.

def _load_fleet_report():
    """tools/fleet_report.py lives at the repo root (outside the
    package, like trace_report); load it by path."""
    import importlib.util
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    p = os.path.join(root, "tools", "fleet_report.py")
    spec = importlib.util.spec_from_file_location("fleet_report", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _FleetCollector:
    """Scrapes /trace (events + clock anchors) and /health from every
    node on a fixed cadence, deduplicating events across overlapping
    ring snapshots, and writes a ``fleet_<run>.json`` the fleet
    report consumes directly.  Best-effort throughout: a node
    mid-restart just misses a round."""

    def __init__(self, rpc_ep: dict, path: str,
                 interval_s: float = 10.0):
        self.rpc_ep = dict(rpc_ep)
        self.path = path
        self.interval_s = interval_s
        self._nodes: dict[str, dict] = {}
        self._health: dict[str, dict] = {}
        self._task = None
        self._stop = asyncio.Event()

    def track(self, name: str, endpoint: str) -> None:
        self.rpc_ep[name] = endpoint

    async def scrape_once(self) -> None:
        from ..rpc.client import HTTPClient
        for name, ep in list(self.rpc_ep.items()):
            cli = HTTPClient(ep, timeout=10.0)
            try:
                body = await cli.call("trace")
            except Exception as e:
                logger.debug("fleet trace scrape failed", node=name,
                             err=repr(e))
                continue
            rec = self._nodes.setdefault(
                name, {"node": name, "anchors": [], "events": {}})
            if body.get("node"):
                rec["node"] = body["node"]
            if body.get("anchors"):
                rec["anchors"] = body["anchors"]
            for e in body.get("events") or []:
                key = (e.get("ts_ns"), e.get("category"),
                       e.get("name"), e.get("dur_ns"))
                rec["events"][key] = e
            try:
                self._health[name] = await cli.call("health")
            except Exception as e:
                logger.debug("fleet health scrape failed", node=name,
                             err=repr(e))

    async def _run(self) -> None:
        while not self._stop.is_set():
            try:
                await self.scrape_once()
            except Exception as e:
                logger.debug("fleet scrape round failed",
                             err=repr(e))
            try:
                await asyncio.wait_for(self._stop.wait(),
                                       self.interval_s)
            except asyncio.TimeoutError:
                pass

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(
            self._run())

    async def stop_and_write(self) -> str:
        """Final fleet-wide scrape (the nodes are still up — this
        runs before teardown), then the artifact.  Returns the path
        or "" if nothing was ever collected."""
        self._stop.set()
        if self._task is not None:
            try:
                await self._task
            except Exception as e:
                logger.debug("fleet collector task died",
                             err=repr(e))
            self._task = None
        try:
            await self.scrape_once()
        except Exception as e:
            logger.debug("final fleet scrape failed", err=repr(e))
        if not self._nodes:
            return ""
        doc = {"nodes": {
            name: {"node": rec["node"], "anchors": rec["anchors"],
                   "events": sorted(
                       rec["events"].values(),
                       key=lambda e: int(e.get("ts_ns") or 0))}
            for name, rec in self._nodes.items()},
            "health": self._health}
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self.path)
        return self.path


# cluster-level gates (ISSUE 19): the waterfall numbers a healthy rig
# must hold.  p95 time-to-2/3-prevotes spans proposal receipt through
# vote gossip across WAN-profile relays under load; inter-node commit
# skew is bounded by one gossip round.  Generous on purpose — these
# catch regressions of kind (a stuck straggler, a gossip plane that
# stopped fanning out), not percentage drift.
PREVOTE_T23_P95_LIMIT_S = 10.0
COMMIT_SKEW_LIMIT_S = 5.0


def _gate_fleet(report: "QAReport", fleet_path: str) -> None:
    """Derive the gated cluster metrics from the collected fleet
    artifact via tools/fleet_report.py.  Self-degrading, never
    raising: a failed analysis leaves the metrics at their -1
    sentinels with a note."""
    if not fleet_path:
        return
    try:
        fr = _load_fleet_report()
        fleet = fr.analyze(fr.load_inputs([fleet_path]))
        t23s = [r["prevote_t23_ms"] / 1e3
                for h in fleet["heights"].values()
                for r in h["nodes"].values()
                if r["prevote_t23_ms"] is not None]
        skews = [h["commit_skew_ms"] / 1e3
                 for h in fleet["heights"].values()]
        if t23s:
            t23s.sort()
            report.prevote_t23_p95_s = round(
                t23s[min(len(t23s) - 1, int(0.95 * len(t23s)))], 4)
            if report.prevote_t23_p95_s > PREVOTE_T23_P95_LIMIT_S:
                report.degraded.append("prevote_t23_p95")
        if skews:
            report.commit_skew_max_s = round(max(skews), 4)
            if report.commit_skew_max_s > COMMIT_SKEW_LIMIT_S:
                report.degraded.append("commit_skew")
    except Exception as e:
        logger.error("fleet gate failed", err=repr(e))
        report.notes.append(f"fleet-gate: {e!r:.120}")


# duplicate-delivery gate (ISSUE 12): at most 2 deliveries per tx
# per node on average — one useful + one duplicate, i.e. a duplicate
# fraction <= 0.5 of all gossip deliveries (flood ran ~0.9, >= 5x
# over the bar).  The hybrid push fast path and want-timeout
# refetches spend SOME redundancy for latency on purpose; a
# regression back toward flood self-degrades the run.  Windows with
# too few gossip deliveries to be meaningful are not judged.
DUP_RATIO_LIMIT = 0.50
_DUP_MIN_SAMPLES = 200


async def _scrape_metric_sums(eps: list, names: tuple) -> dict:
    """Sum the given (label-free) metric families over the /metrics
    endpoints, best-effort: a node mid-restart just drops out of the
    sum.  One transport/parse loop serving every scrape-based gate."""
    import urllib.request

    def _get(u: str) -> str:
        with urllib.request.urlopen(u + "/metrics", timeout=10) as r:
            return r.read().decode(errors="replace")

    out = {n: 0.0 for n in names}
    for ep in eps:
        try:
            text = await asyncio.to_thread(_get, ep)
        except Exception as e:
            logger.debug("metrics scrape failed", endpoint=ep,
                         err=repr(e))
            continue
        for line in text.splitlines():
            name, _, value = line.partition(" ")
            if name in out and value:
                out[name] += float(value)
    return out


async def _scrape_gossip_counters(eps: list) -> tuple[float, float]:
    s = await _scrape_metric_sums(
        eps, ("cometbft_mempool_gossip_txs_received",
              "cometbft_mempool_gossip_txs_duplicate"))
    return (s["cometbft_mempool_gossip_txs_received"],
            s["cometbft_mempool_gossip_txs_duplicate"])


def _apply_dup_window(w: "WindowResult", before: tuple,
                      after: tuple) -> None:
    recv = after[0] - before[0]
    dup = after[1] - before[1]
    w.gossip_txs = int(recv)
    if recv > 0:
        w.dup_ratio = round(dup / recv, 4)


def _gate_dup_ratio(report: "QAReport", totals: tuple) -> None:
    recv, dup = totals
    if recv > 0:
        report.dup_ratio_overall = round(dup / recv, 4)
    judged = [w for w in report.windows
              if w.gossip_txs >= _DUP_MIN_SAMPLES and
              w.dup_ratio >= 0]
    if judged and max(w.dup_ratio for w in judged) > DUP_RATIO_LIMIT:
        report.degraded.append("duplicate_delivery_ratio")


async def _scrape_compact_counters(eps: list) -> dict:
    """Compact-block / vote-batch totals (see _scrape_metric_sums)."""
    names = ("compact_blocks_sent", "compact_blocks_reconstructed",
             "compact_block_misses", "compact_block_mismatches",
             "vote_batches_sent")
    s = await _scrape_metric_sums(
        eps, tuple("cometbft_consensus_" + n for n in names))
    return {n: int(s["cometbft_consensus_" + n]) for n in names}


async def _rpc_ready(endpoint: str, budget: float) -> bool:
    from ..rpc.client import HTTPClient
    deadline = time.monotonic() + budget
    while time.monotonic() < deadline:
        try:
            cli = HTTPClient(endpoint, timeout=5.0)
            await cli.call("status")
            return True
        except Exception:
            await asyncio.sleep(0.5)
    return False


async def _rpc_height(endpoint: str, attempts: int = 4) -> int:
    """Tip height with bounded retries: one slow /status on the
    1-core box right after a load window must not void a 40-minute
    run (the pipelined engine commits sub-second blocks, so the
    post-window burst is much busier than it was at 7 s intervals)."""
    from ..rpc.client import HTTPClient
    last: Exception = RuntimeError("unreachable")
    for i in range(attempts):
        cli = HTTPClient(endpoint, timeout=10.0)
        try:
            st = await cli.call("status")
            return int(st["sync_info"]["latest_block_height"])
        except Exception as e:
            last = e
            logger.debug("status probe failed; retrying",
                         endpoint=endpoint, attempt=i + 1,
                         err=repr(e))
            await asyncio.sleep(2.0)
    raise last


async def run_qa_procs(outdir: str, n_validators: int = 12,
                       n_full: int = 3, ghosts: int = 90,
                       rates: tuple = (10, 25, 50, 100, 200),
                       window_s: float = 90.0,
                       perturb: bool = True,
                       joiner: bool = True,
                       profile: bool = True,
                       commit_timeout_ns: int = 0,
                       single_zone: bool = False,
                       peer_degree: int = 0,
                       max_block_bytes: int = 262144,
                       window_s_high: float = 0.0,
                       high_rate: int = 100) -> QAReport:
    """The reference-method QA run: separate OS process per node,
    90 s load windows, psutil resource series, mempool occupancy.

    Reference: docs/references/qa/method.md (the 90 s window and
    saturation-point procedure) and CometBFT-QA-v1.md:141-170 (result
    tables this report mirrors).

    perturb/joiner gate the kill-restart and statesync stages (the
    sig-scale stage runs without them); profile captures a cProfile
    window from node 0's live pprof in a DEDICATED window after the
    ladder — never overlapping a recorded window, since cProfile
    drags the profiled node ~2x.
    """
    from ..rpc.client import HTTPClient
    from . import loadtime
    from .manifest import Relay, start_relay

    report = QAReport()
    names, zones, cfgs, joiner_cfg, node_ids, p2p_port, relay_specs = \
        _setup_net(outdir, n_validators, n_full, ghosts, report,
                   single_zone=single_zone, peer_degree=peer_degree,
                   max_block_bytes=max_block_bytes)
    pprof_port = _free_port()
    if profile:
        cfgs[names[0]].instrumentation.pprof_listen_addr = \
            f"127.0.0.1:{pprof_port}"
    if commit_timeout_ns:
        for cfg in cfgs.values():
            cfg.consensus.timeout_commit_ns = commit_timeout_ns
    for name in names:
        _write_node_overrides(cfgs[name])

    rpc_ep = {name: "http://" + cfgs[name].rpc.laddr[len("tcp://"):]
              for name in names}
    endpoints = [rpc_ep[n] for n in names[:3]]

    procs: dict = {}
    relays: list[Relay] = []
    sampler: Optional[_Sampler] = None
    fleet: Optional[_FleetCollector] = None
    profile_task = None
    try:
        for spec in relay_specs:
            relays.append(await start_relay(spec))
        for name in names:
            procs[name] = _spawn_node(cfgs[name].base.home)
        ready = await asyncio.gather(
            *(_rpc_ready(rpc_ep[n], 240.0) for n in names))
        if not all(ready):
            raise TimeoutError("not all nodes became RPC-ready")
        sampler = _Sampler(procs)
        sampler.start()
        # fleet collector: /trace + /health across every node,
        # streamed into the run artifact; the final scrape happens in
        # the finally block BEFORE teardown, so even a crashed run
        # leaves the fleet-wide record (not just the failing node's)
        fleet = _FleetCollector(
            rpc_ep, os.path.join(
                outdir,
                f"fleet_{time.strftime('%Y%m%d-%H%M%S')}.json"))
        fleet.start()
        logger.info("process net booted", nodes=len(procs),
                    relays=len(relays))

        async def wait_height(h: int, budget: float, eps=None):
            eps = eps or [endpoints[0]]
            deadline = time.monotonic() + budget
            while time.monotonic() < deadline:
                hs = await asyncio.gather(
                    *(_rpc_height(e) for e in eps),
                    return_exceptions=True)
                if all(isinstance(x, int) and x >= h for x in hs):
                    return
                await asyncio.sleep(0.5)
            raise TimeoutError(f"net stuck below {h}")

        await wait_height(2, 180.0)
        await _selfcheck_generator(report, max(rates))

        async def drain_mempool(budget_s: float = 150.0) -> None:
            """Let the backlog commit before the next stage so every
            window measures its own offered rate (not the previous
            rung's leftovers) and the joiner doesn't have to chase a
            tip that is digesting minutes of queued load."""
            deadline = time.monotonic() + budget_s
            cli0 = HTTPClient(endpoints[0], timeout=10.0)
            while time.monotonic() < deadline:
                try:
                    r = await cli0.call("num_unconfirmed_txs")
                    if int(r.get("n_txs", r.get("total", 0)) or 0) \
                            < 50:
                        return
                except Exception:
                    pass
                await asyncio.sleep(3.0)

        async def occupancy_series(stopper: asyncio.Event, out: list):
            cli = HTTPClient(endpoints[0], timeout=10.0)
            while not stopper.is_set():
                try:
                    r = await cli.call("num_unconfirmed_txs")
                    out.append(int(r.get("n_txs", r.get(
                        "total", 0)) or 0))
                except Exception:
                    pass
                await asyncio.sleep(2.0)

        all_eps = list(rpc_ep.values())
        for wi, rate in enumerate(rates):
            # wider windows at the high end of the ladder (ISSUE 12:
            # the rate-100+ numbers are the acceptance deliverable,
            # so they get more settling time than the warm-up rates)
            ws = window_s_high if (window_s_high > 0 and
                                   rate >= high_rate) else window_s
            occ: list[int] = []
            stop_occ = asyncio.Event()
            occ_task = asyncio.get_running_loop().create_task(
                occupancy_series(stop_occ, occ))
            dup0 = await _scrape_gossip_counters(all_eps)
            t0 = time.monotonic()
            res = await loadtime.generate(
                endpoints, rate=rate, connections=2,
                duration_s=ws, size=256, method="async",
                max_in_flight=16)
            stalled = False
            h0 = await _rpc_height(endpoints[0])
            try:
                await wait_height(h0 + 2, 90.0)
            except TimeoutError:
                # past saturation: record the window, stop escalating
                stalled = True
            t1 = time.monotonic()
            stop_occ.set()
            await occ_task
            rep = await loadtime.report(
                endpoints[0], experiment_id=res.experiment_id)
            w = WindowResult(
                rate=rate, duration_s=ws, sent=res.sent,
                accepted=res.accepted, dropped=res.dropped,
                stalled=stalled, committed=rep.latency.count,
                tx_per_s=rep.latency.count / ws,
                latency_p50_s=rep.latency.p50_s,
                latency_p90_s=rep.latency.p90_s,
                latency_max_s=rep.latency.max_s,
                mempool_avg=(sum(occ) / len(occ)) if occ else 0.0,
                mempool_max=max(occ) if occ else 0)
            for k, v in sampler.window_stats(t0, t1).items():
                setattr(w, k, v)
            _apply_dup_window(
                w, dup0, await _scrape_gossip_counters(all_eps))
            report.windows.append(w)
            logger.info(
                "load window done", rate=rate, committed=w.committed,
                tx_s=round(w.tx_per_s, 1),
                p50=round(w.latency_p50_s, 3),
                rss_max_mb=round(w.rss_max_mb, 1),
                cpu_pct=round(w.cpu_total_pct, 1),
                mempool_max=w.mempool_max,
                dup_ratio=w.dup_ratio, stalled=stalled)
            _note_saturation(report, w, rate)
            if stalled:
                logger.info("net past saturation; stopping the ladder",
                            rate=rate)
                break
            await drain_mempool()

            if wi == 1 and perturb:
                # kill -9 + restart one validator (reference:
                # perturb.go kill); memdb state is lost, so recovery
                # exercises a real from-scratch blocksync
                victim = names[n_validators - 1]
                report.perturbation = f"{victim}:kill9-restart"
                procs[victim].kill()
                await asyncio.to_thread(procs[victim].wait,
                                        timeout=30)
                await asyncio.sleep(0.5)
                procs[victim] = _spawn_node(cfgs[victim].base.home)
                sampler.track(victim, procs[victim])
                if not await _rpc_ready(rpc_ep[victim], 240.0):
                    raise TimeoutError("victim never came back")
                h = await _rpc_height(endpoints[0])
                await wait_height(h + 2, 240.0,
                                  eps=[rpc_ep[victim]])
                report.perturbed_recovered = True
                logger.info("perturbed node recovered",
                            victim=victim)

        if profile:
            # DEDICATED profile window, outside the measured ladder:
            # cProfile costs ~2x on the profiled node and drags the
            # whole net, so it must never overlap a recorded window
            prate = report.saturation_rate or rates[0]
            profile_task = asyncio.get_running_loop().create_task(
                _fetch_profile(pprof_port, seconds=25))
            await loadtime.generate(
                endpoints, rate=prate, connections=2,
                duration_s=30.0, size=256, method="async",
                max_in_flight=16)
            report.profile_top = await profile_task
            profile_task = None
            logger.info("profile window captured", rate=prate,
                        lines=len(report.profile_top))

        cli = HTTPClient(endpoints[0], timeout=30.0)
        joiner_ep = None
        if joiner:
            # let any remaining backlog commit first: the joiner
            # otherwise blocksyncs against a net that is busy
            # committing minutes of queued load
            await drain_mempool(240.0)

            # --- statesync late joiner (own process) ----------------
            th = max(1, await _rpc_height(endpoints[0]) - 8)
            blk = await cli.call("block", height=str(th))
            _configure_joiner(joiner_cfg, endpoints, th,
                              blk["block_id"]["hash"], node_ids,
                              p2p_port, names)
            _write_node_overrides(joiner_cfg)
            target = await _rpc_height(endpoints[0])
            procs["joiner"] = _spawn_node(joiner_cfg.base.home)
            sampler.track("joiner", procs["joiner"])
            joiner_ep = "http://" + \
                joiner_cfg.rpc.laddr[len("tcp://"):]
            if fleet is not None:
                fleet.track("joiner", joiner_ep)
            try:
                if not await _rpc_ready(joiner_ep, 240.0):
                    raise TimeoutError("joiner RPC never came up")
                await wait_height(target, 600.0, eps=[joiner_ep])
                report.statesync_joiner_height = await _rpc_height(
                    joiner_ep)
                logger.info("statesync joiner caught up",
                            height=report.statesync_joiner_height)
            except Exception as e:
                # a late joiner that cannot catch a loaded 1-core box
                # within budget must not void the whole report — the
                # statesync path itself is covered by
                # tests/test_statesync_e2e.py
                logger.error("joiner stage failed", err=repr(e))
                report.notes.append(f"joiner-stage: {e!r:.120}")
                report.degraded.append("statesync_joiner")
                joiner_ep = None

        for _ in range(3):
            try:
                report.final_height = await _rpc_height(endpoints[0])
                break
            except Exception:
                await asyncio.sleep(2.0)
        if not report.final_height:
            report.notes.append(
                "final-height probe failed; commit-sig/interval/"
                "invariant scans skipped")
        _gate_dup_ratio(report,
                        await _scrape_gossip_counters(all_eps))
        report.compact_blocks = await _scrape_compact_counters(
            all_eps)
        await _sample_commit_sigs(report, cli, report.final_height)

        # --- block interval stats over RPC --------------------------
        # best-effort with retries: 40 minutes of window data must
        # never be lost to one slow RPC on the still-busy box
        times = []
        lo = 2
        while lo <= report.final_height:
            hi = min(lo + 19, report.final_height)
            bc = None
            for _ in range(3):
                try:
                    bc = await cli.call("blockchain",
                                        minHeight=str(lo),
                                        maxHeight=str(hi))
                    break
                except Exception:
                    await asyncio.sleep(2.0)
            if bc is None:
                report.notes.append(
                    f"block-interval scan truncated at {lo}")
                break
            for meta in sorted(
                    bc.get("block_metas", []),
                    key=lambda m: int(m["header"]["height"])):
                ts = meta["header"]["time"]
                times.append((int(meta["header"]["height"]), ts))
            lo = hi + 1
        times.sort()

        def _parse_ns(ts: str) -> float:
            from ..types.timestamp import Timestamp
            return Timestamp.from_rfc3339(ts).unix_ns() / 1e9

        _record_intervals(report, [_parse_ns(t) for _, t in times])

        # --- invariants over RPC (sampled heights) ------------------
        # adaptive stride: the scan was sized for ~140-block runs;
        # the pipelined engine commits several blocks per second, so
        # a fixed stride of 5 over a 1000-block run would cost
        # thousands of RPC round trips on the already-busy box
        check_eps = [rpc_ep[n] for n in names] + \
            ([joiner_ep] if joiner_ep else [])
        stride = max(5, report.final_height // 30)
        for h in range(1, report.final_height + 1, stride):
            want = None
            for ep in check_eps:
                c2 = HTTPClient(ep, timeout=15.0)
                try:
                    b = await c2.call("block", height=str(h))
                except Exception:
                    continue
                pair = (b["block_id"]["hash"],
                        b["block"]["header"]["app_hash"])
                if want is None:
                    want = pair
                elif pair != want:
                    report.mismatches.append(
                        f"{ep}@{h}: hash/app_hash mismatch")
    finally:
        if profile_task is not None and not profile_task.done():
            # a mid-window failure must not abandon the urlopen thread
            profile_task.cancel()
            try:
                await profile_task
            except (asyncio.CancelledError, Exception):
                pass
        if fleet is not None:
            # final fleet-wide scrape while the nodes are still up —
            # this is the give-up/violation evidence path too
            try:
                report.fleet_path = await fleet.stop_and_write()
                _gate_fleet(report, report.fleet_path)
            except Exception as e:
                logger.error("fleet collection failed", err=repr(e))
                report.notes.append(f"fleet-collect: {e!r:.120}")
        if sampler is not None:
            sampler.stop()
        for proc in procs.values():
            try:
                proc.terminate()
            except Exception:
                pass
        for proc in procs.values():
            try:
                await asyncio.to_thread(proc.wait, timeout=15)
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass
        for r in relays:
            r.close()
        for r in relays:
            await r.wait_closed()
    return report


# --------------------------------------------------------------------------
# lightserve scale stage (ISSUE 9 / ROADMAP item 3): ~1000 simulated
# light clients hammer a 4-validator net's proof-serving RPC surface
# (light_block / multiproof / commit) at immutable heights while a
# background tx load keeps consensus busy.  Deliverables: the cache
# hit rate on immutable heights (> 90% expected — the whole point of
# the height-keyed tier), light-client request latency quantiles, and
# the consensus latency SLO — block intervals during the hammer vs
# before it.

@dataclass
class LightserveReport:
    nodes: int = 0
    clients: int = 0
    requests_total: int = 0
    request_errors: int = 0
    proofs_verified: int = 0
    proof_verify_errors: int = 0
    req_p50_ms: float = 0.0
    req_p90_ms: float = 0.0
    req_max_ms: float = 0.0
    hammer_duration_s: float = 0.0
    requests_per_s: float = 0.0
    cache_hits: int = 0
    cache_misses: int = 0
    cache_evictions: int = 0
    cache_hit_rate: float = 0.0
    cache_entries: int = 0
    cache_bytes: int = 0
    block_interval_before_s: float = 0.0
    block_interval_during_s: float = 0.0
    slo_ratio: float = 0.0
    slo_ok: bool = False
    heights_served: int = 0
    final_height: int = 0
    degraded: list[str] = field(default_factory=list)
    notes: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        import dataclasses
        return dataclasses.asdict(self)


async def run_lightserve(outdir: str, n_clients: int = 1000,
                         requests_per_client: int = 6,
                         max_in_flight: int = 64) -> LightserveReport:
    """In-process 4-validator net + n_clients simulated light
    clients.  Each client loops over random immutable heights calling
    light_block/multiproof/commit; every ~8th multiproof response is
    verified against the light block's header data_hash, closing the
    proof loop client-side.  max_in_flight bounds concurrently open
    requests (1000 truly simultaneous sockets on a 1-core box would
    measure the OS, not the cache)."""
    import base64 as _b64
    import hashlib as _hashlib
    import random as _random

    from ..abci.kvstore import KVStoreApplication
    from ..crypto.merkle import Multiproof
    from ..db import new_db
    from ..node.node import Node
    from ..rpc.client import HTTPClient
    from . import loadtime

    report = LightserveReport()
    qa_stub = QAReport()
    names, zones, cfgs, _joiner_cfg, node_ids, p2p_port, relay_specs = \
        _setup_net(outdir, n_validators=4, n_full=0, ghosts=0,
                   report=qa_stub, single_zone=True)
    report.nodes = len(names)
    report.clients = n_clients

    nodes: dict[str, "Node"] = {}
    try:
        for name in names:
            app = KVStoreApplication(
                db=new_db("app", "memdb",
                          cfgs[name].base.path("data")),
                snapshot_interval=0)
            nodes[name] = Node(cfgs[name], app=app)
            await nodes[name].start()
        endpoints = [f"http://{nodes[n]._rpc_server.listen_addr}"
                     for n in names]
        ref = nodes[names[0]]

        async def wait_height(h: int, budget: float) -> None:
            deadline = time.monotonic() + budget
            while time.monotonic() < deadline:
                if ref.height >= h:
                    return
                await asyncio.sleep(0.1)
            raise TimeoutError(f"net stuck below {h}")

        # --- warm the chain: commit enough history (with txs) that
        # the hammer has a spread of immutable heights to replay
        await wait_height(2, 120.0)
        await loadtime.generate(endpoints, rate=10, connections=1,
                                duration_s=8.0, size=128,
                                method="async", max_in_flight=8)
        await wait_height(20, 120.0)
        h_start = ref.height

        # --- background tx load for the whole hammer window so the
        # SLO measures consensus UNDER the read traffic
        bg_load = asyncio.get_running_loop().create_task(
            loadtime.generate(endpoints, rate=5, connections=1,
                              duration_s=35.0, size=128,
                              method="async", max_in_flight=4))

        # --- the hammer -------------------------------------------
        latencies: list[float] = []
        errors = 0                  # failed RPC requests
        verified = 0                # client-side proof checks passed
        verify_errors = 0           # ...and failed (NOT request errors)
        gate = asyncio.Semaphore(max_in_flight)

        async def gated_call(cli, method, **params):
            """One accounted request: gated, timed on its own attempt
            (a retry restarts the clock, so a failed first attempt
            never pollutes the latency sample)."""
            async with gate:
                t0 = time.monotonic()
                res = await cli.call(method, **params)
                latencies.append(time.monotonic() - t0)
                return res

        async def light_client(cid: int) -> None:
            nonlocal errors, verified, verify_errors
            rng = _random.Random(cid)
            cli = HTTPClient(endpoints[cid % len(endpoints)],
                             timeout=30.0)
            # clients replay the recent immutable window, zipf-ish:
            # real light clients cluster on the same sync targets
            for r in range(requests_per_client):
                h = 2 + int(rng.betavariate(2, 1) * (h_start - 4))
                # verifying clients ask for tx 0 so the proof check
                # below exercises real leaves; empty blocks answer
                # out-of-range and the client falls back to the
                # (still root-binding) empty key set
                idx = "0" if cid % 8 == 0 else ""
                method, params = [
                    ("light_block", {"height": str(h)}),
                    ("multiproof", {"height": str(h),
                                    "indices": idx}),
                    ("commit", {"height": str(h)}),
                ][r % 3]
                try:
                    try:
                        res = await gated_call(cli, method, **params)
                    except Exception as e:
                        if method == "multiproof" and idx and \
                                "out of range" in str(e):
                            params["indices"] = ""
                            res = await gated_call(cli, method,
                                                   **params)
                        else:
                            raise
                except Exception as e:
                    errors += 1
                    logger.debug("light client request failed",
                                 method=method, height=h,
                                 err=repr(e))
                    continue
                if method == "multiproof" and cid % 8 == 0:
                    # close the loop: fetch the header and check the
                    # (possibly empty-keyset) proof binds data_hash
                    try:
                        lb = await gated_call(cli, "light_block",
                                              height=str(h))
                    except Exception as e:
                        errors += 1
                        logger.debug("light client request failed",
                                     method="light_block", height=h,
                                     err=repr(e))
                        continue
                    try:
                        dh = bytes.fromhex(
                            lb["light_block"]["signed_header"]
                            ["header"]["data_hash"])
                        # the tx tree's items are per-tx digests:
                        # verify() applies the leaf-prefix hash
                        mp = Multiproof.from_dict(res["multiproof"])
                        mp.verify(dh, [
                            _hashlib.sha256(_b64.b64decode(t))
                            .digest() for t in res["txs"]])
                        verified += 1
                    except Exception as e:
                        verify_errors += 1
                        report.notes.append(
                            f"proof-verify@{h}: {e!r:.80}"[:120])

        t_hammer0 = time.monotonic()
        await asyncio.gather(*(light_client(i)
                               for i in range(n_clients)))
        report.hammer_duration_s = time.monotonic() - t_hammer0
        h_end = ref.height          # the window consensus shared
        try:                        # with the read hammer
            await bg_load
        except Exception as e:
            report.notes.append(f"bg-load: {e!r:.100}")

        # --- results ----------------------------------------------
        report.requests_total = len(latencies) + errors
        report.request_errors = errors
        report.proofs_verified = verified
        report.proof_verify_errors = verify_errors
        if latencies:
            latencies.sort()
            report.req_p50_ms = round(
                latencies[len(latencies) // 2] * 1e3, 3)
            report.req_p90_ms = round(
                latencies[int(len(latencies) * 0.9)] * 1e3, 3)
            report.req_max_ms = round(latencies[-1] * 1e3, 3)
        if report.hammer_duration_s > 0:
            report.requests_per_s = round(
                len(latencies) / report.hammer_duration_s, 1)
        for n in nodes.values():
            st = n.lightserve_cache.stats()
            report.cache_hits += st["hits"]
            report.cache_misses += st["misses"]
            report.cache_evictions += st["evictions"]
            report.cache_entries += st["entries"]
            report.cache_bytes += st["bytes"]
        probes = report.cache_hits + report.cache_misses
        report.cache_hit_rate = round(
            report.cache_hits / probes, 4) if probes else 0.0
        report.heights_served = h_start - 2
        report.final_height = h_end

        def _intervals(lo: int, hi: int) -> list[float]:
            ts = []
            for h in range(lo, hi + 1):
                meta = ref.block_store.load_block_meta(h)
                if meta is not None:
                    ts.append(meta.header.time.unix_ns() / 1e9)
            return [b - a for a, b in zip(ts, ts[1:])]

        before = _intervals(2, h_start)
        during = _intervals(h_start, h_end)
        if before:
            report.block_interval_before_s = round(
                statistics.mean(before), 3)
        if during:
            report.block_interval_during_s = round(
                statistics.mean(during), 3)
        # SLO: consensus under the read hammer stays within 2x of its
        # pre-hammer block interval (+100 ms scheduling slack on the
        # shared box) and never stops advancing
        if not during:
            report.slo_ok = False
            report.degraded.append("consensus_stalled_under_hammer")
        else:
            limit = report.block_interval_before_s * 2.0 + 0.1
            report.slo_ratio = round(
                report.block_interval_during_s /
                max(report.block_interval_before_s, 1e-9), 2)
            report.slo_ok = report.block_interval_during_s <= limit
            if not report.slo_ok:
                report.degraded.append("consensus_latency_slo")
        if report.cache_hit_rate < 0.9:
            report.degraded.append("cache_hit_rate_below_90pct")
        if errors > report.requests_total * 0.01:
            report.degraded.append("request_error_rate")
        if verify_errors:
            # a served proof that fails client-side verification is
            # a correctness event, not load noise — any count degrades
            report.degraded.append("proof_verification_failures")
        logger.info("lightserve hammer done",
                    clients=n_clients,
                    requests=report.requests_total,
                    errors=errors,
                    hit_rate=report.cache_hit_rate,
                    p90_ms=report.req_p90_ms,
                    interval_before=report.block_interval_before_s,
                    interval_during=report.block_interval_during_s,
                    slo_ok=report.slo_ok)
    finally:
        for n in nodes.values():
            try:
                await n.stop()
            except Exception as e:
                logger.debug("node stop failed during teardown",
                             err=repr(e))
    return report


async def run_sig_scale(outdir: str,
                        window_s: float = 30.0) -> QAReport:
    """Signature-scale stage (VERDICT r4 #5): 32 LIVE validators
    (power 100 each) + 70 power-1 ghosts, so every commit carries
    >= 32 real signatures through the batch verification path in a
    running network.  Lighter stages (no perturbation / joiner /
    profile — 33 processes on this box saturate the core by
    themselves), and a 2 s commit timeout: at 200 ms the proposer
    commits before the slowest third of 32 time-shared validators
    deliver their precommits, capping the measured width at ~22-24 of
    32.  The deliverable is the per-block verified-signature width +
    that the net sustains load at that width."""
    return await run_qa_procs(
        outdir, n_validators=32, n_full=1, ghosts=70,
        rates=(5, 10), window_s=window_s,
        perturb=False, joiner=False, profile=False,
        commit_timeout_ns=2_000_000_000,
        single_zone=True, peer_degree=6)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small shape for CI (6 nodes, 2 windows)")
    ap.add_argument("--procs", action="store_true",
                    help="one OS process per node + psutil resource "
                         "series (the reference QA method's shape)")
    ap.add_argument("--sigscale", action="store_true",
                    help="32 live validators: every commit carries "
                         ">=32 real signatures through the batch path")
    ap.add_argument("--lightserve", action="store_true",
                    help="~1000 simulated light clients hammer a "
                         "4-node net's proof-serving RPC (cache hit "
                         "rate + consensus latency SLO)")
    ap.add_argument("--clients", type=int, default=1000,
                    help="lightserve stage: simulated light clients")
    ap.add_argument("--no-sigscale", action="store_true",
                    help="full run without the sig-scale stage")
    ap.add_argument("--window", type=float, default=0.0)
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)
    # --quick / --sigscale-only must never clobber the committed
    # full-scale record
    out_path = args.out or (
        "QA_quick.json" if args.quick else
        "QA_sigscale.json" if args.sigscale else
        "QA_r06.json" if args.lightserve else "QA_r05.json")
    if args.lightserve:
        with tempfile.TemporaryDirectory() as d:
            ls_rep = asyncio.run(run_lightserve(
                d, n_clients=args.clients))
        out = {"scenario": "lightserve_scale",
               **ls_rep.to_dict()}
        with open(out_path, "w") as f:
            json.dump(out, f, indent=2)
            f.write("\n")
        print(json.dumps({
            "clients": ls_rep.clients,
            "requests": ls_rep.requests_total,
            "errors": ls_rep.request_errors,
            "cache_hit_rate": ls_rep.cache_hit_rate,
            "req_p90_ms": ls_rep.req_p90_ms,
            "interval_before_s": ls_rep.block_interval_before_s,
            "interval_during_s": ls_rep.block_interval_during_s,
            "slo_ok": ls_rep.slo_ok,
            "degraded": ls_rep.degraded,
        }))
        return 0 if not ls_rep.degraded else 1
    sig_rep: Optional[QAReport] = None
    with tempfile.TemporaryDirectory() as d:
        if args.sigscale:
            rep = asyncio.run(run_sig_scale(
                d, window_s=args.window or 30.0))
        elif args.quick and args.procs:
            rep = asyncio.run(run_qa_procs(
                d, n_validators=4, n_full=1, ghosts=20,
                rates=(25, 50), window_s=args.window or 10.0))
        elif args.quick:
            rep = asyncio.run(run_qa(
                d, n_validators=4, n_full=1, ghosts=20,
                rates=(25, 50), window_s=args.window or 8.0))
        elif args.procs:
            rep = asyncio.run(run_qa_procs(
                d, window_s=args.window or 90.0))
        else:
            rep = asyncio.run(run_qa(d, window_s=args.window or 15.0))
    if args.procs and not args.quick and not args.no_sigscale \
            and not args.sigscale:
        # the full reference-method run carries the sig-scale stage
        # as a second net (the validator set is fixed at genesis)
        with tempfile.TemporaryDirectory() as d:
            try:
                sig_rep = asyncio.run(run_sig_scale(d))
            except Exception as e:
                logger.error("sig-scale stage failed", err=repr(e))
    out = rep.to_dict()
    if sig_rep is not None:
        out["sig_scale"] = sig_rep.to_dict()
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps({
        "nodes": rep.nodes, "validators": rep.validators_total,
        "saturation_rate": rep.saturation_rate,
        "offered_ratio": rep.offered_check.get("offered_ratio"),
        "commit_sigs_avg": rep.commit_sigs_avg,
        "dup_ratio_overall": rep.dup_ratio_overall,
        "windows": [[w.rate, round(w.tx_per_s, 1),
                     round(w.latency_p50_s, 3)]
                    for w in rep.windows],
        "block_interval_avg_s": round(rep.block_interval_avg_s, 3),
        "sig_scale_commit_sigs_avg":
            sig_rep.commit_sigs_avg if sig_rep else None,
        "mismatches": len(rep.mismatches),
        "degraded": rep.degraded,
    }))
    return 0 if not rep.mismatches else 1


if __name__ == "__main__":
    raise SystemExit(main())
