"""QA scale run: a 15+ node, 100+ validator live net under staged
load, with a kill/restart perturbation and a statesync late joiner.

Reference: docs/references/qa/method.md + CometBFT-QA-v1.md (the
200-node / 175-validator DigitalOcean saturation study, scaled to one
host) and test/e2e/runner/benchmark.go (block-interval stats).  The
run records a tx/s saturation table + latency quantiles per load
window into QA_r{N}.json; docs/QA.md carries the narrative.

Shape of the net (single host, in-process asyncio nodes):
- 12 live validators (power 100 each) + 3 full nodes across three
  latency zones (50/100/150 ms one-way links)
- 90 "remote" validators in the genesis set with power 1 and mixed
  key types (ed25519/secp256k1) that never come online: every commit
  carries a 102-slot signature array, so commit verification runs at
  the 100+ validator width the reference QA exercises, while quorum
  rests with the live 12 (1200 of 1290 power)
- one statesync late joiner that bootstraps from a snapshot mid-run

Run:  python -m cometbft_tpu.tools.qa [--quick]
"""
from __future__ import annotations

import asyncio
import json
import os
import statistics
import tempfile
import time
from dataclasses import dataclass, field
from typing import Optional

from ..config import Config
from ..crypto import ed25519, secp256k1
from ..libs.log import new_logger
from ..p2p.key import NodeKey
from ..privval import FilePV
from ..types.genesis import GenesisDoc, GenesisValidator
from ..types.timestamp import Timestamp

logger = new_logger("qa")

ZONES = ["zone-a", "zone-b", "zone-c"]
ZONE_LATENCY_MS = {"zone-a:zone-b": 50, "zone-a:zone-c": 100,
                   "zone-b:zone-c": 150}


@dataclass
class WindowResult:
    rate: int
    duration_s: float
    sent: int = 0
    accepted: int = 0
    committed: int = 0
    tx_per_s: float = 0.0
    latency_p50_s: float = 0.0
    latency_p90_s: float = 0.0
    latency_max_s: float = 0.0


@dataclass
class QAReport:
    nodes: int = 0
    validators_total: int = 0
    validators_live: int = 0
    windows: list[WindowResult] = field(default_factory=list)
    saturation_rate: int = 0
    block_interval_avg_s: float = 0.0
    block_interval_std_s: float = 0.0
    block_interval_min_s: float = 0.0
    block_interval_max_s: float = 0.0
    final_height: int = 0
    perturbation: str = ""
    perturbed_recovered: bool = False
    statesync_joiner_height: int = 0
    mismatches: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        import dataclasses
        return dataclasses.asdict(self)


def _mk_cfg(root: str, name: str, zone: str) -> Config:
    import socket

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    home = os.path.join(root, name)
    cfg = Config()
    cfg.base.home = home
    cfg.base.moniker = name
    cfg.base.db_backend = "memdb"
    cfg.p2p.laddr = f"tcp://127.0.0.1:{free_port()}"
    cfg.rpc.laddr = f"tcp://127.0.0.1:{free_port()}"
    cfg.p2p.allow_duplicate_ip = True
    cfg.p2p.pex = False          # fixed topology under latency relays
    cfg.consensus.timeout_commit_ns = 200_000_000
    cfg.mempool.size = 20_000
    os.makedirs(os.path.join(home, "config"), exist_ok=True)
    os.makedirs(os.path.join(home, "data"), exist_ok=True)
    return cfg


def _ghost_validators(n: int) -> list[GenesisValidator]:
    """Validators in the set that never come online — mixed key types
    so the commit verification path sees a heterogeneous 100+ slot
    array (BASELINE config #5's shape)."""
    out = []
    for i in range(n):
        if i % 2 == 0:
            pub = ed25519.gen_priv_key().pub_key()
        else:
            pub = secp256k1.gen_priv_key().pub_key()
        out.append(GenesisValidator(address=b"", pub_key=pub, power=1))
    return out


async def run_qa(outdir: str, n_validators: int = 12, n_full: int = 3,
                 ghosts: int = 90,
                 rates: tuple = (25, 50, 100, 200),
                 window_s: float = 15.0) -> QAReport:
    from ..abci.kvstore import KVStoreApplication
    from ..db import new_db
    from ..node.node import Node
    from ..rpc.client import HTTPClient
    from . import loadtime
    from .manifest import Relay, RelaySpec, start_relay

    report = QAReport()
    names = [f"validator{i:02d}" for i in range(n_validators)] + \
            [f"full{i:02d}" for i in range(n_full)]
    zones = {name: ZONES[i % len(ZONES)]
             for i, name in enumerate(names)}

    cfgs = {name: _mk_cfg(outdir, name, zones[name])
            for name in names}
    joiner_cfg = _mk_cfg(outdir, "joiner", ZONES[0])

    # genesis: live validators + ghost validators, mixed key types
    pvs = {}
    for name in names + ["joiner"]:
        cfg = cfgs.get(name, joiner_cfg)
        pvs[name] = FilePV.generate(
            cfg.base.path(cfg.base.priv_validator_key_file),
            cfg.base.path(cfg.base.priv_validator_state_file))
        NodeKey.load_or_gen(cfg.base.path(cfg.base.node_key_file))
    vals = [GenesisValidator(address=b"",
                             pub_key=pvs[n].get_pub_key(), power=100)
            for n in names[:n_validators]]
    vals += _ghost_validators(ghosts)
    doc = GenesisDoc(chain_id="qa-net", genesis_time=Timestamp.now(),
                     validators=vals)
    doc.consensus_params.validator.pub_key_types = [
        "ed25519", "secp256k1"]
    doc.consensus_params.feature.pbts_enable_height = 1
    report.validators_total = len(vals)
    report.validators_live = n_validators
    report.nodes = len(names) + 1

    # topology: each node dials every "later" node, through a latency
    # relay when the zones differ (manifest.py setup pattern)
    node_ids = {}
    for name in names + ["joiner"]:
        cfg = cfgs.get(name, joiner_cfg)
        doc.save_as(cfg.base.path(cfg.base.genesis_file))
        node_ids[name] = NodeKey.load_or_gen(
            cfg.base.path(cfg.base.node_key_file)).id
    relay_specs: list[RelaySpec] = []

    def link_port(a: str, b: str, target_port: int) -> int:
        za, zb = zones.get(a, ZONES[0]), zones.get(b, ZONES[0])
        key = f"{za}:{zb}" if f"{za}:{zb}" in ZONE_LATENCY_MS \
            else f"{zb}:{za}"
        ms = ZONE_LATENCY_MS.get(key, 0) if za != zb else 0
        if ms == 0:
            return target_port
        import socket
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        relay_specs.append(RelaySpec(
            port=port, target_host="127.0.0.1",
            target_port=target_port, delay_s=ms / 1000.0))
        return port

    p2p_port = {name: int(cfgs[name].p2p.laddr.rsplit(":", 1)[1])
                for name in names}
    for i, name in enumerate(names):
        peers = []
        for other in names[i + 1:]:
            peers.append(f"{node_ids[other]}@127.0.0.1:"
                         f"{link_port(name, other, p2p_port[other])}")
        cfgs[name].p2p.persistent_peers = ",".join(peers)

    nodes: dict[str, Node] = {}
    relays: list[Relay] = []
    joiner: Optional[Node] = None
    try:
        for spec in relay_specs:
            relays.append(await start_relay(spec))
        for name in names:
            app = KVStoreApplication(
                db=new_db("app", "memdb",
                          cfgs[name].base.path("data")),
                snapshot_interval=5)
            nodes[name] = Node(cfgs[name], app=app)
            await nodes[name].start()
        logger.info("net booted", nodes=len(nodes),
                    relays=len(relays))

        endpoints = [f"http://{nodes[n]._rpc_server.listen_addr}"
                     for n in names[:3]]
        ref = nodes[names[0]]

        async def wait_height(h: int, budget: float,
                              who=None) -> None:
            pool = who if who is not None else list(nodes.values())
            deadline = time.monotonic() + budget
            while time.monotonic() < deadline:
                if all(n.height >= h for n in pool):
                    return
                await asyncio.sleep(0.1)
            raise TimeoutError(
                f"net stuck: {[n.height for n in pool]} < {h}")

        await wait_height(2, 120.0)

        # --- load windows at increasing rates -----------------------
        for wi, rate in enumerate(rates):
            res = await loadtime.generate(
                endpoints, rate=rate, connections=1,
                duration_s=window_s, size=256, method="async")
            # let the tail commit
            h0 = ref.height
            await wait_height(h0 + 2, 60.0, who=[ref])
            rep = await loadtime.report(
                endpoints[0], experiment_id=res.experiment_id)
            w = WindowResult(
                rate=rate, duration_s=window_s, sent=res.sent,
                accepted=res.accepted, committed=rep.latency.count,
                tx_per_s=rep.latency.count / window_s,
                latency_p50_s=rep.latency.p50_s,
                latency_p90_s=rep.latency.p90_s,
                latency_max_s=rep.latency.max_s)
            report.windows.append(w)
            logger.info("load window done", rate=rate,
                        committed=w.committed,
                        tx_s=round(w.tx_per_s, 1),
                        p50=round(w.latency_p50_s, 3))
            # saturation: committed tx/s stops tracking the offered
            # rate (< 80% of it) or stops growing
            if w.tx_per_s >= 0.8 * rate:
                report.saturation_rate = rate

            if wi == 1:
                # --- perturbation between windows: kill/restart one
                # validator (reference: perturb.go)
                victim = names[n_validators - 1]
                report.perturbation = f"{victim}:kill-restart"
                await nodes[victim].stop()
                await asyncio.sleep(0.5)
                app = KVStoreApplication(
                    db=new_db("app", "memdb",
                              cfgs[victim].base.path("data")),
                    snapshot_interval=5)
                nodes[victim] = Node(cfgs[victim], app=app)
                await nodes[victim].start()
                h = ref.height
                await wait_height(h + 2, 120.0,
                                  who=[nodes[victim]])
                report.perturbed_recovered = True
                logger.info("perturbed node recovered",
                            victim=victim)

        # --- statesync late joiner ----------------------------------
        cli = HTTPClient(endpoints[0], timeout=30.0)
        th = max(1, ref.height - 8)
        blk = await cli.call("block", height=str(th))
        joiner_cfg.statesync.enable = True
        joiner_cfg.statesync.rpc_servers = [endpoints[0],
                                            endpoints[1]]
        joiner_cfg.statesync.trust_height = th
        joiner_cfg.statesync.trust_hash = blk["block_id"]["hash"]
        joiner_cfg.statesync.discovery_time_ns = int(2e9)
        joiner_cfg.p2p.persistent_peers = ",".join(
            f"{node_ids[n]}@127.0.0.1:{p2p_port[n]}"
            for n in names[:4])
        app = KVStoreApplication(
            db=new_db("app", "memdb", joiner_cfg.base.path("data")),
            snapshot_interval=5)
        joiner = Node(joiner_cfg, app=app)
        await joiner.start()
        target = ref.height
        await wait_height(target, 180.0, who=[joiner])
        report.statesync_joiner_height = joiner.height
        logger.info("statesync joiner caught up",
                    height=joiner.height)

        report.final_height = ref.height

        # --- block interval stats (benchmark.go:15-24) --------------
        times = []
        for h in range(2, ref.height + 1):
            meta = ref.block_store.load_block_meta(h)
            if meta is not None:
                times.append(meta.header.time.unix_ns() / 1e9)
        intervals = [b - a for a, b in zip(times, times[1:])]
        if intervals:
            report.block_interval_avg_s = statistics.mean(intervals)
            report.block_interval_std_s = (
                statistics.pstdev(intervals)
                if len(intervals) > 1 else 0.0)
            report.block_interval_min_s = min(intervals)
            report.block_interval_max_s = max(intervals)

        # --- invariants ---------------------------------------------
        for h in range(1, report.final_height + 1):
            want = ref.block_store.load_block_meta(h)
            if want is None:
                continue
            for name, n in list(nodes.items()) + [("joiner", joiner)]:
                got = n.block_store.load_block_meta(h)
                if got is None:
                    continue
                if got.block_id.hash != want.block_id.hash:
                    report.mismatches.append(
                        f"{name}@{h}: block hash mismatch")
                if got.header.app_hash != want.header.app_hash:
                    report.mismatches.append(
                        f"{name}@{h}: app hash mismatch")
    finally:
        for n in list(nodes.values()) + ([joiner] if joiner else []):
            try:
                await n.stop()
            except Exception:
                pass
        for r in relays:
            r.close()
        for r in relays:
            await r.wait_closed()
    return report


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small shape for CI (6 nodes, 2 windows)")
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)
    # --quick must never clobber the committed full-scale record
    out_path = args.out or (
        "QA_quick.json" if args.quick else "QA_r03.json")
    with tempfile.TemporaryDirectory() as d:
        if args.quick:
            rep = asyncio.run(run_qa(
                d, n_validators=4, n_full=1, ghosts=20,
                rates=(25, 50), window_s=8.0))
        else:
            rep = asyncio.run(run_qa(d))
    out = rep.to_dict()
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps({
        "nodes": rep.nodes, "validators": rep.validators_total,
        "saturation_rate": rep.saturation_rate,
        "windows": [[w.rate, round(w.tx_per_s, 1),
                     round(w.latency_p50_s, 3)]
                    for w in rep.windows],
        "block_interval_avg_s": round(rep.block_interval_avg_s, 3),
        "mismatches": len(rep.mismatches),
    }))
    return 0 if not rep.mismatches else 1


if __name__ == "__main__":
    raise SystemExit(main())
