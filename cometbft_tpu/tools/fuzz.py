"""Coverage-guided fuzzing for the network-facing parsers.

Reference: test/fuzz/ + oss-fuzz-build.sh — the reference ships
go-fuzz/OSS-Fuzz harnesses with persisted corpora for the JSON-RPC
server, the secret-connection read path, and mempool CheckTx.
VERDICT r4 #7 asked for the same feedback loop here (the round-3
fuzzers were seeded mutational loops with no coverage signal).

Engine: AFL-style corpus growth driven by sys.monitoring (PEP 669)
LINE events — no external tooling (atheris/coverage aren't in this
image, and the stdlib hook is lower-overhead anyway):

  * every first execution of a (code object, line) location fires one
    callback; the callback records locations inside the target
    modules and returns sys.monitoring.DISABLE, so each location
    reports exactly once per run — the callback stream IS the
    "new coverage" signal, with near-zero steady-state overhead;
  * an input that lights up any new location is minimized-ish (kept
    as-is) and persisted to the corpus directory (sha1-named), which
    is checked into the repo — tests/fuzz_corpus/;
  * an input that raises anything outside the target's declared
    error types is persisted to corpus/crashes/ and reported; every
    crash becomes a fixed bug + a regression test.

Run:  python -m cometbft_tpu.tools.fuzz --target all --budget 30
CI:   tests/test_fuzz_coverage.py runs each target for a few seconds
      against the checked-in corpus.
"""
from __future__ import annotations

import argparse
import asyncio
import hashlib
import os
import random
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_CORPUS = os.path.join(_REPO, "tests", "fuzz_corpus")

# PEP 669 is python 3.12+; on older interpreters the CoverageMap
# falls back to sys.settrace (slower, same semantics) instead of
# killing every importer of this module at collection time
_HAVE_MONITORING = hasattr(sys, "monitoring")
_TOOL = sys.monitoring.COVERAGE_ID if _HAVE_MONITORING else 0
_MAX_INPUT = 4096


class CoverageMap:
    """Global line coverage over a set of module files, fed by
    sys.monitoring.  Locations outside the targets are DISABLEd on
    first sight; target locations report once ever, so `fresh` after
    a run means the run reached code no earlier input reached."""

    def __init__(self, filenames: Iterable[str]):
        self._files = {os.path.abspath(f) for f in filenames}
        self.locations: set[tuple[str, int]] = set()
        self.fresh = 0
        self._active = False

    def _on_line(self, code, line):
        fn = code.co_filename
        if fn in self._files:
            self.locations.add((fn, line))
            self.fresh += 1
        return sys.monitoring.DISABLE

    def _trace(self, frame, event, arg):
        # sys.settrace fallback (pre-3.12): per-call filtering keeps
        # the overhead on non-target frames to one dict lookup
        if event == "call":
            return self._trace \
                if frame.f_code.co_filename in self._files else None
        if event == "line":
            loc = (frame.f_code.co_filename, frame.f_lineno)
            if loc not in self.locations:
                self.locations.add(loc)
                self.fresh += 1
        return self._trace

    def __enter__(self):
        if not _HAVE_MONITORING:
            sys.settrace(self._trace)
            self._active = True
            return self
        sys.monitoring.use_tool_id(_TOOL, "cometbft-fuzz")
        sys.monitoring.register_callback(
            _TOOL, sys.monitoring.events.LINE, self._on_line)
        sys.monitoring.set_events(_TOOL, sys.monitoring.events.LINE)
        # locations DISABLEd by a previous session stay disabled
        # process-wide until restarted — without this, a second
        # fuzz_target over the same modules sees zero coverage
        sys.monitoring.restart_events()
        self._active = True
        return self

    def __exit__(self, *exc):
        if self._active:
            if not _HAVE_MONITORING:
                sys.settrace(None)
            else:
                sys.monitoring.set_events(
                    _TOOL, sys.monitoring.events.NO_EVENTS)
                sys.monitoring.register_callback(
                    _TOOL, sys.monitoring.events.LINE, None)
                sys.monitoring.free_tool_id(_TOOL)
            self._active = False
        return False

    def take_fresh(self) -> int:
        n, self.fresh = self.fresh, 0
        return n


def mutate(rng: random.Random, corpus: list[bytes]) -> bytes:
    """One havoc-mutated input from the corpus (or pure random)."""
    if rng.random() < 0.15 or not corpus:
        return rng.randbytes(rng.randrange(0, 256))
    base = bytearray(rng.choice(corpus))
    for _ in range(rng.randrange(1, 8)):
        op = rng.randrange(6)
        if op == 0 and base:                          # bit flip
            i = rng.randrange(len(base))
            base[i] ^= 1 << rng.randrange(8)
        elif op == 1 and base:                        # byte set
            base[rng.randrange(len(base))] = rng.randrange(256)
        elif op == 2 and base:                        # truncate
            del base[rng.randrange(len(base)):]
        elif op == 3:                                 # insert junk
            i = rng.randrange(len(base) + 1)
            base[i:i] = rng.randbytes(rng.randrange(1, 16))
        elif op == 4 and base:                        # splice corpus
            other = rng.choice(corpus)
            i = rng.randrange(len(base))
            base[i:i + rng.randrange(1, 32)] = \
                other[:rng.randrange(1, max(2, len(other)))]
        elif op == 5:                                 # magic ints
            magic = rng.choice(
                [b"\x00", b"\xff\xff\xff\xff", b"\x80", b"\x7f",
                 b"\xff\xff\xff\xff\xff\xff\xff\xff\x7f",
                 b'"', b"{", b"[", b"\\u0000"])
            i = rng.randrange(len(base) + 1)
            base[i:i] = magic
    return bytes(base[:_MAX_INPUT])


@dataclass
class FuzzStats:
    target: str
    runs: int = 0
    locations: int = 0
    corpus_size: int = 0
    new_inputs: int = 0
    crashes: list = field(default_factory=list)

    def to_dict(self) -> dict:
        d = dict(self.__dict__)
        d["crashes"] = [c[:200] for c in self.crashes]
        return d


class Target:
    """One fuzz target: a callable over raw bytes, the modules whose
    coverage guides it, seed inputs, and an optional close() for
    resources (event loops) the harness owns."""

    def __init__(self, name: str, run: Callable[[bytes], None],
                 modules: list[str], seeds: list[bytes],
                 close: Optional[Callable[[], None]] = None):
        self.name = name
        self.run = run
        self.modules = modules
        self.seeds = seeds
        self._close = close

    def close(self) -> None:
        if self._close is not None:
            self._close()


def _load_corpus(d: str) -> list[bytes]:
    out = []
    try:
        for fn in sorted(os.listdir(d)):
            p = os.path.join(d, fn)
            if os.path.isfile(p):
                with open(p, "rb") as f:
                    out.append(f.read(_MAX_INPUT))
    except OSError:
        pass
    return out


def _save(d: str, data: bytes) -> str:
    os.makedirs(d, exist_ok=True)
    name = hashlib.sha1(data).hexdigest()[:16] + ".bin"
    path = os.path.join(d, name)
    if not os.path.exists(path):
        with open(path, "wb") as f:
            f.write(data)
    return name


def fuzz_target(target: Target, budget_s: float,
                corpus_dir: str = DEFAULT_CORPUS,
                seed: int = 0) -> FuzzStats:
    """Run one coverage-guided loop.  Inputs that discover new lines
    are persisted to `{corpus_dir}/{target.name}/`; inputs that raise
    undeclared exceptions go to `.../crashes/` and are reported in
    the stats (the loop keeps going — one crash must not hide
    others)."""
    tdir = os.path.join(corpus_dir, target.name)
    stats = FuzzStats(target=target.name)
    corpus = list(target.seeds) + _load_corpus(tdir)
    rng = random.Random(seed or 0xF17E5)
    crash_sigs: set[str] = set()
    try:
        _fuzz_loop(target, budget_s, tdir, stats, corpus, rng,
                   crash_sigs)
    finally:
        target.close()
    stats.corpus_size = len(corpus)
    return stats


def _fuzz_loop(target, budget_s, tdir, stats, corpus, rng,
               crash_sigs) -> None:
    with CoverageMap(target.modules) as cov:
        # replay the corpus first so "fresh" afterwards means genuinely
        # new coverage, not first-touch of old territory
        for data in corpus:
            try:
                target.run(data)
            except Exception:
                pass
        cov.take_fresh()
        deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline:
            data = mutate(rng, corpus)
            stats.runs += 1
            try:
                target.run(data)
            except Exception as e:
                sig = f"{type(e).__name__}: {e}"[:120]
                if sig not in crash_sigs:
                    crash_sigs.add(sig)
                    name = _save(os.path.join(tdir, "crashes"), data)
                    stats.crashes.append(f"{sig} [{name}]")
            if cov.take_fresh():
                corpus.append(data)
                _save(tdir, data)
                stats.new_inputs += 1
        stats.locations = len(cov.locations)


# --------------------------------------------------------------------------
# targets

def _jsonrpc_target() -> Target:
    from cometbft_tpu.config import RPCConfig
    from cometbft_tpu.rpc import server as rpc_server_mod
    from cometbft_tpu.rpc.server import RPCServer

    class _NullNode:
        metrics_registry = None

    async def echo(*, s: str = "", i: int = 0):
        return {"s": s, "i": i}

    srv = RPCServer(_NullNode(), RPCConfig(), routes={"echo": echo})
    loop = asyncio.new_event_loop()

    def run(data: bytes) -> None:
        resp = loop.run_until_complete(
            srv._dispatch("POST", "/", data))
        assert isinstance(resp, (dict, list))
        import json as _json
        _json.dumps(resp)

    seeds = [
        b'{"jsonrpc":"2.0","method":"echo","params":{"s":"x"},"id":1}',
        b'[{"jsonrpc":"2.0","method":"echo","id":3}]',
        b'{"jsonrpc":"2.0","method":{"method":-1},"id":4}',
        b'{"method":"echo","params":{"i":-1}}',
        b"{}", b"[]", b"null", b"0",
    ]
    return Target("jsonrpc", run, [rpc_server_mod.__file__], seeds,
                  close=loop.close)


def _proto_target() -> Target:
    from cometbft_tpu.wire import abci_pb, pb, proto
    from cometbft_tpu.wire import decode, encode

    descs = [abci_pb.CHECK_TX_REQUEST, abci_pb.FINALIZE_BLOCK_REQUEST,
             abci_pb.INFO_RESPONSE, pb.BLOCK, pb.HEADER, pb.VOTE,
             pb.COMMIT]

    def run(data: bytes) -> None:
        for d in descs:
            try:
                decode(d, data)
            except ValueError:
                pass                # the decoder's declared rejection

    seeds = []
    for d in descs:
        try:
            seeds.append(encode(d, {}))
        except Exception:
            pass
    seeds += [b"\x0a\x02hi", b"\x08\x96\x01", b"\xff" * 10]
    return Target("proto", run, [proto.__file__], seeds)


def _secretconn_target() -> Target:
    from cometbft_tpu.crypto import ed25519
    from cometbft_tpu.p2p import secret_connection as sc_mod
    from cometbft_tpu.p2p.secret_connection import (
        SecretConnection, SecretConnectionError,
    )

    loop = asyncio.new_event_loop()
    key = ed25519.gen_priv_key()

    class _W:
        def write(self, b):
            pass

        async def drain(self):
            pass

        def close(self):
            pass

    def run(data: bytes) -> None:
        async def one():
            reader = asyncio.StreamReader()
            reader.feed_data(data)
            reader.feed_eof()
            try:
                await asyncio.wait_for(
                    SecretConnection.make(reader, _W(), key),
                    timeout=5)
            except (SecretConnectionError, ValueError,
                    asyncio.IncompleteReadError, ConnectionError,
                    asyncio.TimeoutError):
                pass
        loop.run_until_complete(one())

    seeds = [bytes(32), b"\x20" + bytes(32), b"\x20" + os.urandom(32)]
    return Target("secretconn", run, [sc_mod.__file__], seeds,
                  close=loop.close)


TARGETS = {
    "jsonrpc": _jsonrpc_target,
    "proto": _proto_target,
    "secretconn": _secretconn_target,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", default="all",
                    choices=["all"] + sorted(TARGETS))
    ap.add_argument("--budget", type=float, default=30.0,
                    help="seconds per target")
    ap.add_argument("--corpus", default=DEFAULT_CORPUS)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    names = sorted(TARGETS) if args.target == "all" else [args.target]
    rc = 0
    import json
    for name in names:
        stats = fuzz_target(TARGETS[name](), args.budget,
                            corpus_dir=args.corpus, seed=args.seed)
        print(json.dumps(stats.to_dict()))
        if stats.crashes:
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
