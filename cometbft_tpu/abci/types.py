"""ABCI request/response types and the Application interface.

Reference: abci/types/application.go:11-41 (the 15-method interface) and
proto/cometbft/abci/v2/types.proto (message shapes).  Python-native
dataclasses; wire conversion lives in abci/pb.py.
"""
from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Optional

from ..types.timestamp import Timestamp

CODE_TYPE_OK = 0

# CheckTxType
CHECK_TX_TYPE_UNKNOWN = 0
CHECK_TX_TYPE_RECHECK = 1
CHECK_TX_TYPE_CHECK = 2

# ProcessProposalStatus
PROCESS_PROPOSAL_STATUS_UNKNOWN = 0
PROCESS_PROPOSAL_STATUS_ACCEPT = 1
PROCESS_PROPOSAL_STATUS_REJECT = 2

# VerifyVoteExtensionStatus
VERIFY_VOTE_EXTENSION_STATUS_UNKNOWN = 0
VERIFY_VOTE_EXTENSION_STATUS_ACCEPT = 1
VERIFY_VOTE_EXTENSION_STATUS_REJECT = 2

# OfferSnapshotResult
OFFER_SNAPSHOT_RESULT_UNKNOWN = 0
OFFER_SNAPSHOT_RESULT_ACCEPT = 1
OFFER_SNAPSHOT_RESULT_ABORT = 2
OFFER_SNAPSHOT_RESULT_REJECT = 3
OFFER_SNAPSHOT_RESULT_REJECT_FORMAT = 4
OFFER_SNAPSHOT_RESULT_REJECT_SENDER = 5

# ApplySnapshotChunkResult
APPLY_SNAPSHOT_CHUNK_RESULT_UNKNOWN = 0
APPLY_SNAPSHOT_CHUNK_RESULT_ACCEPT = 1
APPLY_SNAPSHOT_CHUNK_RESULT_ABORT = 2
APPLY_SNAPSHOT_CHUNK_RESULT_RETRY = 3
APPLY_SNAPSHOT_CHUNK_RESULT_RETRY_SNAPSHOT = 4
APPLY_SNAPSHOT_CHUNK_RESULT_REJECT_SNAPSHOT = 5

# MisbehaviorType
MISBEHAVIOR_TYPE_UNKNOWN = 0
MISBEHAVIOR_TYPE_DUPLICATE_VOTE = 1
MISBEHAVIOR_TYPE_LIGHT_CLIENT_ATTACK = 2


@dataclass
class EventAttribute:
    key: str = ""
    value: str = ""
    index: bool = False


@dataclass
class Event:
    type: str = ""
    attributes: list[EventAttribute] = field(default_factory=list)


@dataclass
class ABCIValidator:
    """abci.Validator: 20-byte address + power."""
    address: bytes = b""
    power: int = 0


@dataclass
class ValidatorUpdate:
    power: int = 0
    pub_key_bytes: bytes = b""
    pub_key_type: str = ""


@dataclass
class VoteInfo:
    validator: ABCIValidator = field(default_factory=ABCIValidator)
    block_id_flag: int = 0


@dataclass
class ExtendedVoteInfo:
    validator: ABCIValidator = field(default_factory=ABCIValidator)
    vote_extension: bytes = b""
    extension_signature: bytes = b""
    block_id_flag: int = 0
    non_rp_vote_extension: bytes = b""
    non_rp_extension_signature: bytes = b""


@dataclass
class CommitInfo:
    round: int = 0
    votes: list[VoteInfo] = field(default_factory=list)


@dataclass
class ExtendedCommitInfo:
    round: int = 0
    votes: list[ExtendedVoteInfo] = field(default_factory=list)


@dataclass
class Misbehavior:
    type: int = MISBEHAVIOR_TYPE_UNKNOWN
    validator: ABCIValidator = field(default_factory=ABCIValidator)
    height: int = 0
    time: Timestamp = field(default_factory=Timestamp.zero)
    total_voting_power: int = 0


@dataclass
class Snapshot:
    height: int = 0
    format: int = 0
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""


@dataclass
class ExecTxResult:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list[Event] = field(default_factory=list)
    codespace: str = ""
    # state keys this tx read/wrote, reported by the app for the
    # mempool's incremental recheck (docs/pipeline.md).  NOT part of
    # the results hash (like log/info/events, it is local metadata).
    # Empty = the app doesn't attribute keys; the mempool then treats
    # the commit as touching unknown state and falls back to its
    # bounded-age watermark.
    recheck_keys: list[bytes] = field(default_factory=list)

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class TxResult:
    height: int = 0
    index: int = 0
    tx: bytes = b""
    result: ExecTxResult = field(default_factory=ExecTxResult)


# ---------------------------------------------------------------------------
# Requests


@dataclass
class EchoRequest:
    message: str = ""


@dataclass
class FlushRequest:
    pass


@dataclass
class InfoRequest:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0
    abci_version: str = ""


@dataclass
class InitChainRequest:
    time: Timestamp = field(default_factory=Timestamp.zero)
    chain_id: str = ""
    consensus_params: Optional[object] = None   # types.ConsensusParams
    validators: list[ValidatorUpdate] = field(default_factory=list)
    app_state_bytes: bytes = b""
    initial_height: int = 0


@dataclass
class QueryRequest:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False


@dataclass
class CheckTxRequest:
    tx: bytes = b""
    type: int = CHECK_TX_TYPE_CHECK


@dataclass
class CommitRequest:
    pass


@dataclass
class ListSnapshotsRequest:
    pass


@dataclass
class OfferSnapshotRequest:
    snapshot: Optional[Snapshot] = None
    app_hash: bytes = b""


@dataclass
class LoadSnapshotChunkRequest:
    height: int = 0
    format: int = 0
    chunk: int = 0


@dataclass
class ApplySnapshotChunkRequest:
    index: int = 0
    chunk: bytes = b""
    sender: str = ""


@dataclass
class PrepareProposalRequest:
    max_tx_bytes: int = 0
    txs: list[bytes] = field(default_factory=list)
    local_last_commit: ExtendedCommitInfo = field(
        default_factory=ExtendedCommitInfo)
    misbehavior: list[Misbehavior] = field(default_factory=list)
    height: int = 0
    time: Timestamp = field(default_factory=Timestamp.zero)
    next_validators_hash: bytes = b""
    proposer_address: bytes = b""


@dataclass
class ProcessProposalRequest:
    txs: list[bytes] = field(default_factory=list)
    proposed_last_commit: CommitInfo = field(default_factory=CommitInfo)
    misbehavior: list[Misbehavior] = field(default_factory=list)
    hash: bytes = b""
    height: int = 0
    time: Timestamp = field(default_factory=Timestamp.zero)
    next_validators_hash: bytes = b""
    proposer_address: bytes = b""


@dataclass
class ExtendVoteRequest:
    hash: bytes = b""
    height: int = 0
    time: Timestamp = field(default_factory=Timestamp.zero)
    txs: list[bytes] = field(default_factory=list)
    proposed_last_commit: CommitInfo = field(default_factory=CommitInfo)
    misbehavior: list[Misbehavior] = field(default_factory=list)
    next_validators_hash: bytes = b""
    proposer_address: bytes = b""


@dataclass
class VerifyVoteExtensionRequest:
    hash: bytes = b""
    validator_address: bytes = b""
    height: int = 0
    vote_extension: bytes = b""
    non_rp_vote_extension: bytes = b""


@dataclass
class FinalizeBlockRequest:
    txs: list[bytes] = field(default_factory=list)
    decided_last_commit: CommitInfo = field(default_factory=CommitInfo)
    misbehavior: list[Misbehavior] = field(default_factory=list)
    hash: bytes = b""
    height: int = 0
    time: Timestamp = field(default_factory=Timestamp.zero)
    next_validators_hash: bytes = b""
    proposer_address: bytes = b""
    syncing_to_height: int = 0


# ---------------------------------------------------------------------------
# Responses


@dataclass
class ExceptionResponse:
    error: str = ""


@dataclass
class EchoResponse:
    message: str = ""


@dataclass
class FlushResponse:
    pass


@dataclass
class InfoResponse:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""
    lane_priorities: dict[str, int] = field(default_factory=dict)
    default_lane: str = ""


@dataclass
class InitChainResponse:
    consensus_params: Optional[object] = None   # types.ConsensusParams
    validators: list[ValidatorUpdate] = field(default_factory=list)
    app_hash: bytes = b""


@dataclass
class QueryResponse:
    code: int = CODE_TYPE_OK
    log: str = ""
    info: str = ""
    index: int = 0
    key: bytes = b""
    value: bytes = b""
    proof_ops: Optional[object] = None
    height: int = 0
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class CheckTxResponse:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: list[Event] = field(default_factory=list)
    codespace: str = ""
    lane_id: str = ""
    # state keys the tx's validity depends on, for incremental
    # recheck: after a commit the mempool re-runs CheckTx only for
    # pooled txs whose keys overlap the committed block's
    # ExecTxResult.recheck_keys (plus the bounded-age watermark).
    # Empty = unattributed; such a tx is revalidated on the watermark
    # schedule only.
    recheck_keys: list[bytes] = field(default_factory=list)

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK


@dataclass
class CommitResponse:
    retain_height: int = 0


@dataclass
class ListSnapshotsResponse:
    snapshots: list[Snapshot] = field(default_factory=list)


@dataclass
class OfferSnapshotResponse:
    result: int = OFFER_SNAPSHOT_RESULT_UNKNOWN


@dataclass
class LoadSnapshotChunkResponse:
    chunk: bytes = b""


@dataclass
class ApplySnapshotChunkResponse:
    result: int = APPLY_SNAPSHOT_CHUNK_RESULT_UNKNOWN
    refetch_chunks: list[int] = field(default_factory=list)
    reject_senders: list[str] = field(default_factory=list)


@dataclass
class PrepareProposalResponse:
    txs: list[bytes] = field(default_factory=list)


@dataclass
class ProcessProposalResponse:
    status: int = PROCESS_PROPOSAL_STATUS_UNKNOWN

    def is_accepted(self) -> bool:
        return self.status == PROCESS_PROPOSAL_STATUS_ACCEPT


@dataclass
class ExtendVoteResponse:
    vote_extension: bytes = b""
    non_rp_extension: bytes = b""


@dataclass
class VerifyVoteExtensionResponse:
    status: int = VERIFY_VOTE_EXTENSION_STATUS_UNKNOWN

    def is_accepted(self) -> bool:
        return self.status == VERIFY_VOTE_EXTENSION_STATUS_ACCEPT


@dataclass
class FinalizeBlockResponse:
    events: list[Event] = field(default_factory=list)
    tx_results: list[ExecTxResult] = field(default_factory=list)
    validator_updates: list[ValidatorUpdate] = field(default_factory=list)
    consensus_param_updates: Optional[object] = None
    app_hash: bytes = b""
    next_block_delay_ns: int = 0


# ---------------------------------------------------------------------------


class Application(abc.ABC):
    """The 15-method deterministic state machine interface.

    Reference: abci/types/application.go:11-41.  Async so that socket/
    remote clients and in-process apps share one calling convention.
    """

    # Info/Query connection
    async def info(self, req: InfoRequest) -> InfoResponse:
        return InfoResponse()

    async def query(self, req: QueryRequest) -> QueryResponse:
        return QueryResponse(code=CODE_TYPE_OK)

    async def echo(self, req: EchoRequest) -> EchoResponse:
        return EchoResponse(message=req.message)

    # Mempool connection
    async def check_tx(self, req: CheckTxRequest) -> CheckTxResponse:
        return CheckTxResponse(code=CODE_TYPE_OK)

    # Consensus connection
    async def init_chain(self, req: InitChainRequest) -> InitChainResponse:
        return InitChainResponse()

    async def prepare_proposal(self, req: PrepareProposalRequest
                               ) -> PrepareProposalResponse:
        """Default: include txs up to max_tx_bytes (reference:
        BaseApplication.PrepareProposal)."""
        txs, total = [], 0
        for tx in req.txs:
            total += len(tx)
            if req.max_tx_bytes >= 0 and total > req.max_tx_bytes:
                break
            txs.append(tx)
        return PrepareProposalResponse(txs=txs)

    async def process_proposal(self, req: ProcessProposalRequest
                               ) -> ProcessProposalResponse:
        return ProcessProposalResponse(
            status=PROCESS_PROPOSAL_STATUS_ACCEPT)

    async def finalize_block(self, req: FinalizeBlockRequest
                             ) -> FinalizeBlockResponse:
        return FinalizeBlockResponse(
            tx_results=[ExecTxResult() for _ in req.txs])

    async def extend_vote(self, req: ExtendVoteRequest
                          ) -> ExtendVoteResponse:
        return ExtendVoteResponse()

    async def verify_vote_extension(self, req: VerifyVoteExtensionRequest
                                    ) -> VerifyVoteExtensionResponse:
        return VerifyVoteExtensionResponse(
            status=VERIFY_VOTE_EXTENSION_STATUS_ACCEPT)

    async def commit(self, req: CommitRequest) -> CommitResponse:
        return CommitResponse()

    # Snapshot connection
    async def list_snapshots(self, req: ListSnapshotsRequest
                             ) -> ListSnapshotsResponse:
        return ListSnapshotsResponse()

    async def offer_snapshot(self, req: OfferSnapshotRequest
                             ) -> OfferSnapshotResponse:
        return OfferSnapshotResponse()

    async def load_snapshot_chunk(self, req: LoadSnapshotChunkRequest
                                  ) -> LoadSnapshotChunkResponse:
        return LoadSnapshotChunkResponse()

    async def apply_snapshot_chunk(self, req: ApplySnapshotChunkRequest
                                   ) -> ApplySnapshotChunkResponse:
        return ApplySnapshotChunkResponse()


class BaseApplication(Application):
    """Concrete no-op application (reference: BaseApplication)."""
