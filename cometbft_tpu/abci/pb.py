"""ABCI dataclass <-> proto-dict conversion and the socket envelope codec.

Reference: abci/types/messages.go (WriteMessage/ReadMessage framing) and
proto/cometbft/abci/v2/types.proto (Request :18-36 / Response :222-244
oneofs).  Field names of the dataclasses in abci/types.py deliberately
match the proto field names, so most conversion is mechanical; the
exceptions (timestamps, consensus params, durations, the lane-priority
map) are handled explicitly.
"""
from __future__ import annotations

from typing import Any, Optional

from ..types.params import ConsensusParams
from ..types.timestamp import Timestamp
from ..wire import abci_pb, decode, encode
from ..wire.proto import decode_uvarint, encode_uvarint
from . import types as abci


class ABCIProtoError(Exception):
    pass


# --- leaf converters --------------------------------------------------------

def _event_to(e: abci.Event) -> dict:
    return {"type": e.type,
            "attributes": [{"key": a.key, "value": a.value,
                            "index": a.index} for a in e.attributes]}


def _event_from(d: dict) -> abci.Event:
    return abci.Event(
        type=d.get("type", ""),
        attributes=[abci.EventAttribute(key=a.get("key", ""),
                                        value=a.get("value", ""),
                                        index=a.get("index", False))
                    for a in d.get("attributes", [])])


def _validator_to(v: abci.ABCIValidator) -> dict:
    return {"address": v.address, "power": v.power}


def _validator_from(d: dict) -> abci.ABCIValidator:
    return abci.ABCIValidator(address=d.get("address", b""),
                              power=d.get("power", 0))


def _val_update_to(v: abci.ValidatorUpdate) -> dict:
    return {"power": v.power, "pub_key_bytes": v.pub_key_bytes,
            "pub_key_type": v.pub_key_type}


def _val_update_from(d: dict) -> abci.ValidatorUpdate:
    return abci.ValidatorUpdate(power=d.get("power", 0),
                                pub_key_bytes=d.get("pub_key_bytes", b""),
                                pub_key_type=d.get("pub_key_type", ""))


def _commit_info_to(ci: abci.CommitInfo) -> dict:
    return {"round": ci.round,
            "votes": [{"validator": _validator_to(v.validator),
                       "block_id_flag": v.block_id_flag}
                      for v in ci.votes]}


def _commit_info_from(d: dict) -> abci.CommitInfo:
    return abci.CommitInfo(
        round=d.get("round", 0),
        votes=[abci.VoteInfo(
            validator=_validator_from(v.get("validator") or {}),
            block_id_flag=v.get("block_id_flag", 0))
            for v in d.get("votes", [])])


def _ext_commit_info_to(ci: abci.ExtendedCommitInfo) -> dict:
    return {"round": ci.round,
            "votes": [{
                "validator": _validator_to(v.validator),
                "vote_extension": v.vote_extension,
                "extension_signature": v.extension_signature,
                "block_id_flag": v.block_id_flag,
                "non_rp_vote_extension": v.non_rp_vote_extension,
                "non_rp_extension_signature": v.non_rp_extension_signature,
            } for v in ci.votes]}


def _ext_commit_info_from(d: dict) -> abci.ExtendedCommitInfo:
    return abci.ExtendedCommitInfo(
        round=d.get("round", 0),
        votes=[abci.ExtendedVoteInfo(
            validator=_validator_from(v.get("validator") or {}),
            vote_extension=v.get("vote_extension", b""),
            extension_signature=v.get("extension_signature", b""),
            block_id_flag=v.get("block_id_flag", 0),
            non_rp_vote_extension=v.get("non_rp_vote_extension", b""),
            non_rp_extension_signature=v.get(
                "non_rp_extension_signature", b""))
            for v in d.get("votes", [])])


def _misbehavior_to(m: abci.Misbehavior) -> dict:
    return {"type": m.type, "validator": _validator_to(m.validator),
            "height": m.height, "time": m.time.to_proto(),
            "total_voting_power": m.total_voting_power}


def _misbehavior_from(d: dict) -> abci.Misbehavior:
    return abci.Misbehavior(
        type=d.get("type", 0),
        validator=_validator_from(d.get("validator") or {}),
        height=d.get("height", 0),
        time=Timestamp.from_proto(d.get("time") or {}),
        total_voting_power=d.get("total_voting_power", 0))


def _snapshot_to(s: Optional[abci.Snapshot]) -> Optional[dict]:
    if s is None:
        return None
    return {"height": s.height, "format": s.format, "chunks": s.chunks,
            "hash": s.hash, "metadata": s.metadata}


def _snapshot_from(d: Optional[dict]) -> Optional[abci.Snapshot]:
    if not d:
        return None
    return abci.Snapshot(height=d.get("height", 0),
                         format=d.get("format", 0),
                         chunks=d.get("chunks", 0),
                         hash=d.get("hash", b""),
                         metadata=d.get("metadata", b""))


def _exec_tx_result_to(r: abci.ExecTxResult) -> dict:
    return {"code": r.code, "data": r.data, "log": r.log, "info": r.info,
            "gas_wanted": r.gas_wanted, "gas_used": r.gas_used,
            "events": [_event_to(e) for e in r.events],
            "codespace": r.codespace}


def _exec_tx_result_from(d: dict) -> abci.ExecTxResult:
    return abci.ExecTxResult(
        code=d.get("code", 0), data=d.get("data", b""),
        log=d.get("log", ""), info=d.get("info", ""),
        gas_wanted=d.get("gas_wanted", 0), gas_used=d.get("gas_used", 0),
        events=[_event_from(e) for e in d.get("events", [])],
        codespace=d.get("codespace", ""))


def _params_to(p: Optional[Any]) -> Optional[dict]:
    if p is None:
        return None
    return p.to_proto()


def _params_from(d: Optional[dict]) -> Optional[ConsensusParams]:
    if not d:
        return None
    return ConsensusParams.from_proto(d)


# --- request conversion -----------------------------------------------------

def request_to_proto(req: Any) -> dict:
    """ABCI request dataclass -> {oneof_field: body} Request dict."""
    t = type(req).__name__
    if t == "EchoRequest":
        return {"echo": {"message": req.message}}
    if t == "FlushRequest":
        return {"flush": {}}
    if t == "InfoRequest":
        return {"info": {"version": req.version,
                         "block_version": req.block_version,
                         "p2p_version": req.p2p_version,
                         "abci_version": req.abci_version}}
    if t == "InitChainRequest":
        return {"init_chain": {
            "time": req.time.to_proto(),
            "chain_id": req.chain_id,
            "consensus_params": _params_to(req.consensus_params),
            "validators": [_val_update_to(v) for v in req.validators],
            "app_state_bytes": req.app_state_bytes,
            "initial_height": req.initial_height}}
    if t == "QueryRequest":
        return {"query": {"data": req.data, "path": req.path,
                          "height": req.height, "prove": req.prove}}
    if t == "CheckTxRequest":
        return {"check_tx": {"tx": req.tx, "type": req.type}}
    if t == "CommitRequest":
        return {"commit": {}}
    if t == "ListSnapshotsRequest":
        return {"list_snapshots": {}}
    if t == "OfferSnapshotRequest":
        return {"offer_snapshot": {"snapshot": _snapshot_to(req.snapshot),
                                   "app_hash": req.app_hash}}
    if t == "LoadSnapshotChunkRequest":
        return {"load_snapshot_chunk": {"height": req.height,
                                        "format": req.format,
                                        "chunk": req.chunk}}
    if t == "ApplySnapshotChunkRequest":
        return {"apply_snapshot_chunk": {"index": req.index,
                                         "chunk": req.chunk,
                                         "sender": req.sender}}
    if t == "PrepareProposalRequest":
        return {"prepare_proposal": {
            "max_tx_bytes": req.max_tx_bytes, "txs": list(req.txs),
            "local_last_commit": _ext_commit_info_to(req.local_last_commit),
            "misbehavior": [_misbehavior_to(m) for m in req.misbehavior],
            "height": req.height, "time": req.time.to_proto(),
            "next_validators_hash": req.next_validators_hash,
            "proposer_address": req.proposer_address}}
    if t == "ProcessProposalRequest":
        return {"process_proposal": {
            "txs": list(req.txs),
            "proposed_last_commit": _commit_info_to(req.proposed_last_commit),
            "misbehavior": [_misbehavior_to(m) for m in req.misbehavior],
            "hash": req.hash, "height": req.height,
            "time": req.time.to_proto(),
            "next_validators_hash": req.next_validators_hash,
            "proposer_address": req.proposer_address}}
    if t == "ExtendVoteRequest":
        return {"extend_vote": {
            "hash": req.hash, "height": req.height,
            "time": req.time.to_proto(), "txs": list(req.txs),
            "proposed_last_commit": _commit_info_to(req.proposed_last_commit),
            "misbehavior": [_misbehavior_to(m) for m in req.misbehavior],
            "next_validators_hash": req.next_validators_hash,
            "proposer_address": req.proposer_address}}
    if t == "VerifyVoteExtensionRequest":
        return {"verify_vote_extension": {
            "hash": req.hash, "validator_address": req.validator_address,
            "height": req.height, "vote_extension": req.vote_extension,
            "non_rp_vote_extension": req.non_rp_vote_extension}}
    if t == "FinalizeBlockRequest":
        return {"finalize_block": {
            "txs": list(req.txs),
            "decided_last_commit": _commit_info_to(req.decided_last_commit),
            "misbehavior": [_misbehavior_to(m) for m in req.misbehavior],
            "hash": req.hash, "height": req.height,
            "time": req.time.to_proto(),
            "next_validators_hash": req.next_validators_hash,
            "proposer_address": req.proposer_address,
            "syncing_to_height": req.syncing_to_height}}
    raise ABCIProtoError(f"unknown request type {t}")


def request_from_proto(d: dict) -> Any:
    if "echo" in d:
        return abci.EchoRequest(message=d["echo"].get("message", ""))
    if "flush" in d:
        return abci.FlushRequest()
    if "info" in d:
        b = d["info"]
        return abci.InfoRequest(
            version=b.get("version", ""),
            block_version=b.get("block_version", 0),
            p2p_version=b.get("p2p_version", 0),
            abci_version=b.get("abci_version", ""))
    if "init_chain" in d:
        b = d["init_chain"]
        return abci.InitChainRequest(
            time=Timestamp.from_proto(b.get("time") or {}),
            chain_id=b.get("chain_id", ""),
            consensus_params=_params_from(b.get("consensus_params")),
            validators=[_val_update_from(v)
                        for v in b.get("validators", [])],
            app_state_bytes=b.get("app_state_bytes", b""),
            initial_height=b.get("initial_height", 0))
    if "query" in d:
        b = d["query"]
        return abci.QueryRequest(data=b.get("data", b""),
                                 path=b.get("path", ""),
                                 height=b.get("height", 0),
                                 prove=b.get("prove", False))
    if "check_tx" in d:
        b = d["check_tx"]
        return abci.CheckTxRequest(tx=b.get("tx", b""),
                                   type=b.get("type", 0))
    if "commit" in d:
        return abci.CommitRequest()
    if "list_snapshots" in d:
        return abci.ListSnapshotsRequest()
    if "offer_snapshot" in d:
        b = d["offer_snapshot"]
        return abci.OfferSnapshotRequest(
            snapshot=_snapshot_from(b.get("snapshot")),
            app_hash=b.get("app_hash", b""))
    if "load_snapshot_chunk" in d:
        b = d["load_snapshot_chunk"]
        return abci.LoadSnapshotChunkRequest(height=b.get("height", 0),
                                             format=b.get("format", 0),
                                             chunk=b.get("chunk", 0))
    if "apply_snapshot_chunk" in d:
        b = d["apply_snapshot_chunk"]
        return abci.ApplySnapshotChunkRequest(index=b.get("index", 0),
                                              chunk=b.get("chunk", b""),
                                              sender=b.get("sender", ""))
    if "prepare_proposal" in d:
        b = d["prepare_proposal"]
        return abci.PrepareProposalRequest(
            max_tx_bytes=b.get("max_tx_bytes", 0),
            txs=list(b.get("txs", [])),
            local_last_commit=_ext_commit_info_from(
                b.get("local_last_commit") or {}),
            misbehavior=[_misbehavior_from(m)
                         for m in b.get("misbehavior", [])],
            height=b.get("height", 0),
            time=Timestamp.from_proto(b.get("time") or {}),
            next_validators_hash=b.get("next_validators_hash", b""),
            proposer_address=b.get("proposer_address", b""))
    if "process_proposal" in d:
        b = d["process_proposal"]
        return abci.ProcessProposalRequest(
            txs=list(b.get("txs", [])),
            proposed_last_commit=_commit_info_from(
                b.get("proposed_last_commit") or {}),
            misbehavior=[_misbehavior_from(m)
                         for m in b.get("misbehavior", [])],
            hash=b.get("hash", b""), height=b.get("height", 0),
            time=Timestamp.from_proto(b.get("time") or {}),
            next_validators_hash=b.get("next_validators_hash", b""),
            proposer_address=b.get("proposer_address", b""))
    if "extend_vote" in d:
        b = d["extend_vote"]
        return abci.ExtendVoteRequest(
            hash=b.get("hash", b""), height=b.get("height", 0),
            time=Timestamp.from_proto(b.get("time") or {}),
            txs=list(b.get("txs", [])),
            proposed_last_commit=_commit_info_from(
                b.get("proposed_last_commit") or {}),
            misbehavior=[_misbehavior_from(m)
                         for m in b.get("misbehavior", [])],
            next_validators_hash=b.get("next_validators_hash", b""),
            proposer_address=b.get("proposer_address", b""))
    if "verify_vote_extension" in d:
        b = d["verify_vote_extension"]
        return abci.VerifyVoteExtensionRequest(
            hash=b.get("hash", b""),
            validator_address=b.get("validator_address", b""),
            height=b.get("height", 0),
            vote_extension=b.get("vote_extension", b""),
            non_rp_vote_extension=b.get("non_rp_vote_extension", b""))
    if "finalize_block" in d:
        b = d["finalize_block"]
        return abci.FinalizeBlockRequest(
            txs=list(b.get("txs", [])),
            decided_last_commit=_commit_info_from(
                b.get("decided_last_commit") or {}),
            misbehavior=[_misbehavior_from(m)
                         for m in b.get("misbehavior", [])],
            hash=b.get("hash", b""), height=b.get("height", 0),
            time=Timestamp.from_proto(b.get("time") or {}),
            next_validators_hash=b.get("next_validators_hash", b""),
            proposer_address=b.get("proposer_address", b""),
            syncing_to_height=b.get("syncing_to_height", 0))
    raise ABCIProtoError(f"unknown request oneof: {sorted(d)}")


# --- response conversion ----------------------------------------------------

def response_to_proto(resp: Any) -> dict:
    t = type(resp).__name__
    if t == "ExceptionResponse":
        return {"exception": {"error": resp.error}}
    if t == "EchoResponse":
        return {"echo": {"message": resp.message}}
    if t == "FlushResponse":
        return {"flush": {}}
    if t == "InfoResponse":
        return {"info": {
            "data": resp.data, "version": resp.version,
            "app_version": resp.app_version,
            "last_block_height": resp.last_block_height,
            "last_block_app_hash": resp.last_block_app_hash,
            "lane_priorities": [{"key": k, "value": v}
                                for k, v in sorted(
                                    resp.lane_priorities.items())],
            "default_lane": resp.default_lane}}
    if t == "InitChainResponse":
        return {"init_chain": {
            "consensus_params": _params_to(resp.consensus_params),
            "validators": [_val_update_to(v) for v in resp.validators],
            "app_hash": resp.app_hash}}
    if t == "QueryResponse":
        return {"query": {
            "code": resp.code, "log": resp.log, "info": resp.info,
            "index": resp.index, "key": resp.key, "value": resp.value,
            "proof_ops": resp.proof_ops, "height": resp.height,
            "codespace": resp.codespace}}
    if t == "CheckTxResponse":
        return {"check_tx": {
            "code": resp.code, "data": resp.data, "log": resp.log,
            "info": resp.info, "gas_wanted": resp.gas_wanted,
            "gas_used": resp.gas_used,
            "events": [_event_to(e) for e in resp.events],
            "codespace": resp.codespace, "lane_id": resp.lane_id,
            "recheck_keys": list(resp.recheck_keys)}}
    if t == "CommitResponse":
        return {"commit": {"retain_height": resp.retain_height}}
    if t == "ListSnapshotsResponse":
        return {"list_snapshots": {
            "snapshots": [_snapshot_to(s) for s in resp.snapshots]}}
    if t == "OfferSnapshotResponse":
        return {"offer_snapshot": {"result": resp.result}}
    if t == "LoadSnapshotChunkResponse":
        return {"load_snapshot_chunk": {"chunk": resp.chunk}}
    if t == "ApplySnapshotChunkResponse":
        return {"apply_snapshot_chunk": {
            "result": resp.result,
            "refetch_chunks": list(resp.refetch_chunks),
            "reject_senders": list(resp.reject_senders)}}
    if t == "PrepareProposalResponse":
        return {"prepare_proposal": {"txs": list(resp.txs)}}
    if t == "ProcessProposalResponse":
        return {"process_proposal": {"status": resp.status}}
    if t == "ExtendVoteResponse":
        return {"extend_vote": {
            "vote_extension": resp.vote_extension,
            "non_rp_extension": resp.non_rp_extension}}
    if t == "VerifyVoteExtensionResponse":
        return {"verify_vote_extension": {"status": resp.status}}
    if t == "FinalizeBlockResponse":
        from ..state.store import _fbr_to_proto
        return {"finalize_block": _fbr_to_proto(resp)}
    raise ABCIProtoError(f"unknown response type {t}")


def response_from_proto(d: dict) -> Any:
    if "exception" in d:
        return abci.ExceptionResponse(error=d["exception"].get("error", ""))
    if "echo" in d:
        return abci.EchoResponse(message=d["echo"].get("message", ""))
    if "flush" in d:
        return abci.FlushResponse()
    if "info" in d:
        b = d["info"]
        return abci.InfoResponse(
            data=b.get("data", ""), version=b.get("version", ""),
            app_version=b.get("app_version", 0),
            last_block_height=b.get("last_block_height", 0),
            last_block_app_hash=b.get("last_block_app_hash", b""),
            lane_priorities={e.get("key", ""): e.get("value", 0)
                             for e in b.get("lane_priorities", [])},
            default_lane=b.get("default_lane", ""))
    if "init_chain" in d:
        b = d["init_chain"]
        return abci.InitChainResponse(
            consensus_params=_params_from(b.get("consensus_params")),
            validators=[_val_update_from(v)
                        for v in b.get("validators", [])],
            app_hash=b.get("app_hash", b""))
    if "query" in d:
        b = d["query"]
        return abci.QueryResponse(
            code=b.get("code", 0), log=b.get("log", ""),
            info=b.get("info", ""), index=b.get("index", 0),
            key=b.get("key", b""), value=b.get("value", b""),
            proof_ops=b.get("proof_ops"), height=b.get("height", 0),
            codespace=b.get("codespace", ""))
    if "check_tx" in d:
        b = d["check_tx"]
        return abci.CheckTxResponse(
            code=b.get("code", 0), data=b.get("data", b""),
            log=b.get("log", ""), info=b.get("info", ""),
            gas_wanted=b.get("gas_wanted", 0),
            gas_used=b.get("gas_used", 0),
            events=[_event_from(e) for e in b.get("events", [])],
            codespace=b.get("codespace", ""),
            lane_id=b.get("lane_id", ""),
            recheck_keys=list(b.get("recheck_keys", [])))
    if "commit" in d:
        return abci.CommitResponse(
            retain_height=d["commit"].get("retain_height", 0))
    if "list_snapshots" in d:
        return abci.ListSnapshotsResponse(
            snapshots=[_snapshot_from(s)
                       for s in d["list_snapshots"].get("snapshots", [])])
    if "offer_snapshot" in d:
        return abci.OfferSnapshotResponse(
            result=d["offer_snapshot"].get("result", 0))
    if "load_snapshot_chunk" in d:
        return abci.LoadSnapshotChunkResponse(
            chunk=d["load_snapshot_chunk"].get("chunk", b""))
    if "apply_snapshot_chunk" in d:
        b = d["apply_snapshot_chunk"]
        return abci.ApplySnapshotChunkResponse(
            result=b.get("result", 0),
            refetch_chunks=list(b.get("refetch_chunks", [])),
            reject_senders=list(b.get("reject_senders", [])))
    if "prepare_proposal" in d:
        return abci.PrepareProposalResponse(
            txs=list(d["prepare_proposal"].get("txs", [])))
    if "process_proposal" in d:
        return abci.ProcessProposalResponse(
            status=d["process_proposal"].get("status", 0))
    if "extend_vote" in d:
        b = d["extend_vote"]
        return abci.ExtendVoteResponse(
            vote_extension=b.get("vote_extension", b""),
            non_rp_extension=b.get("non_rp_extension", b""))
    if "verify_vote_extension" in d:
        return abci.VerifyVoteExtensionResponse(
            status=d["verify_vote_extension"].get("status", 0))
    if "finalize_block" in d:
        from ..state.store import _fbr_from_proto
        return _fbr_from_proto(d["finalize_block"])
    raise ABCIProtoError(f"unknown response oneof: {sorted(d)}")


# --- length-delimited framing ----------------------------------------------
# Reference: abci/types/messages.go WriteMessage — uvarint length prefix.

MAX_MSG_SIZE = 104_857_600          # 100 MB, reference socket server cap


def encode_request_frame(req: Any) -> bytes:
    payload = encode(abci_pb.REQUEST, request_to_proto(req))
    return encode_uvarint(len(payload)) + payload


def encode_response_frame(resp: Any) -> bytes:
    payload = encode(abci_pb.RESPONSE, response_to_proto(resp))
    return encode_uvarint(len(payload)) + payload


def decode_request(payload: bytes) -> Any:
    return request_from_proto(decode(abci_pb.REQUEST, payload))


def decode_response(payload: bytes) -> Any:
    return response_from_proto(decode(abci_pb.RESPONSE, payload))
