"""ABCI over gRPC: out-of-process applications behind a real gRPC
channel.

Reference: proto/cometbft/abci/v2/service.proto (ABCIService — 16
unary methods), abci/client/grpc_client.go (:247) and
abci/server/grpc_server.go.  Wire messages are the bare per-method
request/response protos (not the socket protocol's Request/Response
oneof envelope); this module reuses the envelope converters in
abci/pb.py and unwraps them per method.
"""
from __future__ import annotations

from typing import Optional

import grpc

from ..libs.log import Logger, new_logger
from ..wire import abci_pb, decode, encode
from . import pb as codec
from . import types as abci

SERVICE = "cometbft.abci.v2.ABCIService"

# gRPC method name -> (oneof key, request desc, response desc)
_METHODS = {
    "Echo": ("echo", abci_pb.ECHO_REQUEST, abci_pb.ECHO_RESPONSE),
    "Flush": ("flush", abci_pb.FLUSH_REQUEST, abci_pb.FLUSH_RESPONSE),
    "Info": ("info", abci_pb.INFO_REQUEST, abci_pb.INFO_RESPONSE),
    "CheckTx": ("check_tx", abci_pb.CHECK_TX_REQUEST,
                abci_pb.CHECK_TX_RESPONSE),
    "Query": ("query", abci_pb.QUERY_REQUEST, abci_pb.QUERY_RESPONSE),
    "Commit": ("commit", abci_pb.COMMIT_REQUEST,
               abci_pb.COMMIT_RESPONSE),
    "InitChain": ("init_chain", abci_pb.INIT_CHAIN_REQUEST,
                  abci_pb.INIT_CHAIN_RESPONSE),
    "ListSnapshots": ("list_snapshots", abci_pb.LIST_SNAPSHOTS_REQUEST,
                      abci_pb.LIST_SNAPSHOTS_RESPONSE),
    "OfferSnapshot": ("offer_snapshot", abci_pb.OFFER_SNAPSHOT_REQUEST,
                      abci_pb.OFFER_SNAPSHOT_RESPONSE),
    "LoadSnapshotChunk": ("load_snapshot_chunk",
                          abci_pb.LOAD_SNAPSHOT_CHUNK_REQUEST,
                          abci_pb.LOAD_SNAPSHOT_CHUNK_RESPONSE),
    "ApplySnapshotChunk": ("apply_snapshot_chunk",
                           abci_pb.APPLY_SNAPSHOT_CHUNK_REQUEST,
                           abci_pb.APPLY_SNAPSHOT_CHUNK_RESPONSE),
    "PrepareProposal": ("prepare_proposal",
                        abci_pb.PREPARE_PROPOSAL_REQUEST,
                        abci_pb.PREPARE_PROPOSAL_RESPONSE),
    "ProcessProposal": ("process_proposal",
                        abci_pb.PROCESS_PROPOSAL_REQUEST,
                        abci_pb.PROCESS_PROPOSAL_RESPONSE),
    "ExtendVote": ("extend_vote", abci_pb.EXTEND_VOTE_REQUEST,
                   abci_pb.EXTEND_VOTE_RESPONSE),
    "VerifyVoteExtension": ("verify_vote_extension",
                            abci_pb.VERIFY_VOTE_EXTENSION_REQUEST,
                            abci_pb.VERIFY_VOTE_EXTENSION_RESPONSE),
    "FinalizeBlock": ("finalize_block", abci_pb.FINALIZE_BLOCK_REQUEST,
                      abci_pb.FINALIZE_BLOCK_RESPONSE),
}


def _grpc_addr(addr: str) -> str:
    for prefix in ("grpc://", "tcp://"):
        if addr.startswith(prefix):
            return addr[len(prefix):]
    return addr


# ABCI payloads (blocks, snapshot chunks) routinely exceed gRPC's
# default 4 MiB cap; the reference client dials with unbounded sizes
GRPC_OPTIONS = [("grpc.max_send_message_length", -1),
                ("grpc.max_receive_message_length", -1)]


class GRPCServer:
    """Serve an Application as the reference's ABCIService
    (abci/server/grpc_server.go)."""

    def __init__(self, address: str, app: abci.Application,
                 logger: Optional[Logger] = None):
        self.address = address
        self.app = app
        self.logger = logger or new_logger("abci-grpc-server")
        self._server: Optional[grpc.aio.Server] = None
        self.port: Optional[int] = None
        self._table = {
            "InfoRequest": app.info,
            "InitChainRequest": app.init_chain,
            "QueryRequest": app.query,
            "CheckTxRequest": app.check_tx,
            "CommitRequest": app.commit,
            "ListSnapshotsRequest": app.list_snapshots,
            "OfferSnapshotRequest": app.offer_snapshot,
            "LoadSnapshotChunkRequest": app.load_snapshot_chunk,
            "ApplySnapshotChunkRequest": app.apply_snapshot_chunk,
            "PrepareProposalRequest": app.prepare_proposal,
            "ProcessProposalRequest": app.process_proposal,
            "ExtendVoteRequest": app.extend_vote,
            "VerifyVoteExtensionRequest": app.verify_vote_extension,
            "FinalizeBlockRequest": app.finalize_block,
        }

    async def start(self) -> None:
        handlers: dict[str, grpc.RpcMethodHandler] = {}
        for method, (key, req_desc, resp_desc) in _METHODS.items():
            async def handler(req_dict, ctx, _key=key):
                req = codec.request_from_proto({_key: req_dict})
                try:
                    resp = await self._dispatch(req)
                except Exception as e:
                    await ctx.abort(grpc.StatusCode.INTERNAL, str(e))
                env = codec.response_to_proto(resp)
                return next(iter(env.values())) if env else {}
            handlers[f"/{SERVICE}/{method}"] = \
                grpc.unary_unary_rpc_method_handler(
                    handler,
                    request_deserializer=(
                        lambda b, d=req_desc: decode(d, b)),
                    response_serializer=(
                        lambda m, d=resp_desc: encode(d, m)))

        class _H(grpc.GenericRpcHandler):
            def service(self, details):
                return handlers.get(details.method)

        self._server = grpc.aio.server(options=GRPC_OPTIONS)
        self._server.add_generic_rpc_handlers((_H(),))
        self.port = self._server.add_insecure_port(
            _grpc_addr(self.address))
        await self._server.start()
        self.logger.info("ABCI gRPC server listening",
                         addr=self.address, port=self.port)

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=1.0)
            self._server = None

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        await self._server.wait_for_termination()

    async def _dispatch(self, req):
        t = type(req).__name__
        if t == "EchoRequest":
            return await self.app.echo(req)
        if t == "FlushRequest":
            return abci.FlushResponse()
        fn = self._table.get(t)
        if fn is None:
            raise ValueError(f"unknown request {t}")
        return await fn(req)


class GRPCClient:
    """ABCI client over a gRPC channel, same surface as SocketClient
    (reference: abci/client/grpc_client.go)."""

    def __init__(self, address: str):
        self.address = address
        self._channel: Optional[grpc.aio.Channel] = None
        self._calls: dict = {}

    async def connect(self, timeout_s: Optional[float] = None) -> None:
        """Dial and block until the channel is READY.

        The reference dials with grpc.WaitForReady(true)
        (abci/client/grpc_client.go:109) — no fixed retry budget; the
        channel's own reconnect logic absorbs slow server startup
        (e.g. a subprocess still importing).  channel_ready() is the
        grpc.aio analog; the deadline only bounds pathological cases.
        """
        if timeout_s is None:
            import os
            timeout_s = float(os.environ.get(
                "COMETBFT_ABCI_GRPC_CONNECT_TIMEOUT", "60"))
        if self._channel is not None:
            await self.close()
        self._channel = grpc.aio.insecure_channel(
            _grpc_addr(self.address), options=GRPC_OPTIONS)
        # one multicallable per method, built once (CheckTx is the
        # per-tx hot path)
        self._calls = {
            method: self._channel.unary_unary(
                f"/{SERVICE}/{method}",
                request_serializer=(
                    lambda m, d=req_desc: encode(d, m)),
                response_deserializer=(
                    lambda b, d=resp_desc: decode(d, b)))
            for method, (key, req_desc, resp_desc)
            in _METHODS.items()
        }
        import asyncio
        try:
            await asyncio.wait_for(self._channel.channel_ready(),
                                   timeout=timeout_s)
            await self.echo("ping")
        except (asyncio.TimeoutError, grpc.aio.AioRpcError):
            await self.close()
            raise

    async def close(self) -> None:
        if self._channel is not None:
            await self._channel.close()
            self._channel = None
            self._calls = {}

    async def _call(self, method: str, req) -> object:
        key = _METHODS[method][0]
        env = codec.request_to_proto(req)
        bare = next(iter(env.values())) if env else {}
        resp_dict = await self._calls[method](bare)
        return codec.response_from_proto({key: resp_dict})

    # -- the 15-method surface + echo/flush -----------------------------
    async def echo(self, message: str) -> abci.EchoResponse:
        return await self._call("Echo", abci.EchoRequest(
            message=message))

    async def flush(self) -> None:
        await self._call("Flush", abci.FlushRequest())

    async def info(self, req): return await self._call("Info", req)

    async def query(self, req): return await self._call("Query", req)

    async def check_tx(self, req):
        return await self._call("CheckTx", req)

    async def init_chain(self, req):
        return await self._call("InitChain", req)

    async def prepare_proposal(self, req):
        return await self._call("PrepareProposal", req)

    async def process_proposal(self, req):
        return await self._call("ProcessProposal", req)

    async def finalize_block(self, req):
        return await self._call("FinalizeBlock", req)

    async def extend_vote(self, req):
        return await self._call("ExtendVote", req)

    async def verify_vote_extension(self, req):
        return await self._call("VerifyVoteExtension", req)

    async def commit(self) -> abci.CommitResponse:
        return await self._call("Commit", abci.CommitRequest())

    async def list_snapshots(self, req):
        return await self._call("ListSnapshots", req)

    async def offer_snapshot(self, req):
        return await self._call("OfferSnapshot", req)

    async def load_snapshot_chunk(self, req):
        return await self._call("LoadSnapshotChunk", req)

    async def apply_snapshot_chunk(self, req):
        return await self._call("ApplySnapshotChunk", req)


class GRPCAppConns:
    """proxy.AppConns over one shared gRPC channel (the reference's
    grpc client is connection-concurrent, so one client serves all
    four logical conns)."""

    def __init__(self, address: str):
        cli = GRPCClient(address)
        self.consensus = cli
        self.mempool = cli
        self.query = cli
        self.snapshot = cli
        self._cli = cli

    async def start(self) -> None:
        await self._cli.connect()

    async def stop(self) -> None:
        await self._cli.close()
