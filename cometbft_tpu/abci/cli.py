"""abci-cli: console for exercising an ABCI application.

Reference: abci/cmd/abci-cli/abci-cli.go (798 LoC) — echo, info,
check_tx, query, prepare/process proposal, finalize_block, commit
against a builtin or socket app, plus an interactive console.

    python -m cometbft_tpu.abci.cli --address unix:///tmp/app.sock \
        echo hello
    python -m cometbft_tpu.abci.cli --app kvstore console
"""
from __future__ import annotations

import argparse
import asyncio
import base64
import shlex
import sys

from . import types as abci
from .types import BaseApplication


def _print(obj) -> None:
    print(obj)


class _Session:
    """One CLI session over either a socket client or an in-proc app."""

    def __init__(self, address: str = "", app_name: str = ""):
        self.address = address
        self.app_name = app_name
        self.client = None

    async def __aenter__(self):
        if self.address:
            from .client import SocketClient
            self.client = SocketClient(self.address)
            await self.client.connect()
        else:
            from .client import LocalClient
            from .server import _build_app
            self.client = LocalClient(
                _build_app(self.app_name or "kvstore"))
        return self

    async def __aexit__(self, *exc):
        if hasattr(self.client, "close"):
            await self.client.close()
        return False

    # -- commands ---------------------------------------------------------
    async def cmd(self, name: str, args: list[str]) -> None:
        c = self.client
        if name == "echo":
            res = await c.echo(" ".join(args))
            _print(f"-> message: {res.message}")
        elif name == "info":
            res = await c.info(abci.InfoRequest())
            _print(f"-> data: {res.data}")
            _print(f"-> last_block_height: {res.last_block_height}")
            _print(f"-> last_block_app_hash: "
                   f"{res.last_block_app_hash.hex().upper()}")
        elif name == "check_tx":
            if not args:
                _print("usage: check_tx <tx>")
                return
            res = await c.check_tx(abci.CheckTxRequest(
                tx=_parse_bytes(args[0]),
                type=abci.CHECK_TX_TYPE_CHECK))
            _print(f"-> code: {res.code}")
            _print(f"-> log: {res.log}")
        elif name == "finalize_block":
            res = await c.finalize_block(abci.FinalizeBlockRequest(
                txs=[_parse_bytes(a) for a in args],
                height=1))
            for i, r in enumerate(res.tx_results):
                _print(f"-> tx {i} code: {r.code}")
            _print(f"-> app_hash: {res.app_hash.hex().upper()}")
        elif name == "commit":
            res = await c.commit()
            _print(f"-> retain_height: {res.retain_height}")
        elif name == "query":
            path = args[0] if args else ""
            data = _parse_bytes(args[1]) if len(args) > 1 else b""
            res = await c.query(abci.QueryRequest(path=path, data=data))
            _print(f"-> code: {res.code}")
            _print(f"-> value: {res.value.decode(errors='replace')}")
        else:
            _print(f"unknown command {name!r}; try: echo info check_tx "
                   f"finalize_block commit query")

    async def console(self) -> None:
        _print("ABCI console (reference: abci-cli console); "
               "'quit' exits")
        loop = asyncio.get_running_loop()
        while True:
            line = await loop.run_in_executor(None, _read_line)
            if line is None or line.strip() in ("quit", "exit"):
                return
            parts = shlex.split(line)
            if not parts:
                continue
            try:
                await self.cmd(parts[0], parts[1:])
            except Exception as e:  # noqa: BLE001 — console survives
                _print(f"error: {e}")


def _read_line():
    try:
        return input("> ")
    except EOFError:
        return None


def _parse_bytes(s: str) -> bytes:
    if s.startswith("0x"):
        return bytes.fromhex(s[2:])
    if s.startswith("b64:"):
        return base64.b64decode(s[4:])
    return s.encode()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="abci-cli (reference: abci/cmd/abci-cli)")
    ap.add_argument("--address", default="",
                    help="socket app address (unix:// or tcp://); "
                         "omit for a builtin app")
    ap.add_argument("--app", default="kvstore",
                    help="builtin app when no --address")
    ap.add_argument("command", nargs="?", default="console")
    ap.add_argument("args", nargs="*")
    ns = ap.parse_args(argv)

    async def run():
        async with _Session(ns.address, ns.app) as sess:
            if ns.command == "console":
                await sess.console()
            else:
                await sess.cmd(ns.command, ns.args)

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
