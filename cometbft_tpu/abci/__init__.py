"""ABCI: the application blockchain interface.

Reference: abci/ — 15-method Application interface over 4 logical
connections (consensus, mempool, info, snapshot), clients (local, socket,
grpc), servers, and the kvstore example app.
"""
