"""ABCI socket server: serve an Application to an out-of-process node.

Reference: abci/server/socket_server.go:334 — accepts connections (the node
opens one per AppConn), reads uvarint-length-delimited Request frames,
dispatches to the Application strictly in order per connection, and writes
the Response stream back.  A request that raises produces an
ExceptionResponse (reference: socket_server.go handleRequest recover).

Runnable stand-alone:  python -m cometbft_tpu.abci.server \
    --address unix:///tmp/kvstore.sock --app kvstore
"""
from __future__ import annotations

import argparse
import asyncio
from typing import Optional
from urllib.parse import urlparse

from ..libs.log import new_logger
from . import pb
from . import types as abci


class ABCIServerError(Exception):
    pass


def parse_address(addr: str) -> tuple[str, str, int]:
    """'unix:///p' | 'tcp://h:p' | 'h:p' -> (scheme, host_or_path, port)."""
    if "://" not in addr:
        addr = "tcp://" + addr
    u = urlparse(addr)
    if u.scheme == "unix":
        return "unix", u.path or addr[len("unix://"):], 0
    if u.scheme == "tcp":
        return "tcp", u.hostname or "127.0.0.1", int(u.port or 26658)
    raise ABCIServerError(f"unsupported ABCI address scheme {u.scheme!r}")


async def read_frame(reader: asyncio.StreamReader,
                     max_size: int = pb.MAX_MSG_SIZE) -> Optional[bytes]:
    """Read one uvarint-length-delimited frame; None on clean EOF."""
    from ..libs.protoio import read_delimited
    return await read_delimited(reader, max_size, ABCIServerError)


class SocketServer:
    """Serves one Application over unix/tcp sockets."""

    def __init__(self, address: str, app: abci.Application, logger=None):
        self.address = address
        self.app = app
        self.logger = logger or new_logger("abci-server")
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set[asyncio.Task] = set()

    async def start(self) -> None:
        scheme, host, port = parse_address(self.address)
        if scheme == "unix":
            self._server = await asyncio.start_unix_server(
                self._handle, path=host)
        else:
            self._server = await asyncio.start_server(
                self._handle, host=host, port=port)
        self.logger.info("ABCI server listening", addr=self.address)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for t in list(self._conns):
            t.cancel()

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task:
            self._conns.add(task)
        try:
            while True:
                payload = await read_frame(reader)
                if payload is None:
                    return
                try:
                    req = pb.decode_request(payload)
                    resp = await self._dispatch(req)
                except Exception as e:  # noqa: BLE001 — becomes Exception resp
                    self.logger.error("ABCI request failed", err=str(e))
                    resp = abci.ExceptionResponse(error=str(e))
                writer.write(pb.encode_response_frame(resp))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                ABCIServerError):
            pass
        finally:
            if task:
                self._conns.discard(task)
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, req) -> object:
        app = self.app
        t = type(req).__name__
        if t == "EchoRequest":
            return await app.echo(req)
        if t == "FlushRequest":
            return abci.FlushResponse()
        if t == "InfoRequest":
            return await app.info(req)
        if t == "InitChainRequest":
            return await app.init_chain(req)
        if t == "QueryRequest":
            return await app.query(req)
        if t == "CheckTxRequest":
            return await app.check_tx(req)
        if t == "CommitRequest":
            return await app.commit(req)
        if t == "ListSnapshotsRequest":
            return await app.list_snapshots(req)
        if t == "OfferSnapshotRequest":
            return await app.offer_snapshot(req)
        if t == "LoadSnapshotChunkRequest":
            return await app.load_snapshot_chunk(req)
        if t == "ApplySnapshotChunkRequest":
            return await app.apply_snapshot_chunk(req)
        if t == "PrepareProposalRequest":
            return await app.prepare_proposal(req)
        if t == "ProcessProposalRequest":
            return await app.process_proposal(req)
        if t == "ExtendVoteRequest":
            return await app.extend_vote(req)
        if t == "VerifyVoteExtensionRequest":
            return await app.verify_vote_extension(req)
        if t == "FinalizeBlockRequest":
            return await app.finalize_block(req)
        raise ABCIServerError(f"unknown request {t}")


def _build_app(name: str) -> abci.Application:
    if name == "kvstore":
        from .kvstore import KVStoreApplication
        return KVStoreApplication()
    if name == "noop":
        from .types import BaseApplication
        return BaseApplication()
    raise ABCIServerError(f"unknown app {name!r} (kvstore|noop)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="ABCI socket server (reference: abci-cli + "
                    "socket_server.go)")
    ap.add_argument("--address", default="unix:///tmp/abci.sock")
    ap.add_argument("--app", default="kvstore")
    ap.add_argument("--transport", default="socket",
                    choices=["socket", "grpc"])
    args = ap.parse_args(argv)
    app = _build_app(args.app)
    if args.transport == "grpc":
        from .grpc import GRPCServer
        srv = GRPCServer(args.address, app)
    else:
        srv = SocketServer(args.address, app)

    async def _serve():
        await srv.start()
        # machine-readable ready line: parents wait for this instead of
        # guessing at import/startup time (reference: e2e runner greps
        # the node's listen log line before dialing)
        print(f"abci-server listening {args.address}", flush=True)
        await srv.serve_forever()

    try:
        asyncio.run(_serve())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
