"""Proxy (ABCI connection) metrics (reference: proxy/metrics.gen.go
method_timing_seconds)."""
from __future__ import annotations

import asyncio
import time
from typing import Optional

from ..libs import metrics as libmetrics


class Metrics:
    def __init__(self, registry: Optional[libmetrics.Registry] = None):
        m = registry if registry is not None else libmetrics.Registry()
        # metrics v2: the reference's second label slot carries the
        # named app connection the call rode (consensus / mempool /
        # query / snapshot) instead of the constant "sync" — per-call
        # ABCI latency splits by both method and connection
        self.method_timing_seconds = m.histogram(
            "proxy", "method_timing_seconds",
            "Per-call ABCI latency in seconds, by method and named "
            "app connection.",
            labels=("method", "conn"),
            buckets=(0.0001, 0.0004, 0.002, 0.009, 0.02, 0.1, 0.65,
                     2.0, 6.0, 25.0))


class _TimedConn:
    """Transparent async-method timing wrapper over an ABCI client
    connection (reference: proxy/client.go recordTiming)."""

    def __init__(self, inner, hist, conn_name: str = "sync"):
        self._inner = inner
        self._hist = hist
        self._conn_name = conn_name

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name.startswith("_") or not callable(attr) or \
                not asyncio.iscoroutinefunction(attr):
            return attr
        hist = self._hist
        conn_name = self._conn_name

        async def timed(*a, **kw):
            t0 = time.perf_counter()
            try:
                return await attr(*a, **kw)
            finally:
                hist.with_labels(name, conn_name).observe(
                    time.perf_counter() - t0)
        # cache so the hot path (every CheckTx) never re-enters
        # __getattr__ for this method again
        object.__setattr__(self, name, timed)
        return timed


def instrument_app_conns(app_conns, metrics: Metrics):
    """Wrap the four named connections with method timing."""
    for conn in ("consensus", "mempool", "query", "snapshot"):
        inner = getattr(app_conns, conn, None)
        if inner is not None and not isinstance(inner, _TimedConn):
            setattr(app_conns, conn,
                    _TimedConn(inner, metrics.method_timing_seconds,
                               conn))
    return app_conns
