"""ABCI clients.

Reference: abci/client/ — local_client (in-process, mutexed),
unsync_local_client, socket_client (pipelined, abci/client/socket_client.go).
The local variants live here; the socket client arrives with the
out-of-process server.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from . import types as abci


class ABCIClientError(Exception):
    pass


class ABCITimeoutError(ABCIClientError):
    """A remote ABCI call exceeded its deadline."""


# ---------------------------------------------------------------------
# Deadline propagation for remote (socket/gRPC) transports: a wedged
# app process must not hang consensus forever.  Consensus-path methods
# may legitimately run long (a big FinalizeBlock), so they get a wider
# budget than queries.

_SLOW_METHODS = frozenset({
    "init_chain", "prepare_proposal", "process_proposal",
    "finalize_block", "commit", "extend_vote", "offer_snapshot",
    "apply_snapshot_chunk"})

# read-only / idempotent methods safe to retry after a transient
# transport error (a state-mutating call may have executed before the
# transport died, so it gets exactly one attempt)
_RETRIABLE_METHODS = frozenset({
    "echo", "info", "query", "flush", "list_snapshots",
    "load_snapshot_chunk"})


def _is_transient_transport_error(e: BaseException) -> bool:
    if isinstance(e, (ConnectionError, asyncio.IncompleteReadError,
                      OSError)):
        return True
    # grpc.aio.AioRpcError without importing grpc here (the socket
    # transport must not require the grpc package)
    code = getattr(e, "code", None)
    if callable(code):
        try:
            return getattr(code(), "name", "") in (
                "UNAVAILABLE", "DEADLINE_EXCEEDED")
        except Exception:
            return False
    return False


class DeadlineClient:
    """Transparent per-call deadline + bounded-retry wrapper over any
    ABCI client (socket or gRPC).

    Every coroutine method gets asyncio.wait_for with a per-method
    timeout (``overrides`` > slow/default split); read-only methods
    are retried up to ``retries`` times on transient transport errors
    with exponential backoff.  A deadline miss surfaces as
    ABCITimeoutError so callers can distinguish a wedged app from an
    app-level failure."""

    def __init__(self, inner, default_timeout_s: float = 20.0,
                 slow_timeout_s: float = 0.0, retries: int = 2,
                 retry_backoff_s: float = 0.1,
                 overrides: Optional[dict] = None, logger=None):
        object.__setattr__(self, "_inner", inner)
        self._default_timeout_s = default_timeout_s
        # consensus-path calls default to 6x the query budget
        self._slow_timeout_s = slow_timeout_s or 6 * default_timeout_s
        self._retries = max(0, retries)
        self._retry_backoff_s = retry_backoff_s
        self._overrides = dict(overrides or {})
        if logger is None:
            from ..libs.log import new_logger
            logger = new_logger("abci-deadline")
        self._logger = logger

    def timeout_for(self, method: str) -> float:
        t = self._overrides.get(method)
        if t is not None:
            return t
        return self._slow_timeout_s if method in _SLOW_METHODS \
            else self._default_timeout_s

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name.startswith("_") or not callable(attr) or \
                not asyncio.iscoroutinefunction(attr):
            return attr
        timeout = self.timeout_for(name)
        attempts = 1 + (self._retries
                        if name in _RETRIABLE_METHODS else 0)
        logger = self._logger
        backoff = self._retry_backoff_s

        async def bounded(*a, **kw):
            for i in range(attempts):
                try:
                    return await asyncio.wait_for(
                        attr(*a, **kw),
                        timeout if timeout > 0 else None)
                except asyncio.TimeoutError:
                    raise ABCITimeoutError(
                        f"ABCI {name} exceeded its {timeout}s "
                        f"deadline") from None
                except Exception as e:  # noqa: BLE001 — classify below
                    if i + 1 < attempts and \
                            _is_transient_transport_error(e):
                        logger.info("retrying ABCI call after "
                                    "transient transport error",
                                    method=name, attempt=i + 1,
                                    err=repr(e))
                        await asyncio.sleep(backoff * (2 ** i))
                        continue
                    raise

        # cache so the hot path (every CheckTx) never re-enters
        # __getattr__ for this method again
        object.__setattr__(self, name, bounded)
        return bounded


class TracingClient:
    """Flight-recorder span per ABCI call (libs/tracing.py category
    "abci", name "<conn>/<method>") — the execute slice of the
    per-height trace timeline.  Transparent like DeadlineClient;
    near-zero overhead when tracing is disabled."""

    def __init__(self, inner, conn_name: str):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_conn_name", conn_name)

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name.startswith("_") or not callable(attr) or \
                not asyncio.iscoroutinefunction(attr):
            return attr
        from ..libs import tracing
        label = f"{self._conn_name}/{name}"

        async def traced(*a, **kw):
            with tracing.span(tracing.ABCI, label):
                return await attr(*a, **kw)

        # cache so the hot path (every CheckTx) never re-enters
        # __getattr__ for this method again
        object.__setattr__(self, name, traced)
        return traced


def apply_tracing(app_conns) -> None:
    """Wrap the four named connections with flight-recorder spans
    (all transports — a builtin app's FinalizeBlock time is exactly
    what the per-height breakdown needs to attribute)."""
    for conn in ("consensus", "mempool", "query", "snapshot"):
        inner = getattr(app_conns, conn, None)
        if inner is not None and not isinstance(inner, TracingClient):
            setattr(app_conns, conn, TracingClient(inner, conn))
    return app_conns


def apply_deadlines(app_conns, default_timeout_s: float,
                    retries: int = 2) -> None:
    """Wrap the four named connections with per-call deadlines
    (remote transports only — a builtin app shares our event loop, so
    a deadline there would fire on our own backpressure)."""
    for conn in ("consensus", "mempool", "query", "snapshot"):
        inner = getattr(app_conns, conn, None)
        if inner is not None and not isinstance(inner, DeadlineClient):
            setattr(app_conns, conn, DeadlineClient(
                inner, default_timeout_s=default_timeout_s,
                retries=retries))
    return app_conns


class LocalClient:
    """In-process client serializing calls with one lock.

    Reference: abci/client/local_client.go — a global mutex makes the app
    see at most one concurrent call, which is the ABCI concurrency
    contract for a single connection.
    """

    def __init__(self, app: abci.Application,
                 lock: Optional[asyncio.Lock] = None):
        self._app = app
        self._lock = lock if lock is not None else asyncio.Lock()

    @property
    def app(self) -> abci.Application:
        return self._app

    async def echo(self, message: str) -> abci.EchoResponse:
        async with self._lock:
            return await self._app.echo(abci.EchoRequest(message=message))

    async def flush(self) -> None:
        return None

    async def info(self, req: abci.InfoRequest) -> abci.InfoResponse:
        async with self._lock:
            return await self._app.info(req)

    async def query(self, req: abci.QueryRequest) -> abci.QueryResponse:
        async with self._lock:
            return await self._app.query(req)

    async def check_tx(self, req: abci.CheckTxRequest
                       ) -> abci.CheckTxResponse:
        async with self._lock:
            return await self._app.check_tx(req)

    async def init_chain(self, req: abci.InitChainRequest
                         ) -> abci.InitChainResponse:
        async with self._lock:
            return await self._app.init_chain(req)

    async def prepare_proposal(self, req: abci.PrepareProposalRequest
                               ) -> abci.PrepareProposalResponse:
        async with self._lock:
            return await self._app.prepare_proposal(req)

    async def process_proposal(self, req: abci.ProcessProposalRequest
                               ) -> abci.ProcessProposalResponse:
        async with self._lock:
            return await self._app.process_proposal(req)

    async def finalize_block(self, req: abci.FinalizeBlockRequest
                             ) -> abci.FinalizeBlockResponse:
        async with self._lock:
            return await self._app.finalize_block(req)

    async def extend_vote(self, req: abci.ExtendVoteRequest
                          ) -> abci.ExtendVoteResponse:
        async with self._lock:
            return await self._app.extend_vote(req)

    async def verify_vote_extension(
            self, req: abci.VerifyVoteExtensionRequest
    ) -> abci.VerifyVoteExtensionResponse:
        async with self._lock:
            return await self._app.verify_vote_extension(req)

    async def commit(self) -> abci.CommitResponse:
        async with self._lock:
            return await self._app.commit(abci.CommitRequest())

    async def list_snapshots(self, req: abci.ListSnapshotsRequest
                             ) -> abci.ListSnapshotsResponse:
        async with self._lock:
            return await self._app.list_snapshots(req)

    async def offer_snapshot(self, req: abci.OfferSnapshotRequest
                             ) -> abci.OfferSnapshotResponse:
        async with self._lock:
            return await self._app.offer_snapshot(req)

    async def load_snapshot_chunk(self, req: abci.LoadSnapshotChunkRequest
                                  ) -> abci.LoadSnapshotChunkResponse:
        async with self._lock:
            return await self._app.load_snapshot_chunk(req)

    async def apply_snapshot_chunk(
            self, req: abci.ApplySnapshotChunkRequest
    ) -> abci.ApplySnapshotChunkResponse:
        async with self._lock:
            return await self._app.apply_snapshot_chunk(req)


class _NoopLock:
    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        return False


class UnsyncLocalClient(LocalClient):
    """Local client without any lock: the app handles its own
    synchronization (reference: unsync_local_client.go has no mutex)."""

    def __init__(self, app: abci.Application):
        super().__init__(app, lock=_NoopLock())


class AppConns:
    """The four named ABCI connections sharing one client.

    Reference: proxy/multi_app_conn.go — consensus/mempool/query/snapshot.
    With a local client they share one mutex (the reference's
    NewConnSyncLocalClientCreator semantics).
    """

    async def start(self) -> None:
        """No-op: local conns have no transport (lifecycle parity with
        SocketAppConns)."""

    async def stop(self) -> None:
        """No-op."""

    def __init__(self, app: abci.Application, sync: bool = True):
        if sync:
            lock = asyncio.Lock()
            self.consensus = LocalClient(app, lock)
            self.mempool = LocalClient(app, lock)
            self.query = LocalClient(app, lock)
            self.snapshot = LocalClient(app, lock)
        else:
            self.consensus = UnsyncLocalClient(app)
            self.mempool = UnsyncLocalClient(app)
            self.query = UnsyncLocalClient(app)
            self.snapshot = UnsyncLocalClient(app)


class ClientCreator:
    """Reference: proxy/client.go ClientCreator — local vs remote."""

    def __init__(self, app: Optional[abci.Application] = None,
                 addr: str = "", transport: str = "local"):
        self._app = app
        self._addr = addr
        self._transport = transport

    def new_app_conns(self):
        if self._transport in ("local", "builtin", "builtin_unsync"):
            if self._app is None:
                raise ABCIClientError("local client requires an app")
            return AppConns(self._app,
                            sync=self._transport != "builtin_unsync")
        if self._transport in ("socket", "unix", "tcp"):
            return SocketAppConns(self._addr)
        if self._transport == "grpc":
            from .grpc import GRPCAppConns
            return GRPCAppConns(self._addr)
        raise ABCIClientError(
            f"transport {self._transport!r} not supported")


class SocketClient:
    """Pipelined async client over a unix/tcp socket.

    Reference: abci/client/socket_client.go:515 — requests are written
    immediately and matched FIFO against the response stream, so many
    calls (e.g. mempool CheckTx under load) can be in flight at once; the
    server processes them in order, which preserves the per-connection
    ABCI ordering contract.  An ExceptionResponse or transport error fails
    every pending call (reference StopForError semantics).
    """

    def __init__(self, address: str, logger=None):
        from ..libs.log import new_logger
        self.address = address
        self.logger = logger or new_logger("abci-client")
        self._reader = None
        self._writer = None
        self._pending: "asyncio.Queue[tuple[str, asyncio.Future]]" = None  # type: ignore[assignment]
        self._recv_task = None
        self._err: Optional[Exception] = None

    async def connect(self, retries: int = 80,
                      retry_delay: float = 0.25) -> None:
        from .server import parse_address
        scheme, host, port = parse_address(self.address)
        last: Optional[Exception] = None
        for _ in range(retries):
            try:
                if scheme == "unix":
                    self._reader, self._writer = \
                        await asyncio.open_unix_connection(host)
                else:
                    self._reader, self._writer = \
                        await asyncio.open_connection(host, port)
                break
            except OSError as e:
                last = e
                await asyncio.sleep(retry_delay)
        else:
            raise ABCIClientError(
                f"cannot connect to ABCI app at {self.address}: {last}")
        self._pending = asyncio.Queue()
        self._recv_task = asyncio.create_task(self._recv_loop())

    async def close(self) -> None:
        if self._err is None:
            self._err = ABCIClientError("client closed")
        self._fail_pending(self._err)
        if self._recv_task is not None:
            self._recv_task.cancel()
            try:
                await self._recv_task
            except (asyncio.CancelledError, Exception):
                pass
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass

    async def _recv_loop(self) -> None:
        from . import pb
        from .server import read_frame
        fut = None
        try:
            while True:
                payload = await read_frame(self._reader)
                if payload is None:
                    raise ABCIClientError("ABCI connection closed by app")
                resp = pb.decode_response(payload)
                if self._pending.empty():
                    raise ABCIClientError(
                        f"unsolicited {type(resp).__name__}")
                want, fut = self._pending.get_nowait()
                if isinstance(resp, abci.ExceptionResponse):
                    # reference StopForError semantics: an app exception
                    # is fatal — the app's state is unknown, so fail this
                    # call, every pending call, and the client itself
                    raise ABCIClientError(f"app exception: {resp.error}")
                got = type(resp).__name__.replace("Response", "")
                if got != want:
                    raise ABCIClientError(
                        f"response out of order: want {want}, got {got}")
                if not fut.done():
                    fut.set_result(resp)
                fut = None
        except asyncio.CancelledError:
            if fut is not None and not fut.done():
                fut.set_exception(ABCIClientError("client stopped"))
            self._fail_pending(ABCIClientError("client stopped"))
            raise
        except Exception as e:  # noqa: BLE001 — fail every in-flight call
            self._err = e
            if fut is not None and not fut.done():
                fut.set_exception(e)
            self._fail_pending(e)

    def _fail_pending(self, err: Exception) -> None:
        while self._pending is not None and not self._pending.empty():
            _, fut = self._pending.get_nowait()
            if not fut.done():
                fut.set_exception(err)

    async def _call(self, req, want: str):
        from . import pb
        if self._err is not None:
            raise ABCIClientError(f"ABCI client dead: {self._err}")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending.put_nowait((want, fut))
        data = pb.encode_request_frame(req)
        if want != "Flush":
            # reference socket_client.go follows every queued request
            # with a Flush so a buffered-writer server (the Go one)
            # actually sends the response; the flush response resolves a
            # throwaway future to keep FIFO matching aligned
            self._pending.put_nowait(("Flush", loop.create_future()))
            data += pb.encode_request_frame(abci.FlushRequest())
        self._writer.write(data)
        await self._writer.drain()
        return await fut

    # -- the 15-method surface + echo/flush ------------------------------
    async def echo(self, message: str) -> abci.EchoResponse:
        return await self._call(abci.EchoRequest(message=message), "Echo")

    async def flush(self) -> None:
        await self._call(abci.FlushRequest(), "Flush")

    async def info(self, req: abci.InfoRequest) -> abci.InfoResponse:
        return await self._call(req, "Info")

    async def query(self, req: abci.QueryRequest) -> abci.QueryResponse:
        return await self._call(req, "Query")

    async def check_tx(self, req: abci.CheckTxRequest
                       ) -> abci.CheckTxResponse:
        return await self._call(req, "CheckTx")

    async def init_chain(self, req: abci.InitChainRequest
                         ) -> abci.InitChainResponse:
        return await self._call(req, "InitChain")

    async def prepare_proposal(self, req: abci.PrepareProposalRequest
                               ) -> abci.PrepareProposalResponse:
        return await self._call(req, "PrepareProposal")

    async def process_proposal(self, req: abci.ProcessProposalRequest
                               ) -> abci.ProcessProposalResponse:
        return await self._call(req, "ProcessProposal")

    async def finalize_block(self, req: abci.FinalizeBlockRequest
                             ) -> abci.FinalizeBlockResponse:
        return await self._call(req, "FinalizeBlock")

    async def extend_vote(self, req: abci.ExtendVoteRequest
                          ) -> abci.ExtendVoteResponse:
        return await self._call(req, "ExtendVote")

    async def verify_vote_extension(
            self, req: abci.VerifyVoteExtensionRequest
    ) -> abci.VerifyVoteExtensionResponse:
        return await self._call(req, "VerifyVoteExtension")

    async def commit(self) -> abci.CommitResponse:
        return await self._call(abci.CommitRequest(), "Commit")

    async def list_snapshots(self, req: abci.ListSnapshotsRequest
                             ) -> abci.ListSnapshotsResponse:
        return await self._call(req, "ListSnapshots")

    async def offer_snapshot(self, req: abci.OfferSnapshotRequest
                             ) -> abci.OfferSnapshotResponse:
        return await self._call(req, "OfferSnapshot")

    async def load_snapshot_chunk(self, req: abci.LoadSnapshotChunkRequest
                                  ) -> abci.LoadSnapshotChunkResponse:
        return await self._call(req, "LoadSnapshotChunk")

    async def apply_snapshot_chunk(
            self, req: abci.ApplySnapshotChunkRequest
    ) -> abci.ApplySnapshotChunkResponse:
        return await self._call(req, "ApplySnapshotChunk")


class SocketAppConns:
    """proxy.AppConns over four socket connections to one app process
    (reference: multi_app_conn.go creates one client per named conn)."""

    def __init__(self, address: str):
        self.consensus = SocketClient(address)
        self.mempool = SocketClient(address)
        self.query = SocketClient(address)
        self.snapshot = SocketClient(address)

    async def start(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            await c.connect()

    async def stop(self) -> None:
        for c in (self.consensus, self.mempool, self.query, self.snapshot):
            await c.close()
