"""ABCI clients.

Reference: abci/client/ — local_client (in-process, mutexed),
unsync_local_client, socket_client (pipelined, abci/client/socket_client.go).
The local variants live here; the socket client arrives with the
out-of-process server.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from . import types as abci


class ABCIClientError(Exception):
    pass


class LocalClient:
    """In-process client serializing calls with one lock.

    Reference: abci/client/local_client.go — a global mutex makes the app
    see at most one concurrent call, which is the ABCI concurrency
    contract for a single connection.
    """

    def __init__(self, app: abci.Application,
                 lock: Optional[asyncio.Lock] = None):
        self._app = app
        self._lock = lock if lock is not None else asyncio.Lock()

    @property
    def app(self) -> abci.Application:
        return self._app

    async def echo(self, message: str) -> abci.EchoResponse:
        async with self._lock:
            return await self._app.echo(abci.EchoRequest(message=message))

    async def flush(self) -> None:
        return None

    async def info(self, req: abci.InfoRequest) -> abci.InfoResponse:
        async with self._lock:
            return await self._app.info(req)

    async def query(self, req: abci.QueryRequest) -> abci.QueryResponse:
        async with self._lock:
            return await self._app.query(req)

    async def check_tx(self, req: abci.CheckTxRequest
                       ) -> abci.CheckTxResponse:
        async with self._lock:
            return await self._app.check_tx(req)

    async def init_chain(self, req: abci.InitChainRequest
                         ) -> abci.InitChainResponse:
        async with self._lock:
            return await self._app.init_chain(req)

    async def prepare_proposal(self, req: abci.PrepareProposalRequest
                               ) -> abci.PrepareProposalResponse:
        async with self._lock:
            return await self._app.prepare_proposal(req)

    async def process_proposal(self, req: abci.ProcessProposalRequest
                               ) -> abci.ProcessProposalResponse:
        async with self._lock:
            return await self._app.process_proposal(req)

    async def finalize_block(self, req: abci.FinalizeBlockRequest
                             ) -> abci.FinalizeBlockResponse:
        async with self._lock:
            return await self._app.finalize_block(req)

    async def extend_vote(self, req: abci.ExtendVoteRequest
                          ) -> abci.ExtendVoteResponse:
        async with self._lock:
            return await self._app.extend_vote(req)

    async def verify_vote_extension(
            self, req: abci.VerifyVoteExtensionRequest
    ) -> abci.VerifyVoteExtensionResponse:
        async with self._lock:
            return await self._app.verify_vote_extension(req)

    async def commit(self) -> abci.CommitResponse:
        async with self._lock:
            return await self._app.commit(abci.CommitRequest())

    async def list_snapshots(self, req: abci.ListSnapshotsRequest
                             ) -> abci.ListSnapshotsResponse:
        async with self._lock:
            return await self._app.list_snapshots(req)

    async def offer_snapshot(self, req: abci.OfferSnapshotRequest
                             ) -> abci.OfferSnapshotResponse:
        async with self._lock:
            return await self._app.offer_snapshot(req)

    async def load_snapshot_chunk(self, req: abci.LoadSnapshotChunkRequest
                                  ) -> abci.LoadSnapshotChunkResponse:
        async with self._lock:
            return await self._app.load_snapshot_chunk(req)

    async def apply_snapshot_chunk(
            self, req: abci.ApplySnapshotChunkRequest
    ) -> abci.ApplySnapshotChunkResponse:
        async with self._lock:
            return await self._app.apply_snapshot_chunk(req)


class _NoopLock:
    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        return False


class UnsyncLocalClient(LocalClient):
    """Local client without any lock: the app handles its own
    synchronization (reference: unsync_local_client.go has no mutex)."""

    def __init__(self, app: abci.Application):
        super().__init__(app, lock=_NoopLock())


class AppConns:
    """The four named ABCI connections sharing one client.

    Reference: proxy/multi_app_conn.go — consensus/mempool/query/snapshot.
    With a local client they share one mutex (the reference's
    NewConnSyncLocalClientCreator semantics).
    """

    def __init__(self, app: abci.Application, sync: bool = True):
        if sync:
            lock = asyncio.Lock()
            self.consensus = LocalClient(app, lock)
            self.mempool = LocalClient(app, lock)
            self.query = LocalClient(app, lock)
            self.snapshot = LocalClient(app, lock)
        else:
            self.consensus = UnsyncLocalClient(app)
            self.mempool = UnsyncLocalClient(app)
            self.query = UnsyncLocalClient(app)
            self.snapshot = UnsyncLocalClient(app)


class ClientCreator:
    """Reference: proxy/client.go ClientCreator — local vs remote."""

    def __init__(self, app: Optional[abci.Application] = None,
                 addr: str = "", transport: str = "local"):
        self._app = app
        self._addr = addr
        self._transport = transport

    def new_app_conns(self) -> AppConns:
        if self._transport in ("local", "builtin", "builtin_unsync"):
            if self._app is None:
                raise ABCIClientError("local client requires an app")
            return AppConns(self._app,
                            sync=self._transport != "builtin_unsync")
        raise ABCIClientError(
            f"transport {self._transport!r} not yet supported")
