"""kvstore: the canonical example/test application.

Reference: abci/example/kvstore/kvstore.go (677 LoC) — key=value txs,
validator-update txs ("val=<type>!<b64 pubkey>!<power>"), priority lanes,
/val query path.  Used by the e2e baseline config #1 and as the
universal test app.

Storage is the committed state tree (cometbft_tpu/statetree/): every
kv pair and validator record is a tree leaf, FinalizeBlock returns
the tree's working root as app_hash and Commit persists it as the
height's version — so ``header.app_hash -> tree root -> key/value``
verifies against any consensus-verified header, queries serve
versioned (historical) reads, and /multistore proofs cover absent
keys too.  (The reference app hashes only its size; that legacy
scheme survives as the migration override for pre-tree chains.)
"""
from __future__ import annotations

import base64
import json
from typing import Callable, Iterable, Optional

from .. import version as _version
from ..db import DB, MemDB
from ..db.db import PrefixDB
from ..libs.log import new_logger
from ..statetree import StateTree
from . import types as abci

VALIDATOR_PREFIX = "val="
APP_VERSION = 1
DEFAULT_LANE = "default"

CODE_TYPE_OK = 0
CODE_TYPE_ENCODING_ERROR = 1
CODE_TYPE_INVALID_TX_FORMAT = 2
CODE_TYPE_UNAUTHORIZED = 3
CODE_TYPE_EXECUTED = 5

_KV_PREFIX = b"kvPairKey:"        # legacy (pre-tree) row prefix
_STATE_KEY = b"appstate"          # legacy (pre-tree) height/size row
_TREE_PREFIX = b"statetree/"

# lane priorities (reference: kvstore.go NewInMemoryApplication lanes)
DEFAULT_LANES = {"val": 9, "foo": 7, DEFAULT_LANE: 3, "bar": 1}


def _zigzag_varint(n: int) -> bytes:
    """Go binary.PutVarint into an 8-byte buffer — the LEGACY app
    hash (reference: State.Hash — kvstore.go:669), kept for the
    pre-tree migration path only."""
    zz = (n << 1) ^ (n >> 63) if n < 0 else n << 1
    out = bytearray(8)
    i = 0
    while True:
        b = zz & 0x7F
        zz >>= 7
        if zz:
            out[i] = b | 0x80
        else:
            out[i] = b
            break
        i += 1
    return bytes(out)


def make_val_set_change_tx(pub_key_type: str, pub_key_bytes: bytes,
                           power: int) -> bytes:
    """Reference: helpers.go MakeValSetChangeTx."""
    pub = base64.b64encode(pub_key_bytes).decode()
    return f"{VALIDATOR_PREFIX}{pub_key_type}!{pub}!{power}".encode()


def _parse_val_value(raw: bytes) -> tuple[str, int]:
    """Stored validator value 'type!power' (pre-mixed-key stores held
    a bare power: treat those as ed25519)."""
    s = raw.decode()
    if "!" in s:
        key_type, power_s = s.split("!", 1)
        return key_type, int(power_s)
    return "ed25519", int(s)


def is_validator_tx(tx: bytes) -> bool:
    return tx.startswith(VALIDATOR_PREFIX.encode())


def parse_validator_tx(tx: bytes) -> tuple[str, bytes, int]:
    """Returns (key_type, pub_key_bytes, power)."""
    body = tx[len(VALIDATOR_PREFIX):].decode()
    parts = body.split("!")
    if len(parts) != 3:
        raise ValueError(f"expected 'type!pubkey!power', got {body!r}")
    key_type, pub_b64, power_s = parts
    pub = base64.b64decode(pub_b64)
    power = int(power_s)
    if power < 0:
        raise ValueError("power can not be less than 0")
    return key_type, pub, power


def parse_tx(tx: bytes) -> tuple[str, str]:
    parts = tx.split(b"=")
    if len(parts) != 2:
        raise ValueError(f"invalid tx format: {tx!r}")
    if not parts[0]:
        raise ValueError("key cannot be empty")
    return parts[0].decode(), parts[1].decode()


def is_valid_tx(tx: bytes) -> bool:
    """key=value or key:value, exactly one separator, not at the ends."""
    for sep, other in ((b":", b"="), (b"=", b":")):
        if tx.count(sep) == 1 and tx.count(other) == 0:
            if not tx.startswith(sep) and not tx.endswith(sep):
                return True
    return False


def tx_recheck_keys(tx: bytes) -> list:
    """The state keys a tx's validity depends on, for the mempool's
    incremental recheck.  kvstore txs write exactly one kv key (or one
    validator record); kvstore CheckTx is stateless, so this is a
    conservative over-report — which is the safe direction."""
    try:
        if is_validator_tx(tx):
            _, pub, _ = parse_validator_tx(tx)
            return [VALIDATOR_PREFIX.encode() +
                    base64.b64encode(pub)]
        key, _ = parse_tx(tx.replace(b":", b"="))
        return [_KV_PREFIX + key.encode()]
    except ValueError:
        return []


def assign_lane(tx: bytes) -> str:
    """Deterministic lane assignment (reference: kvstore.go assignLane)."""
    if is_validator_tx(tx):
        return "val"
    try:
        key, _ = parse_tx(tx)
        key_int = int(key)
    except ValueError:
        return DEFAULT_LANE
    if key_int % 11 == 0:
        return "foo"
    if key_int % 3 == 0:
        return "bar"
    return DEFAULT_LANE


def _val_tree_key(pub_key_bytes: bytes) -> bytes:
    """Validator record key inside the state tree.  kv tx keys can
    never contain '=' (parse_tx requires exactly one separator), so
    the 'val=' prefix cannot collide with a user kv key."""
    return (VALIDATOR_PREFIX +
            base64.b64encode(pub_key_bytes).decode()).encode()


class KVStoreApplication(abci.Application):
    def __init__(self, db: Optional[DB] = None,
                 lane_priorities: Optional[dict[str, int]] = DEFAULT_LANES,
                 snapshot_interval: int = 0):
        self.db = db if db is not None else MemDB()
        self.lane_priorities = dict(lane_priorities or {})
        self.snapshot_interval = snapshot_interval
        self._snapshots: dict[int, bytes] = {}
        self.retain_blocks = 0
        self.logger = new_logger("kvstore")
        self._val_updates: list[abci.ValidatorUpdate] = []
        self._val_addr_to_pubkey: dict[bytes, tuple[str, bytes]] = {}
        self._gen_block_events = False
        self.next_block_delay_ns = 0
        # artificial per-call delays (reference: e2e manifest
        # prepare_proposal_delay / process_proposal_delay /
        # check_tx_delay / finalize_block_delay / vote_extension_delay
        # mimic app computation time)
        self.abci_delays: dict[str, float] = {}
        self._height = 0
        self._size = 0
        # versions the tree must retain beyond the pruning horizon —
        # the node wires this to lightserve's ResponseCache so a
        # height the cache still serves keeps its proofs available
        self.version_pin: Optional[Callable[[], Iterable[int]]] = None
        self.tree = StateTree(PrefixDB(self.db, _TREE_PREFIX))
        self._load_state()

    # ------------------------------------------------------------------
    def _load_state(self) -> None:
        if self.tree.latest_version is not None:
            # the tree is the source of truth: height/size ride the
            # version record's extra blob, written in the same atomic
            # batch as the state — no crash window between them
            self._height = self.tree.latest_version
            self._size = int(
                self.tree.version_extra().get("size", 0))
            self._rebuild_val_map()
            return
        raw = self.db.get(_STATE_KEY)
        if raw:
            st = json.loads(raw)
            self._height = st.get("height", 0)
            self._size = st.get("size", 0)
        self._migrate_legacy()

    def _migrate_legacy(self) -> None:
        """Import a pre-tree store (raw kvPairKey:/val= rows, app
        hash = varint(size)) into the tree at the current height.
        The legacy hash is recorded as that one version's reported
        app_hash so ABCI handshake replay still matches the stored
        state; every height after the migration reports the tree
        root (valid — app_hash changes every height anyway, and all
        upgraded replicas switch at the same height).  Migration
        note: upgrade with the app at the block-store tip; blocks
        finalized before the upgrade carry legacy app_hashes the
        tree no longer reproduces, so a behind-the-store replay
        across the upgrade boundary will refuse those headers."""
        val_prefix = VALIDATOR_PREFIX.encode()
        pairs = []
        legacy_rows = []
        for k, v in self.db.iterator():
            if k.startswith(_KV_PREFIX):
                pairs.append((k[len(_KV_PREFIX):], v))
                legacy_rows.append(k)
            elif k.startswith(val_prefix):
                pairs.append((k, v))
                legacy_rows.append(k)
        if not pairs and self._height == 0:
            return
        self.tree.import_snapshot(
            self._height, pairs,
            app_hash_override=_zigzag_varint(self._size),
            extra={"size": self._size})
        for k in legacy_rows:
            self.db.delete(k)
        self._rebuild_val_map()
        self.logger.info("Migrated legacy kvstore rows into the "
                         "state tree", height=self._height,
                         pairs=len(pairs))

    def _rebuild_val_map(self) -> None:
        from ..crypto import encoding as crypto_encoding
        self._val_addr_to_pubkey.clear()
        val_prefix = VALIDATOR_PREFIX.encode()
        for key, raw_val in self.tree.pairs():
            if not key.startswith(val_prefix):
                continue
            pub = base64.b64decode(key[len(val_prefix):])
            key_type, _ = _parse_val_value(raw_val)
            pk = crypto_encoding.pub_key_from_type_and_bytes(
                key_type, pub)
            self._val_addr_to_pubkey[pk.address()] = (key_type, pub)

    def _app_hash(self) -> bytes:
        """The committed app hash: the state tree root (or, for the
        one migrated legacy version, its recorded override)."""
        return self.tree.reported_hash()

    def set_gen_block_events(self) -> None:
        self._gen_block_events = True

    # ------------------------------------------------------------------
    async def info(self, req: abci.InfoRequest) -> abci.InfoResponse:
        default_lane = ""
        if self.lane_priorities:
            default_lane = DEFAULT_LANE
        return abci.InfoResponse(
            data=json.dumps({"size": self._size}),
            version=_version.ABCI_SEM_VER,
            app_version=APP_VERSION,
            last_block_height=self._height,
            last_block_app_hash=self._app_hash(),
            lane_priorities=dict(self.lane_priorities),
            default_lane=default_lane,
        )

    async def init_chain(self, req: abci.InitChainRequest
                         ) -> abci.InitChainResponse:
        self.tree.reset_working()
        for v in req.validators:
            self._stage_validator(v)
            self._track_validator(v)
        # genesis state = tree version 0; its root is the app_hash
        # block 1's header carries.  Re-running InitChain over an
        # already-committed version 0 (crash before height 1, then
        # handshake replay) is an idempotent no-op in the tree.
        app_hash = self.tree.commit(0, extra={"size": self._size})
        return abci.InitChainResponse(app_hash=app_hash)

    async def _delay(self, call: str) -> None:
        d = self.abci_delays.get(call, 0.0)
        if d > 0:
            import asyncio
            await asyncio.sleep(d)

    async def check_tx(self, req: abci.CheckTxRequest
                       ) -> abci.CheckTxResponse:
        await self._delay("check_tx")
        if is_validator_tx(req.tx):
            try:
                parse_validator_tx(req.tx)
            except ValueError:
                return abci.CheckTxResponse(
                    code=CODE_TYPE_INVALID_TX_FORMAT)
        elif not is_valid_tx(req.tx):
            return abci.CheckTxResponse(code=CODE_TYPE_INVALID_TX_FORMAT)
        keys = tx_recheck_keys(req.tx)
        if not self.lane_priorities:
            return abci.CheckTxResponse(code=CODE_TYPE_OK, gas_wanted=1,
                                        recheck_keys=keys)
        return abci.CheckTxResponse(code=CODE_TYPE_OK, gas_wanted=1,
                                    lane_id=assign_lane(req.tx),
                                    recheck_keys=keys)

    async def prepare_proposal(self, req: abci.PrepareProposalRequest
                               ) -> abci.PrepareProposalResponse:
        """Normalize 'k:v' to 'k=v', drop invalid txs (reference:
        formatTxs)."""
        await self._delay("prepare_proposal")
        txs = []
        for tx in req.txs:
            if is_validator_tx(tx):
                try:
                    parse_validator_tx(tx)
                except ValueError:
                    continue
                txs.append(tx)
            elif is_valid_tx(tx):
                txs.append(tx.replace(b":", b"="))
        return abci.PrepareProposalResponse(txs=txs)

    async def process_proposal(self, req: abci.ProcessProposalRequest
                               ) -> abci.ProcessProposalResponse:
        await self._delay("process_proposal")
        for tx in req.txs:
            if is_validator_tx(tx):
                try:
                    parse_validator_tx(tx)
                except ValueError:
                    return abci.ProcessProposalResponse(
                        status=abci.PROCESS_PROPOSAL_STATUS_REJECT)
            elif not is_valid_tx(tx) or b":" in tx:
                # only the proposer's "=" normal form is acceptable here
                return abci.ProcessProposalResponse(
                    status=abci.PROCESS_PROPOSAL_STATUS_REJECT)
        return abci.ProcessProposalResponse(
            status=abci.PROCESS_PROPOSAL_STATUS_ACCEPT)

    async def finalize_block(self, req: abci.FinalizeBlockRequest
                             ) -> abci.FinalizeBlockResponse:
        await self._delay("finalize_block")
        self._val_updates = []
        # a previous FinalizeBlock whose Commit never arrived (crash
        # replay) must not leak staged writes into this block
        self.tree.reset_working()

        # punish equivocators by one power unit per offence
        # (reference: kvstore.go:318), ONE update per address — a
        # block can carry several evidences against one validator, and
        # duplicate entries in validator_updates are a consensus-
        # failure per the ABCI contract
        punish: dict[bytes, int] = {}
        for ev in req.misbehavior:
            if ev.type == abci.MISBEHAVIOR_TYPE_DUPLICATE_VOTE:
                addr = ev.validator.address
                punish[addr] = min(
                    punish.get(addr, ev.validator.power) - 1,
                    ev.validator.power - 1)
        for addr, new_power in punish.items():
            entry = self._val_addr_to_pubkey.get(addr)
            if entry is not None:
                key_type, pub = entry
                self._val_updates.append(abci.ValidatorUpdate(
                    power=max(new_power, 0),
                    pub_key_type=key_type, pub_key_bytes=pub))
                self.logger.info(
                    "Decreased val power for equivocation",
                    val=addr.hex(), new_power=max(new_power, 0))

        tx_results = []
        for tx in req.txs:
            if is_validator_tx(tx):
                key_type, pub, power = parse_validator_tx(tx)
                self._val_updates.append(abci.ValidatorUpdate(
                    power=power, pub_key_type=key_type,
                    pub_key_bytes=pub))
            else:
                parts = tx.split(b"=")
                if len(parts) == 2:
                    self.tree.set(parts[0], parts[1])
            parts = tx.split(b"=")
            if len(parts) == 2:
                key, value = parts[0].decode(), parts[1].decode()
            else:
                key = value = tx.decode(errors="replace")
            tx_results.append(abci.ExecTxResult(
                code=CODE_TYPE_OK,
                recheck_keys=tx_recheck_keys(tx),
                events=[abci.Event(type="app", attributes=[
                    abci.EventAttribute("creator", "Cosmoshi Netowoko",
                                        True),
                    abci.EventAttribute("key", key, True),
                    abci.EventAttribute("index_key", "index is working",
                                        True),
                    abci.EventAttribute("noindex_key", "index is working",
                                        False),
                ])],
            ))
            self._size += 1

        self._height = req.height
        # one update per pubkey across ALL sources (punishments and
        # validator txs may both touch the same validator in one
        # block; duplicate entries are a consensus failure) — the
        # LAST write wins, so an explicit val-tx overrides the
        # evidence punishment, matching append order
        by_key: dict[bytes, abci.ValidatorUpdate] = {}
        for u in self._val_updates:
            by_key[u.pub_key_bytes] = u
        for u in by_key.values():
            self._stage_validator(u)
        # the app hash IS this height's tree root; Commit persists
        # the same staged view (the tree caches the computation)
        resp = abci.FinalizeBlockResponse(
            tx_results=tx_results,
            validator_updates=list(by_key.values()),
            app_hash=self.tree.working_root(req.height),
            next_block_delay_ns=self.next_block_delay_ns,
        )
        if self._gen_block_events:
            resp.events = [abci.Event(type="begin_event", attributes=[
                abci.EventAttribute("foo", "100", True),
                abci.EventAttribute("bar", "200", True)])]
        return resp

    async def commit(self, req: abci.CommitRequest) -> abci.CommitResponse:
        # one atomic batch: kv writes, validator records, version
        # metadata (height implicit, size in extra) — a crash either
        # side of this line replays to the exact same root
        self.tree.commit(self._height, extra={"size": self._size})
        for u in self._dedup_val_updates():
            self._track_validator(u)
        if self.snapshot_interval > 0 and self._height > 0 and \
                self._height % self.snapshot_interval == 0:
            self._snapshots[self._height] = self._serialize_state()
            # keep a bounded window (reference: the e2e app retains a
            # small recent set) — each entry is a full state copy, so
            # an unpruned dict grows without bound on long-lived nodes
            while len(self._snapshots) > 5:
                del self._snapshots[min(self._snapshots)]
        resp = abci.CommitResponse()
        if self.retain_blocks > 0 and self._height >= self.retain_blocks:
            resp.retain_height = self._height - self.retain_blocks + 1
            # prune tree versions below the retention horizon, except
            # any the lightserve cache still serves (a cached height
            # must stay provable — the acceptance invariant)
            pinned = self.version_pin() if self.version_pin else ()
            self.tree.prune(resp.retain_height - 1, pinned=pinned)
        return resp

    def _dedup_val_updates(self) -> list[abci.ValidatorUpdate]:
        by_key: dict[bytes, abci.ValidatorUpdate] = {}
        for u in self._val_updates:
            by_key[u.pub_key_bytes] = u
        return list(by_key.values())

    # ------------------------------------------------------------------
    # snapshots (reference: the e2e app's snapshot support; single-chunk
    # full-state snapshots keyed by height)

    def _serialize_state(self) -> bytes:
        pairs = [[k.hex(), v.hex()] for k, v in self.tree.pairs()]
        return json.dumps({"height": self._height,
                           "size": self._size,
                           "pairs": pairs}).encode()

    def _restore_state(self, raw: bytes) -> None:
        d = json.loads(raw)
        self._height = d["height"]
        self._size = d["size"]
        # import reproduces a byte-identical root: same pairs, same
        # sorted order, same leaf binding as the snapshot producer
        self.tree.import_snapshot(
            self._height,
            [(bytes.fromhex(k), bytes.fromhex(v))
             for k, v in d["pairs"]],
            extra={"size": self._size})
        self._rebuild_val_map()

    async def list_snapshots(self, req: abci.ListSnapshotsRequest
                             ) -> abci.ListSnapshotsResponse:
        from ..crypto import tmhash
        snaps = [abci.Snapshot(height=h, format=1, chunks=1,
                               hash=tmhash.sum(raw))
                 for h, raw in sorted(self._snapshots.items())]
        return abci.ListSnapshotsResponse(snapshots=snaps)

    async def offer_snapshot(self, req: abci.OfferSnapshotRequest
                             ) -> abci.OfferSnapshotResponse:
        s = req.snapshot
        if s is None or s.format != 1 or s.chunks != 1:
            return abci.OfferSnapshotResponse(
                result=abci.OFFER_SNAPSHOT_RESULT_REJECT_FORMAT)
        self._restoring = s
        return abci.OfferSnapshotResponse(
            result=abci.OFFER_SNAPSHOT_RESULT_ACCEPT)

    async def load_snapshot_chunk(self, req: abci.LoadSnapshotChunkRequest
                                  ) -> abci.LoadSnapshotChunkResponse:
        raw = self._snapshots.get(req.height, b"")
        return abci.LoadSnapshotChunkResponse(chunk=raw)

    async def apply_snapshot_chunk(self,
                                   req: abci.ApplySnapshotChunkRequest
                                   ) -> abci.ApplySnapshotChunkResponse:
        from ..crypto import tmhash
        restoring = getattr(self, "_restoring", None)
        if restoring is None or \
                tmhash.sum(req.chunk) != restoring.hash:
            return abci.ApplySnapshotChunkResponse(
                result=abci.APPLY_SNAPSHOT_CHUNK_RESULT_REJECT_SNAPSHOT)
        self._restore_state(req.chunk)
        self._restoring = None
        return abci.ApplySnapshotChunkResponse(
            result=abci.APPLY_SNAPSHOT_CHUNK_RESULT_ACCEPT)

    def _resolve_version(self, height: int) -> Optional[int]:
        """Query height -> tree version (they coincide: version H is
        the state after block H).  0 = latest.  Raises ValueError for
        a height the tree cannot serve (pruned / not yet committed);
        returns None when nothing was ever committed."""
        latest = self.tree.latest_version
        if latest is None:
            if height > 0:
                raise ValueError("no committed state")
            return None
        if height == 0:
            return latest
        if height > latest:
            raise ValueError(f"height {height} not yet committed "
                             f"(latest {latest})")
        if height < self.tree.base_version:
            raise ValueError(f"height {height} pruned (oldest "
                             f"retained {self.tree.base_version})")
        return height

    async def query(self, req: abci.QueryRequest) -> abci.QueryResponse:
        if req.path == "/multistore":
            return self._multistore_query(req)
        try:
            v = self._resolve_version(req.height)
        except ValueError as e:
            return abci.QueryResponse(code=CODE_TYPE_ENCODING_ERROR,
                                      log=str(e), height=self._height)
        if req.path == "/val":
            value = b""
            if v is not None:
                value = self.tree.get(
                    (VALIDATOR_PREFIX + req.data.decode()).encode(),
                    v) or b""
            if value:
                # external contract stays the bare power (the key
                # type tag is internal to the stored value)
                value = str(_parse_val_value(value)[1]).encode()
            return abci.QueryResponse(key=req.data, value=value)
        value = self.tree.get(req.data, v) if v is not None else None
        return abci.QueryResponse(
            key=req.data,
            value=value or b"",
            log="exists" if value is not None else "does not exist",
            height=v if v is not None else self._height,
        )

    # ------------------------------------------------------------------
    def _multistore_query(self, req: abci.QueryRequest
                          ) -> abci.QueryResponse:
        """Batched provable lookup (lightserve.core.MULTISTORE_PATH):
        request data is JSON {"keys": [hex...]}; the response value is
        the statetree proof envelope — every found (key, value) pair,
        a non-inclusion arm per absent key, and ONE compact multiproof
        whose root IS the app_hash committed by the header at
        version + 1.  Historical heights prove against that height's
        committed root (the tree memoizes materialized versions, so
        thousands of light clients batching against one height pay
        one O(n) scan, not one each)."""
        try:
            keys = [bytes.fromhex(k)
                    for k in json.loads(req.data)["keys"]]
        except (ValueError, KeyError, TypeError) as e:
            return abci.QueryResponse(
                code=CODE_TYPE_ENCODING_ERROR,
                log=f"bad multistore request: {e}")
        try:
            v = self._resolve_version(req.height)
            envelope = self.tree.prove(keys, v)
        except (ValueError, KeyError) as e:
            return abci.QueryResponse(
                code=CODE_TYPE_ENCODING_ERROR,
                log=f"multistore: {e}", height=self._height)
        return abci.QueryResponse(
            key=req.data,
            value=json.dumps(envelope).encode(),
            height=int(envelope["version"]),
        )

    # ------------------------------------------------------------------
    def _stage_validator(self, v: abci.ValidatorUpdate) -> None:
        """Stage a validator record into the tree's working set —
        validator state is part of the committed app state, so it is
        provable (and prunable) like any kv pair."""
        key = _val_tree_key(v.pub_key_bytes)
        if v.power == 0:
            self.tree.delete(key)
        else:
            # record the key TYPE with the power: snapshot restore
            # must rebuild a mixed-key validator map (the b64 pubkey
            # alone can't distinguish ed25519 from secp256k1)
            self.tree.set(key, f"{v.pub_key_type}!{v.power}".encode())

    def _track_validator(self, v: abci.ValidatorUpdate) -> None:
        from ..crypto import encoding as crypto_encoding
        pub = crypto_encoding.pub_key_from_type_and_bytes(
            v.pub_key_type, v.pub_key_bytes)
        addr = pub.address()
        if v.power == 0:
            self._val_addr_to_pubkey.pop(addr, None)
        else:
            self._val_addr_to_pubkey[addr] = (v.pub_key_type,
                                              v.pub_key_bytes)

    def get_validators(self) -> list[abci.ValidatorUpdate]:
        out = []
        for addr, (key_type, pub) in self._val_addr_to_pubkey.items():
            raw = self.tree.get(_val_tree_key(pub))
            if raw:
                out.append(abci.ValidatorUpdate(
                    power=_parse_val_value(raw)[1],
                    pub_key_type=key_type,
                    pub_key_bytes=pub))
        return out
