"""ABCI call-sequence grammar checker.

Reference: test/e2e/pkg/grammar/checker.go + abci_grammar.md — the
spec's expected-behavior grammar
(spec/abci/abci++_comet_expected_behavior.md):

    start             = clean-start / recovery
    clean-start       = ( init-chain / state-sync ) consensus-exec
    state-sync        = *state-sync-attempt success-sync
    state-sync-attempt= offer-snapshot *apply-chunk
    success-sync      = offer-snapshot 1*apply-chunk
    recovery          = [init-chain] consensus-exec
    consensus-exec    = 1*consensus-height
    consensus-height  = *consensus-round finalize-block commit
    consensus-round   = proposer / non-proposer   (any mix of
                        prepare/process/extend/got-vote tokens)

Info/Echo/Query/CheckTx/Flush and snapshot serving calls are ignored
(the reference ignores Info for the same reason).  The checker is an
exact state machine over the remaining calls; because round
productions concatenate freely, any mix of the four round tokens is
derivable between commits — the structure the grammar actually
enforces is the handshake/state-sync prefix, the strict
finalize->commit pairing, and chunk placement.
"""
from __future__ import annotations

# grammar-relevant call names (reference: checker.go filter)
GRAMMAR_CALLS = frozenset({
    "init_chain", "offer_snapshot", "apply_snapshot_chunk",
    "prepare_proposal", "process_proposal", "extend_vote",
    "verify_vote_extension", "finalize_block", "commit",
})

_ROUND = frozenset({"prepare_proposal", "process_proposal",
                    "extend_vote", "verify_vote_extension"})


class GrammarError(Exception):
    def __init__(self, index: int, call: str, msg: str):
        super().__init__(f"call #{index} {call!r}: {msg}")
        self.index = index
        self.call = call


class GrammarChecker:
    """Verify a full execution trace (reference: Checker.Verify)."""

    def verify(self, calls: list[str],
               clean_start: bool = True) -> bool:
        """Raises GrammarError on the first violating call.  calls is
        the raw trace; non-grammar calls are filtered out.  With
        clean_start, the trace must begin with init_chain or a
        state-sync; a recovery trace may jump straight into consensus.
        """
        trace = [c for c in calls if c in GRAMMAR_CALLS]
        state = "start"
        chunks_in_attempt = 0
        commits = 0
        for i, c in enumerate(trace):
            if c == "init_chain":
                if i != 0:
                    raise GrammarError(i, c, "only valid as the "
                                       "first call")
                state = "consensus"
            elif c == "offer_snapshot":
                if state not in ("start", "sync"):
                    raise GrammarError(i, c, "state-sync after "
                                       "consensus started")
                state = "sync"
                chunks_in_attempt = 0
            elif c == "apply_snapshot_chunk":
                if state != "sync":
                    raise GrammarError(i, c, "chunk outside a "
                                       "snapshot attempt")
                chunks_in_attempt += 1
            elif c in _ROUND or c == "finalize_block":
                if state == "start":
                    if clean_start:
                        raise GrammarError(
                            i, c, "consensus before init_chain/"
                            "state-sync on a clean start")
                    state = "consensus"
                elif state == "sync":
                    # leaving state-sync requires a successful final
                    # attempt (success-sync = offer 1*chunk)
                    if chunks_in_attempt == 0:
                        raise GrammarError(
                            i, c, "state-sync never succeeded (last "
                            "offer_snapshot applied no chunks)")
                    state = "consensus"
                elif state == "expect_commit":
                    raise GrammarError(i, c, "expected commit after "
                                       "finalize_block")
                if c == "finalize_block":
                    state = "expect_commit"
            elif c == "commit":
                if state != "expect_commit":
                    raise GrammarError(i, c, "commit without "
                                       "finalize_block")
                state = "consensus"
                commits += 1
        if state == "expect_commit":
            raise GrammarError(len(trace), "<end>",
                               "trace ends between finalize_block "
                               "and commit")
        if state == "sync":
            raise GrammarError(len(trace), "<end>",
                               "trace ends inside state-sync")
        if commits == 0:
            raise GrammarError(len(trace), "<end>",
                               "consensus-exec requires at least one "
                               "height (no commit in trace)")
        return True


class RecordingClient:
    """ABCI client middleware that records the call-name trace for
    grammar checking (reference: the e2e app writes each ABCI request
    to disk for the checker)."""

    _RECORDED = GRAMMAR_CALLS | {"info", "query", "check_tx",
                                 "list_snapshots",
                                 "load_snapshot_chunk"}

    def __init__(self, inner, calls: list[str] | None = None):
        # `calls` may be shared by several connections so the trace
        # preserves true cross-connection call order
        self._inner = inner
        self.calls = calls if calls is not None else []

    def __getattr__(self, name):
        target = getattr(self._inner, name)
        if name in self._RECORDED and callable(target):
            async def wrapper(*a, _t=target, _n=name, **kw):
                self.calls.append(_n)
                return await _t(*a, **kw)
            return wrapper
        return target
