"""Proof envelopes: existence + non-inclusion under one multiproof.

The tree commits sorted-unique keys, so absence is an adjacency
claim: key K is absent from version V iff two leaves that are
ADJACENT in V's sorted leaf array straddle it (key[i] < K <
key[i+1]), or K falls off one end (K < key[0] / K > key[total-1]),
or the tree is empty.  One compact ``Multiproof`` (crypto/merkle.py)
covers the present keys and every absent key's neighbor leaves, so
both proof kinds ride the existing wire format and verify against
the same root — which, with the statetree as the kvstore's storage
engine, IS the app_hash a consensus-verified header carries.

Envelope (JSON-ready; int64s as strings per RPC convention):

  {
    "version": "7",          # tree version the proof is against
    "header_height": "8",    # the header whose app_hash == root
    "root": "AB12..",        # hex-upper tree root
    "total": "5",            # leaves in the tree at that version
    "indices": [0, 2, 3],    # proven leaf positions (sorted unique)
    "keys": [..], "values": [..],   # hex, aligned with indices
    "absent": [{"key": hex, "left": int|null, "right": int|null}],
    "missing": [hex..],      # legacy mirror of absent keys
    "multiproof": {"total", "indices", "aunts"},
  }

Tamper resistance (tests/test_statetree.py pins the matrix):
neighbor-swap fails the adjacency/order checks, range-gap forgery
fails right == left+1 against the proven indices, and a
stale-version proof fails the root comparison against the newer
header's app_hash.
"""
from __future__ import annotations

import bisect
from typing import Iterable, Optional, Sequence

from ..crypto import merkle


def build_proof_envelope(request_keys: Sequence[bytes],
                         keys: Sequence[bytes],
                         values: Sequence[bytes],
                         leaf_hashes: Sequence[bytes],
                         index_of: dict,
                         version: int) -> dict:
    """Build the envelope for ``request_keys`` against the sorted
    committed view (keys/values/leaf_hashes aligned)."""
    prove: set[int] = set()
    absent: list[dict] = []
    missing: list[str] = []
    for k in request_keys:
        i = index_of.get(k)
        if i is not None:
            prove.add(i)
            continue
        missing.append(k.hex())
        j = bisect.bisect_left(keys, k)
        left = j - 1 if j > 0 else None
        right = j if j < len(keys) else None
        if left is not None:
            prove.add(left)
        if right is not None:
            prove.add(right)
        absent.append({"key": k.hex(), "left": left, "right": right})
    root, mp = merkle.multiproof_from_leaf_hashes(
        list(leaf_hashes), sorted(prove))
    return {
        "version": str(version),
        "header_height": str(version + 1),
        "root": root.hex().upper(),
        "total": str(len(keys)),
        "indices": list(mp.indices),
        "keys": [keys[i].hex() for i in mp.indices],
        "values": [values[i].hex() for i in mp.indices],
        "absent": absent,
        "missing": missing,
        "multiproof": mp.to_dict(),
    }


def verify_proof_envelope(proof: dict,
                          present: Iterable[tuple[bytes, bytes]] = (),
                          absent: Iterable[bytes] = (),
                          expected_root: Optional[bytes] = None) -> None:
    """Client-side check of a proof envelope: every (key, value) in
    ``present`` exists at the proven version, every key in ``absent``
    does not.  ``expected_root`` is the trusted commitment — with
    header chaining it is the verified header's app_hash; without it
    the envelope's own root is used (membership-only trust, the
    pre-statetree behavior).  Raises ValueError on any mismatch."""
    root = bytes.fromhex(proof["root"])
    if expected_root is not None and root != expected_root:
        raise ValueError(
            "proof root does not match the verified commitment "
            "(stale version or forged envelope)")
    total = int(proof["total"])
    indices = list(proof["indices"])
    keys = [bytes.fromhex(k) for k in proof["keys"]]
    values = [bytes.fromhex(v) for v in proof["values"]]
    if not (len(indices) == len(keys) == len(values)):
        raise ValueError("proof keys/values/indices misaligned")
    mp = merkle.Multiproof.from_dict(proof["multiproof"])
    if mp.total != total or mp.indices != indices:
        raise ValueError("proof indices do not match multiproof")
    # the one hash check: binds every (key, value) to its leaf
    # position under the root
    mp.verify(root, [merkle.value_op_leaf(k, v)
                     for k, v in zip(keys, values)])
    # the tree commits sorted-unique keys; a proof whose proven keys
    # are not strictly increasing cannot come from a well-formed tree
    # and its adjacency claims would be meaningless
    for a, b in zip(keys, keys[1:]):
        if a >= b:
            raise ValueError("proven keys not strictly increasing")
    index_pos = {idx: n for n, idx in enumerate(indices)}
    proven_keys = set(keys)

    by_value = {}
    for k, v in zip(keys, values):
        by_value[k] = v
    for k, v in present:
        got = by_value.get(k)
        if got is None:
            raise ValueError(f"key {k.hex()} not covered by proof")
        if got != v:
            raise ValueError(f"value mismatch for key {k.hex()}")

    arms = {a["key"]: a for a in proof.get("absent", [])}
    for k in absent:
        if k in proven_keys:
            raise ValueError(
                f"key {k.hex()} claimed absent but proven present")
        arm = arms.get(k.hex())
        if arm is None:
            raise ValueError(f"no non-inclusion arm for {k.hex()}")
        left, right = arm["left"], arm["right"]
        if left is None and right is None:
            if total != 0:
                raise ValueError(
                    "empty-tree absence claim on non-empty tree")
            continue
        if left is None:
            # K precedes every key: the proven leaf 0 must exceed it
            if right != 0:
                raise ValueError("left-edge absence needs leaf 0")
            rk = _arm_key(right, index_pos, keys)
            if not k < rk:
                raise ValueError("left-edge absence order violated")
            continue
        if right is None:
            if left != total - 1:
                raise ValueError(
                    "right-edge absence needs the last leaf")
            lk = _arm_key(left, index_pos, keys)
            if not lk < k:
                raise ValueError("right-edge absence order violated")
            continue
        if right != left + 1:
            raise ValueError(
                "absence neighbors not adjacent (range-gap forgery)")
        lk = _arm_key(left, index_pos, keys)
        rk = _arm_key(right, index_pos, keys)
        if not (lk < k < rk):
            raise ValueError(
                "absent key not inside the neighbor gap "
                "(neighbor-swap forgery)")


def _arm_key(idx: int, index_pos: dict, keys: list) -> bytes:
    n = index_pos.get(idx)
    if n is None:
        raise ValueError(
            f"absence arm references unproven leaf {idx}")
    return keys[n]
