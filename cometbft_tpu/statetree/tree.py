"""Versioned sorted-kv merkle commitment over the db/ layer.

Design: a sorted-kv commitment with versioned nodes (the ISSUE's
sanctioned alternative to a full IAVL rebuild).  The committed state
at version V is the set of live (key, value) pairs; its root is the
RFC-6962 merkle root (crypto/merkle.py hashing, so proofs ride the
existing ``Multiproof`` wire format) over the ``value_op_leaf(key,
value)`` bindings of the pairs in sorted-key order.  Sorted order is
what makes absence provable: a key K is absent iff two ADJACENT
leaves straddle it (proof.py).

Storage layout (inside the caller's DB, typically a PrefixDB
namespace of the app db):

  n/ <uvarint key-len> <key> <be64 version>  ->  0x01 <value>   (set)
                                             ->  0x00           (tombstone)
  v/ <be64 version>  ->  JSON {"root", "total", "app_hash"?}
  m/latest           ->  be64 version
  m/base             ->  be64 oldest retained version

Per-key records are append-only per version (IAVL-style versioned
nodes without the tree shape — the shape is recomputed from sorted
order, which the merkle root pins).  A point read at version V is a
reverse scan for the newest record <= V; a full materialization at V
is one ordered scan keeping the newest record <= V per key.  Commits
write one atomic batch, so a crash between ABCI Commit and the state
store's own fsync recovers the exact pre- or post-commit root and
handshake replay (consensus/replay.py) reconverges.

Versions are app heights: version H is the state after finalizing
block H, and its root lands in block H+1's header.app_hash — the
header_height = version + 1 mapping proof envelopes carry.
"""
from __future__ import annotations

import json
import struct
import threading
from collections import OrderedDict
from typing import Iterable, Optional

from ..crypto import merkle
from ..crypto._native_loader import batched_hashes
from ..db.db import DB
from ..wire.proto import decode_uvarint, encode_uvarint

_NODE = b"n/"
_VERSION = b"v/"
_META_LATEST = b"m/latest"
_META_BASE = b"m/base"
_SET = b"\x01"
_TOMBSTONE = b"\x00"


def _be64(v: int) -> bytes:
    return struct.pack(">Q", v)


def _node_prefix(key: bytes) -> bytes:
    return _NODE + encode_uvarint(len(key)) + key


def _node_key(key: bytes, version: int) -> bytes:
    return _node_prefix(key) + _be64(version)


def _split_node_key(raw: bytes) -> tuple[bytes, int]:
    """``n/``-relative record key -> (user key, version)."""
    klen, pos = decode_uvarint(raw, 0)
    key = raw[pos:pos + klen]
    (version,) = struct.unpack(">Q", raw[pos + klen:pos + klen + 8])
    return key, version


def _leaf_hashes(items: list[bytes]) -> list[bytes]:
    hashes = batched_hashes("leaf_hashes", items)
    if hashes is None:
        hashes = [merkle.leaf_hash(it) for it in items]
    return hashes


class StateTree:
    """Versioned merkle-committed KV store.

    Writes stage into a working set; ``working_root(v)`` computes the
    root the next ``commit(v)`` will produce (FinalizeBlock returns
    the app_hash before Commit persists, so the two are split);
    ``commit(v)`` persists one atomic batch and promotes the working
    view.  Reads (``get``/``pairs``/``prove``) always serve committed
    versions, never the working set.
    """

    def __init__(self, db: DB, memo_versions: int = 4):
        self._db = db
        self._lock = threading.RLock()
        self._memo_versions = max(1, memo_versions)
        # committed latest view
        self._map: dict[bytes, bytes] = {}
        self._sorted: list[bytes] = []
        self._leafh: dict[bytes, bytes] = {}
        # staged writes: key -> value | None (delete)
        self._working: dict[bytes, Optional[bytes]] = {}
        # working_root result awaiting commit:
        # (version, sorted_keys, map, leafh, root)
        self._pending = None
        # version -> (keys, values, leaf_hashes, index_of), LRU
        self._memo: OrderedDict[int, tuple] = OrderedDict()
        self.latest_version: Optional[int] = None
        self.base_version: int = 0
        self._roots: dict[int, bytes] = {}
        self._load()

    # -- open / recover -----------------------------------------------------

    def _load(self) -> None:
        raw = self._db.get(_META_LATEST)
        if raw is None:
            return
        (self.latest_version,) = struct.unpack(">Q", raw)
        base = self._db.get(_META_BASE)
        if base is not None:
            (self.base_version,) = struct.unpack(">Q", base)
        self._map = self._materialize(self.latest_version)
        self._sorted = sorted(self._map)
        leaves = [merkle.value_op_leaf(k, self._map[k])
                  for k in self._sorted]
        self._leafh = dict(zip(self._sorted, _leaf_hashes(leaves)))

    def _materialize(self, version: int) -> dict[bytes, bytes]:
        """Newest record <= version per key, tombstones dropped.  One
        ordered scan: records for one key are contiguous and
        version-ascending, so the last matching record wins."""
        out: dict[bytes, bytes] = {}
        for raw, rec in self._db.iterator(_NODE, _VERSION):
            key, ver = _split_node_key(raw[len(_NODE):])
            if ver > version:
                continue
            if rec[:1] == _TOMBSTONE:
                out.pop(key, None)
            else:
                out[key] = rec[1:]
        return out

    # -- reads (committed state only) ----------------------------------------

    def get(self, key: bytes, version: Optional[int] = None
            ) -> Optional[bytes]:
        with self._lock:
            if version is None or version == self.latest_version:
                return self._map.get(key)
            if self.latest_version is None or \
                    version > self.latest_version or \
                    version < self.base_version:
                return None
            prefix = _node_prefix(key)
            for _, rec in self._db.reverse_iterator(
                    prefix + _be64(0), prefix + _be64(version + 1)):
                return None if rec[:1] == _TOMBSTONE else rec[1:]
            return None

    def has(self, key: bytes, version: Optional[int] = None) -> bool:
        return self.get(key, version) is not None

    def pairs(self, version: Optional[int] = None
              ) -> list[tuple[bytes, bytes]]:
        """Sorted live (key, value) pairs at ``version`` (default
        latest)."""
        with self._lock:
            keys, values, _, _ = self._view(version)
            return list(zip(keys, values))

    def total(self, version: Optional[int] = None) -> int:
        with self._lock:
            if version is None or version == self.latest_version:
                return len(self._map)
            return len(self._view(version)[0])

    def root(self, version: Optional[int] = None) -> bytes:
        """Committed root at ``version`` (default latest); the empty
        tree root for a tree that never committed."""
        with self._lock:
            if self.latest_version is None:
                return merkle.empty_hash()
            v = self.latest_version if version is None else version
            r = self._roots.get(v)
            if r is not None:
                return r
            meta = self._version_meta(v)
            r = bytes.fromhex(meta["root"])
            self._roots[v] = r
            return r

    def reported_hash(self, version: Optional[int] = None) -> bytes:
        """The app_hash to report for ``version``: the migration
        override when one was recorded (pre-tree chains import under
        their legacy hash so handshake replay still matches), else
        the tree root."""
        with self._lock:
            if self.latest_version is None:
                return merkle.empty_hash()
            v = self.latest_version if version is None else version
            meta = self._version_meta(v)
            if "app_hash" in meta:
                return bytes.fromhex(meta["app_hash"])
            return bytes.fromhex(meta["root"])

    def version_extra(self, version: Optional[int] = None) -> dict:
        """App metadata stored with ``commit(..., extra=...)``."""
        with self._lock:
            if self.latest_version is None:
                return {}
            v = self.latest_version if version is None else version
            return self._version_meta(v).get("extra", {})

    def versions(self) -> list[int]:
        with self._lock:
            return [struct.unpack(">Q", raw[len(_VERSION):])[0]
                    for raw, _ in self._db.iterator(
                        _VERSION, _prefix_end(_VERSION))]

    def _version_meta(self, version: int) -> dict:
        raw = self._db.get(_VERSION + _be64(version))
        if raw is None:
            raise KeyError(f"state tree has no version {version}")
        return json.loads(raw)

    # -- writes ---------------------------------------------------------------

    def set(self, key: bytes, value: bytes) -> None:
        if not key:
            raise ValueError("state tree key cannot be empty")
        with self._lock:
            self._working[bytes(key)] = bytes(value)
            self._pending = None

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._working[bytes(key)] = None
            self._pending = None

    def reset_working(self) -> None:
        """Drop staged writes (a FinalizeBlock whose Commit never
        came — crash replay re-executes the block from scratch)."""
        with self._lock:
            self._working.clear()
            self._pending = None

    def working_root(self, version: int) -> bytes:
        """Root the next ``commit(version)`` will produce.  Computed
        incrementally from the latest committed view + the working
        set; cached so commit() reuses it."""
        with self._lock:
            if self._pending is not None and \
                    self._pending[0] == version:
                return self._pending[4]
            new_map = dict(self._map)
            new_leafh = dict(self._leafh)
            new_sorted = list(self._sorted)
            changed: list[bytes] = []
            import bisect
            for k, v in self._working.items():
                if v is None:
                    if k in new_map:
                        del new_map[k]
                        del new_leafh[k]
                        i = bisect.bisect_left(new_sorted, k)
                        new_sorted.pop(i)
                elif new_map.get(k) != v:
                    if k not in new_map:
                        bisect.insort(new_sorted, k)
                    new_map[k] = v
                    changed.append(k)
            if changed:
                hashes = _leaf_hashes(
                    [merkle.value_op_leaf(k, new_map[k])
                     for k in changed])
                new_leafh.update(zip(changed, hashes))
            root = merkle.root_from_leaf_hashes(
                [new_leafh[k] for k in new_sorted])
            self._pending = (version, new_sorted, new_map,
                             new_leafh, root)
            return root

    def commit(self, version: int,
               app_hash_override: Optional[bytes] = None,
               extra: Optional[dict] = None) -> bytes:
        """Persist the working set as ``version`` in one atomic batch
        and promote it to the committed view.  Re-committing the
        current latest version with an identical root is a no-op
        (InitChain replay after a crash before height 1); anything
        else non-monotonic is an error.  ``extra`` is app metadata
        stored in the version record — riding the same batch as the
        nodes, so app state and metadata can never diverge across a
        crash."""
        with self._lock:
            root = self.working_root(version)
            if self.latest_version is not None:
                if version == self.latest_version:
                    if root == self.root(version):
                        self._working.clear()
                        self._pending = None
                        return root
                    raise ValueError(
                        f"conflicting re-commit of version {version}")
                if version <= self.latest_version:
                    raise ValueError(
                        f"commit version {version} <= latest "
                        f"{self.latest_version}")
            _, new_sorted, new_map, new_leafh, _ = self._pending
            batch = self._db.new_batch()
            for k, v in self._working.items():
                if v is None:
                    if k in self._map:
                        batch.set(_node_key(k, version), _TOMBSTONE)
                elif self._map.get(k) != v:
                    batch.set(_node_key(k, version), _SET + v)
            meta = {"root": root.hex(), "total": len(new_sorted)}
            if app_hash_override is not None:
                meta["app_hash"] = app_hash_override.hex()
            if extra:
                meta["extra"] = dict(extra)
            batch.set(_VERSION + _be64(version),
                      json.dumps(meta).encode())
            batch.set(_META_LATEST, _be64(version))
            if self.latest_version is None:
                batch.set(_META_BASE, _be64(version))
                self.base_version = version
            batch.write()
            self._map, self._sorted, self._leafh = \
                new_map, new_sorted, new_leafh
            self.latest_version = version
            self._roots[version] = root
            self._working.clear()
            self._pending = None
            return root

    # -- proofs ---------------------------------------------------------------

    def _view(self, version: Optional[int]) -> tuple:
        """(keys, values, leaf_hashes, index_of) at ``version`` —
        latest from the live view, history via a memoized scan."""
        if self.latest_version is None:
            return [], [], [], {}
        v = self.latest_version if version is None else version
        if v == self.latest_version:
            keys = self._sorted
            values = [self._map[k] for k in keys]
            hashes = [self._leafh[k] for k in keys]
            return keys, values, hashes, \
                {k: i for i, k in enumerate(keys)}
        if v in self._memo:
            self._memo.move_to_end(v)
            return self._memo[v]
        if v > self.latest_version or v < self.base_version or \
                self._db.get(_VERSION + _be64(v)) is None:
            raise KeyError(f"state tree has no version {v}")
        m = self._materialize(v)
        keys = sorted(m)
        values = [m[k] for k in keys]
        hashes = _leaf_hashes(
            [merkle.value_op_leaf(k, m[k]) for k in keys])
        view = (keys, values, hashes,
                {k: i for i, k in enumerate(keys)})
        self._memo[v] = view
        while len(self._memo) > self._memo_versions:
            self._memo.popitem(last=False)
        return view

    def prove(self, request_keys: Iterable[bytes],
              version: Optional[int] = None) -> dict:
        """Proof envelope (proof.py) for ``request_keys`` — existence
        for present keys, non-inclusion for absent ones — at
        ``version`` (default latest)."""
        from .proof import build_proof_envelope
        with self._lock:
            keys, values, hashes, index_of = self._view(version)
            v = self.latest_version if version is None else version
            if v is None:
                v = 0
            return build_proof_envelope(
                list(request_keys), keys, values, hashes, index_of, v)

    # -- pruning / snapshots ---------------------------------------------------

    def prune(self, retain_from: int,
              pinned: Iterable[int] = ()) -> int:
        """Drop versions < ``retain_from`` except ``pinned`` ones
        (heights lightserve's ResponseCache can still serve — pruning
        one would break a cached-height proof).  Node records are
        compacted so every retained version still materializes the
        exact same pairs.  Returns the number of versions dropped."""
        with self._lock:
            if self.latest_version is None:
                return 0
            retain_from = min(retain_from, self.latest_version)
            pinned = {p for p in pinned if p >= self.base_version}
            keep = sorted({v for v in self.versions()
                           if v >= retain_from} | pinned)
            drop = [v for v in self.versions() if v not in keep]
            if not drop:
                return 0
            floor = keep[0]
            batch = self._db.new_batch()
            # per key: records at dropped versions are superseded by
            # the newest record <= each retained version.  Keep a
            # record iff it is the newest <= some kept version;
            # rewrite it AT that version when its own version was
            # dropped (so point reads bounded by [base, v] still see
            # it); drop the rest.
            kept_set = set(keep)
            by_key: dict[bytes, list[tuple[int, bytes, bytes]]] = {}
            for raw, rec in self._db.iterator(_NODE, _VERSION):
                key, ver = _split_node_key(raw[len(_NODE):])
                by_key.setdefault(key, []).append((ver, raw, rec))
            for key, recs in by_key.items():
                recs.sort()
                vers = [r[0] for r in recs]
                import bisect as _b
                needed: dict[int, tuple[int, bytes]] = {}
                for kv in keep:
                    i = _b.bisect_right(vers, kv) - 1
                    if i >= 0:
                        needed[vers[i]] = (kv, recs[i][2])
                for ver, raw, rec in recs:
                    if ver in needed:
                        at, _ = needed[ver]
                        if ver not in kept_set and ver < floor:
                            # re-anchor at the pruning floor so the
                            # record stays visible to every retained
                            # version >= floor that needs it
                            batch.delete(raw)
                            if rec[:1] != _TOMBSTONE:
                                batch.set(_node_key(key, floor), rec)
                    else:
                        batch.delete(raw)
            for v in drop:
                batch.delete(_VERSION + _be64(v))
                self._roots.pop(v, None)
                self._memo.pop(v, None)
            batch.set(_META_BASE, _be64(floor))
            batch.write()
            self.base_version = floor
            return len(drop)

    def import_snapshot(self, version: int,
                        pairs: Iterable[tuple[bytes, bytes]],
                        app_hash_override: Optional[bytes] = None,
                        extra: Optional[dict] = None) -> bytes:
        """Replace all tree content with ``pairs`` committed at
        ``version`` (statesync restore).  The resulting root is
        byte-identical to the snapshot producer's: same pairs, same
        sorted order, same leaf binding."""
        with self._lock:
            batch = self._db.new_batch()
            for raw, _ in self._db.iterator(None, None):
                batch.delete(raw)
            batch.write()
            self._map = {}
            self._sorted = []
            self._leafh = {}
            self._working = {}
            self._pending = None
            self._memo.clear()
            self._roots.clear()
            self.latest_version = None
            self.base_version = version
            for k, v in pairs:
                self.set(k, v)
            return self.commit(
                version, app_hash_override=app_hash_override,
                extra=extra)


def _prefix_end(prefix: bytes) -> bytes:
    from ..db.db import _prefix_end as pe
    return pe(prefix)
