"""Versioned, merkle-committed key-value state tree.

The missing link between ``abci_query_batch`` proofs and consensus
(ROADMAP item 3): the tree's per-version root IS the kvstore's
app_hash, so ``header.app_hash -> tree root -> key/value`` verifies
against any consensus-verified header — for present keys (existence)
and absent keys (non-inclusion via sorted-neighbor adjacency).
"""
from .tree import StateTree
from .proof import build_proof_envelope, verify_proof_envelope

__all__ = ["StateTree", "build_proof_envelope", "verify_proof_envelope"]
