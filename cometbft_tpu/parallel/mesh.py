"""Device-mesh sharding for the signature-verification / vote-tally offload.

Reference parallelism mapped (SURVEY §2.11): the reference's batch verifier
(crypto/ed25519/ed25519.go:189-222) is single-host; here very large batches
(>= 10k signatures, BASELINE config #5) shard across a TPU mesh — lanes are
data-parallel, and the vote-power tally reduces with an XLA psum over ICI.

Validators are WAN peers, so the mesh lives *inside* one node's TPU pod;
p2p traffic never touches ICI (SURVEY §5 "distributed communication backend").
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    _shard_map = jax.shard_map
except AttributeError:
    # dependency gate: jax < 0.5 ships shard_map under experimental;
    # the installed 0.4.37 has no top-level alias
    from jax.experimental.shard_map import shard_map as _shard_map

from ..ops.ed25519_jax import _verify_kernel

BATCH_AXIS = "sig_batch"


def make_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the first n_devices JAX devices."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"requested {n_devices}-device mesh but only "
                f"{len(devs)} JAX devices are available")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (BATCH_AXIS,))


@functools.lru_cache(maxsize=None)
def _sharded_verify_fn(ndev: int, kernel: str, interpret: bool,
                       block: int):
    """Jitted shard_map'ed batch verify over an ndev mesh; per-shard
    body is the selected kernel behind the packed uint8 wire layout
    (a/r [shard,32]u8, s/k [shard,64]u8 — every input shards on the
    lane axis and the int32 unpack runs per-device).  Cached per
    configuration — the jit itself caches per shape."""
    mesh = make_mesh(ndev)
    from ..ops.ed25519_jax import _byte_cols, _win_cols
    if kernel.startswith("pallas"):
        from ..ops.ed25519_jax import _pallas_module
        ep = _pallas_module(kernel)

        def body(a, r, s, k):
            return ep.verify_cols(
                _byte_cols(a), _byte_cols(r),
                _win_cols(s), _win_cols(k), interpret=interpret,
                block=block or ep.BLOCK)
    else:
        def body(a, r, s, k):
            return _verify_kernel(a, r, _win_cols(s), _win_cols(k))

    shard = _shard_map(
        body,
        mesh=mesh,
        in_specs=(P(BATCH_AXIS), P(BATCH_AXIS),
                  P(BATCH_AXIS), P(BATCH_AXIS)),
        out_specs=P(BATCH_AXIS),
    )
    return jax.jit(shard)


class PipelinePartitioner:
    """Per-pipeline pre-partitioning (SNIPPETS: pjit performs best
    when inputs arrive already partitioned per its in_specs — then
    the call never re-partitions).  The mesh, the NamedSharding and
    the jitted shard_map'ed kernel all resolve ONCE here; each tile
    of the verification pipeline then costs one async sharded
    ``device_put`` per input plus the jit call — multi-chip dispatch
    overhead is paid once per pipeline, not once per tile.

    ``dispatch`` returns the UN-forced device array (JAX async
    dispatch): the pipeline settles it with np.asarray only after the
    next tile is in flight."""

    def __init__(self, ndev: int, kernel: str = "xla",
                 interpret: bool = False, block: int = 0):
        from jax.sharding import NamedSharding
        if kernel.startswith("pallas"):
            from ..ops.ed25519_jax import _pallas_module
            block = block or _pallas_module(kernel).BLOCK
        else:
            interpret, block = False, 0     # ignored by the xla body
        self.ndev = ndev
        self.kernel = kernel
        self.block = block
        self.mesh = make_mesh(ndev)
        self.sharding = NamedSharding(self.mesh, P(BATCH_AXIS))
        self.fn = _sharded_verify_fn(ndev, kernel, interpret, block)

    def _padded(self, m: int) -> int:
        shard = -(-m // self.ndev)
        if self.block:
            shard = -(-shard // self.block) * self.block
        return shard * self.ndev

    def dispatch(self, a_b, r_b, s_w8, k_w8):
        m = a_b.shape[0]
        m2 = self._padded(m)
        if m2 != m:
            pad = m2 - m
            a_b = np.concatenate([a_b, np.zeros((pad, 32), a_b.dtype)])
            r_b = np.concatenate([r_b, np.zeros((pad, 32), r_b.dtype)])
            s_w8 = np.concatenate(
                [s_w8, np.zeros((pad, 64), s_w8.dtype)])
            k_w8 = np.concatenate(
                [k_w8, np.zeros((pad, 64), k_w8.dtype)])
        # async sharded transfers into the pre-resolved sharding —
        # the jitted call below sees correctly-partitioned inputs
        da = jax.device_put(a_b, self.sharding)
        dr = jax.device_put(r_b, self.sharding)
        ds = jax.device_put(s_w8, self.sharding)
        dk = jax.device_put(k_w8, self.sharding)
        return self.fn(da, dr, ds, dk)


@functools.lru_cache(maxsize=None)
def pipeline_partitioner(ndev: int, kernel: str = "xla",
                         interpret: bool = False,
                         block: int = 0) -> PipelinePartitioner:
    """Cached partitioner per (ndev, kernel, interpret, block) — the
    once-per-pipeline setup amortizes to once per process."""
    return PipelinePartitioner(ndev, kernel, interpret, block)


def verify_sharded(a_b, r_b, s_w8, k_w8, *, ndev: int,
                   kernel: str = "xla", interpret: bool = False,
                   block: int = 0) -> np.ndarray:
    """Data-parallel batch verify over all ndev devices (SURVEY §2.11:
    pjit/shard_map row).  Pads the lane count so every shard is equal
    (and, for pallas, a block multiple); padding lanes are garbage and
    simply sliced off — the caller masks pre-bad lanes itself.
    Returns the exact per-lane ok mask for the original m lanes."""
    m = a_b.shape[0]
    part = pipeline_partitioner(ndev, kernel, interpret, block)
    ok = np.asarray(part.dispatch(a_b, r_b, s_w8, k_w8))
    return ok[:m]


def sharded_verify_tally(mesh: Mesh):
    """Build the jitted multi-chip step: verify signatures sharded over the
    mesh; the collective is a psum of per-shard valid-lane counts.

    Returns fn(a_bytes[n,32]u8, r_bytes[n,32]u8, s_w8[n,64]u8,
               k_w8[n,64]u8) -> (ok[n] bool, valid_count i32)
    (s_w8/k_w8: lane-major 4-bit windows, ed25519_jax._windows_u8).

    n must be a multiple of the mesh size.  Voting-power totals are
    aggregated on the host from the exact per-lane mask: validator powers
    are int64 (total capped at MaxInt64/8, types/validator_set.go), which
    TPUs don't sum natively — the mask transfer is 1 byte/lane, so the
    host-side exact tally costs nothing at 10k lanes.
    """

    from ..ops.ed25519_jax import _win_cols

    def step(a, r, s, k):
        ok = _verify_kernel(a, r, _win_cols(s), _win_cols(k))
        count = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), BATCH_AXIS)
        return ok, count

    shard = _shard_map(
        step,
        mesh=mesh,
        in_specs=(P(BATCH_AXIS), P(BATCH_AXIS),
                  P(BATCH_AXIS), P(BATCH_AXIS)),
        out_specs=(P(BATCH_AXIS), P()),
    )
    return jax.jit(shard)
