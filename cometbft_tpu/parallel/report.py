"""Sharded-dispatch evidence for the 10k north star (VERDICT r4 #6).

The < 5 ms claim for the 10k commit has always rested on 8-way
sharding.  This module pins down what this environment CAN prove and
derives the sharded estimate from MEASURED single-chip numbers
(BENCH_CACHE.json when the round has one, else round 4's live-TPU
measurement), with every assumption stated in the artifact:

  * geometry: the production verify_sharded padding/rounding for
    m = 10240 over ndev devices (per-shard lanes, pallas grid steps);
  * collective structure: the shard_map'ed verify + tally steps are
    LOWERED on the virtual CPU mesh and the StableHLO is scanned —
    the verify path must contain NO cross-device collective (it is
    embarrassingly lane-parallel) and the tally must contain exactly
    the psum all-reduce;
  * execution: the sharded dispatch RUNS on the virtual mesh at a
    reduced lane count (the full 10k xla-kernel run costs ~7 min of
    serial CPU — the driver's dryrun budget forbids it; geometry and
    collectives don't change with lane count);
  * timing model: sharded_ms = per_shard_lanes x measured_us_per_lane
    + overhead_ms, with measured_us_per_lane = device_ms / bucket from
    the best single-chip hardware record, overhead bounded by the
    dispatch/launch cost measured on the same record's runs.

Run:  python -m cometbft_tpu.parallel.report   (writes SHARDING_10K.json)
"""
from __future__ import annotations

import json
import os
import sys

N_STAR = 10_000
BUCKET = 10_240
NDEV = 8

# Round-4 live-TPU measurement (KERNEL_NOTES.md "MEASURED on TPU
# v5e-1"): the 24-limb pallas kernel, device-only, m=16384 — the
# fallback calibration when the current round has no cache record.
R4_MEASURED = {"device_ms": 116.0, "bucket": 16384,
               "source": "round-4 live measurement (KERNEL_NOTES.md)"}


def _best_device_record() -> dict:
    from ..tools import tpu_probe
    recs = [r for r in tpu_probe.read_records()
            if r.get("platform") == "tpu" and "error" not in r
            and r.get("metric") == "pallas_device_only"
            and r.get("value_ms")]
    if not recs:
        return dict(R4_MEASURED)
    best = min(recs, key=lambda r: r["value_ms"] / r.get("bucket", 1))
    return {"device_ms": best["value_ms"], "bucket": best["bucket"],
            "source": f"BENCH_CACHE.json {best.get('ts')} "
                      f"rev {best.get('git_rev')}"}


def _collectives(hlo: str) -> list[str]:
    ops = []
    for marker in ("all-reduce", "all_reduce", "all-gather",
                   "all_gather", "collective-permute",
                   "collective_permute", "reduce-scatter",
                   "reduce_scatter", "all-to-all", "all_to_all"):
        if marker in hlo:
            ops.append(marker.replace("_", "-"))
    return sorted(set(ops))


def sharded_10k_report(ndev: int = NDEV, m: int = BUCKET,
                       run_lanes: int = 2048) -> dict:
    import numpy as np
    import jax

    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={ndev}").strip()
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from ..ops import ed25519_jax as ej
    from ..ops.ed25519_pallas import BLOCK
    from . import mesh as pmesh

    # --- geometry (mirrors verify_sharded's rounding) ---------------
    shard = -(-m // ndev)
    shard_pallas = -(-shard // BLOCK) * BLOCK
    geometry = {
        "n_signatures": N_STAR, "bucket": m, "devices": ndev,
        "per_shard_lanes": shard_pallas,
        "pallas_grid_steps_per_shard": shard_pallas // BLOCK,
        "block": BLOCK,
        "padded_total": shard_pallas * ndev,
    }

    # --- collective structure from the lowered shard_map ------------
    mesh = pmesh.make_mesh(ndev)
    a = jnp.zeros((shard_pallas * ndev, 32), jnp.uint8)
    w = jnp.zeros((shard_pallas * ndev, 64), jnp.uint8)
    verify_fn = pmesh._sharded_verify_fn(ndev, "xla", False, 0)
    verify_hlo = verify_fn.lower(a, a, w, w).as_text()
    tally_fn = pmesh.sharded_verify_tally(mesh)
    tally_hlo = tally_fn.lower(a, a, w, w).as_text()
    collectives = {
        "verify_path": _collectives(verify_hlo),
        "tally_path": _collectives(tally_hlo),
    }

    # --- execution on the virtual mesh at reduced lanes -------------
    from ..crypto import _ed25519_ref as ref
    items, golden = [], []
    for i in range(run_lanes // 256):
        seed = bytes([i + 1]) * 32
        pub = ref.public_key(seed)
        msg = b"shard-%d" % i
        sig = ref.sign(seed, msg)
        if i % 4 == 3:
            sig = sig[:32] + bytes(32)
        items.append((pub, msg, sig))
        golden.append(ref.verify(pub, msg, sig))
    a_b, r_b, s_w8, k_w8, pre_bad = ej.prep_arrays(items, run_lanes)
    import numpy as _np
    ok = _np.array(pmesh.verify_sharded(a_b, r_b, s_w8, k_w8,
                                        ndev=ndev, kernel="xla"))
    ok = ok[:len(items)]
    ok[pre_bad[:len(items)]] = False
    executed = bool(list(ok) == golden)

    # --- timing model from measured numbers -------------------------
    cal = _best_device_record()
    us_per_lane = cal["device_ms"] * 1000.0 / cal["bucket"]
    # dispatch overhead: bounded by the spread of the measured runs
    # (launch + sync, single chip); use 0.5 ms/chip as the stated cap
    overhead_ms = 0.5
    sharded_ms = geometry["per_shard_lanes"] * us_per_lane / 1000.0 \
        + overhead_ms
    single_ms = BUCKET * us_per_lane / 1000.0
    model = {
        "calibration": cal,
        "us_per_lane_measured": round(us_per_lane, 3),
        "assumptions": [
            "perfect lane scaling (the verify path has no cross-"
            "device collective - checked above; lanes are fully "
            "data-parallel at [24,128] slab granularity)",
            f"per-chip dispatch overhead <= {overhead_ms} ms "
            "(launch + output sync; the mask all-gather is 1 byte/"
            "lane = 1.3 kB/chip, negligible on ICI)",
            "every chip runs the same kernel the single-chip "
            "measurement ran (same AOT artifact, smaller grid)",
        ],
        "single_chip_10240_ms": round(single_ms, 1),
        "sharded_8way_ms": round(sharded_ms, 1),
        "north_star_ms": 5.0,
        "verdict": (
            "MEETS < 5 ms" if sharded_ms < 5.0 else
            f"MISSES < 5 ms at {sharded_ms:.1f} ms with the measured "
            f"kernel: needs ~{sharded_ms / 5.0:.1f}x more chips or "
            "kernel speedup (see KERNEL_NOTES round-5 floor "
            "analysis)"),
    }
    return {"geometry": geometry, "collectives": collectives,
            "executed_reduced": {"lanes": run_lanes, "ok": executed},
            "timing_model": model}


def main() -> int:
    rep = sharded_10k_report()
    out = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "SHARDING_10K.json")
    with open(out, "w") as f:
        json.dump(rep, f, indent=1)
        f.write("\n")
    print(json.dumps(rep["timing_model"], indent=1), file=sys.stderr)
    print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
