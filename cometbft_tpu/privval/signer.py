"""Remote signer: socket privval protocol.

Reference: privval/ —
  * the NODE listens on priv_validator_laddr and the signer process dials
    in (signer_listener_endpoint.go on the node side, signer_server.go +
    signer_dialer_endpoint.go on the signer side);
  * messages are uvarint-length-delimited privval.v2 Message frames
    (msgs.go), request/response in lockstep over one connection;
  * SignerClient implements the PrivValidator interface over the wire
    (signer_client.go); RetrySignerClient wraps it with bounded retries
    (retry_signer_client.go);
  * the double-sign state machine (FilePV's last-sign HRS rules) lives in
    the SIGNER process, so a compromised node cannot make the key
    equivocate.

Runnable signer:  python -m cometbft_tpu.privval.signer \
    --address tcp://127.0.0.1:26659 --chain-id my-chain \
    --key-file priv_validator_key.json \
    --state-file priv_validator_state.json
"""
from __future__ import annotations

import argparse
import asyncio
from typing import Optional

from ..libs.log import Logger, new_logger
from ..types import canonical
from ..types.priv_validator import PrivValidator
from ..types.proposal import Proposal
from ..types.vote import Vote
from ..wire import decode, encode, privval_pb
from .file import DoubleSignError, FilePV, PrivValidatorError


class RemoteSignerError(PrivValidatorError):
    pass


def _frame(msg: dict) -> bytes:
    from ..libs.protoio import write_delimited
    return write_delimited(encode(privval_pb.MESSAGE, msg))


async def _read_frame(reader: asyncio.StreamReader) -> Optional[dict]:
    from ..libs.protoio import read_delimited
    payload = await read_delimited(reader, 1 << 20, RemoteSignerError)
    if payload is None:
        return None
    return decode(privval_pb.MESSAGE, payload)


# --- node side --------------------------------------------------------------

class SignerListenerEndpoint:
    """The node's end: listen, accept ONE signer connection, serialize
    request/response exchanges (reference: signer_listener_endpoint.go)."""

    def __init__(self, laddr: str, timeout_s: float = 5.0,
                 logger: Optional[Logger] = None):
        self.laddr = laddr
        self.timeout_s = timeout_s
        self.logger = logger or new_logger("privval-listener")
        self._server: Optional[asyncio.AbstractServer] = None
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._connected = asyncio.Event()
        self._lock = asyncio.Lock()

    async def start(self) -> None:
        from ..abci.server import parse_address
        scheme, host, port = parse_address(self.laddr)
        if scheme == "unix":
            self._server = await asyncio.start_unix_server(
                self._on_connect, path=host)
        else:
            self._server = await asyncio.start_server(
                self._on_connect, host=host, port=port)
        self.logger.info("privval listening for remote signer",
                         addr=self.laddr)

    @property
    def listen_addr(self) -> str:
        socks = self._server.sockets if self._server else []
        if socks:
            name = socks[0].getsockname()
            if isinstance(name, tuple):
                return f"tcp://{name[0]}:{name[1]}"
            return f"unix://{name}"
        return self.laddr

    async def _on_connect(self, reader, writer) -> None:
        if self._writer is not None:
            writer.close()                  # one signer at a time
            return
        self._reader, self._writer = reader, writer
        self._connected.set()
        self.logger.info("remote signer connected")

    async def wait_for_signer(self, timeout_s: float = 30.0) -> None:
        await asyncio.wait_for(self._connected.wait(), timeout_s)

    async def request(self, msg: dict) -> dict:
        async with self._lock:
            if self._writer is None:
                raise RemoteSignerError("no signer connected")
            try:
                self._writer.write(_frame(msg))
                await self._writer.drain()
                resp = await asyncio.wait_for(
                    _read_frame(self._reader), self.timeout_s)
            except (ConnectionError, asyncio.IncompleteReadError,
                    asyncio.TimeoutError) as e:
                self._drop_conn()
                raise RemoteSignerError(
                    f"remote signer request failed: {e!r}") from None
            if resp is None:
                self._drop_conn()
                raise RemoteSignerError("remote signer closed")
            return resp

    def _drop_conn(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
        self._reader = self._writer = None
        self._connected.clear()

    async def stop(self) -> None:
        self._drop_conn()
        if self._server is not None:
            self._server.close()


_ERR_CODE_DOUBLE_SIGN = 3


def _raise_on_error(resp_body: dict) -> None:
    err = resp_body.get("error")
    if err:
        desc = err.get("description", "")
        if err.get("code") == _ERR_CODE_DOUBLE_SIGN:
            raise DoubleSignError(desc)
        raise RemoteSignerError(desc or f"code {err.get('code')}")


class SignerClient(PrivValidator):
    """PrivValidator over the socket (reference: signer_client.go)."""

    def __init__(self, endpoint: SignerListenerEndpoint, chain_id: str):
        self.endpoint = endpoint
        self.chain_id = chain_id
        self._pub_key = None

    async def ping(self) -> None:
        resp = await self.endpoint.request({"ping_request": {}})
        if "ping_response" not in resp:
            raise RemoteSignerError(f"unexpected reply {sorted(resp)}")

    async def fetch_pub_key(self):
        from ..crypto import encoding as crypto_encoding
        resp = await self.endpoint.request(
            {"pub_key_request": {"chain_id": self.chain_id}})
        body = resp.get("pub_key_response")
        if body is None:
            raise RemoteSignerError(f"unexpected reply {sorted(resp)}")
        _raise_on_error(body)
        self._pub_key = crypto_encoding.pub_key_from_type_and_bytes(
            body.get("pub_key_type", "ed25519"),
            body.get("pub_key_bytes", b""))
        return self._pub_key

    def get_pub_key(self):
        if self._pub_key is None:
            raise RemoteSignerError(
                "pub key not fetched yet (call fetch_pub_key)")
        return self._pub_key

    # async signing surface; ConsensusState dispatches through its
    # _pv_sign_vote/_pv_sign_proposal helpers, which await these when
    # present and fall back to the sync PrivValidator methods otherwise
    async def sign_vote_async(self, chain_id: str, vote: Vote,
                              sign_extension: bool) -> None:
        resp = await self.endpoint.request({"sign_vote_request": {
            "vote": vote.to_proto(), "chain_id": chain_id,
            "skip_extension_signing": not sign_extension,
        }})
        body = resp.get("signed_vote_response")
        if body is None:
            raise RemoteSignerError(f"unexpected reply {sorted(resp)}")
        _raise_on_error(body)
        signed = Vote.from_proto(body.get("vote") or {})
        vote.signature = signed.signature
        vote.timestamp = signed.timestamp
        vote.extension_signature = signed.extension_signature
        vote.non_rp_extension_signature = \
            signed.non_rp_extension_signature

    async def sign_proposal_async(self, chain_id: str,
                                  proposal: Proposal) -> None:
        resp = await self.endpoint.request({"sign_proposal_request": {
            "proposal": proposal.to_proto(), "chain_id": chain_id,
        }})
        body = resp.get("signed_proposal_response")
        if body is None:
            raise RemoteSignerError(f"unexpected reply {sorted(resp)}")
        _raise_on_error(body)
        signed = Proposal.from_proto(body.get("proposal") or {})
        proposal.signature = signed.signature
        proposal.timestamp = signed.timestamp

    # sync PrivValidator interface (used by code paths that don't await):
    def sign_vote(self, chain_id: str, vote: Vote,
                  sign_extension: bool) -> None:
        raise RemoteSignerError(
            "SignerClient is async; use sign_vote_async")

    def sign_proposal(self, chain_id: str, proposal: Proposal) -> None:
        raise RemoteSignerError(
            "SignerClient is async; use sign_proposal_async")


class RetrySignerClient(PrivValidator):
    """Bounded retry wrapper (reference: retry_signer_client.go).
    Double-sign refusals are NEVER retried — they are final."""

    def __init__(self, client: SignerClient, retries: int = 5,
                 delay_s: float = 0.2):
        self.client = client
        self.retries = retries
        self.delay_s = delay_s

    def get_pub_key(self):
        return self.client.get_pub_key()

    async def _retry(self, coro_fn, *args):
        last: Exception = RemoteSignerError("no attempts")
        for _ in range(self.retries):
            try:
                return await coro_fn(*args)
            except DoubleSignError:
                raise
            except (RemoteSignerError, PrivValidatorError) as e:
                last = e
                await asyncio.sleep(self.delay_s)
        raise last

    async def fetch_pub_key(self):
        return await self._retry(self.client.fetch_pub_key)

    async def sign_vote_async(self, chain_id, vote, sign_extension):
        return await self._retry(self.client.sign_vote_async, chain_id,
                                 vote, sign_extension)

    async def sign_proposal_async(self, chain_id, proposal):
        return await self._retry(self.client.sign_proposal_async,
                                 chain_id, proposal)

    def sign_vote(self, chain_id, vote, sign_extension):
        raise RemoteSignerError(
            "SignerClient is async; use sign_vote_async")

    def sign_proposal(self, chain_id, proposal):
        raise RemoteSignerError(
            "SignerClient is async; use sign_proposal_async")


# --- signer side ------------------------------------------------------------

class SignerServer:
    """The external signer process: dial the node, serve signing requests
    from a FilePV (reference: signer_server.go + signer_dialer_endpoint).
    The FilePV's HRS state machine enforces double-sign protection here,
    across restarts, regardless of what the node asks for."""

    def __init__(self, address: str, chain_id: str, pv: FilePV,
                 logger: Optional[Logger] = None,
                 retries: int = 40, retry_delay_s: float = 0.25):
        self.address = address
        self.chain_id = chain_id
        self.pv = pv
        self.logger = logger or new_logger("signer-server")
        self.retries = retries
        self.retry_delay_s = retry_delay_s
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _connect(self):
        from ..abci.server import parse_address
        scheme, host, port = parse_address(self.address)
        last = None
        for _ in range(self.retries):
            try:
                if scheme == "unix":
                    return await asyncio.open_unix_connection(host)
                return await asyncio.open_connection(host, port)
            except OSError as e:
                last = e
                await asyncio.sleep(self.retry_delay_s)
        raise RemoteSignerError(f"cannot reach node: {last}")

    async def _run(self) -> None:
        while True:
            try:
                reader, writer = await self._connect()
                self.logger.info("connected to node",
                                 addr=self.address)
                await self.serve_conn(reader, writer)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — reconnect loop
                self.logger.error("signer connection lost",
                                  err=str(e))
                await asyncio.sleep(self.retry_delay_s)

    async def serve_conn(self, reader, writer) -> None:
        while True:
            req = await _read_frame(reader)
            if req is None:
                raise RemoteSignerError("node closed connection")
            writer.write(_frame(self._handle(req)))
            await writer.drain()

    def _handle(self, req: dict) -> dict:
        if "ping_request" in req:
            return {"ping_response": {}}
        if "pub_key_request" in req:
            pub = self.pv.get_pub_key()
            return {"pub_key_response": {
                "pub_key_bytes": pub.bytes(),
                "pub_key_type": pub.type()}}
        if "sign_vote_request" in req:
            body = req["sign_vote_request"]
            vote = Vote.from_proto(body.get("vote") or {})
            try:
                self.pv.sign_vote(
                    body.get("chain_id", self.chain_id), vote,
                    sign_extension=not body.get(
                        "skip_extension_signing", False))
            except DoubleSignError as e:
                return {"signed_vote_response": {
                    "vote": {}, "error": {
                        "code": _ERR_CODE_DOUBLE_SIGN,
                        "description": str(e)}}}
            except PrivValidatorError as e:
                return {"signed_vote_response": {
                    "vote": {}, "error": {"code": 2,
                                          "description": str(e)}}}
            return {"signed_vote_response": {"vote": vote.to_proto()}}
        if "sign_proposal_request" in req:
            body = req["sign_proposal_request"]
            proposal = Proposal.from_proto(body.get("proposal") or {})
            try:
                self.pv.sign_proposal(
                    body.get("chain_id", self.chain_id), proposal)
            except DoubleSignError as e:
                return {"signed_proposal_response": {
                    "proposal": {}, "error": {
                        "code": _ERR_CODE_DOUBLE_SIGN,
                        "description": str(e)}}}
            except PrivValidatorError as e:
                return {"signed_proposal_response": {
                    "proposal": {}, "error": {"code": 2,
                                              "description": str(e)}}}
            return {"signed_proposal_response": {
                "proposal": proposal.to_proto()}}
        if "sign_bytes_request" in req:
            try:
                sig = self.pv.sign_bytes(
                    req["sign_bytes_request"].get("value", b""))
            except PrivValidatorError as e:
                return {"sign_bytes_response": {
                    "error": {"code": 2, "description": str(e)}}}
            return {"sign_bytes_response": {"signature": sig}}
        return {"ping_response": {}}        # unknown: benign reply


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="remote signer process "
                    "(reference: cmd/priv_val_server)")
    ap.add_argument("--address", required=True,
                    help="node's priv_validator_laddr to dial")
    ap.add_argument("--chain-id", default="")
    ap.add_argument("--key-file", required=True)
    ap.add_argument("--state-file", required=True)
    args = ap.parse_args(argv)
    pv = FilePV.load(args.key_file, args.state_file)

    async def run():
        srv = SignerServer(args.address, args.chain_id, pv)
        await srv.start()
        await srv._task

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
