"""Validator key management and signing with double-sign protection."""
from .file import FilePV, DoubleSignError, PrivValidatorError

__all__ = ["FilePV", "DoubleSignError", "PrivValidatorError"]
