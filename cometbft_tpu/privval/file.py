"""FilePV: file-backed validator signer with double-sign protection.

Reference: privval/file.go — persisted last-signed HRS state (:100
CheckHRS), sign-vote (:281/:332) with the same-HRS recovery rules
(identical sign-bytes reuse the signature; timestamp-only differences
reuse signature + old timestamp; anything else is a double-sign
attempt), fsync'd state file before every signature leaves the process.
"""
from __future__ import annotations

import base64
import json
import os
from dataclasses import dataclass, field
from typing import Optional

from ..crypto import ed25519
from ..crypto import encoding as crypto_encoding
from ..crypto.keys import PrivKey, PubKey
from ..types import canonical
from ..types.priv_validator import PrivValidator
from ..types.proposal import Proposal
from ..types.timestamp import Timestamp
from ..types.vote import Vote
from ..wire import pb, unmarshal_delimited

# amino-JSON type names (single registry: crypto/encoding.py)
_AMINO_NAMES = {
    kt: (crypto_encoding.AMINO_PUBKEY_NAMES[kt],
         crypto_encoding.AMINO_PRIVKEY_NAMES[kt])
    for kt in crypto_encoding.AMINO_PUBKEY_NAMES
}
_KEY_TYPE_BY_PRIV_NAME = {v[1]: k for k, v in _AMINO_NAMES.items()}

# sign step (reference: privval/file.go stepPropose/Prevote/Precommit)
STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3

_STEP_BY_VOTE_TYPE = {
    canonical.PREVOTE_TYPE: STEP_PREVOTE,
    canonical.PRECOMMIT_TYPE: STEP_PRECOMMIT,
}


class PrivValidatorError(Exception):
    pass


class DoubleSignError(PrivValidatorError):
    pass


@dataclass
class LastSignState:
    height: int = 0
    round: int = 0
    step: int = 0
    signature: bytes = b""
    sign_bytes: bytes = b""

    def check_hrs(self, height: int, round_: int,
                  step: int) -> bool:
        """True when (height, round, step) matches the last signed HRS;
        raises on regression (reference: CheckHRS :100)."""
        if self.height > height:
            raise DoubleSignError(
                f"height regression: got {height}, last {self.height}")
        if self.height != height:
            return False
        if self.round > round_:
            raise DoubleSignError(
                f"round regression at height {height}: got {round_}, "
                f"last {self.round}")
        if self.round != round_:
            return False
        if self.step > step:
            raise DoubleSignError(
                f"step regression at {height}/{round_}: got {step}, "
                f"last {self.step}")
        if self.step < step:
            return False
        if not self.sign_bytes:
            raise PrivValidatorError("no SignBytes found")
        if not self.signature:
            raise PrivValidatorError(
                "signature is empty but sign bytes are not")
        return True

    def to_json(self) -> dict:
        return {
            "height": str(self.height),
            "round": self.round,
            "step": self.step,
            "signature": base64.b64encode(self.signature).decode()
            if self.signature else "",
            "signbytes": self.sign_bytes.hex().upper()
            if self.sign_bytes else "",
        }

    @classmethod
    def from_json(cls, d: dict) -> "LastSignState":
        return cls(
            height=int(d.get("height", 0)),
            round=int(d.get("round", 0)),
            step=int(d.get("step", 0)),
            signature=base64.b64decode(d["signature"])
            if d.get("signature") else b"",
            sign_bytes=bytes.fromhex(d["signbytes"])
            if d.get("signbytes") else b"",
        )


class FilePV(PrivValidator):
    def __init__(self, priv_key: PrivKey, key_file_path: str,
                 state_file_path: str,
                 last_sign_state: Optional[LastSignState] = None):
        self.priv_key = priv_key
        self.key_file_path = key_file_path
        self.state_file_path = state_file_path
        self.last_sign_state = last_sign_state or LastSignState()

    # ------------------------------------------------------------------
    @classmethod
    def generate(cls, key_file_path: str, state_file_path: str,
                 key_type: str = ed25519.KEY_TYPE) -> "FilePV":
        """Reference: privval.GenFilePV with keytypes registry (testnet
        --key-type flag)."""
        pv = cls(crypto_encoding.gen_priv_key_by_type(key_type),
                 key_file_path, state_file_path)
        pv.save()
        return pv

    @classmethod
    def load(cls, key_file_path: str,
             state_file_path: str) -> "FilePV":
        with open(key_file_path) as f:
            kd = json.load(f)
        amino_name = kd["priv_key"].get("type",
                                        "tendermint/PrivKeyEd25519")
        key_type = _KEY_TYPE_BY_PRIV_NAME.get(amino_name)
        if key_type is None:
            raise PrivValidatorError(
                f"unknown priv_key type {amino_name!r}")
        priv = crypto_encoding.priv_key_from_type_and_bytes(
            key_type, base64.b64decode(kd["priv_key"]["value"]))
        lss = LastSignState()
        if os.path.exists(state_file_path):
            with open(state_file_path) as f:
                lss = LastSignState.from_json(json.load(f))
        return cls(priv, key_file_path, state_file_path, lss)

    @classmethod
    def load_or_generate(cls, key_file_path: str, state_file_path: str,
                         key_type: str = ed25519.KEY_TYPE) -> "FilePV":
        if os.path.exists(key_file_path):
            return cls.load(key_file_path, state_file_path)
        return cls.generate(key_file_path, state_file_path, key_type)

    def save(self) -> None:
        pub = self.priv_key.pub_key()
        os.makedirs(os.path.dirname(self.key_file_path) or ".",
                    exist_ok=True)
        with open(self.key_file_path, "w") as f:
            json.dump({
                "address": pub.address().hex().upper(),
                "pub_key": {"type": _AMINO_NAMES[pub.type()][0],
                            "value": base64.b64encode(
                                pub.bytes()).decode()},
                "priv_key": {"type": _AMINO_NAMES[pub.type()][1],
                             "value": base64.b64encode(
                                 self.priv_key.bytes()).decode()},
            }, f, indent=2)
        os.chmod(self.key_file_path, 0o600)  # private key: owner-only
        self._save_state()

    def _save_state(self) -> None:
        """Durably record the last-signed state BEFORE the signature can
        leave the process (the double-sign barrier)."""
        os.makedirs(os.path.dirname(self.state_file_path) or ".",
                    exist_ok=True)
        tmp = self.state_file_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.last_sign_state.to_json(), f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.state_file_path)
        os.chmod(self.state_file_path, 0o600)

    # ------------------------------------------------------------------
    def get_pub_key(self) -> PubKey:
        return self.priv_key.pub_key()

    def sign_vote(self, chain_id: str, vote: Vote,
                  sign_extension: bool) -> None:
        """Reference: signVote (:332)."""
        height, round_ = vote.height, vote.round
        step = _STEP_BY_VOTE_TYPE.get(vote.type)
        if step is None:
            raise PrivValidatorError(f"unknown vote type {vote.type}")
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, step)
        sign_bytes = vote.sign_bytes(chain_id)

        if sign_extension:
            if vote.type == canonical.PRECOMMIT_TYPE and \
                    not vote.block_id.is_nil():
                # extensions are non-deterministic; always re-sign them
                vote.extension_signature = self.priv_key.sign(
                    vote.extension_sign_bytes(chain_id))
                vote.non_rp_extension_signature = self.priv_key.sign(
                    vote.non_rp_extension_sign_bytes())
            elif vote.extension or vote.non_rp_extension:
                raise PrivValidatorError(
                    "unexpected vote extension on non-nil-precommit")

        if same_hrs:
            # crashed between signing and WAL write: recover
            if sign_bytes == lss.sign_bytes:
                vote.signature = lss.signature
                return
            ts = _votes_differ_only_by_timestamp(lss.sign_bytes,
                                                 sign_bytes)
            if ts is not None:
                vote.timestamp = ts
                vote.signature = lss.signature
                return
            raise DoubleSignError(
                f"conflicting vote data at {height}/{round_}/{step}")

        sig = self.priv_key.sign(sign_bytes)
        self.last_sign_state = LastSignState(
            height=height, round=round_, step=step, signature=sig,
            sign_bytes=sign_bytes)
        self._save_state()
        vote.signature = sig

    def sign_proposal(self, chain_id: str,
                      proposal: Proposal) -> None:
        """Reference: signProposal."""
        height, round_ = proposal.height, proposal.round
        lss = self.last_sign_state
        same_hrs = lss.check_hrs(height, round_, STEP_PROPOSE)
        sign_bytes = proposal.sign_bytes(chain_id)

        if same_hrs:
            if sign_bytes == lss.sign_bytes:
                proposal.signature = lss.signature
                return
            ts = _proposals_differ_only_by_timestamp(lss.sign_bytes,
                                                     sign_bytes)
            if ts is not None:
                proposal.timestamp = ts
                proposal.signature = lss.signature
                return
            raise DoubleSignError(
                f"conflicting proposal data at {height}/{round_}")

        sig = self.priv_key.sign(sign_bytes)
        self.last_sign_state = LastSignState(
            height=height, round=round_, step=STEP_PROPOSE,
            signature=sig, sign_bytes=sign_bytes)
        self._save_state()
        proposal.signature = sig

    def sign_bytes(self, msg: bytes) -> bytes:
        return self.priv_key.sign(msg)

    def reset(self) -> None:
        """Danger: wipes double-sign protection (reference:
        unsafe_reset_priv_validator)."""
        self.last_sign_state = LastSignState()
        self._save_state()


def _strip_timestamp(desc, raw: bytes, ts_field: str):
    """Decode a canonical sign-bytes message and return (fields minus
    timestamp, timestamp)."""
    d, _ = unmarshal_delimited(desc, raw)
    ts = d.pop(ts_field, None)
    return d, ts


def _votes_differ_only_by_timestamp(last: bytes,
                                    new: bytes) -> Optional[Timestamp]:
    """Reference: checkVotesOnlyDifferByTimestamp — returns the LAST
    timestamp when everything else matches."""
    try:
        d1, ts1 = _strip_timestamp(pb.CANONICAL_VOTE, last, "timestamp")
        d2, _ = _strip_timestamp(pb.CANONICAL_VOTE, new, "timestamp")
    except Exception:
        return None
    if d1 == d2 and ts1 is not None:
        return Timestamp.from_proto(ts1)
    return None


def _proposals_differ_only_by_timestamp(last: bytes, new: bytes
                                        ) -> Optional[Timestamp]:
    try:
        d1, ts1 = _strip_timestamp(pb.CANONICAL_PROPOSAL, last,
                                   "timestamp")
        d2, _ = _strip_timestamp(pb.CANONICAL_PROPOSAL, new,
                                 "timestamp")
    except Exception:
        return None
    if d1 == d2 and ts1 is not None:
        return Timestamp.from_proto(ts1)
    return None
