"""Wire descriptors for cometbft.abci.v2 (subset used on disk and over
the socket protocol).

Reference: proto/cometbft/abci/v2/types.proto.
"""
from .proto import F, Msg
from .pb import CONSENSUS_PARAMS, PROOF_OPS, TIMESTAMP, DURATION

EVENT_ATTRIBUTE = Msg(
    "cometbft.abci.v2.EventAttribute",
    F(1, "key", "string"),
    F(2, "value", "string"),
    F(3, "index", "bool"),
)

EVENT = Msg(
    "cometbft.abci.v2.Event",
    F(1, "type", "string"),
    F(2, "attributes", "msg", msg=EVENT_ATTRIBUTE, repeated=True),
)

EXEC_TX_RESULT = Msg(
    "cometbft.abci.v2.ExecTxResult",
    F(1, "code", "uint32"),
    F(2, "data", "bytes"),
    F(3, "log", "string"),
    F(4, "info", "string"),
    F(5, "gas_wanted", "int64"),
    F(6, "gas_used", "int64"),
    F(7, "events", "msg", msg=EVENT, repeated=True),
    F(8, "codespace", "string"),
    # local extension (high tag, clear of upstream fields): app-
    # reported state keys for incremental mempool recheck; excluded
    # from the results hash like log/info/events
    F(100, "recheck_keys", "bytes", repeated=True),
)

TX_RESULT = Msg(
    "cometbft.abci.v2.TxResult",
    F(1, "height", "int64"),
    F(2, "index", "uint32"),
    F(3, "tx", "bytes"),
    F(4, "result", "msg", msg=EXEC_TX_RESULT, always=True),
)

ABCI_VALIDATOR = Msg(
    "cometbft.abci.v2.Validator",
    F(1, "address", "bytes"),
    F(3, "power", "int64"),
)

VALIDATOR_UPDATE = Msg(
    "cometbft.abci.v2.ValidatorUpdate",
    F(2, "power", "int64"),
    F(3, "pub_key_bytes", "bytes"),
    F(4, "pub_key_type", "string"),
)

VOTE_INFO = Msg(
    "cometbft.abci.v2.VoteInfo",
    F(1, "validator", "msg", msg=ABCI_VALIDATOR, always=True),
    F(3, "block_id_flag", "enum"),
)

EXTENDED_VOTE_INFO = Msg(
    "cometbft.abci.v2.ExtendedVoteInfo",
    F(1, "validator", "msg", msg=ABCI_VALIDATOR, always=True),
    F(3, "vote_extension", "bytes"),
    F(4, "extension_signature", "bytes"),
    F(5, "block_id_flag", "enum"),
    F(6, "non_rp_vote_extension", "bytes"),
    F(7, "non_rp_extension_signature", "bytes"),
)

COMMIT_INFO = Msg(
    "cometbft.abci.v2.CommitInfo",
    F(1, "round", "int32"),
    F(2, "votes", "msg", msg=VOTE_INFO, repeated=True),
)

EXTENDED_COMMIT_INFO = Msg(
    "cometbft.abci.v2.ExtendedCommitInfo",
    F(1, "round", "int32"),
    F(2, "votes", "msg", msg=EXTENDED_VOTE_INFO, repeated=True),
)

MISBEHAVIOR = Msg(
    "cometbft.abci.v2.Misbehavior",
    F(1, "type", "enum"),
    F(2, "validator", "msg", msg=ABCI_VALIDATOR, always=True),
    F(3, "height", "int64"),
    F(4, "time", "msg", msg=TIMESTAMP, always=True),
    F(5, "total_voting_power", "int64"),
)

SNAPSHOT = Msg(
    "cometbft.abci.v2.Snapshot",
    F(1, "height", "uint64"),
    F(2, "format", "uint32"),
    F(3, "chunks", "uint32"),
    F(4, "hash", "bytes"),
    F(5, "metadata", "bytes"),
)

FINALIZE_BLOCK_RESPONSE = Msg(
    "cometbft.abci.v2.FinalizeBlockResponse",
    F(1, "events", "msg", msg=EVENT, repeated=True),
    F(2, "tx_results", "msg", msg=EXEC_TX_RESULT, repeated=True),
    F(3, "validator_updates", "msg", msg=VALIDATOR_UPDATE, repeated=True),
    F(4, "consensus_param_updates", "msg", msg=CONSENSUS_PARAMS),
    F(5, "app_hash", "bytes"),
    F(6, "next_block_delay", "msg", msg=DURATION, always=True),
)


# ---------------------------------------------------------------------------
# Socket-protocol envelope: Request/Response oneofs and every method message.
# Reference: proto/cometbft/abci/v2/types.proto (Request :18-36,
# Response :222-244) and abci/client/socket_client.go's length-delimited
# framing.

ECHO_REQUEST = Msg("cometbft.abci.v2.EchoRequest", F(1, "message", "string"))
FLUSH_REQUEST = Msg("cometbft.abci.v2.FlushRequest")
INFO_REQUEST = Msg(
    "cometbft.abci.v2.InfoRequest",
    F(1, "version", "string"),
    F(2, "block_version", "uint64"),
    F(3, "p2p_version", "uint64"),
    F(4, "abci_version", "string"),
)
INIT_CHAIN_REQUEST = Msg(
    "cometbft.abci.v2.InitChainRequest",
    F(1, "time", "msg", msg=TIMESTAMP, always=True),
    F(2, "chain_id", "string"),
    F(3, "consensus_params", "msg", msg=CONSENSUS_PARAMS),
    F(4, "validators", "msg", msg=VALIDATOR_UPDATE, repeated=True),
    F(5, "app_state_bytes", "bytes"),
    F(6, "initial_height", "int64"),
)
QUERY_REQUEST = Msg(
    "cometbft.abci.v2.QueryRequest",
    F(1, "data", "bytes"),
    F(2, "path", "string"),
    F(3, "height", "int64"),
    F(4, "prove", "bool"),
)
CHECK_TX_REQUEST = Msg(
    "cometbft.abci.v2.CheckTxRequest",
    F(1, "tx", "bytes"),
    F(3, "type", "enum"),
)
COMMIT_REQUEST = Msg("cometbft.abci.v2.CommitRequest")
LIST_SNAPSHOTS_REQUEST = Msg("cometbft.abci.v2.ListSnapshotsRequest")
OFFER_SNAPSHOT_REQUEST = Msg(
    "cometbft.abci.v2.OfferSnapshotRequest",
    F(1, "snapshot", "msg", msg=SNAPSHOT),
    F(2, "app_hash", "bytes"),
)
LOAD_SNAPSHOT_CHUNK_REQUEST = Msg(
    "cometbft.abci.v2.LoadSnapshotChunkRequest",
    F(1, "height", "uint64"),
    F(2, "format", "uint32"),
    F(3, "chunk", "uint32"),
)
APPLY_SNAPSHOT_CHUNK_REQUEST = Msg(
    "cometbft.abci.v2.ApplySnapshotChunkRequest",
    F(1, "index", "uint32"),
    F(2, "chunk", "bytes"),
    F(3, "sender", "string"),
)
PREPARE_PROPOSAL_REQUEST = Msg(
    "cometbft.abci.v2.PrepareProposalRequest",
    F(1, "max_tx_bytes", "int64"),
    F(2, "txs", "bytes", repeated=True),
    F(3, "local_last_commit", "msg", msg=EXTENDED_COMMIT_INFO, always=True),
    F(4, "misbehavior", "msg", msg=MISBEHAVIOR, repeated=True),
    F(5, "height", "int64"),
    F(6, "time", "msg", msg=TIMESTAMP, always=True),
    F(7, "next_validators_hash", "bytes"),
    F(8, "proposer_address", "bytes"),
)
PROCESS_PROPOSAL_REQUEST = Msg(
    "cometbft.abci.v2.ProcessProposalRequest",
    F(1, "txs", "bytes", repeated=True),
    F(2, "proposed_last_commit", "msg", msg=COMMIT_INFO, always=True),
    F(3, "misbehavior", "msg", msg=MISBEHAVIOR, repeated=True),
    F(4, "hash", "bytes"),
    F(5, "height", "int64"),
    F(6, "time", "msg", msg=TIMESTAMP, always=True),
    F(7, "next_validators_hash", "bytes"),
    F(8, "proposer_address", "bytes"),
)
EXTEND_VOTE_REQUEST = Msg(
    "cometbft.abci.v2.ExtendVoteRequest",
    F(1, "hash", "bytes"),
    F(2, "height", "int64"),
    F(3, "time", "msg", msg=TIMESTAMP, always=True),
    F(4, "txs", "bytes", repeated=True),
    F(5, "proposed_last_commit", "msg", msg=COMMIT_INFO, always=True),
    F(6, "misbehavior", "msg", msg=MISBEHAVIOR, repeated=True),
    F(7, "next_validators_hash", "bytes"),
    F(8, "proposer_address", "bytes"),
)
VERIFY_VOTE_EXTENSION_REQUEST = Msg(
    "cometbft.abci.v2.VerifyVoteExtensionRequest",
    F(1, "hash", "bytes"),
    F(2, "validator_address", "bytes"),
    F(3, "height", "int64"),
    F(4, "vote_extension", "bytes"),
    F(5, "non_rp_vote_extension", "bytes"),
)
FINALIZE_BLOCK_REQUEST = Msg(
    "cometbft.abci.v2.FinalizeBlockRequest",
    F(1, "txs", "bytes", repeated=True),
    F(2, "decided_last_commit", "msg", msg=COMMIT_INFO, always=True),
    F(3, "misbehavior", "msg", msg=MISBEHAVIOR, repeated=True),
    F(4, "hash", "bytes"),
    F(5, "height", "int64"),
    F(6, "time", "msg", msg=TIMESTAMP, always=True),
    F(7, "next_validators_hash", "bytes"),
    F(8, "proposer_address", "bytes"),
    F(9, "syncing_to_height", "int64"),
)

REQUEST = Msg(
    "cometbft.abci.v2.Request",
    F(1, "echo", "msg", msg=ECHO_REQUEST),
    F(2, "flush", "msg", msg=FLUSH_REQUEST),
    F(3, "info", "msg", msg=INFO_REQUEST),
    F(5, "init_chain", "msg", msg=INIT_CHAIN_REQUEST),
    F(6, "query", "msg", msg=QUERY_REQUEST),
    F(8, "check_tx", "msg", msg=CHECK_TX_REQUEST),
    F(11, "commit", "msg", msg=COMMIT_REQUEST),
    F(12, "list_snapshots", "msg", msg=LIST_SNAPSHOTS_REQUEST),
    F(13, "offer_snapshot", "msg", msg=OFFER_SNAPSHOT_REQUEST),
    F(14, "load_snapshot_chunk", "msg", msg=LOAD_SNAPSHOT_CHUNK_REQUEST),
    F(15, "apply_snapshot_chunk", "msg", msg=APPLY_SNAPSHOT_CHUNK_REQUEST),
    F(16, "prepare_proposal", "msg", msg=PREPARE_PROPOSAL_REQUEST),
    F(17, "process_proposal", "msg", msg=PROCESS_PROPOSAL_REQUEST),
    F(18, "extend_vote", "msg", msg=EXTEND_VOTE_REQUEST),
    F(19, "verify_vote_extension", "msg", msg=VERIFY_VOTE_EXTENSION_REQUEST),
    F(20, "finalize_block", "msg", msg=FINALIZE_BLOCK_REQUEST),
)

EXCEPTION_RESPONSE = Msg(
    "cometbft.abci.v2.ExceptionResponse", F(1, "error", "string"))
ECHO_RESPONSE = Msg("cometbft.abci.v2.EchoResponse",
                    F(1, "message", "string"))
FLUSH_RESPONSE = Msg("cometbft.abci.v2.FlushResponse")
LANE_PRIORITY_ENTRY = Msg(
    "cometbft.abci.v2.InfoResponse.LanePrioritiesEntry",
    F(1, "key", "string"),
    F(2, "value", "uint32"),
)
INFO_RESPONSE = Msg(
    "cometbft.abci.v2.InfoResponse",
    F(1, "data", "string"),
    F(2, "version", "string"),
    F(3, "app_version", "uint64"),
    F(4, "last_block_height", "int64"),
    F(5, "last_block_app_hash", "bytes"),
    F(6, "lane_priorities", "msg", msg=LANE_PRIORITY_ENTRY, repeated=True),
    F(7, "default_lane", "string"),
)
INIT_CHAIN_RESPONSE = Msg(
    "cometbft.abci.v2.InitChainResponse",
    F(1, "consensus_params", "msg", msg=CONSENSUS_PARAMS),
    F(2, "validators", "msg", msg=VALIDATOR_UPDATE, repeated=True),
    F(3, "app_hash", "bytes"),
)
QUERY_RESPONSE = Msg(
    "cometbft.abci.v2.QueryResponse",
    F(1, "code", "uint32"),
    F(3, "log", "string"),
    F(4, "info", "string"),
    F(5, "index", "int64"),
    F(6, "key", "bytes"),
    F(7, "value", "bytes"),
    F(8, "proof_ops", "msg", msg=PROOF_OPS),
    F(9, "height", "int64"),
    F(10, "codespace", "string"),
)
CHECK_TX_RESPONSE = Msg(
    "cometbft.abci.v2.CheckTxResponse",
    F(1, "code", "uint32"),
    F(2, "data", "bytes"),
    F(3, "log", "string"),
    F(4, "info", "string"),
    F(5, "gas_wanted", "int64"),
    F(6, "gas_used", "int64"),
    F(7, "events", "msg", msg=EVENT, repeated=True),
    F(8, "codespace", "string"),
    F(12, "lane_id", "string"),
    # local extension: state keys the tx's validity depends on
    # (incremental mempool recheck)
    F(100, "recheck_keys", "bytes", repeated=True),
)
COMMIT_RESPONSE = Msg(
    "cometbft.abci.v2.CommitResponse",
    F(3, "retain_height", "int64"),
)
LIST_SNAPSHOTS_RESPONSE = Msg(
    "cometbft.abci.v2.ListSnapshotsResponse",
    F(1, "snapshots", "msg", msg=SNAPSHOT, repeated=True),
)
OFFER_SNAPSHOT_RESPONSE = Msg(
    "cometbft.abci.v2.OfferSnapshotResponse", F(1, "result", "enum"))
LOAD_SNAPSHOT_CHUNK_RESPONSE = Msg(
    "cometbft.abci.v2.LoadSnapshotChunkResponse", F(1, "chunk", "bytes"))
APPLY_SNAPSHOT_CHUNK_RESPONSE = Msg(
    "cometbft.abci.v2.ApplySnapshotChunkResponse",
    F(1, "result", "enum"),
    F(2, "refetch_chunks", "uint32", repeated=True),
    F(3, "reject_senders", "string", repeated=True),
)
PREPARE_PROPOSAL_RESPONSE = Msg(
    "cometbft.abci.v2.PrepareProposalResponse",
    F(1, "txs", "bytes", repeated=True),
)
PROCESS_PROPOSAL_RESPONSE = Msg(
    "cometbft.abci.v2.ProcessProposalResponse", F(1, "status", "enum"))
EXTEND_VOTE_RESPONSE = Msg(
    "cometbft.abci.v2.ExtendVoteResponse",
    F(1, "vote_extension", "bytes"),
    F(2, "non_rp_extension", "bytes"),
)
VERIFY_VOTE_EXTENSION_RESPONSE = Msg(
    "cometbft.abci.v2.VerifyVoteExtensionResponse", F(1, "status", "enum"))

RESPONSE = Msg(
    "cometbft.abci.v2.Response",
    F(1, "exception", "msg", msg=EXCEPTION_RESPONSE),
    F(2, "echo", "msg", msg=ECHO_RESPONSE),
    F(3, "flush", "msg", msg=FLUSH_RESPONSE),
    F(4, "info", "msg", msg=INFO_RESPONSE),
    F(6, "init_chain", "msg", msg=INIT_CHAIN_RESPONSE),
    F(7, "query", "msg", msg=QUERY_RESPONSE),
    F(9, "check_tx", "msg", msg=CHECK_TX_RESPONSE),
    F(12, "commit", "msg", msg=COMMIT_RESPONSE),
    F(13, "list_snapshots", "msg", msg=LIST_SNAPSHOTS_RESPONSE),
    F(14, "offer_snapshot", "msg", msg=OFFER_SNAPSHOT_RESPONSE),
    F(15, "load_snapshot_chunk", "msg", msg=LOAD_SNAPSHOT_CHUNK_RESPONSE),
    F(16, "apply_snapshot_chunk", "msg", msg=APPLY_SNAPSHOT_CHUNK_RESPONSE),
    F(17, "prepare_proposal", "msg", msg=PREPARE_PROPOSAL_RESPONSE),
    F(18, "process_proposal", "msg", msg=PROCESS_PROPOSAL_RESPONSE),
    F(19, "extend_vote", "msg", msg=EXTEND_VOTE_RESPONSE),
    F(20, "verify_vote_extension", "msg", msg=VERIFY_VOTE_EXTENSION_RESPONSE),
    F(21, "finalize_block", "msg", msg=FINALIZE_BLOCK_RESPONSE),
)
