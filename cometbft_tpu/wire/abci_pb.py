"""Wire descriptors for cometbft.abci.v2 (subset used on disk and over
the socket protocol).

Reference: proto/cometbft/abci/v2/types.proto.
"""
from .proto import F, Msg
from .pb import CONSENSUS_PARAMS, PROOF_OPS, TIMESTAMP, DURATION

EVENT_ATTRIBUTE = Msg(
    "cometbft.abci.v2.EventAttribute",
    F(1, "key", "string"),
    F(2, "value", "string"),
    F(3, "index", "bool"),
)

EVENT = Msg(
    "cometbft.abci.v2.Event",
    F(1, "type", "string"),
    F(2, "attributes", "msg", msg=EVENT_ATTRIBUTE, repeated=True),
)

EXEC_TX_RESULT = Msg(
    "cometbft.abci.v2.ExecTxResult",
    F(1, "code", "uint32"),
    F(2, "data", "bytes"),
    F(3, "log", "string"),
    F(4, "info", "string"),
    F(5, "gas_wanted", "int64"),
    F(6, "gas_used", "int64"),
    F(7, "events", "msg", msg=EVENT, repeated=True),
    F(8, "codespace", "string"),
)

TX_RESULT = Msg(
    "cometbft.abci.v2.TxResult",
    F(1, "height", "int64"),
    F(2, "index", "uint32"),
    F(3, "tx", "bytes"),
    F(4, "result", "msg", msg=EXEC_TX_RESULT, always=True),
)

ABCI_VALIDATOR = Msg(
    "cometbft.abci.v2.Validator",
    F(1, "address", "bytes"),
    F(3, "power", "int64"),
)

VALIDATOR_UPDATE = Msg(
    "cometbft.abci.v2.ValidatorUpdate",
    F(2, "power", "int64"),
    F(3, "pub_key_bytes", "bytes"),
    F(4, "pub_key_type", "string"),
)

VOTE_INFO = Msg(
    "cometbft.abci.v2.VoteInfo",
    F(1, "validator", "msg", msg=ABCI_VALIDATOR, always=True),
    F(3, "block_id_flag", "enum"),
)

EXTENDED_VOTE_INFO = Msg(
    "cometbft.abci.v2.ExtendedVoteInfo",
    F(1, "validator", "msg", msg=ABCI_VALIDATOR, always=True),
    F(3, "vote_extension", "bytes"),
    F(4, "extension_signature", "bytes"),
    F(5, "block_id_flag", "enum"),
    F(6, "non_rp_vote_extension", "bytes"),
    F(7, "non_rp_extension_signature", "bytes"),
)

COMMIT_INFO = Msg(
    "cometbft.abci.v2.CommitInfo",
    F(1, "round", "int32"),
    F(2, "votes", "msg", msg=VOTE_INFO, repeated=True),
)

EXTENDED_COMMIT_INFO = Msg(
    "cometbft.abci.v2.ExtendedCommitInfo",
    F(1, "round", "int32"),
    F(2, "votes", "msg", msg=EXTENDED_VOTE_INFO, repeated=True),
)

MISBEHAVIOR = Msg(
    "cometbft.abci.v2.Misbehavior",
    F(1, "type", "enum"),
    F(2, "validator", "msg", msg=ABCI_VALIDATOR, always=True),
    F(3, "height", "int64"),
    F(4, "time", "msg", msg=TIMESTAMP, always=True),
    F(5, "total_voting_power", "int64"),
)

SNAPSHOT = Msg(
    "cometbft.abci.v2.Snapshot",
    F(1, "height", "uint64"),
    F(2, "format", "uint32"),
    F(3, "chunks", "uint32"),
    F(4, "hash", "bytes"),
    F(5, "metadata", "bytes"),
)

FINALIZE_BLOCK_RESPONSE = Msg(
    "cometbft.abci.v2.FinalizeBlockResponse",
    F(1, "events", "msg", msg=EVENT, repeated=True),
    F(2, "tx_results", "msg", msg=EXEC_TX_RESULT, repeated=True),
    F(3, "validator_updates", "msg", msg=VALIDATOR_UPDATE, repeated=True),
    F(4, "consensus_param_updates", "msg", msg=CONSENSUS_PARAMS),
    F(5, "app_hash", "bytes"),
    F(6, "next_block_delay", "msg", msg=DURATION, always=True),
)
