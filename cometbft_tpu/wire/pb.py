"""Message descriptors for the consensus wire schema.

Mirrors the reference's proto packages (proto/cometbft/**/*.proto at v2 for
types, v1 for crypto/version).  Field numbers, kinds and gogoproto
nullability are the consensus-critical contract; descriptor names follow the
proto message names.
"""
from .proto import F, Msg

# ---------------------------------------------------------------------------
# well-known types

TIMESTAMP = Msg(
    "google.protobuf.Timestamp",
    F(1, "seconds", "int64"),
    F(2, "nanos", "int32"),
)

DURATION = Msg(
    "google.protobuf.Duration",
    F(1, "seconds", "int64"),
    F(2, "nanos", "int32"),
)

# wrapper types used by cdcEncode-style field hashing (gogotypes wrappers)
INT64_VALUE = Msg("google.protobuf.Int64Value", F(1, "value", "int64"))
STRING_VALUE = Msg("google.protobuf.StringValue", F(1, "value", "string"))
BYTES_VALUE = Msg("google.protobuf.BytesValue", F(1, "value", "bytes"))

# ---------------------------------------------------------------------------
# cometbft.crypto.v1

PUBLIC_KEY = Msg(
    "cometbft.crypto.v1.PublicKey",  # oneof sum: exactly one field set
    F(1, "ed25519", "bytes"),
    F(2, "secp256k1", "bytes"),
    F(3, "bls12381", "bytes"),
    F(4, "secp256k1eth", "bytes"),
)

PROOF = Msg(
    "cometbft.crypto.v1.Proof",
    F(1, "total", "int64"),
    F(2, "index", "int64"),
    F(3, "leaf_hash", "bytes"),
    F(4, "aunts", "bytes", repeated=True),
)

PROOF_OP = Msg(
    "cometbft.crypto.v1.ProofOp",
    F(1, "type", "string"),
    F(2, "key", "bytes"),
    F(3, "data", "bytes"),
)

PROOF_OPS = Msg(
    "cometbft.crypto.v1.ProofOps",
    F(1, "ops", "msg", msg=PROOF_OP, repeated=True),
)

# ---------------------------------------------------------------------------
# cometbft.version.v1

CONSENSUS_VERSION = Msg(
    "cometbft.version.v1.Consensus",
    F(1, "block", "uint64"),
    F(2, "app", "uint64"),
)

APP_VERSION = Msg(
    "cometbft.version.v1.App",
    F(1, "protocol", "uint64"),
    F(2, "software", "string"),
)

# ---------------------------------------------------------------------------
# cometbft.types.v2 — core block/vote types

PART_SET_HEADER = Msg(
    "cometbft.types.v2.PartSetHeader",
    F(1, "total", "uint32"),
    F(2, "hash", "bytes"),
)

PART = Msg(
    "cometbft.types.v2.Part",
    F(1, "index", "uint32"),
    F(2, "bytes", "bytes"),
    F(3, "proof", "msg", msg=PROOF, always=True),
)

BLOCK_ID = Msg(
    "cometbft.types.v2.BlockID",
    F(1, "hash", "bytes"),
    F(2, "part_set_header", "msg", msg=PART_SET_HEADER, always=True),
)

HEADER = Msg(
    "cometbft.types.v2.Header",
    F(1, "version", "msg", msg=CONSENSUS_VERSION, always=True),
    F(2, "chain_id", "string"),
    F(3, "height", "int64"),
    F(4, "time", "msg", msg=TIMESTAMP, always=True),
    F(5, "last_block_id", "msg", msg=BLOCK_ID, always=True),
    F(6, "last_commit_hash", "bytes"),
    F(7, "data_hash", "bytes"),
    F(8, "validators_hash", "bytes"),
    F(9, "next_validators_hash", "bytes"),
    F(10, "consensus_hash", "bytes"),
    F(11, "app_hash", "bytes"),
    F(12, "last_results_hash", "bytes"),
    F(13, "evidence_hash", "bytes"),
    F(14, "proposer_address", "bytes"),
)

DATA = Msg(
    "cometbft.types.v2.Data",
    F(1, "txs", "bytes", repeated=True),
)

VOTE = Msg(
    "cometbft.types.v2.Vote",
    F(1, "type", "enum"),
    F(2, "height", "int64"),
    F(3, "round", "int32"),
    F(4, "block_id", "msg", msg=BLOCK_ID, always=True),
    F(5, "timestamp", "msg", msg=TIMESTAMP, always=True),
    F(6, "validator_address", "bytes"),
    F(7, "validator_index", "int32"),
    F(8, "signature", "bytes"),
    F(9, "extension", "bytes"),
    F(10, "extension_signature", "bytes"),
    F(11, "non_rp_extension", "bytes"),
    F(12, "non_rp_extension_signature", "bytes"),
)

COMMIT_SIG = Msg(
    "cometbft.types.v2.CommitSig",
    F(1, "block_id_flag", "enum"),
    F(2, "validator_address", "bytes"),
    F(3, "timestamp", "msg", msg=TIMESTAMP, always=True),
    F(4, "signature", "bytes"),
)

COMMIT = Msg(
    "cometbft.types.v2.Commit",
    F(1, "height", "int64"),
    F(2, "round", "int32"),
    F(3, "block_id", "msg", msg=BLOCK_ID, always=True),
    F(4, "signatures", "msg", msg=COMMIT_SIG, repeated=True),
)

# TPU-native extension (docs/aggregate_commits.md): one BLS signature
# + a signer bitmap instead of per-validator CommitSigs.  Rides in new
# OPTIONAL fields beside the Commit arms (BLOCK field 5, SIGNED_HEADER
# field 3), so chains that never enable the feature stay byte-identical
# on the wire.
AGGREGATE_COMMIT = Msg(
    "cometbft.types.v2.AggregateCommit",
    F(1, "height", "int64"),
    F(2, "round", "int32"),
    F(3, "block_id", "msg", msg=BLOCK_ID, always=True),
    F(4, "signer_count", "int64"),
    F(5, "signers", "bytes"),
    F(6, "signature", "bytes"),
)

EXTENDED_COMMIT_SIG = Msg(
    "cometbft.types.v2.ExtendedCommitSig",
    F(1, "block_id_flag", "enum"),
    F(2, "validator_address", "bytes"),
    F(3, "timestamp", "msg", msg=TIMESTAMP, always=True),
    F(4, "signature", "bytes"),
    F(5, "extension", "bytes"),
    F(6, "extension_signature", "bytes"),
    F(7, "non_rp_extension", "bytes"),
    F(8, "non_rp_extension_signature", "bytes"),
)

EXTENDED_COMMIT = Msg(
    "cometbft.types.v2.ExtendedCommit",
    F(1, "height", "int64"),
    F(2, "round", "int32"),
    F(3, "block_id", "msg", msg=BLOCK_ID, always=True),
    F(4, "extended_signatures", "msg", msg=EXTENDED_COMMIT_SIG,
      repeated=True),
)

PROPOSAL = Msg(
    "cometbft.types.v2.Proposal",
    F(1, "type", "enum"),
    F(2, "height", "int64"),
    F(3, "round", "int32"),
    F(4, "pol_round", "int32"),
    F(5, "block_id", "msg", msg=BLOCK_ID, always=True),
    F(6, "timestamp", "msg", msg=TIMESTAMP, always=True),
    F(7, "signature", "bytes"),
)

VALIDATOR = Msg(
    "cometbft.types.v2.Validator",
    F(1, "address", "bytes"),
    F(2, "pub_key", "msg", msg=PUBLIC_KEY),  # deprecated in reference
    F(3, "voting_power", "int64"),
    F(4, "proposer_priority", "int64"),
    F(5, "pub_key_bytes", "bytes"),
    F(6, "pub_key_type", "string"),
)

SIMPLE_VALIDATOR = Msg(
    "cometbft.types.v2.SimpleValidator",
    F(1, "pub_key", "msg", msg=PUBLIC_KEY),
    F(2, "voting_power", "int64"),
)

VALIDATOR_SET = Msg(
    "cometbft.types.v2.ValidatorSet",
    F(1, "validators", "msg", msg=VALIDATOR, repeated=True),
    F(2, "proposer", "msg", msg=VALIDATOR),
    F(3, "total_voting_power", "int64"),
)

SIGNED_HEADER = Msg(
    "cometbft.types.v2.SignedHeader",
    F(1, "header", "msg", msg=HEADER),
    F(2, "commit", "msg", msg=COMMIT),
    F(3, "aggregate_commit", "msg", msg=AGGREGATE_COMMIT),
)

LIGHT_BLOCK = Msg(
    "cometbft.types.v2.LightBlock",
    F(1, "signed_header", "msg", msg=SIGNED_HEADER),
    F(2, "validator_set", "msg", msg=VALIDATOR_SET),
)

BLOCK_META = Msg(
    "cometbft.types.v2.BlockMeta",
    F(1, "block_id", "msg", msg=BLOCK_ID, always=True),
    F(2, "block_size", "int64"),
    F(3, "header", "msg", msg=HEADER, always=True),
    F(4, "num_txs", "int64"),
)

TX_PROOF = Msg(
    "cometbft.types.v2.TxProof",
    F(1, "root_hash", "bytes"),
    F(2, "data", "bytes"),
    F(3, "proof", "msg", msg=PROOF),
)

# ---------------------------------------------------------------------------
# cometbft.types.v2 — evidence

DUPLICATE_VOTE_EVIDENCE = Msg(
    "cometbft.types.v2.DuplicateVoteEvidence",
    F(1, "vote_a", "msg", msg=VOTE),
    F(2, "vote_b", "msg", msg=VOTE),
    F(3, "total_voting_power", "int64"),
    F(4, "validator_power", "int64"),
    F(5, "timestamp", "msg", msg=TIMESTAMP, always=True),
)

LIGHT_CLIENT_ATTACK_EVIDENCE = Msg(
    "cometbft.types.v2.LightClientAttackEvidence",
    F(1, "conflicting_block", "msg", msg=LIGHT_BLOCK),
    F(2, "common_height", "int64"),
    F(3, "byzantine_validators", "msg", msg=VALIDATOR, repeated=True),
    F(4, "total_voting_power", "int64"),
    F(5, "timestamp", "msg", msg=TIMESTAMP, always=True),
)

EVIDENCE = Msg(
    "cometbft.types.v2.Evidence",  # oneof sum
    F(1, "duplicate_vote_evidence", "msg", msg=DUPLICATE_VOTE_EVIDENCE),
    F(2, "light_client_attack_evidence", "msg",
      msg=LIGHT_CLIENT_ATTACK_EVIDENCE),
)

EVIDENCE_LIST = Msg(
    "cometbft.types.v2.EvidenceList",
    F(1, "evidence", "msg", msg=EVIDENCE, repeated=True),
)

BLOCK = Msg(
    "cometbft.types.v2.Block",
    F(1, "header", "msg", msg=HEADER, always=True),
    F(2, "data", "msg", msg=DATA, always=True),
    F(3, "evidence", "msg", msg=EVIDENCE_LIST, always=True),
    F(4, "last_commit", "msg", msg=COMMIT),
    F(5, "last_aggregate_commit", "msg", msg=AGGREGATE_COMMIT),
)

# ---------------------------------------------------------------------------
# cometbft.types.v2 — canonical sign-bytes messages (canonical.proto)

CANONICAL_PART_SET_HEADER = Msg(
    "cometbft.types.v2.CanonicalPartSetHeader",
    F(1, "total", "uint32"),
    F(2, "hash", "bytes"),
)

CANONICAL_BLOCK_ID = Msg(
    "cometbft.types.v2.CanonicalBlockID",
    F(1, "hash", "bytes"),
    F(2, "part_set_header", "msg", msg=CANONICAL_PART_SET_HEADER,
      always=True),
)

CANONICAL_PROPOSAL = Msg(
    "cometbft.types.v2.CanonicalProposal",
    F(1, "type", "enum"),
    F(2, "height", "sfixed64"),
    F(3, "round", "sfixed64"),
    F(4, "pol_round", "int64"),
    F(5, "block_id", "msg", msg=CANONICAL_BLOCK_ID),  # nullable
    F(6, "timestamp", "msg", msg=TIMESTAMP, always=True),
    F(7, "chain_id", "string"),
)

CANONICAL_VOTE = Msg(
    "cometbft.types.v2.CanonicalVote",
    F(1, "type", "enum"),
    F(2, "height", "sfixed64"),
    F(3, "round", "sfixed64"),
    F(4, "block_id", "msg", msg=CANONICAL_BLOCK_ID),  # nullable
    F(5, "timestamp", "msg", msg=TIMESTAMP, always=True),
    F(6, "chain_id", "string"),
)

CANONICAL_VOTE_EXTENSION = Msg(
    "cometbft.types.v2.CanonicalVoteExtension",
    F(1, "extension", "bytes"),
    F(2, "height", "sfixed64"),
    F(3, "round", "sfixed64"),
    F(4, "chain_id", "string"),
)

# ---------------------------------------------------------------------------
# cometbft.types.v2 — consensus params (params.proto)

BLOCK_PARAMS = Msg(
    "cometbft.types.v2.BlockParams",
    F(1, "max_bytes", "int64"),
    F(2, "max_gas", "int64"),
)

EVIDENCE_PARAMS = Msg(
    "cometbft.types.v2.EvidenceParams",
    F(1, "max_age_num_blocks", "int64"),
    F(2, "max_age_duration", "msg", msg=DURATION, always=True),
    F(3, "max_bytes", "int64"),
)

VALIDATOR_PARAMS = Msg(
    "cometbft.types.v2.ValidatorParams",
    F(1, "pub_key_types", "string", repeated=True),
)

VERSION_PARAMS = Msg(
    "cometbft.types.v2.VersionParams",
    F(1, "app", "uint64"),
)

SYNCHRONY_PARAMS = Msg(
    "cometbft.types.v2.SynchronyParams",
    F(1, "precision", "msg", msg=DURATION),
    F(2, "message_delay", "msg", msg=DURATION),
)

FEATURE_PARAMS = Msg(
    "cometbft.types.v2.FeatureParams",
    F(1, "vote_extensions_enable_height", "msg", msg=INT64_VALUE),
    F(2, "pbts_enable_height", "msg", msg=INT64_VALUE),
    F(3, "aggregate_commit_enable_height", "msg", msg=INT64_VALUE),
)

CONSENSUS_PARAMS = Msg(
    "cometbft.types.v2.ConsensusParams",
    F(1, "block", "msg", msg=BLOCK_PARAMS),
    F(2, "evidence", "msg", msg=EVIDENCE_PARAMS),
    F(3, "validator", "msg", msg=VALIDATOR_PARAMS),
    F(4, "version", "msg", msg=VERSION_PARAMS),
    F(6, "synchrony", "msg", msg=SYNCHRONY_PARAMS),
    F(7, "feature", "msg", msg=FEATURE_PARAMS),
)

HASHED_PARAMS = Msg(
    "cometbft.types.v2.HashedParams",
    F(1, "block_max_bytes", "int64"),
    F(2, "block_max_gas", "int64"),
)
