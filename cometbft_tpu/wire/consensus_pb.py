"""Wire descriptors for cometbft.consensus.v2 gossip messages.

Reference: proto/cometbft/consensus/v2/types.proto.
"""
from .proto import F, Msg
from .pb import (AGGREGATE_COMMIT, BLOCK_ID, PART, PART_SET_HEADER,
                 PROPOSAL, VOTE)

BIT_ARRAY = Msg(
    "cometbft.libs.bits.v1.BitArray",
    F(1, "bits", "int64"),
    F(2, "elems", "uint64", repeated=True),
)

NEW_ROUND_STEP = Msg(
    "cometbft.consensus.v2.NewRoundStep",
    F(1, "height", "int64"),
    F(2, "round", "int32"),
    F(3, "step", "uint32"),
    F(4, "seconds_since_start_time", "int64"),
    F(5, "last_commit_round", "int32"),
)

NEW_VALID_BLOCK = Msg(
    "cometbft.consensus.v2.NewValidBlock",
    F(1, "height", "int64"),
    F(2, "round", "int32"),
    F(3, "block_part_set_header", "msg", msg=PART_SET_HEADER,
      always=True),
    F(4, "block_parts", "msg", msg=BIT_ARRAY),
    F(5, "is_commit", "bool"),
)

PROPOSAL_MSG = Msg(
    "cometbft.consensus.v2.Proposal",
    F(1, "proposal", "msg", msg=PROPOSAL, always=True),
)

PROPOSAL_POL = Msg(
    "cometbft.consensus.v2.ProposalPOL",
    F(1, "height", "int64"),
    F(2, "proposal_pol_round", "int32"),
    F(3, "proposal_pol", "msg", msg=BIT_ARRAY, always=True),
)

BLOCK_PART = Msg(
    "cometbft.consensus.v2.BlockPart",
    F(1, "height", "int64"),
    F(2, "round", "int32"),
    F(3, "part", "msg", msg=PART, always=True),
)

VOTE_MSG = Msg(
    "cometbft.consensus.v2.Vote",
    F(1, "vote", "msg", msg=VOTE),
)

HAS_VOTE = Msg(
    "cometbft.consensus.v2.HasVote",
    F(1, "height", "int64"),
    F(2, "round", "int32"),
    F(3, "type", "enum"),
    F(4, "index", "int32"),
)

VOTE_SET_MAJ23 = Msg(
    "cometbft.consensus.v2.VoteSetMaj23",
    F(1, "height", "int64"),
    F(2, "round", "int32"),
    F(3, "type", "enum"),
    F(4, "block_id", "msg", msg=BLOCK_ID, always=True),
)

VOTE_SET_BITS = Msg(
    "cometbft.consensus.v2.VoteSetBits",
    F(1, "height", "int64"),
    F(2, "round", "int32"),
    F(3, "type", "enum"),
    F(4, "block_id", "msg", msg=BLOCK_ID, always=True),
    F(5, "votes", "msg", msg=BIT_ARRAY, always=True),
)

HAS_PROPOSAL_BLOCK_PART = Msg(
    "cometbft.consensus.v2.HasProposalBlockPart",
    F(1, "height", "int64"),
    F(2, "round", "int32"),
    F(3, "index", "int32"),
)

# compact-block proposal relay (docs/gossip.md): the proposal as the
# block's proto bytes WITHOUT data.txs plus the ordered full tx
# hashes; receivers splice txs from their mempool, re-encode (the
# codec is canonical) and rebuild the identical part set.  Negotiated
# via the "compactblocks/1" handshake capability.
COMPACT_BLOCK = Msg(
    "cometbft.consensus.v2.CompactBlock",
    F(1, "height", "int64"),
    F(2, "round", "int32"),
    F(3, "part_set_header", "msg", msg=PART_SET_HEADER, always=True),
    F(4, "skeleton", "bytes"),
    F(5, "tx_hashes", "bytes"),     # n * 32 bytes, block order
)

# receiver-driven fallback: "I could not rebuild your compact
# proposal — send full parts now".  Cancels the sender's grace
# window; without it a miss only falls back after the grace timer,
# which can outlive a whole round under aggressive timeouts.
COMPACT_BLOCK_NACK = Msg(
    "cometbft.consensus.v2.CompactBlockNack",
    F(1, "height", "int64"),
    F(2, "round", "int32"),
)

# vote batching ("votebatch/1"): missing votes coalesced per wire
# message on the vote channel, like the mempool's tx batching
VOTE_BATCH = Msg(
    "cometbft.consensus.v2.VoteBatch",
    F(1, "votes", "msg", msg=VOTE, repeated=True),
)

# aggregate-commit catchup (docs/aggregate_commits.md): on an
# aggregate chain a lagging peer cannot be served reconstructed
# precommit votes — the stored commit is one aggregate signature —
# so the reactor ships the aggregate itself.  Only sent to peers
# that negotiated "aggcommit/1".
AGG_COMMIT_MSG = Msg(
    "cometbft.consensus.v2.AggregateCommitCatchup",
    F(1, "commit", "msg", msg=AGGREGATE_COMMIT, always=True),
)

MESSAGE = Msg(
    "cometbft.consensus.v2.Message",   # oneof sum
    F(1, "new_round_step", "msg", msg=NEW_ROUND_STEP),
    F(2, "new_valid_block", "msg", msg=NEW_VALID_BLOCK),
    F(3, "proposal", "msg", msg=PROPOSAL_MSG),
    F(4, "proposal_pol", "msg", msg=PROPOSAL_POL),
    F(5, "block_part", "msg", msg=BLOCK_PART),
    F(6, "vote", "msg", msg=VOTE_MSG),
    F(7, "has_vote", "msg", msg=HAS_VOTE),
    F(8, "vote_set_maj23", "msg", msg=VOTE_SET_MAJ23),
    F(9, "vote_set_bits", "msg", msg=VOTE_SET_BITS),
    F(10, "has_proposal_block_part", "msg",
      msg=HAS_PROPOSAL_BLOCK_PART),
    F(11, "compact_block", "msg", msg=COMPACT_BLOCK),
    F(12, "vote_batch", "msg", msg=VOTE_BATCH),
    F(13, "compact_block_nack", "msg", msg=COMPACT_BLOCK_NACK),
    F(14, "aggregate_commit", "msg", msg=AGG_COMMIT_MSG),
)
