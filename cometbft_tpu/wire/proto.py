"""Descriptor-driven protobuf wire codec.

Gogoproto-compatible semantics (reference: api/ generated marshalers):
  * fields serialized in ascending field-number order;
  * proto3 scalar fields omitted when zero ("" / b"" / 0 / False);
  * embedded messages: `always=True` mirrors gogoproto `nullable=false`
    (field emitted even when the value is all-zero); otherwise a None value
    omits the field;
  * int32/int64/enum negatives encode as 10-byte two's-complement varints;
  * unknown fields are skipped on decode (forward compatibility).

Messages are plain dicts keyed by field name; absent == default.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field as dc_field
from typing import Any, Optional, Sequence

_MASK64 = (1 << 64) - 1

# wire types
_WT_VARINT = 0
_WT_FIXED64 = 1
_WT_LEN = 2
_WT_FIXED32 = 5

# the scalar kinds are defined once by _KIND_WT below;
# _SCALAR_KINDS = frozenset(_KIND_WT) next to it


@dataclass(frozen=True)
class F:
    """One field of a message descriptor.

    The wire tag bytes and the kind's encoder function are bound once
    here — encode() is on the consensus gossip hot path (every vote /
    block part / mempool tx marshals through it), and per-call tag
    arithmetic plus a 12-way kind chain measured ~2x the whole encode
    cost."""
    num: int
    name: str
    kind: str                      # scalar kind or "msg"
    msg: Optional["Msg"] = None    # sub-descriptor when kind == "msg"
    repeated: bool = False
    always: bool = False           # gogoproto nullable=false for msg kinds

    def __post_init__(self):
        if self.kind == "msg":
            if self.msg is None:
                raise ValueError(f"{self.name}: msg kind needs descriptor")
            wt = _WT_LEN
        elif self.kind not in _SCALAR_KINDS:
            raise ValueError(f"{self.name}: unknown kind {self.kind}")
        else:
            wt = _KIND_WT[self.kind]
        object.__setattr__(self, "tag", _tag(self.num, wt))
        object.__setattr__(self, "enc", _ENCODERS.get(self.kind))


@dataclass(frozen=True)
class Msg:
    """A message descriptor: name + ordered fields."""
    name: str
    fields: Sequence[F] = dc_field(default_factory=tuple)

    def __init__(self, name: str, *fields: F):
        object.__setattr__(self, "name", name)
        object.__setattr__(
            self, "fields", tuple(sorted(fields, key=lambda f: f.num)))
        by_num = {f.num: f for f in self.fields}
        if len(by_num) != len(self.fields):
            raise ValueError(f"{name}: duplicate field numbers")
        object.__setattr__(self, "_by_num", by_num)

    def empty(self) -> dict:
        return {}


def encode_uvarint(u: int) -> bytes:
    if u < 0:
        raise ValueError("uvarint must be non-negative")
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _append_uvarint(out: bytearray, u: int) -> None:
    """encode_uvarint without the intermediate bytes allocation."""
    while u > 0x7F:
        out.append((u & 0x7F) | 0x80)
        u >>= 7
    out.append(u)


def _tag(num: int, wt: int) -> bytes:
    return encode_uvarint((num << 3) | wt)


_KIND_WT = {
    "int32": _WT_VARINT, "int64": _WT_VARINT, "enum": _WT_VARINT,
    "uint32": _WT_VARINT, "uint64": _WT_VARINT, "bool": _WT_VARINT,
    "sfixed64": _WT_FIXED64, "fixed64": _WT_FIXED64,
    "sfixed32": _WT_FIXED32, "fixed32": _WT_FIXED32,
    "bytes": _WT_LEN, "string": _WT_LEN,
}
_SCALAR_KINDS = frozenset(_KIND_WT)


def _e_int(tag: bytes, v: Any, out: bytearray) -> None:
    out += tag
    _append_uvarint(out, int(v) & _MASK64)


def _e_uint(tag: bytes, v: Any, out: bytearray) -> None:
    u = int(v)
    if u < 0:
        raise ValueError("uvarint must be non-negative")
    out += tag
    _append_uvarint(out, u)


def _e_bool(tag: bytes, v: Any, out: bytearray) -> None:
    out += tag
    out.append(1 if v else 0)


_PACK_q = struct.Struct("<q").pack
_PACK_Q = struct.Struct("<Q").pack
_PACK_i = struct.Struct("<i").pack
_PACK_I = struct.Struct("<I").pack


def _e_sfixed64(tag: bytes, v: Any, out: bytearray) -> None:
    out += tag
    out += _PACK_q(int(v))


def _e_fixed64(tag: bytes, v: Any, out: bytearray) -> None:
    out += tag
    out += _PACK_Q(int(v))


def _e_sfixed32(tag: bytes, v: Any, out: bytearray) -> None:
    out += tag
    out += _PACK_i(int(v))


def _e_fixed32(tag: bytes, v: Any, out: bytearray) -> None:
    out += tag
    out += _PACK_I(int(v))


def _e_bytes(tag: bytes, v: Any, out: bytearray) -> None:
    b = bytes(v)
    out += tag
    _append_uvarint(out, len(b))
    out += b


def _e_string(tag: bytes, v: Any, out: bytearray) -> None:
    b = v.encode("utf-8")
    out += tag
    _append_uvarint(out, len(b))
    out += b


_ENCODERS = {
    "int32": _e_int, "int64": _e_int, "enum": _e_int,
    "uint32": _e_uint, "uint64": _e_uint, "bool": _e_bool,
    "sfixed64": _e_sfixed64, "fixed64": _e_fixed64,
    "sfixed32": _e_sfixed32, "fixed32": _e_fixed32,
    "bytes": _e_bytes, "string": _e_string,
}




def _is_zero(kind: str, v: Any) -> bool:
    if v is None:
        return True
    if kind == "bytes":
        return len(v) == 0
    if kind == "string":
        return v == ""
    if kind == "bool":
        return not v
    return int(v) == 0


def encode(desc: Msg, d: dict) -> bytes:
    out = bytearray()
    for f in desc.fields:
        v = d.get(f.name)
        if f.repeated:
            if not v:
                continue
            enc = f.enc
            if enc is None:                    # msg kind
                for item in v:
                    body = encode(f.msg, item)
                    out += f.tag
                    _append_uvarint(out, len(body))
                    out += body
            else:
                tag = f.tag
                for item in v:
                    enc(tag, item, out)
        elif f.kind == "msg":
            if v is None:
                if not f.always:
                    continue
                v = {}
            body = encode(f.msg, v)
            out += f.tag
            _append_uvarint(out, len(body))
            out += body
        else:
            if _is_zero(f.kind, v):
                continue
            f.enc(f.tag, v, out)
    return bytes(out)


def decode_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _to_signed64(u: int) -> int:
    return u - (1 << 64) if u >= (1 << 63) else u


def _to_signed32(u: int) -> int:
    u &= 0xFFFFFFFF
    return u - (1 << 32) if u >= (1 << 31) else u


def _dec_scalar(f: F, data: bytes, pos: int, wt: int) -> tuple[Any, int]:
    k = f.kind
    if wt == _WT_VARINT:
        u, pos = decode_uvarint(data, pos)
        if k in ("int64", "enum"):
            return _to_signed64(u), pos
        if k == "int32":
            return _to_signed32(_to_signed64(u)), pos
        if k == "bool":
            return bool(u), pos
        return u, pos
    if wt == _WT_FIXED64:
        raw = data[pos:pos + 8]
        if len(raw) != 8:
            raise ValueError("truncated fixed64")
        pos += 8
        fmt = "<q" if k == "sfixed64" else "<Q"
        return struct.unpack(fmt, raw)[0], pos
    if wt == _WT_FIXED32:
        raw = data[pos:pos + 4]
        if len(raw) != 4:
            raise ValueError("truncated fixed32")
        pos += 4
        fmt = "<i" if k == "sfixed32" else "<I"
        return struct.unpack(fmt, raw)[0], pos
    if wt == _WT_LEN:
        ln, pos = decode_uvarint(data, pos)
        raw = data[pos:pos + ln]
        if len(raw) != ln:
            raise ValueError("truncated length-delimited field")
        pos += ln
        if k == "string":
            return raw.decode("utf-8"), pos
        return bytes(raw), pos
    raise ValueError(f"unsupported wire type {wt}")


def _skip(data: bytes, pos: int, wt: int) -> int:
    if wt == _WT_VARINT:
        _, pos = decode_uvarint(data, pos)
        return pos
    if wt == _WT_FIXED64:
        return pos + 8
    if wt == _WT_FIXED32:
        return pos + 4
    if wt == _WT_LEN:
        ln, pos = decode_uvarint(data, pos)
        return pos + ln
    raise ValueError(f"cannot skip wire type {wt}")


def decode(desc: Msg, data: bytes) -> dict:
    d: dict = {}
    pos = 0
    n = len(data)
    by_num = desc._by_num  # type: ignore[attr-defined]
    while pos < n:
        key, pos = decode_uvarint(data, pos)
        num, wt = key >> 3, key & 0x7
        f = by_num.get(num)
        if f is None:
            pos = _skip(data, pos, wt)
            continue
        if f.kind == "msg":
            if wt != _WT_LEN:
                raise ValueError(f"{desc.name}.{f.name}: bad wire type {wt}")
            ln, pos = decode_uvarint(data, pos)
            raw = data[pos:pos + ln]
            if len(raw) != ln:
                raise ValueError("truncated embedded message")
            pos += ln
            v = decode(f.msg, raw)
            if f.repeated:
                d.setdefault(f.name, []).append(v)
            else:
                d[f.name] = v
        else:
            v, pos = _dec_scalar(f, data, pos, wt)
            if f.repeated:
                d.setdefault(f.name, []).append(v)
            else:
                d[f.name] = v
    # gogoproto nullable=false embedded messages decode to their zero value
    for f in desc.fields:
        if f.kind == "msg" and f.always and not f.repeated and f.name not in d:
            d[f.name] = {}
    return d


def marshal_delimited(desc: Msg, d: dict) -> bytes:
    """uvarint-length-prefixed encoding (reference: libs/protoio)."""
    body = encode(desc, d)
    return encode_uvarint(len(body)) + body


def unmarshal_delimited(desc: Msg, data: bytes) -> tuple[dict, int]:
    """Decode one length-prefixed message; returns (msg, bytes consumed)."""
    ln, pos = decode_uvarint(data, 0)
    raw = data[pos:pos + ln]
    if len(raw) != ln:
        raise ValueError("truncated delimited message")
    return decode(desc, raw), pos + ln
