"""Wire descriptors for cometbft.privval.v2 (remote signer protocol).

Reference: proto/cometbft/privval/v2/types.proto — the Message oneof
carried as uvarint-length-delimited frames between the node's
SignerListenerEndpoint and the external SignerServer.
"""
from .pb import PROPOSAL, VOTE
from .proto import F, Msg

REMOTE_SIGNER_ERROR = Msg(
    "cometbft.privval.v2.RemoteSignerError",
    F(1, "code", "int32"),
    F(2, "description", "string"),
)

PUB_KEY_REQUEST = Msg(
    "cometbft.privval.v2.PubKeyRequest",
    F(1, "chain_id", "string"),
)

PUB_KEY_RESPONSE = Msg(
    "cometbft.privval.v2.PubKeyResponse",
    F(2, "error", "msg", msg=REMOTE_SIGNER_ERROR),
    F(3, "pub_key_bytes", "bytes"),
    F(4, "pub_key_type", "string"),
)

SIGN_VOTE_REQUEST = Msg(
    "cometbft.privval.v2.SignVoteRequest",
    F(1, "vote", "msg", msg=VOTE),
    F(2, "chain_id", "string"),
    F(3, "skip_extension_signing", "bool"),
)

SIGNED_VOTE_RESPONSE = Msg(
    "cometbft.privval.v2.SignedVoteResponse",
    F(1, "vote", "msg", msg=VOTE, always=True),
    F(2, "error", "msg", msg=REMOTE_SIGNER_ERROR),
)

SIGN_PROPOSAL_REQUEST = Msg(
    "cometbft.privval.v2.SignProposalRequest",
    F(1, "proposal", "msg", msg=PROPOSAL),
    F(2, "chain_id", "string"),
)

SIGNED_PROPOSAL_RESPONSE = Msg(
    "cometbft.privval.v2.SignedProposalResponse",
    F(1, "proposal", "msg", msg=PROPOSAL, always=True),
    F(2, "error", "msg", msg=REMOTE_SIGNER_ERROR),
)

SIGN_BYTES_REQUEST = Msg(
    "cometbft.privval.v2.SignBytesRequest",
    F(1, "value", "bytes"),
)

SIGN_BYTES_RESPONSE = Msg(
    "cometbft.privval.v2.SignBytesResponse",
    F(1, "signature", "bytes"),
    F(2, "error", "msg", msg=REMOTE_SIGNER_ERROR),
)

PING_REQUEST = Msg("cometbft.privval.v2.PingRequest")
PING_RESPONSE = Msg("cometbft.privval.v2.PingResponse")

MESSAGE = Msg(
    "cometbft.privval.v2.Message",
    F(1, "pub_key_request", "msg", msg=PUB_KEY_REQUEST),
    F(2, "pub_key_response", "msg", msg=PUB_KEY_RESPONSE),
    F(3, "sign_vote_request", "msg", msg=SIGN_VOTE_REQUEST),
    F(4, "signed_vote_response", "msg", msg=SIGNED_VOTE_RESPONSE),
    F(5, "sign_proposal_request", "msg", msg=SIGN_PROPOSAL_REQUEST),
    F(6, "signed_proposal_response", "msg", msg=SIGNED_PROPOSAL_RESPONSE),
    F(7, "ping_request", "msg", msg=PING_REQUEST),
    F(8, "ping_response", "msg", msg=PING_RESPONSE),
    F(9, "sign_bytes_request", "msg", msg=SIGN_BYTES_REQUEST),
    F(10, "sign_bytes_response", "msg", msg=SIGN_BYTES_RESPONSE),
)
