"""Wire descriptors for cometbft.state.v2 (on-disk state records).

Reference: proto/cometbft/state/v2/types.proto.
"""
from .proto import F, Msg
from .pb import (
    BLOCK_ID, CONSENSUS_PARAMS, CONSENSUS_VERSION, DURATION, TIMESTAMP,
    VALIDATOR_SET,
)
from .abci_pb import FINALIZE_BLOCK_RESPONSE

STATE_VERSION = Msg(
    "cometbft.state.v2.Version",
    F(1, "consensus", "msg", msg=CONSENSUS_VERSION, always=True),
    F(2, "software", "string"),
)

STATE = Msg(
    "cometbft.state.v2.State",
    F(1, "version", "msg", msg=STATE_VERSION, always=True),
    F(2, "chain_id", "string"),
    F(3, "last_block_height", "int64"),
    F(4, "last_block_id", "msg", msg=BLOCK_ID, always=True),
    F(5, "last_block_time", "msg", msg=TIMESTAMP, always=True),
    F(6, "next_validators", "msg", msg=VALIDATOR_SET),
    F(7, "validators", "msg", msg=VALIDATOR_SET),
    F(8, "last_validators", "msg", msg=VALIDATOR_SET),
    F(9, "last_height_validators_changed", "int64"),
    F(10, "consensus_params", "msg", msg=CONSENSUS_PARAMS, always=True),
    F(11, "last_height_consensus_params_changed", "int64"),
    F(12, "last_results_hash", "bytes"),
    F(13, "app_hash", "bytes"),
    F(14, "initial_height", "int64"),
    F(15, "next_block_delay", "msg", msg=DURATION, always=True),
)

VALIDATORS_INFO = Msg(
    "cometbft.state.v2.ValidatorsInfo",
    F(1, "validator_set", "msg", msg=VALIDATOR_SET),
    F(2, "last_height_changed", "int64"),
)

CONSENSUS_PARAMS_INFO = Msg(
    "cometbft.state.v2.ConsensusParamsInfo",
    F(1, "consensus_params", "msg", msg=CONSENSUS_PARAMS, always=True),
    F(2, "last_height_changed", "int64"),
)

ABCI_RESPONSES_INFO = Msg(
    "cometbft.state.v2.ABCIResponsesInfo",
    F(2, "height", "int64"),
    F(3, "finalize_block", "msg", msg=FINALIZE_BLOCK_RESPONSE),
)
