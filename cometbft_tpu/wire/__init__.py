"""Deterministic wire encoding (protobuf) for consensus-critical bytes.

The reference serializes every consensus-visible artifact (sign-bytes, header
field hashes, stored blocks, p2p messages) as gogoproto-generated protobuf
(reference: proto/ + api/, ~173k generated LoC).  Here the same wire format is
produced by a ~200-line descriptor-driven encoder instead of codegen: each
message is a dict, each schema a `Msg` descriptor, and encoding is canonical
(ascending field order, proto3 zero-omission, gogoproto non-nullable embedded
messages always emitted).  Byte-compatibility is pinned by the reference's own
sign-bytes test vectors (tests/test_wire.py).
"""
from .proto import Msg, F, encode, decode, marshal_delimited, unmarshal_delimited
from . import pb

__all__ = [
    "Msg", "F", "encode", "decode", "marshal_delimited",
    "unmarshal_delimited", "pb",
]
