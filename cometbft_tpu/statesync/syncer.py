"""Statesync syncer: discover snapshots → offer → fetch chunks → apply.

Reference: statesync/syncer.go (:144 SyncAny, :236 Sync),
statesync/chunks.go (queue), statesync/stateprovider.go (light-client
backed trusted state at the snapshot height).
"""
from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Optional

from ..abci import types as abci
from ..libs.log import Logger, new_logger
from ..state.state import State as SMState, StateVersion
from ..types.block import ConsensusVersion
from ..types.block_id import BlockID
from ..types.commit import Commit


class StatesyncError(Exception):
    pass


class RejectSnapshotError(StatesyncError):
    pass


@dataclass(frozen=True)
class SnapshotKey:
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""


class StateProvider:
    """Trusted state + commit at a height, via the light client
    (reference: stateprovider.go:29 — the light client runs over RPC;
    here over any light Provider)."""

    def __init__(self, light_client, chain_id: str,
                 genesis_doc):
        self.light_client = light_client
        self.chain_id = chain_id
        self.genesis_doc = genesis_doc

    async def app_hash(self, height: int) -> bytes:
        # header at height+1 carries the app hash AFTER height
        lb = await self.light_client.verify_light_block_at_height(
            height + 1)
        return lb.signed_header.header.app_hash

    async def commit(self, height: int) -> Commit:
        lb = await self.light_client.verify_light_block_at_height(
            height)
        return lb.signed_header.commit

    async def state(self, height: int) -> SMState:
        """Reconstruct sm.State at `height` (reference:
        stateprovider State)."""
        cur = await self.light_client.verify_light_block_at_height(
            height)
        nxt = await self.light_client.verify_light_block_at_height(
            height + 1)
        nxt2 = await self.light_client.verify_light_block_at_height(
            height + 2)
        state = SMState(
            version=StateVersion(consensus=ConsensusVersion(
                block=cur.signed_header.header.version.block,
                app=cur.signed_header.header.version.app)),
            chain_id=self.chain_id,
            initial_height=self.genesis_doc.initial_height,
            last_block_height=cur.height,
            # the commit AT `height` carries block `height`'s BlockID —
            # including the part-set header blocksync validates the next
            # block's Header.LastBlockID against
            last_block_id=cur.signed_header.commit.block_id,
            last_block_time=cur.signed_header.header.time,
            validators=nxt.validator_set,
            next_validators=nxt2.validator_set,
            last_validators=cur.validator_set,
            last_height_validators_changed=cur.height,
            consensus_params=self.genesis_doc.consensus_params
            .update(None),
            last_height_consensus_params_changed=(
                self.genesis_doc.initial_height),
            last_results_hash=(
                nxt.signed_header.header.last_results_hash),
            app_hash=nxt.signed_header.header.app_hash,
        )
        return state


class ChunkQueue:
    """Disk-backed chunk staging (reference: statesync/chunks.go — a
    temp-dir queue so a large snapshot never lives in process memory,
    with per-chunk sender tracking for reject_senders)."""

    def __init__(self, snap: SnapshotKey, directory: str):
        import os
        self.snap = snap
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._have: set[int] = set()
        self._senders: dict[int, str] = {}
        self.event = asyncio.Event()

    def _path(self, index: int) -> str:
        import os
        return os.path.join(self.dir, f"chunk-{index:06d}")

    def add(self, index: int, chunk: bytes, sender: str = "") -> bool:
        if index in self._have or not (0 <= index < self.snap.chunks):
            return False
        with open(self._path(index), "wb") as f:
            f.write(chunk)
        self._have.add(index)
        self._senders[index] = sender
        self.event.set()
        return True

    def has(self, index: int) -> bool:
        return index in self._have

    def load(self, index: int) -> bytes:
        with open(self._path(index), "rb") as f:
            return f.read()

    def sender(self, index: int) -> str:
        return self._senders.get(index, "")

    def discard(self, index: int) -> None:
        """Drop a chunk so it gets refetched (reference: chunks.go
        Discard)."""
        import os
        if index in self._have:
            self._have.discard(index)
            self._senders.pop(index, None)
            try:
                os.remove(self._path(index))
            except OSError:
                pass

    def discard_from_sender(self, sender: str) -> list[int]:
        """Drop every chunk from a banned sender (reject_senders)."""
        bad = [i for i, s in self._senders.items() if s == sender]
        for i in bad:
            self.discard(i)
        return bad

    def close(self) -> None:
        import shutil
        shutil.rmtree(self.dir, ignore_errors=True)


class Syncer:
    """Reference: statesync/syncer.go."""

    def __init__(self, app_conns, state_provider: StateProvider,
                 request_chunk,
                 chunk_timeout_s: float = 10.0,
                 chunk_fetch_rounds: int = 4,
                 chunk_dir: Optional[str] = None,
                 logger: Optional[Logger] = None):
        """request_chunk(snapshot, index) asks some peer for a chunk;
        results arrive via add_chunk.  chunk_fetch_rounds bounds how
        many consecutive chunk-timeout rounds with zero progress are
        tolerated before the snapshot is rejected (reference:
        syncer.go fetchChunks errTimeout -> SyncAny tries the next
        snapshot instead of waiting forever on a peer that pruned
        it)."""
        self.app_conns = app_conns
        self.state_provider = state_provider
        self.request_chunk = request_chunk
        self.request_snapshots = None   # optional reactor hook
        self.chunk_timeout_s = chunk_timeout_s
        self.chunk_fetch_rounds = chunk_fetch_rounds
        self.chunk_dir = chunk_dir
        self._owns_chunk_dir = chunk_dir is None
        self.logger = logger if logger is not None else \
            new_logger("statesync")
        self.snapshots: dict[SnapshotKey, set[str]] = {}
        self._queue: Optional[ChunkQueue] = None
        self.banned_senders: set[str] = set()

    # ------------------------------------------------------------------
    def add_snapshot(self, peer_id: str, snap: SnapshotKey) -> None:
        self.snapshots.setdefault(snap, set()).add(peer_id)

    def add_chunk(self, height: int, format_: int, index: int,
                  chunk: bytes, sender: str = "") -> None:
        q = self._queue
        if q is None or q.snap.height != height or \
                q.snap.format != format_:
            return
        if sender in self.banned_senders:
            return
        q.add(index, chunk, sender)

    # ------------------------------------------------------------------
    async def sync_any(self, discovery_time_s: float = 2.0,
                       max_discovery_rounds: int = 20
                       ) -> tuple[SMState, Commit]:
        """Try snapshots best-first until one applies; keeps
        re-discovering while none are available (reference: SyncAny
        retries discovery instead of failing on a slow peer)."""
        await asyncio.sleep(discovery_time_s)
        tried: set[SnapshotKey] = set()
        rounds = 0
        while True:
            best = self._best_snapshot(tried)
            if best is None:
                rounds += 1
                if rounds >= max_discovery_rounds:
                    raise StatesyncError(
                        "no viable snapshots (discovered "
                        f"{len(self.snapshots)})")
                self.logger.info("no snapshots yet; rediscovering",
                                 round=rounds)
                if self.request_snapshots is not None:
                    # ask peers again — sources prune old snapshots
                    # and take new ones while we retry (reference:
                    # reactor.go re-requests on recentSnapshots)
                    self.request_snapshots()
                await asyncio.sleep(discovery_time_s)
                continue
            tried.add(best)
            try:
                return await self._sync(best)
            except RejectSnapshotError as e:
                self.logger.info("snapshot rejected; trying next",
                                 height=best.height, err=str(e))
                if self.request_snapshots is not None:
                    self.request_snapshots()
                continue

    def _best_snapshot(self, tried: set) -> Optional[SnapshotKey]:
        candidates = [s for s in self.snapshots if s not in tried]
        if not candidates:
            return None
        return max(candidates, key=lambda s: (s.height, -s.format))

    async def _sync(self, snap: SnapshotKey) -> tuple[SMState, Commit]:
        """Reference: syncer.Sync (:236)."""
        # verify the app hash for the snapshot height FIRST (trusted
        # via the light client)
        app_hash = await self.state_provider.app_hash(snap.height)
        offer = await self.app_conns.snapshot.offer_snapshot(
            abci.OfferSnapshotRequest(
                snapshot=abci.Snapshot(
                    height=snap.height, format=snap.format,
                    chunks=snap.chunks, hash=snap.hash,
                    metadata=snap.metadata),
                app_hash=app_hash))
        if offer.result != abci.OFFER_SNAPSHOT_RESULT_ACCEPT:
            raise RejectSnapshotError(
                f"app rejected snapshot: {offer.result}")

        import os
        import tempfile
        if self.chunk_dir is None:
            self.chunk_dir = tempfile.mkdtemp(
                prefix="statesync-chunks-")
        self._queue = ChunkQueue(
            snap, os.path.join(self.chunk_dir,
                               f"snap-{snap.height}-{snap.format}"))
        q = self._queue
        try:
            # parallel fetchers with per-chunk retry; chunks applied
            # strictly in order (reference: syncer.go fetchChunks +
            # applyChunks)
            applied = 0
            requested: set[int] = set()
            dry_rounds = 0
            while applied < snap.chunks:
                for i in range(snap.chunks):
                    if not q.has(i) and i not in requested:
                        self.request_chunk(snap, i)
                        requested.add(i)
                if not q.has(applied):
                    # clear BEFORE re-checking: a chunk landing between
                    # a has() miss and the clear would otherwise wipe
                    # its own wakeup and stall a full chunk_timeout_s
                    q.event.clear()
                    if not q.has(applied):
                        try:
                            await asyncio.wait_for(
                                q.event.wait(), self.chunk_timeout_s)
                            dry_rounds = 0
                        except asyncio.TimeoutError:
                            dry_rounds += 1
                            if dry_rounds >= self.chunk_fetch_rounds:
                                # the advertising peers cannot serve it
                                # anymore (pruned / gone) — reject and
                                # let sync_any pick a newer snapshot
                                raise RejectSnapshotError(
                                    "timed out waiting for chunks "
                                    f"({dry_rounds} rounds)")
                            # re-request everything missing
                            requested.clear()
                    continue
                dry_rounds = 0
                resp = await \
                    self.app_conns.snapshot.apply_snapshot_chunk(
                        abci.ApplySnapshotChunkRequest(
                            index=applied, chunk=q.load(applied),
                            sender=q.sender(applied)))
                # senders the app rejects are banned and their chunks
                # refetched (reference: syncer.go applyChunks)
                for bad in resp.reject_senders:
                    if bad:
                        self.banned_senders.add(bad)
                        for i in q.discard_from_sender(bad):
                            requested.discard(i)
                for i in resp.refetch_chunks:
                    q.discard(i)
                    requested.discard(i)
                if resp.result == \
                        abci.APPLY_SNAPSHOT_CHUNK_RESULT_ACCEPT:
                    applied += 1
                elif resp.result == \
                        abci.APPLY_SNAPSHOT_CHUNK_RESULT_RETRY:
                    q.discard(applied)
                    requested.discard(applied)
                else:
                    raise RejectSnapshotError(
                        f"chunk apply failed: {resp.result}")
        finally:
            q.close()
            self._queue = None
            if self._owns_chunk_dir and self.chunk_dir is not None:
                import shutil
                shutil.rmtree(self.chunk_dir, ignore_errors=True)
                self.chunk_dir = None

        # verify the app's restored state matches the trusted app hash
        info = await self.app_conns.query.info(abci.InfoRequest())
        if info.last_block_app_hash != app_hash:
            raise RejectSnapshotError(
                "restored app hash does not match trusted header")
        if info.last_block_height != snap.height:
            raise RejectSnapshotError(
                "restored app height does not match snapshot")

        state = await self.state_provider.state(snap.height)
        commit = await self.state_provider.commit(snap.height)
        self.logger.info("Snapshot restored", height=snap.height)
        return state, commit


async def new_rpc_state_provider(chain_id: str, genesis_doc,
                                 servers: list[str],
                                 trust_height: int, trust_hash: bytes,
                                 trust_period_ns: int = 168 * 3600 * 10**9
                                 ) -> StateProvider:
    """StateProvider backed by a light client over real RPC servers
    (reference: stateprovider.go:29 NewLightClientStateProvider — the
    config.statesync rpc_servers + trust height/hash path).  The first
    server is the primary, the rest are witnesses."""
    from ..db.db import MemDB
    from ..light.client import Client as LightClient, TrustOptions
    from ..light.provider import HttpProvider
    from ..light.store import TrustedStore

    if not servers:
        raise StatesyncError("statesync needs at least one RPC server")
    providers = [HttpProvider(addr, chain_id) for addr in servers]
    client = LightClient(
        chain_id,
        TrustOptions(period_ns=trust_period_ns, height=trust_height,
                     header_hash=trust_hash),
        providers[0], providers[1:], TrustedStore(MemDB()))
    await client.initialize()
    return StateProvider(client, chain_id, genesis_doc)
