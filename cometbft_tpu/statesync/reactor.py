"""Statesync reactor: snapshot discovery + chunk transfer channels.

Reference: statesync/reactor.go — SnapshotChannel 0x60 and ChunkChannel
0x61; serves snapshots from the local app, feeds the Syncer.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from ..abci import types as abci
from ..libs.log import Logger
from ..p2p.conn import ChannelDescriptor
from ..p2p.switch import Peer, Reactor
from ..wire import encode, decode
from ..wire.proto import F, Msg
from .syncer import SnapshotKey, Syncer

SNAPSHOT_CHANNEL = 0x60
CHUNK_CHANNEL = 0x61

SNAPSHOTS_REQUEST = Msg("cometbft.statesync.v1.SnapshotsRequest")
SNAPSHOTS_RESPONSE = Msg(
    "cometbft.statesync.v1.SnapshotsResponse",
    F(1, "height", "uint64"), F(2, "format", "uint32"),
    F(3, "chunks", "uint32"), F(4, "hash", "bytes"),
    F(5, "metadata", "bytes"))
CHUNK_REQUEST = Msg(
    "cometbft.statesync.v1.ChunkRequest",
    F(1, "height", "uint64"), F(2, "format", "uint32"),
    F(3, "index", "uint32"))
CHUNK_RESPONSE = Msg(
    "cometbft.statesync.v1.ChunkResponse",
    F(1, "height", "uint64"), F(2, "format", "uint32"),
    F(3, "index", "uint32"), F(4, "chunk", "bytes"),
    F(5, "missing", "bool"))
MESSAGE = Msg(
    "cometbft.statesync.v1.Message",
    F(1, "snapshots_request", "msg", msg=SNAPSHOTS_REQUEST),
    F(2, "snapshots_response", "msg", msg=SNAPSHOTS_RESPONSE),
    F(3, "chunk_request", "msg", msg=CHUNK_REQUEST),
    F(4, "chunk_response", "msg", msg=CHUNK_RESPONSE),
)


class StatesyncReactor(Reactor):
    def __init__(self, app_conns, syncer: Optional[Syncer] = None,
                 logger: Optional[Logger] = None, metrics=None):
        """syncer present = we are state-syncing; absent = serve only."""
        super().__init__("STATESYNC")
        if logger is not None:
            self.logger = logger
        from .metrics import Metrics
        self.metrics = metrics if metrics is not None else Metrics()
        self.metrics.syncing.set(1 if syncer is not None else 0)
        self.app_conns = app_conns
        self.syncer = syncer
        if syncer is not None:
            syncer.request_snapshots = self.request_snapshots
        # chunk requests round-robin across peers that offered the
        # snapshot
        self._snapshot_peers: dict[SnapshotKey, list[str]] = {}
        self._rr = 0

    def get_channels(self) -> list[ChannelDescriptor]:
        return [
            ChannelDescriptor(id=SNAPSHOT_CHANNEL, priority=5,
                              send_queue_capacity=10),
            ChannelDescriptor(id=CHUNK_CHANNEL, priority=3,
                              send_queue_capacity=16),
        ]

    async def add_peer(self, peer: Peer) -> None:
        if self.syncer is not None:
            peer.send(SNAPSHOT_CHANNEL,
                      encode(MESSAGE, {"snapshots_request": {}}))

    def request_snapshots(self) -> None:
        """Re-poll every peer's snapshot list (Syncer re-discovery
        hook: advertised snapshots age out on the serving side while
        chunks are being fetched)."""
        if self.switch is None:
            return
        for peer in list(self.switch.peers.values()):
            peer.send(SNAPSHOT_CHANNEL,
                      encode(MESSAGE, {"snapshots_request": {}}))

    async def receive(self, chan_id: int, peer: Peer,
                      msg_bytes: bytes) -> None:
        d = decode(MESSAGE, msg_bytes)
        if "snapshots_request" in d:
            res = await self.app_conns.snapshot.list_snapshots(
                abci.ListSnapshotsRequest())
            for s in res.snapshots[:10]:
                peer.send(SNAPSHOT_CHANNEL, encode(MESSAGE, {
                    "snapshots_response": {
                        **({"height": s.height} if s.height else {}),
                        **({"format": s.format} if s.format else {}),
                        **({"chunks": s.chunks} if s.chunks else {}),
                        **({"hash": s.hash} if s.hash else {}),
                        **({"metadata": s.metadata}
                           if s.metadata else {})}}))
        elif "snapshots_response" in d and self.syncer is not None:
            sr = d["snapshots_response"]
            snap = SnapshotKey(
                height=sr.get("height", 0), format=sr.get("format", 0),
                chunks=sr.get("chunks", 0), hash=sr.get("hash", b""),
                metadata=sr.get("metadata", b""))
            self.syncer.add_snapshot(peer.id, snap)
            self._snapshot_peers.setdefault(snap, [])
            if peer.id not in self._snapshot_peers[snap]:
                self._snapshot_peers[snap].append(peer.id)
        elif "chunk_request" in d:
            cr = d["chunk_request"]
            res = await self.app_conns.snapshot.load_snapshot_chunk(
                abci.LoadSnapshotChunkRequest(
                    height=cr.get("height", 0),
                    format=cr.get("format", 0),
                    chunk=cr.get("index", 0)))
            peer.send(CHUNK_CHANNEL, encode(MESSAGE, {
                "chunk_response": {
                    **({"height": cr.get("height", 0)}
                       if cr.get("height") else {}),
                    **({"format": cr.get("format", 0)}
                       if cr.get("format") else {}),
                    **({"index": cr.get("index", 0)}
                       if cr.get("index") else {}),
                    **({"chunk": res.chunk} if res.chunk else {}),
                    **({} if res.chunk else {"missing": True})}}))
        elif "chunk_response" in d and self.syncer is not None:
            cr = d["chunk_response"]
            if not cr.get("missing", False):
                self.syncer.add_chunk(
                    cr.get("height", 0), cr.get("format", 0),
                    cr.get("index", 0), cr.get("chunk", b""),
                    sender=peer.id)

    # ------------------------------------------------------------------
    def request_chunk(self, snap: SnapshotKey, index: int) -> None:
        """Chunk fetch hook for the Syncer (round-robin over the peers
        that advertised this snapshot)."""
        if self.switch is None:
            return
        peer_ids = self._snapshot_peers.get(snap, [])
        candidates = [self.switch.peers[pid] for pid in peer_ids
                      if pid in self.switch.peers]
        if not candidates:
            candidates = list(self.switch.peers.values())
        if not candidates:
            return
        self._rr += 1
        peer = candidates[self._rr % len(candidates)]
        peer.send(CHUNK_CHANNEL, encode(MESSAGE, {
            "chunk_request": {
                **({"height": snap.height} if snap.height else {}),
                **({"format": snap.format} if snap.format else {}),
                **({"index": index} if index else {})}}))
