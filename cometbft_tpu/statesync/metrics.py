"""Statesync metrics (reference: statesync/metrics.gen.go)."""
from __future__ import annotations

from typing import Optional

from ..libs import metrics as libmetrics


class Metrics:
    def __init__(self, registry: Optional[libmetrics.Registry] = None):
        m = registry if registry is not None else libmetrics.Registry()
        self.syncing = m.gauge(
            "statesync", "syncing",
            "Whether or not a node is state syncing. 1 if yes, 0 if "
            "no.")
