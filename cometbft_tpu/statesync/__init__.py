"""State sync: bootstrap a fresh node from application snapshots."""
from .reactor import StatesyncReactor
from .syncer import StateProvider, Syncer

__all__ = ["StatesyncReactor", "StateProvider", "Syncer"]
