"""State store: persistence of sm.State and per-height lookback records.

Reference: state/store.go:157 (Store interface, dbStore impl) — state
record, validator sets and consensus params by height (with lookback
pointers so unchanged heights store only a reference), finalize-block
responses, pruning, bootstrap.
"""
from __future__ import annotations

import struct
import threading
from typing import Optional

from ..db import DB
from ..types.params import ConsensusParams
from ..types.validator_set import ValidatorSet
from ..wire import state_pb, abci_pb, encode, decode
from .state import State

_STATE_KEY = b"stateKey"
_VALIDATORS = b"\x10"       # height -> ValidatorsInfo
_CONSENSUS_PARAMS = b"\x11"  # height -> ConsensusParamsInfo
_ABCI_RESPONSES = b"\x12"   # height -> ABCIResponsesInfo

# how far ahead validator sets are known (nextValSet delay)
VAL_SET_CHECKPOINT_INTERVAL = 100000


def _h(height: int) -> bytes:
    return struct.pack(">q", height)


def _validators_key(height: int) -> bytes:
    return _VALIDATORS + _h(height)


def _params_key(height: int) -> bytes:
    return _CONSENSUS_PARAMS + _h(height)


def _abci_responses_key(height: int) -> bytes:
    return _ABCI_RESPONSES + _h(height)


class StateStoreError(Exception):
    pass


class Store:
    def __init__(self, db: DB):
        self._db = db
        self._lock = threading.RLock()
        # height -> (last_height_changed, set AS OF height, rolled).
        # The sparse storage scheme (full set only at change/checkpoint
        # heights) makes a cold load_validators(h) roll proposer
        # priorities forward O(h - stored) steps; block application
        # loads h-1 every height, which is O(h^2) over a run and
        # starves the event loop on long-lived chains.  Caching the
        # last few rolled-forward sets makes the sequential pattern
        # one increment step per height.
        #
        # Bit-equality with the cold path: increment(k) applies
        # rescale+shift ONCE, then k raw steps — so advancing a cached
        # set by one height must apply rescale+shift only when the
        # entry is the as-stored base (rolled=False); an already-rolled
        # entry advances by one RAW step.  Chaining any other way
        # diverges from the reference's one-shot LoadValidators when
        # the stored priority spread exceeds the rescale window.
        self._val_cache: dict[
            int, tuple[int, ValidatorSet, bool]] = {}

    # ------------------------------------------------------------------
    def load(self) -> Optional[State]:
        raw = self._db.get(_STATE_KEY)
        if raw is None:
            return None
        return State.from_bytes(raw)

    def save(self, state: State) -> None:
        """Persist state + the next validator set + params records.

        Reference: store.go save — writes validators at
        LastBlockHeight+2 (the nextValSet delay) and params at +1."""
        with self._lock:
            next_height = state.last_block_height + 1
            if state.last_block_height == 0:   # genesis bootstrap
                # reference: save uses InitialHeight when nextHeight == 1
                next_height = state.initial_height
                self._save_validators(next_height, state.validators,
                                      state.last_height_validators_changed)
            self._save_validators(next_height + 1, state.next_validators,
                                  state.last_height_validators_changed)
            self._save_params(next_height, state.consensus_params,
                              state.last_height_consensus_params_changed)
            self._db.set_sync(_STATE_KEY, state.bytes())

    def bootstrap(self, state: State) -> None:
        """Reference: store.go Bootstrap — used by state sync."""
        with self._lock:
            self._val_cache.clear()
            height = state.last_block_height + 1
            if height > 1 and state.last_validators is not None and \
                    state.last_validators.size() > 0:
                self._save_validators(
                    height - 1, state.last_validators, height - 1)
            self._save_validators(height, state.validators, height)
            self._save_validators(height + 1, state.next_validators,
                                  height + 1)
            self._save_params(
                height, state.consensus_params,
                state.last_height_consensus_params_changed or height)
            self._db.set_sync(_STATE_KEY, state.bytes())

    # ------------------------------------------------------------------
    def _save_validators(self, height: int, vals: ValidatorSet,
                         last_changed: int) -> None:
        # store the full set at change/checkpoint heights, else a pointer
        d: dict = {"last_height_changed": last_changed}
        if height == last_changed or \
                height % VAL_SET_CHECKPOINT_INTERVAL == 0:
            d["validator_set"] = vals.to_proto()
        self._val_cache.pop(height, None)   # record is being rewritten
        self._db.set(_validators_key(height),
                     encode(state_pb.VALIDATORS_INFO, d))

    @staticmethod
    def _last_stored_height_for(height: int, last_changed: int) -> int:
        """Reference: store.go lastStoredHeightFor — the nearest height
        at which a FULL validator set exists: the later of the last
        change height and the last checkpoint."""
        checkpoint = height - height % VAL_SET_CHECKPOINT_INTERVAL
        return max(checkpoint, last_changed)

    def load_validators(self, height: int) -> ValidatorSet:
        """Reference: store.go LoadValidators with checkpoint-aware
        lookback (plus the incremental roll-forward cache above)."""
        with self._lock:
            hit = self._val_cache.get(height)
            if hit is not None:
                return hit[1].copy()
            raw = self._db.get(_validators_key(height))
            if raw is None:
                raise StateStoreError(
                    f"no validator set found for height {height}")
            info = decode(state_pb.VALIDATORS_INFO, raw)
            if info.get("validator_set") is not None:
                vals = ValidatorSet.from_proto(info["validator_set"])
                self._cache_validators(
                    height, info.get("last_height_changed", height),
                    vals, rolled=False)
                return vals
            last_changed = info.get("last_height_changed", 0)
            prev = self._val_cache.get(height - 1)
            if prev is not None and prev[0] == last_changed:
                # same lineage: one priority step from height-1
                prev_lc, prev_vals, prev_rolled = prev
                if prev_rolled:
                    # already past rescale+shift: raw step only
                    vals = prev_vals.copy()
                    vals.advance_proposer_priority_step()
                else:
                    vals = prev_vals.copy_increment_proposer_priority(1)
                self._cache_validators(height, last_changed, vals,
                                       rolled=True)
                return vals
            stored_height = self._last_stored_height_for(
                height, last_changed)
            raw2 = self._db.get(_validators_key(stored_height))
            if raw2 is None:
                raise StateStoreError(
                    f"validator lookback to {stored_height} failed "
                    f"for height {height}")
            info2 = decode(state_pb.VALIDATORS_INFO, raw2)
            if info2.get("validator_set") is None:
                raise StateStoreError(
                    f"validator set at lookback height {stored_height} "
                    f"is empty")
            vals = ValidatorSet.from_proto(info2["validator_set"])
            # roll priorities forward to the requested height
            rolled = height > stored_height
            if rolled:
                vals.increment_proposer_priority(height - stored_height)
            self._cache_validators(height, last_changed, vals,
                                   rolled=rolled)
            return vals

    def _cache_validators(self, height: int, last_changed: int,
                          vals: ValidatorSet, *,
                          rolled: bool) -> None:
        """Remember the set (own copy); keep the cache to a handful of
        recent heights — the sequential block-apply pattern only ever
        needs height-1.  `rolled` records whether increment's
        rescale+shift prologue has run (see the cache comment)."""
        self._val_cache[height] = (last_changed, vals.copy(), rolled)
        if len(self._val_cache) > 8:
            for h in sorted(self._val_cache)[:-4]:
                del self._val_cache[h]

    # ------------------------------------------------------------------
    def _save_params(self, height: int, params: ConsensusParams,
                     last_changed: int) -> None:
        d: dict = {"last_height_changed": last_changed}
        if height == last_changed or \
                height % VAL_SET_CHECKPOINT_INTERVAL == 0:
            d["consensus_params"] = params.to_proto()
        else:
            d["consensus_params"] = {}
        self._db.set(_params_key(height),
                     encode(state_pb.CONSENSUS_PARAMS_INFO, d))

    def load_consensus_params(self, height: int) -> ConsensusParams:
        raw = self._db.get(_params_key(height))
        if raw is None:
            raise StateStoreError(
                f"no consensus params found for height {height}")
        info = decode(state_pb.CONSENSUS_PARAMS_INFO, raw)
        params_d = info.get("consensus_params") or {}
        if params_d:
            return ConsensusParams.from_proto(params_d)
        last_changed = info.get("last_height_changed", 0)
        raw2 = self._db.get(_params_key(last_changed))
        if raw2 is None:
            raise StateStoreError(
                f"params lookback to {last_changed} failed")
        info2 = decode(state_pb.CONSENSUS_PARAMS_INFO, raw2)
        if not info2.get("consensus_params"):
            raise StateStoreError(
                f"params at change-height {last_changed} are empty")
        return ConsensusParams.from_proto(info2["consensus_params"])

    # ------------------------------------------------------------------
    def save_finalize_block_response(self, height: int, resp) -> None:
        """Persist the FinalizeBlockResponse BEFORE app Commit so crash
        recovery can reconstruct results (reference: store.go
        SaveFinalizeBlockResponse)."""
        d = _fbr_to_proto(resp)
        self._db.set_sync(
            _abci_responses_key(height),
            encode(state_pb.ABCI_RESPONSES_INFO,
                   {"height": height, "finalize_block": d}))

    def load_finalize_block_response(self, height: int):
        raw = self._db.get(_abci_responses_key(height))
        if raw is None:
            return None
        info = decode(state_pb.ABCI_RESPONSES_INFO, raw)
        fb = info.get("finalize_block")
        return _fbr_from_proto(fb) if fb is not None else None

    # ------------------------------------------------------------------
    def prune_abci_responses(self, from_height: int,
                             to_height: int) -> int:
        """Delete stored FinalizeBlockResponses in [from, to) — the
        data-companion artifact class (reference: store.go
        PruneABCIResponses).  Returns number deleted."""
        if from_height <= 0 or to_height <= from_height:
            return 0
        batch = self._db.new_batch()
        pruned = 0
        for k, _ in list(self._db.iterator(
                _abci_responses_key(from_height),
                _abci_responses_key(to_height))):
            batch.delete(k)
            pruned += 1
        batch.write()
        return pruned

    # ------------------------------------------------------------------
    def prune_states(self, from_height: int, to_height: int,
                     evidence_threshold_height: int) -> int:
        """Delete state records in [from, to) (reference: store.go
        PruneStates — kept heights are materialized in full BEFORE their
        lookback targets are deleted); returns number pruned."""
        if from_height <= 0 or to_height <= from_height:
            return 0
        self._val_cache.clear()
        # heights whose FULL validator records must survive: the lookback
        # targets of to_height and of the evidence threshold (reference:
        # store.go PruneStates keepVals)
        keep_val_heights: set[int] = set()
        for keep in {to_height, evidence_threshold_height}:
            if keep <= 0:
                continue
            raw = self._db.get(_validators_key(keep))
            if raw is None:
                continue
            info = decode(state_pb.VALIDATORS_INFO, raw)
            if info.get("validator_set") is None:
                keep_val_heights.add(self._last_stored_height_for(
                    keep, info.get("last_height_changed", 0)))
        # materialize params at to_height so its pointer cannot dangle
        try:
            params = self.load_consensus_params(to_height)
            self._db.set(
                _params_key(to_height),
                encode(state_pb.CONSENSUS_PARAMS_INFO,
                       {"last_height_changed": to_height,
                        "consensus_params": params.to_proto()}))
        except StateStoreError:
            pass
        pruned = 0
        batch = self._db.new_batch()
        for h in range(from_height, to_height):
            batch.delete(_abci_responses_key(h))
            if h < evidence_threshold_height and \
                    h not in keep_val_heights:
                batch.delete(_validators_key(h))
            batch.delete(_params_key(h))
            pruned += 1
        batch.write()
        return pruned


def _fbr_to_proto(resp) -> dict:
    """abci.FinalizeBlockResponse dataclass -> proto dict."""
    def event(e):
        return {
            **({"type": e.type} if e.type else {}),
            "attributes": [
                {**({"key": a.key} if a.key else {}),
                 **({"value": a.value} if a.value else {}),
                 **({"index": True} if a.index else {})}
                for a in e.attributes],
        }

    def txr(r):
        d: dict = {}
        if r.code:
            d["code"] = r.code
        if r.data:
            d["data"] = r.data
        if r.log:
            d["log"] = r.log
        if r.info:
            d["info"] = r.info
        if r.gas_wanted:
            d["gas_wanted"] = r.gas_wanted
        if r.gas_used:
            d["gas_used"] = r.gas_used
        if r.events:
            d["events"] = [event(e) for e in r.events]
        if r.codespace:
            d["codespace"] = r.codespace
        if r.recheck_keys:
            d["recheck_keys"] = list(r.recheck_keys)
        return d

    d: dict = {"next_block_delay": {}}
    if resp.events:
        d["events"] = [event(e) for e in resp.events]
    if resp.tx_results:
        d["tx_results"] = [txr(r) for r in resp.tx_results]
    if resp.validator_updates:
        d["validator_updates"] = [
            {**({"power": v.power} if v.power else {}),
             **({"pub_key_bytes": v.pub_key_bytes}
                if v.pub_key_bytes else {}),
             **({"pub_key_type": v.pub_key_type}
                if v.pub_key_type else {})}
            for v in resp.validator_updates]
    if resp.consensus_param_updates is not None:
        d["consensus_param_updates"] = \
            resp.consensus_param_updates.to_proto()
    if resp.app_hash:
        d["app_hash"] = resp.app_hash
    if resp.next_block_delay_ns:
        s, ns = divmod(resp.next_block_delay_ns, 1_000_000_000)
        nd: dict = {}
        if s:
            nd["seconds"] = s
        if ns:
            nd["nanos"] = ns
        d["next_block_delay"] = nd
    return d


def _fbr_from_proto(d: dict):
    from ..abci import types as abci_types

    def event(e):
        return abci_types.Event(
            type=e.get("type", ""),
            attributes=[abci_types.EventAttribute(
                key=a.get("key", ""), value=a.get("value", ""),
                index=a.get("index", False))
                for a in e.get("attributes", [])])

    nd = d.get("next_block_delay") or {}
    cpu = d.get("consensus_param_updates")
    return abci_types.FinalizeBlockResponse(
        events=[event(e) for e in d.get("events", [])],
        tx_results=[abci_types.ExecTxResult(
            code=r.get("code", 0), data=r.get("data", b""),
            log=r.get("log", ""), info=r.get("info", ""),
            gas_wanted=r.get("gas_wanted", 0),
            gas_used=r.get("gas_used", 0),
            events=[event(e) for e in r.get("events", [])],
            codespace=r.get("codespace", ""),
            recheck_keys=list(r.get("recheck_keys", [])))
            for r in d.get("tx_results", [])],
        validator_updates=[abci_types.ValidatorUpdate(
            power=v.get("power", 0),
            pub_key_bytes=v.get("pub_key_bytes", b""),
            pub_key_type=v.get("pub_key_type", ""))
            for v in d.get("validator_updates", [])],
        consensus_param_updates=ConsensusParams.from_proto(cpu)
        if cpu is not None else None,
        app_hash=d.get("app_hash", b""),
        next_block_delay_ns=nd.get("seconds", 0) * 1_000_000_000 +
        nd.get("nanos", 0),
    )
