"""sm.State: the deterministic node state between blocks.

Reference: state/state.go — State value (:47-84), MakeGenesisState
(:303), MakeBlock (:253-ish).
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

from .. import version as _version
from ..types.block import Block, ConsensusVersion, Data, Header
from ..types.block_id import BlockID
from ..types.commit import Commit
from ..types.genesis import GenesisDoc
from ..types.params import ConsensusParams
from ..types.timestamp import Timestamp
from ..types.validator import Validator
from ..types.validator_set import ValidatorSet
from ..wire import pb, state_pb, encode, decode


class StateError(Exception):
    pass


@dataclass
class StateVersion:
    consensus: ConsensusVersion = field(default_factory=ConsensusVersion)
    software: str = _version.CMT_SEM_VER


@dataclass
class State:
    version: StateVersion = field(default_factory=StateVersion)
    chain_id: str = ""
    initial_height: int = 0

    last_block_height: int = 0
    last_block_id: BlockID = field(default_factory=BlockID)
    last_block_time: Timestamp = field(default_factory=Timestamp.zero)

    next_validators: Optional[ValidatorSet] = None
    validators: Optional[ValidatorSet] = None
    last_validators: Optional[ValidatorSet] = None
    last_height_validators_changed: int = 0

    consensus_params: ConsensusParams = field(
        default_factory=ConsensusParams)
    last_height_consensus_params_changed: int = 0

    last_results_hash: bytes = b""
    app_hash: bytes = b""
    # delay between committing a block and starting the next height
    next_block_delay_ns: int = 0

    def copy(self) -> "State":
        return State(
            version=replace(self.version),
            chain_id=self.chain_id,
            initial_height=self.initial_height,
            last_block_height=self.last_block_height,
            last_block_id=self.last_block_id,
            last_block_time=self.last_block_time,
            next_validators=self.next_validators.copy()
            if self.next_validators else None,
            validators=self.validators.copy() if self.validators else None,
            last_validators=self.last_validators.copy()
            if self.last_validators else None,
            last_height_validators_changed=(
                self.last_height_validators_changed),
            consensus_params=self.consensus_params.update(None),
            last_height_consensus_params_changed=(
                self.last_height_consensus_params_changed),
            last_results_hash=self.last_results_hash,
            app_hash=self.app_hash,
            next_block_delay_ns=self.next_block_delay_ns,
        )

    def is_empty(self) -> bool:
        return self.validators is None

    # ------------------------------------------------------------------
    def block_time(self, height: int, last_commit: Commit) -> Timestamp:
        """The consensus-mandated block time (reference: state.go
        MakeBlock:252-260): now() under PBTS — INCLUDING the initial
        height; genesis time at the initial height otherwise; else the
        BFT-time weighted median of LastCommit.

        The PBTS check must come first: with it second, a PBTS chain
        whose nodes boot more than message_delay after the genesis
        timestamp proposes height 1 with the genesis time, every
        validator finds the proposal untimely, and the net churns
        rounds at height 1 until the adaptive delay (+10%/round)
        catches up with the boot lag — observed live as a 16-node
        process net stuck for 20+ rounds."""
        if self.consensus_params.feature.pbts_enabled(height):
            return Timestamp.now()
        if height == self.initial_height:
            return self.last_block_time
        return last_commit.median_time(self.last_validators)

    def make_block(self, height: int, txs: list[bytes],
                   last_commit: Commit, evidence: list,
                   proposer_address: bytes,
                   block_time: Optional[Timestamp] = None) -> Block:
        """Build a block wired to this state (reference: state.go
        MakeBlock — fills header from state)."""
        block = Block(
            header=Header(
                version=ConsensusVersion(
                    block=self.version.consensus.block,
                    app=self.version.consensus.app),
                chain_id=self.chain_id,
                height=height,
                time=block_time if block_time is not None
                else self.block_time(height, last_commit),
                last_block_id=self.last_block_id,
                validators_hash=self.validators.hash(),
                next_validators_hash=self.next_validators.hash(),
                consensus_hash=self.consensus_params.hash(),
                app_hash=self.app_hash,
                last_results_hash=self.last_results_hash,
                proposer_address=proposer_address,
            ),
            data=Data(txs=txs),
            evidence=list(evidence),
            last_commit=last_commit,
        )
        block.fill_header()
        return block

    # ------------------------------------------------------------------
    def to_proto(self) -> dict:
        d: dict = {
            "version": {
                "consensus": self.version.consensus.to_proto(),
                "software": self.version.software,
            },
            "last_block_id": self.last_block_id.to_proto(),
            "last_block_time": self.last_block_time.to_proto(),
            "consensus_params": self.consensus_params.to_proto(),
            "next_block_delay": _dur_proto(self.next_block_delay_ns),
        }
        if self.chain_id:
            d["chain_id"] = self.chain_id
        if self.initial_height:
            d["initial_height"] = self.initial_height
        if self.last_block_height:
            d["last_block_height"] = self.last_block_height
        if self.next_validators is not None:
            d["next_validators"] = self.next_validators.to_proto()
        if self.validators is not None:
            d["validators"] = self.validators.to_proto()
        if self.last_validators is not None and \
                self.last_validators.size() > 0:
            d["last_validators"] = self.last_validators.to_proto()
        if self.last_height_validators_changed:
            d["last_height_validators_changed"] = \
                self.last_height_validators_changed
        if self.last_height_consensus_params_changed:
            d["last_height_consensus_params_changed"] = \
                self.last_height_consensus_params_changed
        if self.last_results_hash:
            d["last_results_hash"] = self.last_results_hash
        if self.app_hash:
            d["app_hash"] = self.app_hash
        return d

    @classmethod
    def from_proto(cls, d: dict) -> "State":
        ver = d.get("version") or {}
        nv, v, lv = (d.get("next_validators"), d.get("validators"),
                     d.get("last_validators"))
        return cls(
            version=StateVersion(
                consensus=ConsensusVersion.from_proto(
                    ver.get("consensus") or {}),
                software=ver.get("software", "")),
            chain_id=d.get("chain_id", ""),
            initial_height=d.get("initial_height", 0),
            last_block_height=d.get("last_block_height", 0),
            last_block_id=BlockID.from_proto(d.get("last_block_id") or {}),
            last_block_time=Timestamp.from_proto(
                d.get("last_block_time") or {}),
            next_validators=ValidatorSet.from_proto(nv)
            if nv is not None else None,
            validators=ValidatorSet.from_proto(v) if v is not None
            else None,
            last_validators=ValidatorSet.from_proto(lv)
            if lv is not None else ValidatorSet(),
            last_height_validators_changed=d.get(
                "last_height_validators_changed", 0),
            consensus_params=ConsensusParams.from_proto(
                d.get("consensus_params") or {}),
            last_height_consensus_params_changed=d.get(
                "last_height_consensus_params_changed", 0),
            last_results_hash=d.get("last_results_hash", b""),
            app_hash=d.get("app_hash", b""),
            next_block_delay_ns=_dur_from_proto(
                d.get("next_block_delay") or {}),
        )

    def bytes(self) -> bytes:
        return encode(state_pb.STATE, self.to_proto())

    @classmethod
    def from_bytes(cls, raw: bytes) -> "State":
        return cls.from_proto(decode(state_pb.STATE, raw))


def _dur_proto(ns: int) -> dict:
    d: dict = {}
    s, rem = divmod(ns, 1_000_000_000)
    if s:
        d["seconds"] = s
    if rem:
        d["nanos"] = rem
    return d


def _dur_from_proto(d: dict) -> int:
    return d.get("seconds", 0) * 1_000_000_000 + d.get("nanos", 0)


def make_genesis_state(gen_doc: GenesisDoc) -> State:
    """Reference: state.go MakeGenesisState (:303)."""
    gen_doc.validate_and_complete()
    if gen_doc.validators:
        validators = [Validator.new(v.pub_key, v.power)
                      for v in gen_doc.validators]
        validator_set = ValidatorSet(validators)
        next_validator_set = ValidatorSet(validators)
        next_validator_set.increment_proposer_priority(1)
    else:
        validator_set = ValidatorSet()
        next_validator_set = ValidatorSet()

    return State(
        version=StateVersion(),
        chain_id=gen_doc.chain_id,
        initial_height=gen_doc.initial_height,
        last_block_height=0,
        last_block_id=BlockID(),
        last_block_time=gen_doc.genesis_time,
        next_validators=next_validator_set,
        validators=validator_set,
        last_validators=ValidatorSet(),
        last_height_validators_changed=gen_doc.initial_height,
        consensus_params=gen_doc.consensus_params.update(None),
        last_height_consensus_params_changed=gen_doc.initial_height,
        app_hash=gen_doc.app_hash,
    )
