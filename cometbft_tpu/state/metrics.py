"""State/execution + pruner metrics (reference: state/metrics.gen.go)."""
from __future__ import annotations

from typing import Optional

from ..libs import metrics as libmetrics


class Metrics:
    def __init__(self, registry: Optional[libmetrics.Registry] = None):
        m = registry if registry is not None else libmetrics.Registry()
        self.consensus_param_updates = m.counter(
            "state", "consensus_param_updates",
            "Number of consensus parameter updates returned by the "
            "application since process start.")
        self.validator_set_updates = m.counter(
            "state", "validator_set_updates",
            "Number of validator set updates returned by the "
            "application since process start.")
        self.application_block_retain_height = m.gauge(
            "state", "application_block_retain_height",
            "The retain height set by the application.")
        self.pruning_service_block_retain_height = m.gauge(
            "state", "pruning_service_block_retain_height",
            "The retain height set by the pruning service (data "
            "companion).")
        self.block_store_base_height = m.gauge(
            "state", "block_store_base_height",
            "The first height the block store retains.")
