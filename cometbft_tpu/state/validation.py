"""Block validation against state.

Reference: state/validation.go — validateBlock: header wiring checks +
LastCommit verification via state.LastValidators.VerifyCommit (the batch
seam), evidence size checks.
"""
from __future__ import annotations

from ..types import validation as types_validation
from ..types.block import Block
from ..types.timestamp import Timestamp
from .state import State


class BlockValidationError(Exception):
    pass


def validate_block(state: State, block: Block) -> None:
    """Reference: state/validation.go validateBlock."""
    try:
        block.validate_basic()
    except Exception as e:  # BlockError and friends -> one error type,
        # so every caller's "invalid block" handling sees it
        raise BlockValidationError(f"invalid block: {e}") from e

    h = block.header
    # header wiring to state
    if h.version.block != state.version.consensus.block or \
            h.version.app != state.version.consensus.app:
        raise BlockValidationError(
            f"wrong Block.Header.Version: {h.version}")
    if h.chain_id != state.chain_id:
        raise BlockValidationError(
            f"wrong Block.Header.ChainID: {h.chain_id!r}")
    if state.last_block_height == 0:
        if h.height != state.initial_height:
            raise BlockValidationError(
                f"wrong Block.Header.Height: want "
                f"{state.initial_height} (initial), got {h.height}")
    elif h.height != state.last_block_height + 1:
        raise BlockValidationError(
            f"wrong Block.Header.Height: want "
            f"{state.last_block_height + 1}, got {h.height}")
    if h.last_block_id != state.last_block_id:
        raise BlockValidationError(
            f"wrong Block.Header.LastBlockID: want "
            f"{state.last_block_id}, got {h.last_block_id}")

    if h.app_hash != state.app_hash:
        raise BlockValidationError(
            f"wrong Block.Header.AppHash: want "
            f"{state.app_hash.hex().upper()}, got "
            f"{h.app_hash.hex().upper()}")
    if h.consensus_hash != state.consensus_params.hash():
        raise BlockValidationError("wrong Block.Header.ConsensusHash")
    if h.last_results_hash != state.last_results_hash:
        raise BlockValidationError("wrong Block.Header.LastResultsHash")
    if h.validators_hash != state.validators.hash():
        raise BlockValidationError("wrong Block.Header.ValidatorsHash")
    if h.next_validators_hash != state.next_validators.hash():
        raise BlockValidationError(
            "wrong Block.Header.NextValidatorsHash")

    # LastCommit verification — the batch-verify hot path
    if state.last_block_height == 0:
        if block.last_commit is not None and \
                block.last_commit.size() != 0:
            raise BlockValidationError(
                "initial block can't have LastCommit signatures")
    else:
        if block.last_commit is None:
            raise BlockValidationError("nil LastCommit")
        # commit-form discipline: past the aggregate enable height the
        # chain's blocks must carry the aggregate form (and never
        # before it), so the commit encoding is deterministic per
        # height — a proposer cannot downgrade to per-signature
        # commits and reintroduce O(n) verification
        expect_agg = state.consensus_params.feature \
            .aggregate_commits_enabled(h.height - 1)
        is_agg = isinstance(block.last_commit,
                            types_validation.AggregateCommit)
        if expect_agg and not is_agg:
            raise BlockValidationError(
                "per-signature LastCommit on an aggregate-commit "
                "chain")
        if is_agg and not expect_agg:
            raise BlockValidationError(
                "aggregate LastCommit before the enable height")
        if block.last_commit.size() != state.last_validators.size():
            raise BlockValidationError(
                f"invalid block commit size: want "
                f"{state.last_validators.size()}, got "
                f"{block.last_commit.size()}")
        try:
            types_validation.verify_commit(
                state.chain_id, state.last_validators,
                state.last_block_id, h.height - 1, block.last_commit)
        except types_validation.VerificationError as e:
            raise BlockValidationError(
                f"invalid LastCommit: {e}") from e

    # block time rules (reference: validation.go — BFT time requires the
    # exact weighted median of LastCommit; PBTS requires monotonicity,
    # with timeliness checked at prevote time)
    validate_block_time(
        state, block,
        state.consensus_params.feature.pbts_enabled(h.height))

    # evidence size cap (reference: validation.go:137 ErrEvidenceOverflow)
    max_ev_bytes = state.consensus_params.evidence.max_bytes
    ev_bytes = _evidence_byte_size(block.evidence)
    if ev_bytes > max_ev_bytes:
        raise BlockValidationError(
            f"evidence overflow: max {max_ev_bytes} bytes, "
            f"got {ev_bytes} bytes")

    # proposer must be in the current validator set
    if not state.validators.has_address(h.proposer_address):
        raise BlockValidationError(
            f"block proposer {h.proposer_address.hex().upper()} is not "
            f"a validator")


def _evidence_byte_size(evidence: list) -> int:
    """Proto-encoded EvidenceList size (reference: types/evidence.go
    EvidenceList ByteSize via EvidenceData)."""
    from ..wire import pb, encode
    if not evidence:
        return 0
    return len(encode(pb.EVIDENCE_LIST, {
        "evidence": [ev.to_proto_wrapped() for ev in evidence]}))


def validate_block_time(state: State, block: Block,
                        pbts_enabled: bool) -> None:
    """BFT-time / PBTS monotonicity checks (reference:
    validation.go time checks)."""
    h = block.header
    if h.height == state.initial_height:
        genesis_time = state.last_block_time
        if pbts_enabled:
            if h.time.unix_ns() < genesis_time.unix_ns():
                raise BlockValidationError(
                    "block time before genesis time")
        elif h.time != genesis_time:
            raise BlockValidationError(
                f"block time {h.time} != genesis time {genesis_time}")
    else:
        if not pbts_enabled:
            # BFT time: must equal MedianTime of LastCommit
            med = block.last_commit.median_time(state.last_validators)
            if h.time != med:
                raise BlockValidationError(
                    f"invalid block time: want {med}, got {h.time}")
        elif h.time.unix_ns() <= state.last_block_time.unix_ns():
            raise BlockValidationError("block time not monotonic")
