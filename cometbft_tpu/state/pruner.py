"""Pruner: background service driving block/state/ABCI-results pruning.

Reference: state/pruner.go (520 LoC) — two retain-height knobs, the
application's (set via the Commit response's retain_height) and the data
companion's (set over the pruning RPC service); the service prunes up to
the MINIMUM of the enabled knobs on an interval.  Retain heights are
persisted so they survive restarts.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from ..libs.log import Logger, new_logger

_APP_RETAIN_KEY = b"prune/app_retain_height"
_COMPANION_RETAIN_KEY = b"prune/companion_retain_height"
_ABCI_RESULTS_RETAIN_KEY = b"prune/abci_results_retain_height"
_TX_INDEXER_RETAIN_KEY = b"prune/tx_indexer_retain_height"
_BLOCK_INDEXER_RETAIN_KEY = b"prune/block_indexer_retain_height"


class Pruner:
    """Reference: state/pruner.go Pruner."""

    def __init__(self, state_store, block_store, db,
                 interval_s: float = 10.0,
                 companion_enabled: bool = False,
                 logger: Optional[Logger] = None,
                 tx_indexer=None, block_indexer=None,
                 metrics=None):
        from .metrics import Metrics
        self.metrics = metrics if metrics is not None else Metrics()
        self.state_store = state_store
        self.block_store = block_store
        self._db = db                       # persistence for retain heights
        self.interval_s = interval_s
        self.companion_enabled = companion_enabled
        self.logger = logger or new_logger("pruner")
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        # per-pass bound on companion-artifact heights (event-loop
        # latency cap; the watermark carries progress across passes)
        self.max_heights_per_pass = 10_000
        self._task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()

    # -- retain heights ----------------------------------------------------
    def _get(self, key: bytes) -> int:
        raw = self._db.get(key)
        return int(raw.decode()) if raw else 0

    def _set(self, key: bytes, h: int) -> None:
        self._db.set_sync(key, str(h).encode())

    def set_application_retain_height(self, height: int) -> None:
        """Called after every Commit with the app's retain_height
        (reference: SetApplicationBlockRetainHeight)."""
        if height <= 0:
            return
        if height <= self._get(_APP_RETAIN_KEY):
            return      # unchanged or backwards: skip the sync write —
                        # this runs on the per-block commit path
        self._set(_APP_RETAIN_KEY, height)
        self.metrics.application_block_retain_height.set(height)
        self._wake.set()

    def set_companion_retain_height(self, height: int) -> None:
        """Reference: SetCompanionBlockRetainHeight (pruning RPC)."""
        self._set_companion_only(_COMPANION_RETAIN_KEY, height)
        self.metrics.pruning_service_block_retain_height.set(height)

    def get_application_retain_height(self) -> int:
        return self._get(_APP_RETAIN_KEY)

    def get_companion_retain_height(self) -> int:
        return self._get(_COMPANION_RETAIN_KEY)

    # companion-only retain heights for the three data-companion
    # artifact classes (reference: state/pruner.go
    # SetABCIResRetainHeight / SetTxIndexerRetainHeight /
    # SetBlockIndexerRetainHeight, driven by the pruning gRPC service)
    def set_abci_results_retain_height(self, height: int) -> None:
        self._set_companion_only(_ABCI_RESULTS_RETAIN_KEY, height)

    def get_abci_results_retain_height(self) -> int:
        return self._get(_ABCI_RESULTS_RETAIN_KEY)

    def set_tx_indexer_retain_height(self, height: int) -> None:
        self._set_companion_only(_TX_INDEXER_RETAIN_KEY, height)

    def get_tx_indexer_retain_height(self) -> int:
        return self._get(_TX_INDEXER_RETAIN_KEY)

    def set_block_indexer_retain_height(self, height: int) -> None:
        self._set_companion_only(_BLOCK_INDEXER_RETAIN_KEY, height)

    def get_block_indexer_retain_height(self) -> int:
        return self._get(_BLOCK_INDEXER_RETAIN_KEY)

    def _set_companion_only(self, key: bytes, height: int) -> None:
        if height <= 0:
            raise ValueError("retain height must be positive")
        if height > self.block_store.height:
            raise ValueError("retain height beyond store height")
        if height < self._get(key):
            raise ValueError("retain height cannot move backwards")
        self._set(key, height)
        self._wake.set()

    def effective_retain_height(self) -> int:
        """min of the enabled knobs (reference: findMinRetainHeight).
        With the companion enabled, nothing is pruned until BOTH knobs
        have been set — the companion must explicitly release data."""
        app = self._get(_APP_RETAIN_KEY)
        if not self.companion_enabled:
            return app
        comp = self._get(_COMPANION_RETAIN_KEY)
        if app == 0 or comp == 0:
            return 0
        return min(app, comp)

    # -- service -----------------------------------------------------------
    async def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        while True:
            try:
                self._wake.clear()
                self.prune_once()
                try:
                    await asyncio.wait_for(self._wake.wait(),
                                           self.interval_s)
                except asyncio.TimeoutError:
                    pass
            except asyncio.CancelledError:
                raise
            except Exception:
                self.logger.error("pruning failed", exc_info=True)
                await asyncio.sleep(self.interval_s)

    def prune_once(self) -> tuple[int, int]:
        """One pruning pass; returns (blocks_pruned, new_base)."""
        self._prune_companion_artifacts()
        retain = self.effective_retain_height()
        # a buggy app can return a retain height beyond the chain tip;
        # clamp instead of erroring forever (prune_blocks would raise)
        retain = min(retain, self.block_store.height)
        if retain <= self.block_store.base or retain <= 0:
            return 0, self.block_store.base
        pruned, new_base = self.block_store.prune_blocks(retain)
        self.metrics.block_store_base_height.set(new_base)
        if pruned:
            # state + ABCI results follow the block base
            self.state_store.prune_states(self.block_store.base - pruned,
                                          retain, retain)
            self.logger.info("pruned blocks", pruned=pruned,
                             new_base=new_base)
        return pruned, new_base

    def _prune_companion_artifacts(self) -> None:
        """Prune ABCI results and tx/block indices up to their
        companion-set retain heights (reference: pruner.go
        pruneABCIResToRetainHeight / pruneIndexesToRetainHeight).
        Each class tracks its own last-pruned watermark so a pass only
        touches new heights."""
        tip = self.block_store.height
        # a target that isn't wired (yet) returns None: the watermark
        # must NOT advance, or its heights would be skipped forever
        targets = [
            (_ABCI_RESULTS_RETAIN_KEY, b"prune/abci_results_last",
             lambda lo, hi: self.state_store.prune_abci_responses(lo, hi)
             if hasattr(self.state_store, "prune_abci_responses")
             else None),
            (_TX_INDEXER_RETAIN_KEY, b"prune/tx_indexer_last",
             lambda lo, hi: self.tx_indexer.prune(lo, hi)
             if self.tx_indexer is not None else None),
            (_BLOCK_INDEXER_RETAIN_KEY, b"prune/block_indexer_last",
             lambda lo, hi: self.block_indexer.prune(lo, hi)
             if self.block_indexer is not None else None),
        ]
        for retain_key, last_key, do_prune in targets:
            # always keep the latest height (reference keeps the tip for
            # crash recovery)
            retain = min(self._get(retain_key), tip)
            last = self._get(last_key)
            if retain <= last or retain <= 0:
                continue
            # bound the synchronous work per pass: prune_once runs on
            # the event loop, and a companion jumping the retain height
            # by millions must not stall consensus for the whole scan
            lo = max(last, 1)
            hi = min(retain, lo + self.max_heights_per_pass)
            try:
                n = do_prune(lo, hi)
            except Exception:
                self.logger.error("companion prune failed",
                                  exc_info=True)
                continue
            if n is None:
                continue
            self._set(last_key, hi)
            if hi < retain:
                self._wake.set()    # continue promptly next pass
            if n:
                self.logger.info("pruned companion artifacts",
                                 kind=retain_key.decode(), pruned=n)
