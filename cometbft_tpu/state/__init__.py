"""State & execution: the bridge between consensus and the application.

Reference: state/ — sm.State value, Store persistence, BlockExecutor
(ApplyBlock / CreateProposalBlock), block validation against state.
"""
from .state import State, StateError, make_genesis_state

__all__ = ["State", "StateError", "make_genesis_state"]
