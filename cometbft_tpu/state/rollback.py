"""State rollback: rewind one height after an app upgrade gone wrong.

Reference: state/rollback.go (:126) — reconstruct the previous state
from stored validators/params + the rolled-back block's header.
"""
from __future__ import annotations

from ..types.block_id import BlockID
from .state import State
from .store import Store


class RollbackError(Exception):
    pass


def rollback_state(state_store: Store, block_store,
                   remove_block: bool = False) -> tuple[int, bytes]:
    """Roll state back one height; optionally delete the latest block
    too.  Returns (new_height, app_hash)."""
    invalid_state = state_store.load()
    if invalid_state is None:
        raise RollbackError("no state found")
    height = block_store.height

    # the block at `height` is the one being discarded; its header
    # carries the app hash AFTER height-1
    rollback_height = invalid_state.last_block_height
    if rollback_height != height and rollback_height != height - 1:
        raise RollbackError(
            f"statestore height ({rollback_height}) is not one off "
            f"from blockstore height ({height})")

    rolled_back_block = block_store.load_block_meta(rollback_height)
    if rolled_back_block is None:
        raise RollbackError(f"block at height {rollback_height} "
                            f"not found")
    prev_height = rollback_height - 1
    prev_meta = block_store.load_block_meta(prev_height)
    if prev_meta is None:
        raise RollbackError(f"block at height {prev_height} not found")

    # state with last_block_height = H-1 holds: LastValidators = set at
    # H-1, Validators = set at H, NextValidators = set at H+1
    params = state_store.load_consensus_params(rollback_height)
    validators = state_store.load_validators(rollback_height)
    next_validators = state_store.load_validators(rollback_height + 1)
    try:
        last_validators = state_store.load_validators(prev_height)
    except Exception:
        from ..types.validator_set import ValidatorSet
        last_validators = ValidatorSet()

    new_state = State(
        version=invalid_state.version,
        chain_id=invalid_state.chain_id,
        initial_height=invalid_state.initial_height,
        last_block_height=prev_meta.header.height,
        last_block_id=BlockID(
            hash=prev_meta.block_id.hash,
            part_set_header=prev_meta.block_id.part_set_header),
        last_block_time=prev_meta.header.time,
        next_validators=next_validators,
        validators=validators,
        last_validators=last_validators,
        last_height_validators_changed=(
            invalid_state.last_height_validators_changed),
        consensus_params=params,
        last_height_consensus_params_changed=(
            invalid_state.last_height_consensus_params_changed),
        last_results_hash=rolled_back_block.header.last_results_hash,
        app_hash=rolled_back_block.header.app_hash,
    )
    state_store.save(new_state)
    if remove_block and height == rollback_height:
        block_store.delete_latest_block()
    return new_state.last_block_height, new_state.app_hash
