"""BlockExecutor: the consensus ↔ ABCI bridge.

Reference: state/execution.go:55 — CreateProposalBlock (:113),
ProcessProposal (:173), ApplyBlock (:224) → FinalizeBlock → save results
→ updateState → app Commit + mempool update → events; ExtendVote /
VerifyVoteExtension (:339,369).
"""
from __future__ import annotations

from typing import Optional

from ..abci import types as abci
from ..crypto import encoding as crypto_encoding, merkle
from ..libs import fail
from ..libs.log import Logger, new_logger
from ..types.block import Block
from ..types.block_id import BlockID
from ..types.commit import AggregateCommit, Commit, ExtendedCommit
from ..types.events import EventBus, NopEventBus
from ..types.params import MAX_BLOCK_SIZE_BYTES, ParamsError
from ..types.tx import compute_proto_size_overhead
from ..types.validator import Validator
from ..types.vote import (
    BLOCK_ID_FLAG_ABSENT, BLOCK_ID_FLAG_COMMIT, Vote,
)
from ..wire import abci_pb, encode
from .state import State
from .store import Store
from .validation import BlockValidationError, validate_block

# Max overhead for the block envelope beyond header/data/evidence/commit
# (reference: types/block.go MaxDataBytes accounting)
_MAX_HEADER_BYTES = 626
_MAX_OVERHEAD_FOR_BLOCK = 11
_MAX_COMMIT_SIG_BYTES = 109 + 2  # CommitSig proto + repeated overhead


class ExecutionError(Exception):
    pass


class InvalidBlockError(ExecutionError):
    pass


def max_data_bytes(max_bytes: int, ev_size: int, n_vals: int) -> int:
    """Reference: types/block.go MaxDataBytes (panics when negative)."""
    commit_bytes = 4 + 10 + 76 + n_vals * _MAX_COMMIT_SIG_BYTES
    cap_ = (max_bytes - _MAX_OVERHEAD_FOR_BLOCK - _MAX_HEADER_BYTES -
            commit_bytes - ev_size)
    if cap_ < 0:
        raise ExecutionError(
            f"negative MaxDataBytes: block.MaxBytes={max_bytes} is too "
            f"small to fit a header plus a {n_vals}-validator commit")
    return cap_


def tx_results_hash(tx_results: list[abci.ExecTxResult]) -> bytes:
    """Merkle root over deterministic ExecTxResult proto bytes.

    Reference: state/store.go TxResultsHash + types/results.go
    (log/info/events stripped)."""
    leaves = []
    for r in tx_results:
        d: dict = {}
        if r.code:
            d["code"] = r.code
        if r.data:
            d["data"] = r.data
        if r.gas_wanted:
            d["gas_wanted"] = r.gas_wanted
        if r.gas_used:
            d["gas_used"] = r.gas_used
        if r.codespace:
            d["codespace"] = r.codespace
        leaves.append(encode(abci_pb.EXEC_TX_RESULT, d))
    return merkle.hash_from_byte_slices(leaves)


def build_last_commit_info(block: Block, last_val_set,
                           initial_height: int) -> abci.CommitInfo:
    """Reference: state/execution.go BuildLastCommitInfo.

    An AggregateCommit reports COMMIT for every signer bit and ABSENT
    otherwise (the aggregate form cannot distinguish nil votes from
    absence — both are excluded from the bitmap)."""
    if block.header.height == initial_height:
        return abci.CommitInfo()
    commit = block.last_commit
    if last_val_set.size() != commit.size():
        raise ExecutionError(
            f"commit size {commit.size()} doesn't match valset length "
            f"{last_val_set.size()} at height {block.header.height}")
    votes = []
    if isinstance(commit, AggregateCommit):
        for i, val in enumerate(last_val_set.validators):
            votes.append(abci.VoteInfo(
                validator=abci.ABCIValidator(address=val.address,
                                             power=val.voting_power),
                block_id_flag=(BLOCK_ID_FLAG_COMMIT
                               if commit.signers.get_index(i)
                               else BLOCK_ID_FLAG_ABSENT)))
        return abci.CommitInfo(round=commit.round, votes=votes)
    for i, cs in enumerate(commit.signatures):
        val = last_val_set.validators[i]
        votes.append(abci.VoteInfo(
            validator=abci.ABCIValidator(address=val.address,
                                         power=val.voting_power),
            block_id_flag=cs.block_id_flag))
    return abci.CommitInfo(round=commit.round, votes=votes)


def build_extended_commit_info(ext_commit: ExtendedCommit, val_set,
                               initial_height: int,
                               feature_params) -> abci.ExtendedCommitInfo:
    """Reference: state/execution.go buildExtendedCommitInfo."""
    if ext_commit.height < initial_height:
        return abci.ExtendedCommitInfo()
    if val_set.size() != ext_commit.size():
        raise ExecutionError(
            f"extended commit size {ext_commit.size()} does not match "
            f"validator set length {val_set.size()} at height "
            f"{ext_commit.height}")
    ext_enabled = feature_params.vote_extensions_enabled(
        ext_commit.height)
    votes = []
    for i, ecs in enumerate(ext_commit.extended_signatures):
        val = val_set.validators[i]
        if ext_enabled and ecs.block_id_flag == BLOCK_ID_FLAG_COMMIT \
                and (not ecs.extension_signature or
                     not ecs.non_rp_extension_signature):
            raise ExecutionError(
                f"commit at height {ext_commit.height} received with "
                f"missing vote extension signature")
        votes.append(abci.ExtendedVoteInfo(
            validator=abci.ABCIValidator(address=val.address,
                                         power=val.voting_power),
            vote_extension=ecs.extension,
            extension_signature=ecs.extension_signature,
            block_id_flag=ecs.block_id_flag,
            non_rp_vote_extension=ecs.non_rp_extension,
            non_rp_extension_signature=ecs.non_rp_extension_signature))
    return abci.ExtendedCommitInfo(round=ext_commit.round, votes=votes)


def validate_validator_updates(updates: list[abci.ValidatorUpdate],
                               validator_params) -> list[Validator]:
    """Reference: execution.go validateValidatorUpdates + PB2TM."""
    out = []
    for vu in updates:
        if vu.power < 0:
            raise ExecutionError(
                f"voting power can't be negative: {vu.power}")
        if vu.power == 0:
            # deletions are ok
            pass
        if not validator_params.is_valid_pub_key_type(vu.pub_key_type):
            raise ExecutionError(
                f"validator {vu.pub_key_bytes.hex()[:16]} is using "
                f"pubkey type {vu.pub_key_type!r}, which is unsupported "
                f"for consensus")
        pk = crypto_encoding.pub_key_from_type_and_bytes(
            vu.pub_key_type, vu.pub_key_bytes)
        out.append(Validator.new(pk, vu.power))
    return out


class _NopEvidencePool:
    """Reference: sm.EmptyEvidencePool."""

    def pending_evidence(self, max_bytes: int):
        return [], 0

    def check_evidence(self, evidence: list) -> None:
        pass

    def update(self, state: State, evidence: list) -> None:
        pass


class _NopMempool:
    def lock(self):
        pass

    def unlock(self):
        pass

    def pre_update(self):
        pass

    async def flush_app_conn(self):
        pass

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int
                               ) -> list[bytes]:
        return []

    async def update(self, height, txs, tx_results, pre_check=None,
                     post_check=None):
        pass


class BlockExecutor:
    def __init__(self, state_store: Store, proxy_app,
                 mempool=None, evpool=None,
                 event_bus: Optional[EventBus] = None,
                 block_store=None,
                 logger: Optional[Logger] = None,
                 metrics=None):
        from .metrics import Metrics
        self.metrics = metrics if metrics is not None else Metrics()
        self.store = state_store
        self.proxy_app = proxy_app   # ABCI consensus connection
        self.mempool = mempool if mempool is not None else _NopMempool()
        self.evpool = evpool if evpool is not None else _NopEvidencePool()
        self.event_bus = event_bus if event_bus is not None \
            else NopEventBus()
        self.block_store = block_store
        self.logger = logger if logger is not None else \
            new_logger("state")
        self._last_validated_hash: bytes = b""
        self.last_retain_height = 0
        self.pruner = None          # attached by the node (state/pruner.py)

    # ------------------------------------------------------------------
    async def create_proposal_block(
            self, height: int, state: State,
            last_ext_commit: ExtendedCommit,
            proposer_addr: bytes,
            last_aggregate_commit: Optional[AggregateCommit] = None
            ) -> Block:
        """Reference: execution.go CreateProposalBlock (:113).

        On an aggregate-commit chain the block embeds the aggregate
        form: normally aggregated here from the extended commit's
        per-vote signatures; a node restored from an aggregate seen
        commit (blocksync/statesync — no per-vote signatures on disk)
        passes the stored aggregate as ``last_aggregate_commit``."""
        max_bytes = state.consensus_params.block.max_bytes
        empty_max_bytes = max_bytes == -1
        if empty_max_bytes:
            max_bytes = MAX_BLOCK_SIZE_BYTES
        max_gas = state.consensus_params.block.max_gas

        evidence, ev_size = self.evpool.pending_evidence(
            state.consensus_params.evidence.max_bytes)
        data_cap = max_data_bytes(max_bytes, ev_size,
                                  state.validators.size())
        reap_cap = -1 if empty_max_bytes else data_cap
        txs = self.mempool.reap_max_bytes_max_gas(reap_cap, max_gas)
        commit: Commit | AggregateCommit = last_ext_commit.to_commit()
        if height != state.initial_height and \
                state.consensus_params.feature \
                .aggregate_commits_enabled(height - 1):
            commit = last_aggregate_commit \
                if last_aggregate_commit is not None \
                else AggregateCommit.from_commit(commit)
        block = state.make_block(height, txs, commit, evidence,
                                 proposer_addr)
        rpp = await self.proxy_app.prepare_proposal(
            abci.PrepareProposalRequest(
                max_tx_bytes=data_cap,
                txs=list(block.data.txs),
                local_last_commit=build_extended_commit_info(
                    last_ext_commit, self._load_valset(
                        last_ext_commit.height, state),
                    state.initial_height,
                    state.consensus_params.feature),
                misbehavior=_evidence_to_abci(evidence),
                height=block.header.height,
                time=block.header.time,
                next_validators_hash=block.header.next_validators_hash,
                proposer_address=block.header.proposer_address,
            ))
        total = sum(len(tx) + compute_proto_size_overhead(len(tx))
                    for tx in rpp.txs)
        if total > data_cap:
            raise ExecutionError(
                f"post-PrepareProposal txs exceed max data bytes "
                f"{total} > {data_cap}")
        return state.make_block(height, list(rpp.txs), commit, evidence,
                                proposer_addr,
                                block_time=block.header.time)

    def _load_valset(self, height: int, state: State):
        """The validator set that SIGNED height (reference:
        buildExtendedCommitInfoFromStore → LoadValidators(ec.Height))."""
        try:
            return self.store.load_validators(height)
        except Exception:
            if height == state.last_block_height and \
                    state.last_validators is not None:
                return state.last_validators
            raise

    async def process_proposal(self, block: Block, state: State) -> bool:
        """Reference: execution.go ProcessProposal (:173)."""
        resp = await self.proxy_app.process_proposal(
            abci.ProcessProposalRequest(
                hash=block.hash(),
                height=block.header.height,
                time=block.header.time,
                txs=list(block.data.txs),
                proposed_last_commit=self._last_commit_info(block, state),
                misbehavior=_evidence_to_abci(block.evidence),
                proposer_address=block.header.proposer_address,
                next_validators_hash=block.header.next_validators_hash,
            ))
        if resp.status == abci.PROCESS_PROPOSAL_STATUS_UNKNOWN:
            raise ExecutionError(
                "ProcessProposal responded with status UNKNOWN")
        return resp.is_accepted()

    def _last_commit_info(self, block: Block,
                          state: State) -> abci.CommitInfo:
        if block.header.height == state.initial_height:
            return abci.CommitInfo()
        last_vals = self.store.load_validators(block.header.height - 1)
        return build_last_commit_info(block, last_vals,
                                      state.initial_height)

    # ------------------------------------------------------------------
    def validate_block(self, state: State, block: Block) -> None:
        """Reference: execution.go ValidateBlock."""
        if self._last_validated_hash != block.hash():
            validate_block(state, block)
            self._last_validated_hash = block.hash()
        try:
            self.evpool.check_evidence(block.evidence)
        except BlockValidationError:
            raise
        except Exception as e:  # EvidenceError -> invalid block
            raise BlockValidationError(f"invalid evidence: {e}") from e

    async def apply_block(self, state: State, block_id: BlockID,
                          block: Block,
                          syncing_to_height: int = 0) -> State:
        """Validate + execute + commit (reference: ApplyBlock :224)."""
        if self._last_validated_hash != block.hash():
            try:
                validate_block(state, block)
            except BlockValidationError as e:
                raise InvalidBlockError(str(e)) from e
            self._last_validated_hash = block.hash()
        return await self._apply_block(state, block_id, block,
                                       syncing_to_height)

    async def apply_verified_block(self, state: State, block_id: BlockID,
                                   block: Block,
                                   syncing_to_height: int = 0) -> State:
        return await self._apply_block(state, block_id, block,
                                       syncing_to_height)

    async def _apply_block(self, state: State, block_id: BlockID,
                           block: Block,
                           syncing_to_height: int) -> State:
        h = block.header
        abci_response = await self.proxy_app.finalize_block(
            abci.FinalizeBlockRequest(
                hash=block.hash(),
                next_validators_hash=h.next_validators_hash,
                proposer_address=h.proposer_address,
                height=h.height,
                time=h.time,
                decided_last_commit=self._last_commit_info(block, state),
                misbehavior=_evidence_to_abci(block.evidence),
                txs=list(block.data.txs),
                syncing_to_height=syncing_to_height or h.height,
            ))
        self.logger.info("Finalized block", height=h.height,
                         num_txs_res=len(abci_response.tx_results),
                         num_val_updates=len(
                             abci_response.validator_updates))
        if len(block.data.txs) != len(abci_response.tx_results):
            raise ExecutionError(
                f"expected tx results length to match block txs: "
                f"{len(block.data.txs)} != "
                f"{len(abci_response.tx_results)}")

        fail.fail()    # crash point: finalized, responses unsaved
                       # (execution.go:267)

        # save results BEFORE app commit (crash-consistency barrier)
        self.store.save_finalize_block_response(h.height, abci_response)

        fail.fail()    # crash point: responses saved, state not updated
                       # (execution.go:274)

        validator_updates = validate_validator_updates(
            abci_response.validator_updates,
            state.consensus_params.validator)
        if validator_updates:
            self.metrics.validator_set_updates.add()
        if abci_response.consensus_param_updates is not None:
            self.metrics.consensus_param_updates.add()

        state = update_state(state, block_id, block, abci_response,
                             validator_updates)

        # lock mempool, app Commit, update mempool
        retain_height = await self.commit(state, block, abci_response)

        self.evpool.update(state, block.evidence)

        fail.fail()    # crash point: app committed, state unsaved
                       # (execution.go:315)

        state.app_hash = abci_response.app_hash
        self.store.save(state)

        # app-requested pruning: hand the retain height to the pruner
        # service (reference: execution.go pruneBlocks -> state/pruner.go)
        self.last_retain_height = retain_height
        if self.pruner is not None and retain_height > 0:
            self.pruner.set_application_retain_height(retain_height)

        self._fire_events(block, block_id, abci_response,
                          validator_updates)
        return state

    async def commit(self, state: State, block: Block,
                     abci_response: abci.FinalizeBlockResponse) -> int:
        """Reference: execution.go Commit (:403)."""
        self.mempool.pre_update()
        self.mempool.lock()
        try:
            await self.mempool.flush_app_conn()
            res = await self.proxy_app.commit()
            self.logger.info("Committed state", height=block.header.height)
            await self.mempool.update(
                block.header.height, list(block.data.txs),
                abci_response.tx_results)
        finally:
            self.mempool.unlock()
        return res.retain_height

    # ------------------------------------------------------------------
    async def extend_vote(self, vote: Vote, block: Block,
                          state: State) -> tuple[bytes, bytes]:
        """Reference: execution.go ExtendVote (:339)."""
        if block.hash() != vote.block_id.hash:
            raise ExecutionError("vote's hash does not match block")
        if vote.height != block.header.height:
            raise ExecutionError("vote and block heights do not match")
        resp = await self.proxy_app.extend_vote(abci.ExtendVoteRequest(
            hash=vote.block_id.hash,
            height=vote.height,
            time=block.header.time,
            txs=list(block.data.txs),
            proposed_last_commit=self._last_commit_info(block, state),
            misbehavior=_evidence_to_abci(block.evidence),
            next_validators_hash=block.header.next_validators_hash,
            proposer_address=block.header.proposer_address,
        ))
        return resp.vote_extension, resp.non_rp_extension

    async def verify_vote_extension(self, vote: Vote) -> bool:
        """Reference: execution.go VerifyVoteExtension (:369)."""
        resp = await self.proxy_app.verify_vote_extension(
            abci.VerifyVoteExtensionRequest(
                hash=vote.block_id.hash,
                validator_address=vote.validator_address,
                height=vote.height,
                vote_extension=vote.extension,
                non_rp_vote_extension=vote.non_rp_extension,
            ))
        if resp.status == abci.VERIFY_VOTE_EXTENSION_STATUS_UNKNOWN:
            raise ExecutionError(
                "VerifyVoteExtension responded with status UNKNOWN")
        return resp.is_accepted()

    # ------------------------------------------------------------------
    def _fire_events(self, block: Block, block_id: BlockID,
                     abci_response: abci.FinalizeBlockResponse,
                     validator_updates: list[Validator]) -> None:
        """Reference: execution.go fireEvents."""
        bus = self.event_bus
        bus.publish_new_block(block, block_id, abci_response)
        bus.publish_new_block_header(block.header)
        if abci_response.events:
            bus.publish_new_block_events(block.header.height,
                                         abci_response.events,
                                         len(block.data.txs))
        for ev in block.evidence:
            bus.publish_new_evidence(ev, block.header.height)
        for i, tx in enumerate(block.data.txs):
            bus.publish_tx(block.header.height, i, tx,
                           abci_response.tx_results[i],
                           abci_response.tx_results[i].events)
        if validator_updates:
            bus.publish_validator_set_updates(validator_updates)


def provisional_next_state(state: State, block_id: BlockID,
                           block: Block) -> State:
    """The H+1 state the consensus machine can know BEFORE height H's
    FinalizeBlock/Commit have run — the pipelined-commit seam
    (docs/pipeline.md).

    Everything H+1 needs up to (but not including) block validation
    and proposal construction is already determined when H is decided:
    the H+1 validator set is ``state.next_validators`` (validator
    updates from H only land at H+2), the chain id and vote-extension
    schedule come from the pre-H consensus params, and the last
    validators are H's signers.  The fields only execution can produce
    — ``app_hash``, ``last_results_hash``, validator/param updates,
    ``next_block_delay`` — are left at their pre-H values; the
    pipeline barrier replaces this provisional state with the real
    post-apply state before anything reads them (ConsensusState
    reconciles on the apply-done handoff and rebuilds the height vote
    set in the rare case a param update changed what the provisional
    state baked in)."""
    return update_state(state, block_id, block,
                        abci.FinalizeBlockResponse(
                            next_block_delay_ns=state.next_block_delay_ns),
                        [])


def update_state(state: State, block_id: BlockID, block: Block,
                 abci_response: abci.FinalizeBlockResponse,
                 validator_updates: list[Validator]) -> State:
    """Reference: execution.go updateState."""
    header = block.header
    n_val_set = state.next_validators.copy()

    last_height_vals_changed = state.last_height_validators_changed
    if validator_updates:
        n_val_set.update_with_change_set(validator_updates)
        # changes from height H apply at H+2 (nextValSet delay)
        last_height_vals_changed = header.height + 1 + 1
    n_val_set.increment_proposer_priority(1)

    from .state import StateVersion
    next_version = StateVersion(
        consensus=state.version.consensus,
        software=state.version.software)
    next_params = state.consensus_params
    last_height_params_changed = state.last_height_consensus_params_changed
    if abci_response.consensus_param_updates is not None:
        next_params = state.consensus_params.update(
            abci_response.consensus_param_updates)
        try:
            next_params.validate_basic()
        except ParamsError as e:
            raise ExecutionError(
                f"validating new consensus params: {e}") from e
        # bump only the new state's version; the caller's snapshot stays
        # untouched (Go passes State by value)
        next_version.consensus = type(state.version.consensus)(
            block=state.version.consensus.block,
            app=next_params.version.app)
        last_height_params_changed = header.height + 1

    new_state = State(
        version=next_version,
        chain_id=state.chain_id,
        initial_height=state.initial_height,
        last_block_height=header.height,
        last_block_id=block_id,
        last_block_time=header.time,
        next_validators=n_val_set,
        validators=state.next_validators.copy(),
        last_validators=state.validators.copy(),
        last_height_validators_changed=last_height_vals_changed,
        consensus_params=next_params,
        last_height_consensus_params_changed=last_height_params_changed,
        last_results_hash=tx_results_hash(abci_response.tx_results),
        app_hash=b"",   # filled after app Commit
        next_block_delay_ns=abci_response.next_block_delay_ns,
    )
    return new_state


def _evidence_to_abci(evidence: list) -> list[abci.Misbehavior]:
    """Reference: types/evidence.go Evidence.ABCI()."""
    from ..types.evidence import (
        DuplicateVoteEvidence, LightClientAttackEvidence,
    )
    out = []
    for ev in evidence:
        if isinstance(ev, DuplicateVoteEvidence):
            out.append(abci.Misbehavior(
                type=abci.MISBEHAVIOR_TYPE_DUPLICATE_VOTE,
                validator=abci.ABCIValidator(
                    address=ev.vote_a.validator_address,
                    power=ev.validator_power),
                height=ev.vote_a.height,
                time=ev.timestamp,
                total_voting_power=ev.total_voting_power))
        elif isinstance(ev, LightClientAttackEvidence):
            for val in ev.byzantine_validators:
                out.append(abci.Misbehavior(
                    type=abci.MISBEHAVIOR_TYPE_LIGHT_CLIENT_ATTACK,
                    validator=abci.ABCIValidator(
                        address=val.address, power=val.voting_power),
                    height=ev.common_height,
                    time=ev.timestamp,
                    total_voting_power=ev.total_voting_power))
    return out
