"""GF(2^255 - 19) in 24 balanced limbs with an (11,11,10)-bit cycle.

The radix schedule for the second-generation Pallas kernel
(ed25519_pallas.py).  Design, from the r3 cost model
(KERNEL_NOTES.md): the 32x8-bit kernel spends 2048 of its ~3150
per-lane ops in the 1024-MAC limb convolution; a bigger radix cuts the
MAC count quadratically as long as every accumulated sum stays inside
int32 (the VPU lane width).

Why THIS schedule and not 22x12-bit (the first sketch in the model):

  * Limb sizes cycle (11, 11, 10), eight times — 256 bits total, so
    the carry out of limb 23 folds back into limb 0 with weight
    2^256 mod p = 38, exactly like the byte kernel (2^256 = 2p + 38).
  * The off-grid corrections are SEPARABLE.  With bit offsets
    s_i = ceil(32*i/3), the product a_i*b_j carries an extra factor
    2^(s_i + s_j - s_{i+j}) which depends only on (i mod 3, j mod 3):
    it is 2 iff (i mod 3) + (j mod 3) >= 3.  So the convolution still
    runs as 24 uniform slab MACs — row i just selects one of three
    pre-scaled copies of b (plain / residue-2 doubled / residue-1,2
    doubled) and their 38-folded counterparts.  A 22x12 schedule has
    no such structure (the correction is a dense 22x22 matrix) and its
    worst-case accumulator overflows int32 by ~0.7 bits.
  * Balanced (signed, round-to-nearest carry) limbs: |limb| <= 2^10
    for 11-bit positions, 2^9 for 10-bit ones.  Worst-case MAC
    accumulation: 24 terms * (1026 * 1026*2*38) ~ 1.92e9 < 2^31,
    with one normalizing carry pass applied to each multiplier input.

Reference seam: crypto/ed25519/ed25519.go:189-222 (BatchVerifier);
this module is the host-side mirror (converters + golden ops) used by
the kernel's constant tables and by the unit tests.
"""
from __future__ import annotations

import numpy as np

P = 2**255 - 19
LIMBS = 24
FOLD = 38                       # 2^256 mod p  (2^256 = 2p + 38)

# bit offsets s_i = ceil(32*i/3); sizes cycle (11, 11, 10)
OFFSETS = [(32 * i + 2) // 3 for i in range(LIMBS + 1)]
SIZES = [OFFSETS[i + 1] - OFFSETS[i] for i in range(LIMBS)]
assert OFFSETS[LIMBS] == 256 and set(SIZES) == {10, 11}

# doubling pattern: product (i, j) needs x2 iff (i%3) + (j%3) >= 3
PAT_R1 = np.array([2 if j % 3 == 2 else 1 for j in range(LIMBS)],
                  np.int32)      # rows i with i%3 == 1
PAT_R2 = np.array([2 if j % 3 >= 1 else 1 for j in range(LIMBS)],
                  np.int32)      # rows i with i%3 == 2


def to_limbs(x: int) -> np.ndarray:
    """python int -> 24 canonical (unsigned) digits, int32."""
    return _digits_raw(x % P)


def from_limbs(a) -> int:
    """limb array (any redundancy, signed ok) -> int mod p."""
    limbs = np.asarray(a, dtype=np.int64).reshape(-1)
    val = 0
    for i, limb in enumerate(limbs):
        val += int(limb) << OFFSETS[i]
    return val % P


def _digits_raw(x: int) -> np.ndarray:
    """Digit rows of a value < 2^256 WITHOUT mod-p reduction (to_limbs
    reduces first, which would turn p itself into zeros)."""
    out = np.zeros(LIMBS, np.int32)
    for i in range(LIMBS):
        out[i] = (x >> OFFSETS[i]) & ((1 << SIZES[i]) - 1)
    return out


# canonical digit rows used by the kernel's exact comparisons
P_DIGITS = _digits_raw(P)
TWO_P_DIGITS = _digits_raw(2 * P)
assert from_limbs(TWO_P_DIGITS) == 0          # 2p ≡ 0, fits 256 bits
FOUR_P_DIGITS = 2 * TWO_P_DIGITS              # redundant, limbs < 2^12


def carry(x: np.ndarray) -> np.ndarray:
    """One balanced parallel carry pass (golden model of the kernel's
    _carry): round-to-nearest split per position, top carry folds at
    38 into limb 0 and is immediately split again (fold-settle, same
    as the kernel) so limb 0 keeps its resting bound.
    x: [..., 24] int64-safe."""
    x = np.asarray(x, np.int64)
    c = np.empty_like(x)
    lo = np.empty_like(x)
    for i in range(LIMBS):
        t = SIZES[i]
        h = 1 << (t - 1)
        ci = (x[..., i] + h) >> t
        c[..., i] = ci
        lo[..., i] = x[..., i] - (ci << t)
    out = lo.copy()
    out[..., 1:] += c[..., :-1]
    f = FOLD * c[..., -1]
    fc = (f + 1024) >> 11
    out[..., 0] += f - (fc << 11)
    out[..., 1] += fc
    return out


def mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Golden-model field multiply mirroring the kernel's slab/variant
    structure (incl. the exact int32-range assertion the kernel's
    bounds analysis claims)."""
    a = carry(np.asarray(a, np.int64))
    b = carry(np.asarray(b, np.int64))
    v = [b, b * PAT_R1, b * PAT_R2]
    w = [x * FOLD for x in v]
    acc = np.zeros(a.shape[:-1] + (LIMBS,), np.int64)
    for i in range(LIMBS):
        sel_v, sel_w = v[i % 3], w[i % 3]
        for j in range(LIMBS):
            k = i + j
            term = a[..., i] * (sel_v[..., j] if k < LIMBS
                                else sel_w[..., j])
            acc[..., k % LIMBS] += term
    assert np.abs(acc).max() < 2**31, "int32 accumulator overflow"
    return carry(carry(acc))


def balance(x) -> np.ndarray:
    """Host-side: one balanced carry pass over digit rows, int32 out.
    Used to pre-balance the kernel's constant tables so they can enter
    the limb convolution without a device-side carry (raw canonical
    digits reach 2^t - 1 ≈ 2x the balanced bound, which would push the
    worst-case conv accumulator past int32 — see conv_bound)."""
    return carry(np.asarray(x, np.int64)).astype(np.int32)


# --- exact magnitude-bound propagation (the kernel's overflow proof) -------
#
# The Pallas kernel (ed25519_pallas.py) skips input-normalizing carry
# passes wherever the operands' worst-case magnitudes keep the conv
# accumulator (and the carry pass's x*prescale) inside int32.  These
# functions compute those worst cases EXACTLY (python ints, no float),
# and tests/test_field24.py re-derives the kernel's bound claims from
# them — the discipline is proven, not estimated.

_PRESCALE = [2 if i % 3 == 2 else 1 for i in range(LIMBS)]


def carry_bound(bx) -> list:
    """Per-limb worst-case |out| after one kernel _carry pass given
    per-limb |x| <= bx (mirrors ed25519_pallas._carry exactly)."""
    bx = [int(v) for v in bx]
    c, lo = [], []
    for i in range(LIMBS):
        t = SIZES[i]
        m = 1 << (11 - t)
        c.append(max((bx[i] * m + 1024) >> 11,
                     (bx[i] * m - 1024 + 2047) >> 11))
        lo.append(1 << (t - 1))
    f = c[LIMBS - 1] * FOLD
    fc = (f + 1024) >> 11
    out = [lo[0] + min(1024, f), lo[1] + fc + c[0]]
    for i in range(2, LIMBS):
        out.append(lo[i] + c[i - 1])
    return out


def conv_bound(ba, bb) -> list:
    """Per-position worst-case |accumulator| of the kernel's 24-slab
    convolution (pattern x2 factors + 38-fold) for operands bounded by
    ba/bb per limb."""
    ba = [int(v) for v in ba]
    bb = [int(v) for v in bb]
    acc = [0] * LIMBS
    for i in range(LIMBS):
        for j in range(LIMBS):
            pat = 2 if (i % 3) + (j % 3) >= 3 else 1
            term = ba[i] * bb[j] * pat
            if i + j >= LIMBS:
                term *= FOLD
            acc[(i + j) % LIMBS] += term
    return acc


def prescaled_max(bx) -> int:
    """max over limbs of |x|*prescale — the quantity the kernel's
    _carry computes before its 11-bit shift; must stay < 2^31."""
    return max(int(v) * p for v, p in zip(bx, _PRESCALE))


def resting_bound() -> list:
    """Fixed point of bound -> carry(carry(conv(bound, bound))): the
    worst-case per-limb magnitude of any _norm(.., 2) output when conv
    operands are themselves resting values (the relaxed discipline's
    steady state)."""
    b = [1 << (t - 1) for t in SIZES]
    for _ in range(12):
        nxt = carry_bound(carry_bound(conv_bound(b, b)))
        b = [max(a, c) for a, c in zip(nxt, b)]
    return b


def bytes_to_limbs(b: np.ndarray) -> np.ndarray:
    """[..., 32] byte values -> [..., 24] digits (golden model of the
    kernel's in-VMEM conversion)."""
    b = np.asarray(b, np.int64)
    out = np.zeros(b.shape[:-1] + (LIMBS,), np.int64)
    for i in range(LIMBS):
        s, t = OFFSETS[i], SIZES[i]
        b0, sh = s >> 3, s & 7
        acc = b[..., b0] >> sh
        if sh + t > 8:
            acc = acc | (b[..., b0 + 1] << (8 - sh))
        if sh + t > 16 and b0 + 2 < 32:
            acc = acc | (b[..., b0 + 2] << (16 - sh))
        out[..., i] = acc & ((1 << t) - 1)
    return out
