"""Pallas TPU kernel for batch ed25519 verification - 32x8-bit radix.

First-generation kernel, kept as the fallback behind
COMETBFT_TPU_KERNEL=pallas8 (the 24-limb kernel in ed25519_pallas.py
is the default; see ops/field24.py for why the radix changed).

The hot path of the framework (reference seam: crypto/ed25519/ed25519.go
BatchVerifier → types/validation.go verifyCommitBatch).  One fused Mosaic
kernel verifies a block of lanes end-to-end: ZIP-215 decompression,
4-bit-windowed Straus ladder for [8](s·B - R - k·A), and the identity
test — all in VMEM.

Layout is LIMB-MAJOR: a field element batch is int32[32, B] (limb rows ×
lane columns), so every limb row is a full VPU vector and the limb
convolution becomes 32 statically-shifted row MACs — ~2k vector MACs per
multiply, with no selector matmul (the XLA formulation in ed25519_jax.py
needs a [1024, 64] contraction per multiply to stay compile-time-sane;
inside Mosaic the unrolled form compiles directly).  The ladder and the
scalar-chain exponentiation run as fori_loops; the per-lane window tables
live in VMEM scratch and are read back with masked selects (there is no
cross-lane gather on the VPU).

The math (radix-2^8 redundant limbs, carry folding at weight 38,
magnitude discipline) matches ops/field.py — see the bounds notes there.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..crypto import _ed25519_ref as ref
from . import field

LIMBS = 32
_FOLD = 38
BLOCK = 128                     # lanes per grid step (one VPU row set)
_WINDOWS = 64


def _carry(x):
    """One parallel carry pass, limb-major ([32, B])."""
    c = x >> 8
    lo = x & 255
    c = jnp.concatenate([c[LIMBS - 1:] * _FOLD, c[:LIMBS - 1]], axis=0)
    return lo + c


def _norm(x, passes):
    for _ in range(passes):
        x = _carry(x)
    return x


def _mul(a, b):
    """Field multiply, limb-major.  |inputs| <= ~1600, output <= ~600."""
    a = _norm(a, 2)
    b = _norm(b, 2)
    xt = jnp.concatenate([a[1:] * _FOLD, a], axis=0)      # [63, B]
    acc = xt[31:63] * b[0:1]
    for j in range(1, LIMBS):
        acc = acc + xt[31 - j:63 - j] * b[j:j + 1]
    return _norm(acc, 3)


def _sqr(a):
    return _mul(a, a)


def _mul_const(x, c):
    return _norm(x * c, 3)


def _pow2k_loop(x, k):
    return lax.fori_loop(0, k, lambda _, v: _sqr(v), x)


def _pow_p58(x):
    """x^(2^252 - 3) (same chain as field.pow_p58)."""
    x2 = _sqr(x)
    t = _sqr(_sqr(x2))
    z9 = _mul(x, t)
    z11 = _mul(x2, z9)
    z_5_0 = _mul(z9, _sqr(z11))
    z_10_0 = _mul(_pow2k_loop(z_5_0, 5), z_5_0)
    z_20_0 = _mul(_pow2k_loop(z_10_0, 10), z_10_0)
    z_40_0 = _mul(_pow2k_loop(z_20_0, 20), z_20_0)
    z_50_0 = _mul(_pow2k_loop(z_40_0, 10), z_10_0)
    z_100_0 = _mul(_pow2k_loop(z_50_0, 50), z_50_0)
    z_200_0 = _mul(_pow2k_loop(z_100_0, 100), z_100_0)
    z_250_0 = _mul(_pow2k_loop(z_200_0, 50), z_50_0)
    return _mul(x, _pow2k_loop(z_250_0, 2))


# --- canonical / comparisons (limb-major) -----------------------------------

_P_NP = np.frombuffer(field.P.to_bytes(32, "little"), np.uint8
                      ).astype(np.int32)



def _seq_carry(x):
    """Exact sequential sweep: rows -> [0,256), plus carry row."""
    outs = []
    c = jnp.zeros_like(x[0:1])
    for i in range(LIMBS):
        v = x[i:i + 1] + c
        outs.append(v & 255)
        c = v >> 8
    return jnp.concatenate(outs, axis=0), c


def _canonical(x, four_p):
    x = _norm(x, 4)
    x = x + four_p                                            # + 4p
    for _ in range(3):
        x, c = _seq_carry(x)
        x = jnp.concatenate([x[0:1] + _FOLD * c, x[1:]], axis=0)
    for _ in range(2):
        ge = jnp.ones_like(x[0:1], dtype=jnp.bool_)
        gt = jnp.zeros_like(x[0:1], dtype=jnp.bool_)
        for i in range(LIMBS - 1, -1, -1):
            pi = int(_P_NP[i])
            gt = gt | (ge & (x[i:i + 1] > pi))
            ge = ge & (x[i:i + 1] == pi)
        take = gt | ge
        # subtract p where take
        outs = []
        c = jnp.zeros_like(x[0:1])
        for i in range(LIMBS):
            v = x[i:i + 1] - int(_P_NP[i]) + c
            outs.append(v & 255)
            c = v >> 8
        sub = jnp.concatenate(outs, axis=0)
        x = jnp.where(take, sub, x)
    return x


def _is_zero(x, four_p):
    """[1, B] bool: x == 0 mod p."""
    c = _canonical(x, four_p)
    nz = c[0:1]
    for i in range(1, LIMBS):
        nz = nz | c[i:i + 1]
    return nz == 0


def _eq(a, b, four_p):
    return _is_zero(a - b, four_p)


def _parity(x, four_p):
    return _canonical(x, four_p)[0:1] & 1


# --- point ops (extended twisted Edwards, limb-major) -----------------------

_D_COL = field.to_limbs(ref.D).reshape(LIMBS, 1)
_2D_COL = field.to_limbs(2 * ref.D % ref.P).reshape(LIMBS, 1)
_SQRT_M1_COL = field.to_limbs(ref.SQRT_M1).reshape(LIMBS, 1)


def _ext_add(p, q, two_d):
    """Unified add (complete for a=-1)."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = _mul(Y1 - X1, Y2 - X2)
    b = _mul(Y1 + X1, Y2 + X2)
    c = _mul(_mul(T1, T2), two_d)
    d = _mul_const(_mul(Z1, Z2), 2)
    e = b - a
    f = d - c
    g = d + c
    h = b + a
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _ext_double(p):
    """dbl-2008-hwcd, a=-1: 4 squarings + 4 products."""
    X1, Y1, Z1, _ = p
    a = _sqr(X1)
    b = _sqr(Y1)
    c = _mul_const(_sqr(Z1), 2)
    e = _sqr(X1 + Y1) - a - b
    g = b - a
    f = g - c
    h = -(a + b)
    return (_mul(e, f), _mul(g, h), _mul(f, g), _mul(e, h))


def _decompress(b, d_col, sqrt_m1, four_p):
    """b: [32, B] int32 byte values -> (x, y, ok) limb-major."""
    sign = b[31:32] >> 7
    y = jnp.concatenate([b[:31], b[31:32] & 0x7F], axis=0)
    # concatenate, not .at[].set: scatter has no Mosaic TPU lowering
    one = jnp.concatenate(
        [jnp.ones_like(y[0:1]), jnp.zeros_like(y[1:])], axis=0)
    yy = _sqr(y)
    u = yy - one
    v = _mul(yy, d_col) + one
    v3 = _mul(_sqr(v), v)
    v7 = _mul(_sqr(v3), v)
    x = _mul(_mul(u, v3), _pow_p58(_mul(u, v7)))
    vxx = _mul(v, _sqr(x))
    ok_direct = _eq(vxx, u, four_p)
    ok_flip = _eq(vxx, -u, four_p)
    x = jnp.where(ok_flip, _mul(x, sqrt_m1), x)
    valid = ok_direct | ok_flip
    wrong_sign = _parity(x, four_p) != sign
    x = jnp.where(wrong_sign, -x, x)
    return x, y, valid


# --- the kernel -------------------------------------------------------------

def _build_b_table_cols() -> np.ndarray:
    """Constant i·B table, [16, 4, 32, 1]: (entry, coord, limb, bcast)."""
    pts = [(0, 1)] + [ref.scalar_mult(i, ref.B) for i in range(1, 16)]
    out = np.zeros((16, 4, LIMBS, 1), np.int32)
    for i, (x, y) in enumerate(pts):
        out[i, 0, :, 0] = field.to_limbs(x)
        out[i, 1, :, 0] = field.to_limbs(y)
        out[i, 2, :, 0] = field.to_limbs(1)
        out[i, 3, :, 0] = field.to_limbs(x * y % ref.P)
    return out


_B_TABLE_NP = _build_b_table_cols()

# packed constants input: D, 2D, sqrt(-1), 4p, then the flattened B table
_CONSTS_NP = np.concatenate([
    field.to_limbs(ref.D).reshape(LIMBS, 1).astype(np.int32),
    field.to_limbs(2 * ref.D % ref.P).reshape(LIMBS, 1).astype(np.int32),
    field.to_limbs(ref.SQRT_M1).reshape(LIMBS, 1).astype(np.int32),
    # 4p as limb-wise double of 2p = 2^256 - 38 (fits 32 bytes)
    (2 * np.frombuffer((2 * field.P).to_bytes(32, "little"), np.uint8)
     .astype(np.int32)).reshape(LIMBS, 1),
    _B_TABLE_NP.reshape(16 * 4 * LIMBS, 1),
], axis=0)


def _kernel(a_ref, r_ref, swin_ref, kwin_ref, consts_ref, ok_ref,
            tab_ref):
    B = a_ref.shape[1]
    a_b = a_ref[:]
    r_b = r_ref[:]
    d_col = consts_ref[0:LIMBS]
    two_d = consts_ref[LIMBS:2 * LIMBS]
    sqrt_m1 = consts_ref[2 * LIMBS:3 * LIMBS]
    four_p = consts_ref[3 * LIMBS:4 * LIMBS]
    b_tab = consts_ref[4 * LIMBS:].reshape(16, 4, LIMBS, 1)

    ax, ay, a_ok = _decompress(a_b, d_col, sqrt_m1, four_p)
    rx, ry, r_ok = _decompress(r_b, d_col, sqrt_m1, four_p)
    zero = jnp.zeros((LIMBS, B), jnp.int32)
    one = jnp.concatenate(
        [jnp.ones((1, B), jnp.int32), zero[1:]], axis=0)

    # -A in extended coords
    nax, nay = -ax, ay
    nat = _mul(nax, nay)

    # per-lane table of i·(-A), i=0..15, in VMEM scratch
    # tab layout: [16, 4*LIMBS, B] (coords stacked along the limb axis)
    ident = jnp.concatenate([zero, one, one, zero], axis=0)
    tab_ref[0] = ident
    neg_a_stack = jnp.concatenate([nax, nay, one, nat], axis=0)
    tab_ref[1] = neg_a_stack

    def build_body(i, _):
        prev = tab_ref[i]
        p = (prev[0:LIMBS], prev[LIMBS:2 * LIMBS],
             prev[2 * LIMBS:3 * LIMBS], prev[3 * LIMBS:])
        q = (nax, nay, one, nat)
        r = _ext_add(p, q, two_d)
        tab_ref[i + 1] = jnp.concatenate(r, axis=0)
        return 0

    lax.fori_loop(1, 15, build_body, 0)

    def select_lane_table(w):
        """w: [1, B] 0..15 -> 4 coords [32, B] via masked sum."""
        acc = None
        for t in range(16):
            m = (w == t).astype(jnp.int32)
            term = tab_ref[t] * m
            acc = term if acc is None else acc + term
        return (acc[0:LIMBS], acc[LIMBS:2 * LIMBS],
                acc[2 * LIMBS:3 * LIMBS], acc[3 * LIMBS:])

    def select_b_table(w):
        coords = []
        for cix in range(4):
            acc = None
            for t in range(16):
                m = (w == t).astype(jnp.int32)
                term = b_tab[t, cix] * m
                acc = term if acc is None else acc + term
            coords.append(acc)
        return tuple(coords)

    def ladder_body(j, acc):
        for _ in range(4):
            acc = _ext_double(acc)
        w = (_WINDOWS - 1) - j
        # dynamic REF reads (pl.ds) — dynamic_slice on values has no
        # Mosaic TPU lowering
        sw = swin_ref[pl.ds(w, 1)]
        kw = kwin_ref[pl.ds(w, 1)]
        acc = _ext_add(acc, select_b_table(sw), two_d)
        acc = _ext_add(acc, select_lane_table(kw), two_d)
        return acc

    acc = lax.fori_loop(0, _WINDOWS, ladder_body,
                        (zero, one, one, zero))

    # subtract R, clear cofactor, identity test
    nrt = _mul(-rx, ry)
    acc = _ext_add(acc, (-rx, ry, one, nrt), two_d)
    for _ in range(3):
        acc = _ext_double(acc)
    X, Y, Z, _T = acc
    ok = _is_zero(X, four_p) & _eq(Y, Z, four_p) & a_ok & r_ok
    ok_ref[:] = jnp.broadcast_to(ok.astype(jnp.int32), (8, B))


@functools.partial(jax.jit, static_argnames=("interpret", "block"))
def _pallas_verify(a_cols, r_cols, s_win, k_win, interpret=False,
                   block=BLOCK):
    """a_cols, r_cols: [32, n] int32; s_win, k_win: [64, n] int32.
    Returns ok [n] bool.  n must be a multiple of block (the
    production path pads to BLOCK; tests run interpret mode with a
    small block so the emulated kernel stays tractable)."""
    n = a_cols.shape[1]
    if n % block != 0:
        raise ValueError(
            f"lane count {n} must be a multiple of block {block} — "
            "remainder lanes would never be written by the kernel")
    grid = n // block
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((8, n), jnp.int32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((LIMBS, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((LIMBS, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_WINDOWS, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_WINDOWS, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_CONSTS_NP.shape[0], 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((8, block), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((16, 4 * LIMBS, block), jnp.int32),
        ],
        interpret=interpret,
    )(a_cols, r_cols, s_win, k_win, jnp.asarray(_CONSTS_NP))
    return out[0] != 0


def verify_cols(a_cols, r_cols, s_win, k_win, interpret=False,
                block=BLOCK):
    return _pallas_verify(a_cols, r_cols, s_win, k_win,
                          interpret=interpret, block=block)
