"""Per-primitive TPU microbenchmarks — the kernel floor analysis.

VERDICT r4 #2: op-shaving on the 24-limb kernel is nearly exhausted;
what's missing is a HARDWARE-CALIBRATED floor — measured per-primitive
throughput that either names the structural win or proves the
single-chip target unreachable.  Each benchmark here is a tiny Pallas
kernel that runs K repetitions of ONE primitive from the production
kernel (ed25519_pallas.py — same code objects, not copies) over the
same [24, B] limb-major slabs, so a pool window yields the real cost
of: a carry pass, a field multiply, a doubling, the two addition
forms, the window-table select, and one full ladder window.

The kernels are AOT-exported alongside the main kernels
(``python -m cometbft_tpu.ops.microbench`` regenerates; artifacts in
ops/exported/mb_*.jaxexport) so a claimed window spends no time
tracing.  tools/tpu_probe.py runs `run_suite` opportunistically and
persists each record to BENCH_CACHE.json the moment it lands.

Values flowing through the primitives are arbitrary bounded limb
vectors, not curve points — primitive cost is data-independent (no
data-dependent control flow exists under jit), and the chained carry
discipline keeps magnitudes inside the proven int32 bounds either way.
"""
from __future__ import annotations

import functools
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import ed25519_pallas as ep

LIMBS = ep.LIMBS
BLOCK = ep.BLOCK
M_DEFAULT = 16384

# repetitions per primitive, sized so each run lands ~20-40 ms at
# m=16384 given the r4 measured kernel time (116 ms / ~3770 muls)
REPS = {
    "noop": 1,
    "carry": 4096,
    "mul": 1024,
    "sqr": 1024,
    "double": 128,
    "add": 128,
    "madd": 128,
    "select16": 512,
    "window": 16,
}


def _where_tree(w, rows):
    """16-entry select as a 4-level binary where-tree (the production
    kernel's select form — ed25519_pallas._kernel)."""
    bit = 1
    while len(rows) > 1:
        cond = (w & bit) != 0
        rows = [jnp.where(cond, rows[i + 1], rows[i])
                for i in range(0, len(rows), 2)]
        bit <<= 1
    return rows[0]


def _unpack_consts(consts_ref):
    d_col = consts_ref[0:LIMBS]
    two_d = consts_ref[LIMBS:2 * LIMBS]
    sqrt_m1 = consts_ref[2 * LIMBS:3 * LIMBS]
    four_p = consts_ref[3 * LIMBS:4 * LIMBS]
    pats = (consts_ref[4 * LIMBS:5 * LIMBS],
            consts_ref[5 * LIMBS:6 * LIMBS])
    b_tab = consts_ref[6 * LIMBS:].reshape(16, 3, LIMBS, 1)
    return d_col, two_d, sqrt_m1, four_p, pats, b_tab


def _make_kernel(op: str, reps: int):
    """A Pallas kernel running `reps` iterations of one primitive.
    x_ref: [32, B] int32 byte columns (seed data); consts_ref: the
    production kernel's packed constant block; out_ref: [8, B]."""

    def kernel(x_ref, consts_ref, out_ref):
        B = x_ref.shape[1]
        _d, two_d, _s, _fp, pats, b_tab = _unpack_consts(consts_ref)
        x = ep._norm(ep._from_bytes(x_ref[:]), 2)        # resting seed
        y = ep._norm(x + x, 2)
        one = jnp.concatenate(
            [jnp.ones((1, B), jnp.int32),
             jnp.zeros((LIMBS - 1, B), jnp.int32)], axis=0)
        t = ep._mul(x, y, pats, 0, 0)
        p = (x, y, one, t)

        if op == "noop":
            out_ref[:] = x[0:8]
            return
        if op == "carry":
            v = lax.fori_loop(0, reps, lambda _, u: ep._carry(u), x)
            out_ref[:] = v[0:8]
            return
        if op == "mul":
            def body(_, st):
                u, w = st
                return (ep._mul_nn(u, w, pats), u)
            u, _w = lax.fori_loop(0, reps, body, (x, y))
            out_ref[:] = u[0:8]
            return
        if op == "sqr":
            sqr = ep._make_sqr(pats)
            v = lax.fori_loop(0, reps, lambda _, u: sqr(u), x)
            out_ref[:] = v[0:8]
            return
        if op == "double":
            q = lax.fori_loop(
                0, reps, lambda _, u: ep._ext_double(u, pats), p)
            out_ref[:] = q[0][0:8]
            return
        if op == "add":
            def body(_, u):
                return ep._ext_add(u, p, two_d, pats)
            q = lax.fori_loop(0, reps, body, p)
            out_ref[:] = q[0][0:8]
            return
        if op == "madd":
            entry = (b_tab[3, 0], b_tab[3, 1], b_tab[3, 2])

            def body(_, u):
                return ep._madd_affine(u, entry, pats)
            q = lax.fori_loop(0, reps, body, p)
            out_ref[:] = q[0][0:8]
            return
        if op == "select16":
            w0 = x_ref[0:1] & 0xF

            def body(j, acc):
                w = (w0 + j) & 0xF
                sel = _where_tree(
                    w, [b_tab[i, 0] for i in range(16)])
                return acc + sel
            v = lax.fori_loop(0, reps, body,
                              jnp.zeros((LIMBS, B), jnp.int32))
            out_ref[:] = v[0:8]
            return
        if op == "window":
            # one full ladder window: 4 doublings + B-table madd +
            # lane-table ext_add, with both where-tree selects — the
            # lane table is stood in by 16 copies of p (same select
            # cost, no scratch build)
            w0 = x_ref[0:1] & 0xF
            lane_rows = [jnp.concatenate(p, axis=0)] * 16

            def body(j, acc):
                for i in range(4):
                    acc = ep._ext_double(acc, pats, need_t=(i == 3))
                w = (w0 + j) & 0xF
                bsel = tuple(_where_tree(
                    w, [b_tab[i, cix] for i in range(16)])
                    for cix in range(3))
                acc = ep._madd_affine(acc, bsel, pats)
                lsel = _where_tree(w, lane_rows)
                q = (lsel[0:LIMBS], lsel[LIMBS:2 * LIMBS],
                     lsel[2 * LIMBS:3 * LIMBS], lsel[3 * LIMBS:])
                return ep._ext_add(acc, q, two_d, pats)
            q = lax.fori_loop(0, reps, body, p)
            out_ref[:] = q[0][0:8]
            return
        raise ValueError(f"unknown op {op}")

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("op", "reps", "block", "interpret"))
def _bench_call(x_cols, op: str, reps: int, block: int = BLOCK,
                interpret: bool = False):
    n = x_cols.shape[1]
    grid = n // block
    return pl.pallas_call(
        _make_kernel(op, reps),
        out_shape=jax.ShapeDtypeStruct((8, n), jnp.int32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((32, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ep._CONSTS_NP.shape[0], 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((8, block), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x_cols, jnp.asarray(ep._CONSTS_NP))


def _artifact(op: str, m: int) -> str:
    from .aot import ARTIFACT_DIR
    return os.path.join(ARTIFACT_DIR, f"mb_{op}_{m}.jaxexport")


def generate(m: int = M_DEFAULT, ops=None) -> list[str]:
    """AOT-export every microbench kernel for the TPU platform (run on
    any host: lowering is device-free).  python -m ...ops.microbench"""
    jax.config.update("jax_platforms", "cpu")
    from jax import export
    written = []
    for op in (ops or REPS):
        x = jnp.asarray(np.zeros((32, m), np.int32))
        fn = jax.jit(functools.partial(_bench_call, op=op,
                                       reps=REPS[op]))
        exp = export.export(fn, platforms=["tpu"])(x)
        path = _artifact(op, m)
        with open(path, "wb") as f:
            f.write(exp.serialize())
        written.append(path)
        print(f"exported mb_{op}_{m}: {os.path.getsize(path)} bytes",
              file=sys.stderr)
    return written


def run_suite(base_rec, smoke: bool = False, m: int = M_DEFAULT,
              reps_timing: int = 5) -> list[dict]:
    """Run every microbench on the live backend, appending one record
    per op to the probe cache AS EACH COMPLETES (the pool can vanish
    mid-suite).  Returns the records."""
    from ..tools.tpu_probe import append_records
    if smoke:
        return []            # compiled pallas kernels are TPU-only
    from jax import export as jexport
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.integers(0, 256, (32, m), dtype=np.int32))
    x.block_until_ready()
    out = []
    for op, k in REPS.items():
        try:
            exp = None
            path = _artifact(op, m)
            if os.path.exists(path):
                with open(path, "rb") as f:
                    exp = jexport.deserialize(f.read())

            def dispatch():
                if exp is not None:
                    np.asarray(exp.call(x))
                else:
                    np.asarray(_bench_call(x, op=op, reps=k))
            t_first = time.perf_counter()
            if exp is not None:
                # trial-call the artifact rather than string-matching
                # the backend name (the pooled chip may register as
                # "axon"); a genuine platform refusal falls back to
                # live jit
                try:
                    dispatch()
                except Exception:
                    exp = None
                    dispatch()
            else:
                dispatch()                   # warm / compile
            first_s = time.perf_counter() - t_first
            ts = []
            for _ in range(reps_timing):
                t0 = time.perf_counter()
                dispatch()
                ts.append((time.perf_counter() - t0) * 1000.0)
            med = float(np.median(ts))
            rec = base_rec(
                metric=f"mb_{op}", bucket=m, value_ms=round(med, 2),
                reps=k, per_op_us=round(med * 1000.0 / k / 1.0, 3),
                aot=exp is not None, first_call_s=round(first_s, 1),
                runs=[round(t, 1) for t in ts])
            append_records([rec])
            out.append(rec)
        except Exception as e:
            rec = base_rec(metric=f"mb_{op}", bucket=m,
                           error=repr(e)[:300])
            append_records([rec])
            out.append(rec)
    return out


if __name__ == "__main__":
    generate()
