"""AOT-exported kernel artifacts (jax.export serialization).

The pooled TPU backend in this environment is flaky, so the first
live window must pay as close to zero preparation as possible
(VERDICT r2 #1).  The bucketed ed25519 kernels are exported
ahead-of-time — traced and LOWERED for the TPU platform on any host,
no TPU needed — and the serialized StableHLO artifacts are committed
under ops/exported/.  On a live TPU the dispatch deserializes and
calls them: zero tracing, stable programs keyed into the persistent
compilation cache.

Exporting also VALIDATES TPU lowerability today: generating these
artifacts is what surfaced (and now guards against) Mosaic's
unsupported scatter/dynamic_slice primitives in the Pallas kernel.

Regenerate after kernel changes:  python -m cometbft_tpu.ops.aot
"""
from __future__ import annotations

import functools
import os
import sys
from typing import Optional

ARTIFACT_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "exported")

def _xla_buckets() -> tuple:
    """Mirror the dispatch's runtime buckets exactly — a mismatched
    artifact is unreachable dead weight.  Includes the overlapped
    pipeline's tile bucket (ed25519_jax._verify_pipelined pads every
    balanced tile to a pad-bucket shape, so a COMETBFT_TPU_VERIFY_TILE
    override outside the base ladder still exports an artifact)."""
    from .ed25519_jax import _BUCKETS
    return tuple(sorted(set(_BUCKETS) | set(tile_buckets())))


def tile_buckets() -> tuple:
    """Pad-bucket shapes the tiled verification pipeline dispatches
    at: every balanced tile pads to ``_bucket(tile)`` for tiles up to
    the configured tile size (crypto/pipeline.tile_size)."""
    from ..crypto.pipeline import tile_size
    from .ed25519_jax import _bucket
    return (_bucket(tile_size()),)


def missing_tile_artifacts(kernel: str = "xla") -> list:
    """Tile-bucket shapes the pipeline would dispatch that have no
    committed artifact — tpu_probe surfaces these before a hardware
    window so the window is never burned tracing a tile shape."""
    out = []
    for m in tile_buckets():
        if kernel.startswith("pallas"):
            from .ed25519_pallas import BLOCK
            m = max(m, BLOCK)
        if not os.path.exists(_path(kernel, m)):
            out.append(m)
    return out


def _pallas_buckets() -> tuple:
    from .ed25519_pallas import BLOCK
    return tuple(max(b, BLOCK) for b in _xla_buckets())


def _path(kernel: str, m: int) -> str:
    return os.path.join(ARTIFACT_DIR, f"ed25519_{kernel}_{m}.jaxexport")


@functools.lru_cache(maxsize=None)
def load(kernel: str, m: int):
    """Deserialized exported kernel for (kernel, lane count), or None
    when no artifact exists (caller falls back to plain jit)."""
    p = _path(kernel, m)
    try:
        with open(p, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    try:
        from jax import export
        return export.deserialize(blob)
    except Exception:
        return None


def call(kernel: str, a, r, s_w8, k_w8):
    """Run the exported kernel on the current default platform, or
    return None when no artifact matches.  Both kernels ship behind
    the packed uint8 wire layout: a/r [m,32]u8, s/k [m,64]u8
    (lane-major windows); the exported program unpacks on device."""
    m = a.shape[0]
    exp = load(kernel, m)
    if exp is None:
        return None
    import jax

    from .ed25519_jax import TPU_PLATFORMS
    try:
        backend = jax.default_backend()
    except Exception:
        return None
    if backend not in TPU_PLATFORMS:
        # artifacts are TPU-lowered; the allowlist covers the pooled
        # plugin name ("axon"), while CPU/GPU/unknown accelerators use
        # live jit instead of failing the artifact per batch
        return None
    try:
        return exp.call(a, r, s_w8, k_w8)
    except Exception:
        return None


def generate(xla_buckets=None, pallas_buckets=None,
             out_dir: Optional[str] = None) -> list[str]:
    """Export + serialize every bucketed kernel for the TPU platform.
    Runs on any host (lowering doesn't need the device)."""
    import jax

    # lowering happens per TARGET platform regardless of the local
    # backend; pin CPU so generation never dials the pooled TPU (even
    # probing jax.default_backend() would block on the axon claim)
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax import export

    from . import ed25519_jax as ej

    if xla_buckets is None:
        xla_buckets = _xla_buckets()
    if pallas_buckets is None:
        pallas_buckets = _pallas_buckets()
    out_dir = out_dir or ARTIFACT_DIR
    os.makedirs(out_dir, exist_ok=True)
    written = []

    # TPU-only: a serialized XLA:CPU executable is pinned to the
    # generating host's CPU features (SIGILL risk across hosts, and
    # measured far slower than the live-jit path even on the same
    # host); CPU runs jit + the persistent compile cache instead.
    for m in xla_buckets:
        a = jnp.asarray(np.zeros((m, 32), np.uint8))
        w8 = jnp.asarray(np.zeros((m, 64), np.uint8))
        exp = export.export(ej._jit_verify_packed,
                            platforms=["tpu"])(a, a, w8, w8)
        p = os.path.join(out_dir, f"ed25519_xla_{m}.jaxexport")
        with open(p, "wb") as f:
            f.write(exp.serialize())
        written.append(p)
        print(f"exported xla m={m}: {os.path.getsize(p)} bytes",
              file=sys.stderr)

    for m in pallas_buckets:
        a = jnp.asarray(np.zeros((m, 32), np.uint8))
        w8 = jnp.asarray(np.zeros((m, 64), np.uint8))
        fn = jax.jit(functools.partial(ej._pallas_verify_packed,
                                       kernel="pallas"))
        exp = export.export(fn, platforms=["tpu"])(a, a, w8, w8)
        p = os.path.join(out_dir, f"ed25519_pallas_{m}.jaxexport")
        with open(p, "wb") as f:
            f.write(exp.serialize())
        written.append(p)
        print(f"exported pallas m={m}: {os.path.getsize(p)} bytes",
              file=sys.stderr)
    return written


if __name__ == "__main__":
    generate()
