"""GF(2^255 - 19) arithmetic as vectorized limb operations for TPU.

Design notes (TPU-first; the reference uses curve25519-voi's 64-bit host
arithmetic, crypto/ed25519/ed25519.go — nothing here is a translation):

  * A field element is an int32 array whose last axis holds 32 little-endian
    radix-2^8 limbs.  8-bit limbs keep every partial product far inside int32
    (32 * 600^2 < 2^24) and line up with the int8 MXU path for later
    optimization.
  * Representations are redundant: limbs may be negative or exceed 255
    between operations.  `mul` renormalizes its output to |limb| <= ~300;
    add/sub/neg are lazy (no carry).  All ops are correct mod p for inputs
    with |limb| <= ~600, which every composition below respects.
  * 2^256 = 2*p + 38, so folding the carry out of limb 31 into limb 0 with
    weight 38 preserves the value mod p.
  * `canonical` produces the unique representative in [0, p) with limbs in
    [0, 256); carry resolution there runs *sequentially over the 32 limbs*
    (exact in one sweep) — the batch axis provides all the parallelism, so
    32 scalar-per-lane steps cost nothing.

Everything is shape-polymorphic over leading batch axes and jit-safe.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.lax as lax
import jax.numpy as jnp

P = 2**255 - 19
LIMBS = 32
_FOLD = 38  # 2^256 mod p


def to_limbs(x: int) -> np.ndarray:
    """Host: python int -> 32 int32 limbs (canonical)."""
    x = x % P
    return np.frombuffer(x.to_bytes(32, "little"), dtype=np.uint8).astype(np.int32)


def from_limbs(a) -> int:
    """Host: limb array (any redundancy) -> python int mod p. Test helper."""
    limbs = np.asarray(a, dtype=np.int64).reshape(-1)
    val = 0
    for i, limb in enumerate(limbs):
        val += int(limb) << (8 * i)
    return val % P


def constant(x: int) -> jnp.ndarray:
    return jnp.asarray(to_limbs(x))


def carry_fold(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry pass; the carry out of limb 31 folds back into
    limb 0 with weight 38.  Value preserved mod p; magnitudes shrink ~256x
    per pass.  Handles negative limbs (arithmetic shift = floor division)."""
    c = x >> 8
    lo = x & 255
    c = jnp.roll(c, 1, axis=-1)
    c = c.at[..., 0].multiply(_FOLD)
    return lo + c


def normalize(x: jnp.ndarray, passes: int = 4) -> jnp.ndarray:
    for _ in range(passes):
        x = carry_fold(x)
    return x


def add(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return x + y


def sub(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return x - y


def neg(x: jnp.ndarray) -> jnp.ndarray:
    return -x


# The limb product is the length-63 convolution of the two limb vectors,
# with columns >= 32 folded back at weight 38 (2^256 = 2p + 38).  The
# convolution is ONE dot_general: flatten the outer product x_i*y_j to
# [..., 1024] and contract with the constant 0/1 selector _CONVMAT
# [1024, 64] (entry (i*32+j, i+j) = 1; column 63 stays zero), then fold
# lo + 38*hi on the vector unit.
#
# The dot runs in float32 at Precision.HIGHEST, which is EXACT here and is
# the whole point of the layout: operands are pre-normalized to
# |limb| <= 293 (2 carry passes), so each product is an integer < 2^17 and
# each 0/1 column sums <= 32 of them < 2^22 — far inside float32's 2^24
# exact-integer range.  f32-HIGHEST contraction maps onto the MXU
# (bf16x3 passes on TPU, sgemm on CPU); an int32 formulation of the same
# contraction lowers to slow vector-unit loops, and an unrolled 32-slice
# MAC formulation is ~10x cheaper arithmetically but blows up XLA compile
# time (~30s for point decompression alone), so this is the sweet spot of
# compile time x runtime x exactness.
def _build_convmat() -> np.ndarray:
    m = np.zeros((LIMBS * LIMBS, 2 * LIMBS), np.float32)
    for i in range(LIMBS):
        for j in range(LIMBS):
            m[i * LIMBS + j, i + j] = 1.0
    return m


_CONVMAT = jnp.asarray(_build_convmat())


def mul(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Field multiply. |input limbs| <= ~1600 allowed; output <= ~600."""
    batch = jnp.broadcast_shapes(x.shape[:-1], y.shape[:-1])
    x = jnp.broadcast_to(normalize(x, passes=2), batch + (LIMBS,))
    y = jnp.broadcast_to(normalize(y, passes=2), batch + (LIMBS,))
    xf = x.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    outer = (xf[..., :, None] * yf[..., None, :]).reshape(
        batch + (LIMBS * LIMBS,))
    conv = jax.lax.dot_general(
        outer, _CONVMAT,
        dimension_numbers=(((outer.ndim - 1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST).astype(jnp.int32)
    folded = conv[..., :LIMBS] + _FOLD * conv[..., LIMBS:]
    return normalize(folded, passes=3)


def sqr(x: jnp.ndarray) -> jnp.ndarray:
    return mul(x, x)


def mul_const(x: jnp.ndarray, c: int) -> jnp.ndarray:
    """Multiply by a small nonnegative int (< 2^15)."""
    return normalize(x * jnp.int32(c), passes=3)


def pow2k(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """x^(2^k) via k squarings (fori_loop keeps the trace small)."""
    if k <= 4:
        for _ in range(k):
            x = sqr(x)
        return x
    return lax.fori_loop(0, k, lambda _, v: sqr(v), x)


def pow_p58(x: jnp.ndarray) -> jnp.ndarray:
    """x^((p-5)/8) = x^(2^252 - 3). Standard ed25519 addition chain."""
    x2 = sqr(x)                      # 2
    t = sqr(sqr(x2))                 # 8
    z9 = mul(x, t)                   # 9
    z11 = mul(x2, z9)                # 11
    z22 = sqr(z11)                   # 22
    z_5_0 = mul(z9, z22)             # 2^5 - 1
    t = pow2k(z_5_0, 5)
    z_10_0 = mul(t, z_5_0)           # 2^10 - 1
    t = pow2k(z_10_0, 10)
    z_20_0 = mul(t, z_10_0)          # 2^20 - 1
    t = pow2k(z_20_0, 20)
    z_40_0 = mul(t, z_20_0)          # 2^40 - 1
    t = pow2k(z_40_0, 10)
    z_50_0 = mul(t, z_10_0)          # 2^50 - 1
    t = pow2k(z_50_0, 50)
    z_100_0 = mul(t, z_50_0)         # 2^100 - 1
    t = pow2k(z_100_0, 100)
    z_200_0 = mul(t, z_100_0)        # 2^200 - 1
    t = pow2k(z_200_0, 50)
    z_250_0 = mul(t, z_50_0)         # 2^250 - 1
    t = pow2k(z_250_0, 2)
    return mul(x, t)                 # 2^252 - 3


# --- canonicalization -------------------------------------------------------

# A 4p offset in 32 limbs (limb values up to 510): adding it makes any
# redundant value here (|v| <= ~1.2 * 2^256 < 2.4p) positive without changing
# it mod p.  Built as 2 * (2p), where 2p = 2^256 - 38 fits canonical limbs.
_2P_BYTES = np.frombuffer((2 * P).to_bytes(32, "little"), np.uint8).astype(np.int32)
_FOUR_P = jnp.asarray(2 * _2P_BYTES)
_P_NP = np.frombuffer(P.to_bytes(32, "little"), np.uint8).astype(np.int32)
_P_LIMBS = jnp.asarray(_P_NP)


def _seq_carry(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact sequential carry sweep: limbs -> [0,256) plus carry-out.
    32 scalar-per-lane steps; batch axes carry the parallelism."""
    outs = []
    c = jnp.zeros(x.shape[:-1], jnp.int32)
    for i in range(LIMBS):
        v = x[..., i] + c
        outs.append(v & 255)
        c = v >> 8
    return jnp.stack(outs, axis=-1), c


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Unique representative in [0, p), limbs in [0, 256)."""
    x = normalize(x, passes=4)          # |limbs| <= ~300
    x = x + _FOUR_P                     # value now positive, < 2^257 + 2^256
    for _ in range(3):                  # sweep + fold until carry-out is 0
        x, c = _seq_carry(x)
        x = x.at[..., 0].add(_FOLD * c)
    # value in [0, 2^256) < 3p: subtract p at most twice
    for _ in range(2):
        ge = _ge_p(x)
        diff = _seq_sub_p(x)
        x = jnp.where(ge[..., None], diff, x)
    return x


def _ge_p(x: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic x >= p for canonical-limbed x ([0,256))."""
    ge = jnp.zeros(x.shape[:-1], bool)
    eq_above = jnp.ones(x.shape[:-1], bool)
    for i in range(LIMBS - 1, -1, -1):
        pi = int(_P_NP[i])
        ge = ge | (eq_above & (x[..., i] > pi))
        eq_above = eq_above & (x[..., i] == pi)
    return ge | eq_above                # x == p counts as >=


def _seq_sub_p(x: jnp.ndarray) -> jnp.ndarray:
    """x - p with an exact sequential borrow sweep (x assumed >= p)."""
    outs = []
    c = jnp.zeros(x.shape[:-1], jnp.int32)
    for i in range(LIMBS):
        v = x[..., i] - int(_P_NP[i]) + c
        outs.append(v & 255)
        c = v >> 8
    return jnp.stack(outs, axis=-1)


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """True where x ≡ 0 mod p (bool over batch axes)."""
    return jnp.all(canonical(x) == 0, axis=-1)


def eq(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return is_zero(x - y)


def parity(x: jnp.ndarray) -> jnp.ndarray:
    """Low bit of the canonical value (the ed25519 sign-of-x bit)."""
    return canonical(x)[..., 0] & 1


def bytes_to_limbs(b: jnp.ndarray) -> jnp.ndarray:
    """[..., 32] uint8 -> int32 limbs (no reduction; values >= p are fine in
    the redundant representation — ZIP-215 permissive decoding relies on it)."""
    return b.astype(jnp.int32)


def canonical_bytes(x: jnp.ndarray) -> jnp.ndarray:
    """[..., 32] uint8 canonical little-endian encoding."""
    return canonical(x).astype(jnp.uint8)
